"""AOT prewarm: compile the planned program set ahead of the first step.

BENCH_r02 measured 37.9 s of compile+warmup before the first useful train
step, and every serving replica re-pays that tax per shape bucket on first
touch. The pieces to kill it already exist and are composed here:

- strict mode *enumerates* the full planned program set
  (``utils/strictmode.py``: ``train_planned_programs`` — the
  ``(single|multi, second_order, msl)`` train variants plus eval — and
  ``serving_planned_programs`` — the (kind, shape-bucket, batch-bucket)
  grid);
- the compile ledger (``observability/compile_ledger.py``) already does the
  explicit ``.lower()``/``.compile()`` AOT split with per-signature caching
  and persistent-cache hit accounting (``utils/compcache.py``).

:func:`prewarm_train` / :func:`prewarm_serving` walk the planned set,
build each program through the system's/engine's own program-cache seam (so
the strict :class:`RecompileGuard` notes every key — the prewarm plan and
the guard's planned set cannot drift apart), and warm each one through
``LedgerWrapped.warm`` — lower timed, compile timed, one ledger entry with
``phase="prewarm"``, **no execution**. Arguments are
``jax.ShapeDtypeStruct`` specs (shape/dtype/sharding only — the same
abstract signature a real call computes), so prewarm never materializes a
batch. Compiles overlap across programs in a bounded thread pool — XLA
compiles release the GIL, so the overlap is real even on one core.

Warm artifacts persist two ways:

1. the JAX persistent compilation cache (:func:`ensure_persistent_cache`
   wires ``utils/compcache.py`` on when nothing else has): the XLA
   artifact itself, so a restarted run pays tracing, not XLA;
2. an **executable store** written alongside the checkpoints
   (:class:`ExecutableStore`): the fully serialized executables
   (``jax.experimental.serialize_executable``), one file per (program,
   signature), so a restarted run or a freshly spawned fleet/serving
   replica skips tracing AND XLA entirely — on the toy CPU benchmark this
   is the difference between a ~50% and a ~90% compile-tax kill, because
   tracing is what the compilation cache cannot absorb. Its **manifest**
   (``experiment/checkpoint.py::save_prewarm_manifest``): program key ->
   signature digest, a jax/jaxlib/backend/device-kind/mesh fingerprint,
   and the cache dir's entry digest — is how a fresh process *verifies* it
   will hit warm (:func:`verify_manifest`) before accepting work; a
   jaxlib/device-kind change gates the store to write-only and falls back
   to a logged cold compile instead of loading stale artifacts.

After a prewarm the strict guard is sealed (``mark_prewarmed``): any
program compiled outside prewarm is a finding, not a convenience — the
contract flips from "detect drift" to "enforce the prewarmed set".

Entry points: the runner (``experiment/runner.py``) under ``Config.aot``,
the serving frontend (``serving/server.py`` — background, with ``/healthz``
503 "warming" until done), ``scripts/prewarm.py`` standalone, and
``scripts/loadgen.py``'s pre-clock warmup (via ``AdaptationEngine.prewarm``).
"""

import hashlib
import os
import pickle
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.compcache import (
    active_cache_dir,
    cache_entry_count,
    setup_compilation_cache,
)

MANIFEST_VERSION = 1

#: fingerprint fields that must match exactly for a manifest to promise a
#: warm start — a different jaxlib serializes different executables, a
#: different device kind compiles different code, a different mesh bakes
#: different shardings into every program
_FINGERPRINT_FIELDS = ("jax", "jaxlib", "backend", "device_kind", "n_devices", "mesh")


# ---------------------------------------------------------------------------
# argument specs (ShapeDtypeStruct pytrees — nothing is materialized)
# ---------------------------------------------------------------------------


def _sds(shape: Tuple[int, ...], dtype, sharding=None):
    import jax

    try:
        return jax.ShapeDtypeStruct(tuple(shape), dtype, sharding=sharding)
    except TypeError:  # a jax without the sharding kwarg: shape/dtype only
        return jax.ShapeDtypeStruct(tuple(shape), dtype)


def shape_specs(tree, leading: Tuple[int, ...] = ()):
    """Pytree of arrays -> matching pytree of ``ShapeDtypeStruct`` specs
    (per-leaf shardings carried when the leaves have them), optionally with
    extra ``leading`` axes — the non-materializing argument form
    ``LedgerWrapped.warm`` lowers against. Works on device arrays and host
    numpy alike."""
    import jax

    def spec(leaf):
        return _sds(
            tuple(leading) + tuple(np.shape(leaf)),
            leaf.dtype,
            # only device arrays carry a sharding; adding leading axes to a
            # sharded leaf would misalign its spec, so shardings only ride
            # the no-leading (real argument) form
            getattr(leaf, "sharding", None) if not leading else None,
        )

    return jax.tree.map(spec, tree)


def train_batch_spec(cfg, sharding=None, leading: Tuple[int, ...] = ()):
    """The loader's episode-batch pytree as specs: leaves shaped
    ``leading + [B, n_way, k, ...]`` with the loader's dtypes (x float32,
    y int32), ``B = batch_size * samples_per_iter`` — exactly what
    ``MetaLearningDataLoader`` yields and ``runner._put`` places."""
    b = cfg.batch_size * cfg.samples_per_iter
    n_way, k = cfg.num_classes_per_set, cfg.num_samples_per_class
    t = cfg.num_target_samples
    h, w, c = cfg.image_shape
    lead = tuple(leading)
    return {
        "x_support": _sds(lead + (b, n_way, k, h, w, c), np.float32, sharding),
        "y_support": _sds(lead + (b, n_way, k), np.int32, sharding),
        "x_target": _sds(lead + (b, n_way, t, h, w, c), np.float32, sharding),
        "y_target": _sds(lead + (b, n_way, t), np.int32, sharding),
    }


# ---------------------------------------------------------------------------
# the warm pool
# ---------------------------------------------------------------------------


def _warm_one(fn: Callable, args: Sequence[Any], store=None) -> Dict[str, Any]:
    warm = getattr(fn, "warm", None)
    if warm is not None:
        return warm(*args, store=store)
    # a plain jitted program (built before any ledger was attached): AOT
    # lower+compile still seeds the persistent cache, but jit's own call
    # cache stays cold — the first real call re-traces and hits the cache
    fn.lower(*args).compile()
    return {"already_warm": False, "signature": None, "unledgered": True}


def signature_digest(sig: Any) -> Optional[str]:
    """Short stable digest of a warm()'d abstract signature — the manifest's
    program identity and the executable store's file key (the full repr is
    pages of pytree; the digest is structural, so the spec-built prewarm
    signature and a real call's signature digest identically across
    processes)."""
    if sig is None:
        return None
    return hashlib.sha256(repr(sig).encode()).hexdigest()[:16]


class ExecutableStore:
    """Serialized-executable persistence: one pickle of
    ``jax.experimental.serialize_executable.serialize(compiled)`` (payload +
    in/out pytree defs) per (program, signature digest), written atomically
    under ``<saved_models>/executables/``. Loading one skips tracing AND
    XLA — the part of the cold start the persistent compilation cache
    cannot absorb. ``allow_load=False`` makes the store write-only: the
    caller verified the manifest fingerprint and refuses to load artifacts
    serialized by a different jaxlib/device-kind/mesh (deserialization of a
    stale payload is undefined behavior, not a recoverable miss). Load and
    save failures are counted, never raised — a broken store degrades to a
    plain cold compile."""

    def __init__(self, directory: str, allow_load: bool = True):
        self.dir = directory
        self.allow_load = allow_load
        self._lock = threading.Lock()
        self._counts = {"loads": 0, "saves": 0, "load_errors": 0, "save_errors": 0}

    def _bump(self, key: str) -> None:
        with self._lock:
            self._counts[key] += 1

    def _path(self, program: str, digest: str) -> str:
        return os.path.join(self.dir, f"{program.replace('/', '_')}__{digest}.exe")

    def load(self, program: str, sig: Any) -> Optional[Callable]:
        """The warm fast path: deserialize the stored executable for this
        (program, signature), or None (absent / gated / unreadable)."""
        digest = signature_digest(sig)
        if not self.allow_load or digest is None:
            return None
        path = self._path(program, digest)
        if not os.path.exists(path):
            return None
        try:
            from jax.experimental import serialize_executable

            with open(path, "rb") as f:
                payload, in_tree, out_tree = pickle.load(f)
            fn = serialize_executable.deserialize_and_load(payload, in_tree, out_tree)
        except Exception:  # noqa: BLE001 — torn file, version skew, pickle drift
            self._bump("load_errors")
            return None
        self._bump("loads")
        return fn

    def save(self, program: str, sig: Any, compiled: Callable) -> bool:
        """Serialize a freshly compiled executable (atomic tmp+rename — a
        kill mid-write must never leave a torn store entry). Non-``Compiled``
        objects (an AOT-failure fallback to plain jit) are skipped."""
        import jax

        digest = signature_digest(sig)
        if digest is None or not isinstance(compiled, jax.stages.Compiled):
            return False
        try:
            from jax.experimental import serialize_executable

            payload, in_tree, out_tree = serialize_executable.serialize(compiled)
            os.makedirs(self.dir, exist_ok=True)
            path = self._path(program, digest)
            tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
            with open(tmp, "wb") as f:
                pickle.dump((payload, in_tree, out_tree), f)
            os.replace(tmp, path)
        except Exception:  # noqa: BLE001 — full disk, unpicklable callback, ...
            self._bump("save_errors")
            return False
        self._bump("saves")
        return True

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            counts = dict(self._counts)
        return {"dir": self.dir, "allow_load": self.allow_load, **counts}


def _run_warm_pool(
    jobs: List[Tuple[str, Callable, Sequence[Any]]],
    ledger,
    guard,
    max_workers: int,
    compile_timeout_s: float,
    on_program: Optional[Callable[[str], None]],
    store: Optional[ExecutableStore] = None,
) -> Dict[str, Any]:
    """Warm every job and fold the results + the ledger delta into one
    summary. With ``max_workers > 1``: a bounded pool of DAEMON worker
    threads overlaps compiles; a program exceeding the compile budget is
    reported, not waited on forever — and because the workers are daemons,
    a wedged XLA compile can't block process exit either (a
    ThreadPoolExecutor's non-daemon workers would be joined at interpreter
    shutdown, turning the contained timeout back into a hang). With ONE
    worker the jobs run inline on the calling thread instead: a lone worker
    buys no overlap, and its loads/compiles convoy with the caller's
    ``Event.wait`` on the GIL (measured 2-4x inflation on a 1-core box) —
    inline mode has exact timings and leaves hang coverage to the caller's
    watchdog (the runner beats per program). Warm failures are contained
    per program either way (that program stays lazily jitted)."""
    before = ledger.summary() if ledger is not None else None
    t0 = time.perf_counter()
    results: Dict[str, Dict[str, Any]] = {}
    errors: Dict[str, str] = {}
    workers = max(1, min(max_workers, len(jobs) or 1))
    if workers == 1:
        for name, fn, args in jobs:
            try:
                results[name] = _warm_one(fn, args, store)
            except Exception as exc:  # noqa: BLE001 — contained per program
                errors[name] = f"{type(exc).__name__}: {exc}"
            if on_program is not None:
                on_program(name)
    else:
        outcome_lock = threading.Lock()
        outcomes: Dict[str, Any] = {}
        done = {name: threading.Event() for name, _, _ in jobs}
        job_queue: "queue.SimpleQueue" = queue.SimpleQueue()
        for job in jobs:
            job_queue.put(job)

        def worker() -> None:
            while True:
                try:
                    name, fn, args = job_queue.get_nowait()
                except queue.Empty:
                    return
                try:
                    out = _warm_one(fn, args, store)
                except Exception as exc:  # noqa: BLE001 — contained per program
                    out = exc
                with outcome_lock:
                    outcomes[name] = out
                done[name].set()

        for i in range(workers):
            threading.Thread(
                target=worker, name=f"prewarm-{i}", daemon=True
            ).start()
        for name, _, _ in jobs:
            # per-program budget, measured from when its wait starts (the
            # same semantics fut.result(timeout=...) gave): queued jobs
            # keep compiling while earlier ones are waited on
            if done[name].wait(timeout=compile_timeout_s):
                with outcome_lock:
                    out = outcomes[name]
                if isinstance(out, Exception):
                    errors[name] = f"{type(out).__name__}: {out}"
                else:
                    results[name] = out
            else:
                errors[name] = (
                    f"TimeoutError: still compiling past the "
                    f"{compile_timeout_s}s prewarm budget"
                )
            if on_program is not None:
                on_program(name)
    wall = time.perf_counter() - t0
    after = ledger.summary() if ledger is not None else None
    by_program: Dict[str, Dict[str, Any]] = {}
    for name, _, _ in jobs:
        res = results.get(name)
        agg = (after or {}).get("by_program", {}).get(name, {})
        by_program[name] = {
            "signature": signature_digest((res or {}).get("signature")),
            "total_s": agg.get("total_s"),
            "cache_hit": bool(agg.get("cache_hits")),
            "loaded": bool((res or {}).get("loaded")),
            "stored": bool((res or {}).get("stored")),
            "already_warm": bool((res or {}).get("already_warm")),
        }
        if name in errors:
            by_program[name]["error"] = errors[name]
    summary = {
        "programs": len(jobs),
        "seconds": round(wall, 3),
        "compile_s": (
            round(after["total_s"] - before["total_s"], 3)
            if before is not None
            else None
        ),
        "cache_hits": (
            after["cache_hits"] - before["cache_hits"] if before is not None else 0
        ),
        # programs that skipped tracing AND XLA via the executable store —
        # the deepest warm-start tier
        "store_hits": sum(1 for r in results.values() if r.get("loaded")),
        "already_warm": sum(1 for r in results.values() if r.get("already_warm")),
        "errors": len(errors),
        "by_program": by_program,
    }
    if store is not None:
        summary["store"] = store.stats()
    if guard is not None:
        # the contract flip: the planned family is now declared complete —
        # any later first-compile is a strict-mode finding
        guard.mark_prewarmed()
    return summary


# ---------------------------------------------------------------------------
# train-side prewarm (MAMLSystem)
# ---------------------------------------------------------------------------


def prewarm_train(
    system,
    state,
    batch_sharding=None,
    chunk_sharding=None,
    max_workers: int = 4,
    compile_timeout_s: float = 3600.0,
    on_program: Optional[Callable[[str], None]] = None,
    store: Optional[ExecutableStore] = None,
) -> Dict[str, Any]:
    """AOT-compile the ENTIRE planned train family — exactly
    ``train_planned_programs(cfg)``, the same registry the strict
    ``RecompileGuard`` enforces, so plan and guard cannot drift: every
    ``(train|train_multi, second_order, msl)`` variant plus ``eval`` and
    ``eval_multi``. ``state`` supplies the TrainState specs (as placed —
    shardings ride along); batch specs come from the config's episode
    shape. Attaches a collector-only compile ledger when the system has
    none (the warm executables live in ``LedgerWrapped``'s per-signature
    cache, which is also how the first real dispatch finds them)."""
    from ..observability.compile_ledger import CompileLedger, program_name
    from ..utils.strictmode import train_planned_programs

    cfg = system.cfg
    if system.compile_ledger is None:
        system.attach_compile_ledger(CompileLedger())
    plan = train_planned_programs(cfg)
    state_spec = shape_specs(state)
    batch = train_batch_spec(cfg, batch_sharding)
    k = max(1, cfg.train_steps_per_dispatch)
    chunk = train_batch_spec(cfg, chunk_sharding, leading=(k,))
    n_eval = max(cfg.num_evaluation_tasks // (cfg.batch_size * cfg.samples_per_iter), 1)
    eval_stack = train_batch_spec(cfg, chunk_sharding, leading=(n_eval,))
    jobs: List[Tuple[str, Callable, Sequence[Any]]] = []
    # deterministic job order (plan is a set — sorted, or the pool order
    # and the manifest would wander run to run). Kinds carry the system's
    # strategy as an @-suffix for non-default strategies (config.kind_base
    # strips it — the program identity keeps the suffix, the dispatch here
    # only cares about the base).
    from ..config import kind_base

    for key in sorted(plan, key=repr):
        kind = kind_base(key[0])
        if kind == "train":
            fn, args = system._compiled_train_step(key[1], key[2]), (state_spec, batch)
        elif kind == "train_multi":
            fn, args = system._compiled_train_multi(key[1], key[2]), (state_spec, chunk)
        elif kind == "eval":
            fn, args = system._eval_step, (state_spec, batch)
        elif kind == "eval_multi":
            fn, args = system._compiled_eval_multi(), (state_spec, eval_stack)
        else:  # a future planned kind: skip loudly in the summary
            continue
        jobs.append((program_name(key), fn, args))
    return _run_warm_pool(
        jobs,
        system.compile_ledger,
        system.recompile_guard,
        max_workers,
        compile_timeout_s,
        on_program,
        store=store,
    )


# ---------------------------------------------------------------------------
# serving-side prewarm (AdaptationEngine)
# ---------------------------------------------------------------------------


def prewarm_serving(
    engine,
    max_workers: int = 4,
    compile_timeout_s: float = 3600.0,
    image_shape: Optional[Tuple[int, int, int]] = None,
    on_program: Optional[Callable[[str], None]] = None,
    store: Optional[ExecutableStore] = None,
) -> Dict[str, Any]:
    """AOT-compile the full serving grid — exactly
    ``serving_planned_programs(engine.serving)``: (adapt|predict) x shape
    bucket x task-batch bucket, the same set the strict guard pins. This is
    THE warm path a fresh replica runs before accepting work (and what
    ``scripts/loadgen.py`` runs before its measurement clock starts —
    previously a hand-rolled duplicate of this grid)."""
    from ..config import kind_base, kind_strategy
    from ..observability.compile_ledger import CompileLedger
    from ..utils.strictmode import serving_planned_programs

    if engine.compile_ledger is None:
        engine.compile_ledger = CompileLedger()
    h, w, c = image_shape or engine.cfg.image_shape
    params = engine.state.params
    plan = serving_planned_programs(engine.serving)
    fw_specs: Dict[Any, Any] = {}
    # tenant mode (serving/tenancy.py): the engine's programs take the
    # master state as their first argument — the prewarm specs gain it, and
    # the ONE compiled executable per (kind, bucket, batch) then serves
    # every tenant (a cold tenant costs a page-in, never a compile)
    state_specs = (
        (shape_specs(engine.state),)
        if getattr(engine, "pager", None) is not None
        else ()
    )
    jobs: List[Tuple[str, Callable, Sequence[Any]]] = []
    for key in sorted(plan, key=repr):
        kind, bucket, b = key
        # the kind carries the strategy ("adapt@protonet") — the whole
        # configured strategy menu prewarms through the same grid walk
        base, strategy = kind_base(kind), kind_strategy(kind)
        tag = getattr(engine, "ledger_tag", "")
        if base == "adapt":
            fn = engine._compiled_adapt(bucket, b, strategy=strategy)
            args = state_specs + (
                _sds((b, bucket, h, w, c), np.float32),
                _sds((b, bucket), np.int32),
                _sds((b, bucket), np.float32),
            )
            # the engine's ledger tag ("@r1" on fleet clones) keeps every
            # replica's rows distinct in merged prewarm/ledger tables
            name = f"serve_{kind}{tag}/{bucket}/{b}"
        elif base == "refine":
            # session refinement (engine._compiled_refine; planned only
            # under serving.refine_enabled): adapt's support specs PLUS the
            # stacked per-item fast weights the rollout starts from — never
            # protonet-shaped, protonet refreshes reuse the adapt program
            fn = engine._compiled_refine(bucket, b, strategy=strategy)
            spec_key = ("params", b)
            if spec_key not in fw_specs:
                fw_specs[spec_key] = shape_specs(params, leading=(b,))
            args = state_specs + (
                fw_specs[spec_key],
                _sds((b, bucket, h, w, c), np.float32),
                _sds((b, bucket), np.int32),
                _sds((b, bucket), np.float32),
            )
            name = f"serve_{kind}{tag}/{bucket}/{b}"
        else:  # predict: per-item fast weights stacked on the task axis
            fn = engine._compiled_predict(bucket, b, strategy=strategy)
            # the per-item fast-weight tree is strategy-shaped: a prototype
            # table for protonet, the full parameter tree otherwise
            spec_key = ("protonet" if strategy == "protonet" else "params", b)
            if spec_key not in fw_specs:
                if strategy == "protonet":
                    from ..core.strategies import protonet_prototype_shape

                    fw_specs[spec_key] = {
                        "prototypes": _sds(
                            (b,) + protonet_prototype_shape(engine.num_classes),
                            np.float32,
                        )
                    }
                else:
                    fw_specs[spec_key] = shape_specs(params, leading=(b,))
            args = state_specs + (
                fw_specs[spec_key],
                _sds((b, bucket, h, w, c), np.float32),
                _sds((b, bucket), np.float32),
            )
            name = f"serve_{kind}{tag}/{bucket}/{b}"
        jobs.append((name, fn, args))
    return _run_warm_pool(
        jobs,
        engine.compile_ledger,
        engine.recompile_guard,
        max_workers,
        compile_timeout_s,
        on_program,
        store=store,
    )


def prewarm_pool(pool, **kwargs) -> Dict[str, Any]:
    """Per-replica warm gating for a serving fleet
    (``serving/pool.py::EnginePool``): every DISTINCT engine behind the
    pool is warmed through its own :meth:`AdaptationEngine.prewarm` —
    manifest-gated executable-store loads and all — exactly once;
    same-device replicas sharing an engine share its warm set for free.
    Returns the single-engine summary schema (totals summed, seconds are
    the wall cost actually paid) plus a per-replica table mapping each
    replica to the warm verdict of the engine it serves through."""
    engines = pool.engines()
    summaries: List[Dict[str, Any]] = []
    for engine in engines:
        summaries.append(engine.prewarm(**kwargs))
    merged: Dict[str, Any] = {
        "programs": sum(s.get("programs", 0) for s in summaries),
        "seconds": round(sum(s.get("seconds", 0.0) for s in summaries), 3),
        "cache_hits": sum(s.get("cache_hits", 0) for s in summaries),
        "store_hits": sum(s.get("store_hits", 0) for s in summaries),
        "errors": sum(s.get("errors", 0) for s in summaries),
        "by_program": {
            k: v for s in summaries for k, v in s.get("by_program", {}).items()
        },
    }
    per_replica = []
    for replica in pool.replicas:
        engine_idx = next(
            i for i, e in enumerate(engines) if e is replica.engine
        )
        s = summaries[engine_idx]
        per_replica.append(
            {
                "replica": replica.index,
                "engine": engine_idx,
                "shared": sum(
                    1 for r in pool.replicas if r.engine is replica.engine
                )
                > 1,
                "programs": s.get("programs", 0),
                "seconds": s.get("seconds", 0.0),
                "errors": s.get("errors", 0),
            }
        )
    merged["replicas"] = per_replica
    return merged


# ---------------------------------------------------------------------------
# persistence: the cache wiring + the executable-store manifest
# ---------------------------------------------------------------------------


def ensure_persistent_cache(cfg=None) -> Optional[str]:
    """Make sure the persistent XLA compilation cache is on (the default-on
    wiring ``Config.aot`` promises): a no-op when an entry point already
    configured it, otherwise ``utils/compcache.py``'s standard setup with
    the config's directory. Returns the active dir."""
    active = active_cache_dir()
    if active:
        return active
    return setup_compilation_cache(getattr(cfg, "compilation_cache_dir", "") or "")


def environment_fingerprint(mesh_shape=None) -> Dict[str, Any]:
    """What the compiled executables are valid FOR: jax/jaxlib versions
    (serialization format), backend + device kind (the code XLA emitted),
    device count and mesh (the shardings baked into every program)."""
    import jax

    try:
        import jaxlib

        jaxlib_version = getattr(jaxlib, "__version__", None)
    except Exception:  # noqa: BLE001 — fingerprint must never block a run
        jaxlib_version = None
    try:
        device_kind = str(jax.devices()[0].device_kind)
        n_devices = len(jax.devices())
    except Exception:  # noqa: BLE001
        device_kind, n_devices = None, None
    return {
        "jax": getattr(jax, "__version__", None),
        "jaxlib": jaxlib_version,
        "backend": jax.default_backend(),
        "device_kind": device_kind,
        "n_devices": n_devices,
        "mesh": list(mesh_shape) if mesh_shape is not None else None,
    }


def cache_state(cache_dir: Optional[str] = None) -> Dict[str, Any]:
    """Entry count + listing digest of the persistent cache dir — the
    manifest's proof that the XLA artifacts it promises actually exist."""
    d = cache_dir or active_cache_dir()
    try:
        names = sorted(os.listdir(d)) if d else []
    except OSError:
        names = []
    return {
        "dir": d,
        "entries": len(names),
        "digest": hashlib.sha256("\n".join(names).encode()).hexdigest()
        if names
        else None,
    }


def build_manifest(
    train_summary: Optional[Dict[str, Any]] = None,
    serving_summary: Optional[Dict[str, Any]] = None,
    mesh_shape=None,
    store: Optional[ExecutableStore] = None,
) -> Dict[str, Any]:
    """The executable-store manifest: program key -> signature digest +
    compile seconds + cache/store verdicts, under the environment
    fingerprint, the cache dir's state, and the store's counters. Written
    alongside checkpoints
    (``experiment/checkpoint.py::save_prewarm_manifest``)."""
    programs: Dict[str, Any] = {}
    for summary in (train_summary, serving_summary):
        if summary:
            programs.update(summary.get("by_program", {}))
    manifest = {
        "version": MANIFEST_VERSION,
        "ts": time.time(),
        "fingerprint": environment_fingerprint(mesh_shape),
        "cache": cache_state(),
        "programs": programs,
    }
    if store is not None:
        manifest["store"] = store.stats()
    return manifest


#: the environment-only subset: what a SINGLE-DEVICE consumer (the serving
#: grid — its programs never bake a mesh) must match. A replica spawned
#: with fewer visible devices than the training host can still load the
#: serving executables it stored, so its warm check skips n_devices/mesh.
ENVIRONMENT_FIELDS = ("jax", "jaxlib", "backend", "device_kind")


def verify_manifest(
    manifest: Optional[Dict[str, Any]],
    mesh_shape=None,
    fields: Tuple[str, ...] = _FINGERPRINT_FIELDS,
) -> Tuple[bool, Optional[str]]:
    """Will a prewarm against THIS process hit warm? ``(True, None)`` when
    the manifest's fingerprint matches the live environment and its cache
    entries are still present; ``(False, reason)`` otherwise — the caller
    proceeds with a cold compile and logs the reason instead of trusting
    stale artifacts. ``fields`` narrows the fingerprint comparison (e.g.
    :data:`ENVIRONMENT_FIELDS` for single-device serving programs, whose
    validity doesn't depend on the training host's device count or mesh).
    Never raises."""
    if not manifest:
        return False, "no prewarm manifest"
    if manifest.get("version") != MANIFEST_VERSION:
        return False, f"unknown manifest version {manifest.get('version')!r}"
    then = manifest.get("fingerprint") or {}
    now = environment_fingerprint(mesh_shape)
    for name in fields:
        if name == "mesh" and mesh_shape is None:
            continue  # caller doesn't know its mesh yet: don't guess
        if then.get(name) != now.get(name):
            return False, (
                f"fingerprint mismatch: {name} manifest={then.get(name)!r} "
                f"!= current={now.get(name)!r}"
            )
    cache = manifest.get("cache") or {}
    if not cache.get("entries"):
        return False, "manifest records no persistent-cache entries"
    entries_now = cache_entry_count(cache.get("dir"))
    if entries_now is None:
        return False, f"persistent cache dir {cache.get('dir')!r} is gone"
    if entries_now < int(cache["entries"]):
        return False, (
            f"persistent cache at {cache.get('dir')} shrank "
            f"({entries_now} < {cache['entries']} entries)"
        )
    return True, None
