#!/usr/bin/env python
"""Serving-path benchmark: adapt latency, cached-predict latency, predict
throughput through the full engine (bucketing + masking + jit).

Prints ONE JSON line, same contract as ``bench.py``: ``{"metric", "value",
"unit", "vs_baseline"}`` plus diagnostics. The headline is cached-predict
throughput — the steady-state serving number once a client's support set is
adapted and cached (the adapt-once / predict-many workload shape). There is
no reference serving implementation to baseline against (the reference repo
has no inference path at all), so ``vs_baseline`` is null.

Runnable anywhere::

    JAX_PLATFORMS=cpu python bench_serving.py            # CPU smoke numbers
    python bench_serving.py --n-way 20 --k-shot 5        # flagship episode shape

Model/episode defaults are the Omniglot 5-way 1-shot ablation shape with the
full Conv-4 backbone; ``--tiny`` shrinks the model for CI smoke runs.
"""

import argparse
import json
import os
import sys
import time

# cold-start anchor: cold_start_s in the JSON line is "process start ->
# first served request" — the serving replica's spawn tax, the number the
# AOT prewarm (compile/aot.py) exists to shrink
_PROC_T0 = time.perf_counter()

os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")

import numpy as np


def _gateway_bench(args, url: str) -> int:
    """External-process target (``--url`` / ``BENCH_GATEWAY``): drive an
    ALREADY-RUNNING gateway with the open-loop SLO staircase and emit ONE
    JSON line with per-backend outcome counts — the recipe a chip session
    uses to measure a live multi-host fleet without rebuilding it."""
    from howtotrainyourmamlpytorch_tpu.data.synthetic import synthetic_batch
    from howtotrainyourmamlpytorch_tpu.observability import slo

    img = (28, 28, 1)
    stairs = [
        float(s)
        for s in os.environ.get("BENCH_SLO_STAIRS", "4,8").split(",")
        if s.strip()
    ]
    duration = float(os.environ.get("BENCH_SLO_DURATION_S", "10"))
    schedule = slo.generate_schedule(
        0, duration, stairs,
        adapt_frac=0.25, query_sizes=(args.n_query,), query_weights=(1.0,),
    )
    if not schedule:
        print("bench_serving: empty schedule for the gateway staircase",
              file=sys.stderr)
        return 2

    def episode(seed):
        b = synthetic_batch(
            1, args.n_way, args.k_shot,
            max(args.n_query // args.n_way, 1), img, seed & 0x7FFFFFFF,
        )
        return (
            b["x_support"][0],
            b["y_support"][0],
            b["x_target"][0].reshape((-1,) + img)[: args.n_query],
        )

    frontend = slo.HttpFrontend(url)
    run = slo.run_load(
        frontend,
        schedule,
        lambda seed: episode(seed)[:2],
        lambda seed, n_q: episode(seed)[2][:n_q],
        log=lambda m: print(m, file=sys.stderr, flush=True),
    )
    report = slo.slo_report(
        schedule, run, stairs_rps=stairs, duration_s=duration, seed=0,
        slo_p99_ms=float(os.environ.get("BENCH_SLO_P99_MS", "2000")),
        max_shed_rate=0.05,
        metric_suffix=f"_gateway_{args.n_way}w{args.k_shot}s",
        platform="external",
        target=url,
        per_backend=frontend.per_backend(),
    )
    print(json.dumps(report), flush=True)
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--n-way", type=int, default=5)
    parser.add_argument("--k-shot", type=int, default=1)
    parser.add_argument("--n-query", type=int, default=15, help="query count per request")
    parser.add_argument("--adapt-reps", type=int, default=8)
    parser.add_argument("--predict-reps", type=int, default=32)
    parser.add_argument("--batch", type=int, default=8, help="micro-batch size for throughput")
    parser.add_argument("--tiny", action="store_true",
                        help="2-stage 4-filter backbone (CI smoke)")
    parser.add_argument(
        "--url", default=None,
        help="drive an already-running gateway/frontend at this base URL "
        "(BENCH_GATEWAY env is the same knob): SLO staircase only, with "
        "per-backend outcome counts in the JSON line",
    )
    args = parser.parse_args(argv)

    # BENCH_AUTOSCALE, same rc-2 contract, validated BEFORE anything heavy
    # spins up: "1" runs the real fleet-surge cycle (subprocess gateway +
    # backends + supervisor) and reports the control-plane timings in the
    # same line. "" / "0" = the recipe exactly as before.
    autoscale_knob = os.environ.get("BENCH_AUTOSCALE", "")
    if autoscale_knob not in ("", "0", "1"):
        print(
            f"bench_serving: bad BENCH_AUTOSCALE {autoscale_knob!r} "
            "(want '' / '0' / '1')",
            file=sys.stderr,
        )
        return 2
    bench_autoscale = autoscale_knob == "1"

    gateway_url = args.url or os.environ.get("BENCH_GATEWAY", "")
    if gateway_url:
        return _gateway_bench(args, gateway_url)

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        # Site hooks (e.g. a TPU-tunnel plugin) may override the platform
        # selection after capturing the env; re-assert the user's choice.
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from howtotrainyourmamlpytorch_tpu.config import Config, ServingConfig
    from howtotrainyourmamlpytorch_tpu.core import MAMLSystem
    from howtotrainyourmamlpytorch_tpu.data.synthetic import synthetic_batch
    from howtotrainyourmamlpytorch_tpu.models import build_vgg
    from howtotrainyourmamlpytorch_tpu.serving import AdaptationEngine

    img = (28, 28, 1)
    support = args.n_way * args.k_shot
    # BENCH_PRECISION knob, same contract as bench.py: "" = the recipe as
    # before (f32 here), "bf16" = the principled policy (ops/precision.py),
    # "f32"/"legacy" explicit — so the armed chip queue can A/B the serving
    # path's precision in the same session as the train bench.
    knob = os.environ.get("BENCH_PRECISION", "")
    if knob not in ("", "legacy", "f32", "bf16"):
        print(f"bench_serving: bad BENCH_PRECISION {knob!r}", file=sys.stderr)
        return 2
    # BENCH_REMAT, same contract as bench.py: "" keeps the recipe (the
    # serving default remat_inner_steps=True -> "full" on the adapt
    # programs), a policy name A/Bs the adapt rollout's remat dial.
    # Validated here like BENCH_PRECISION above: a typo'd arm exits the
    # clean rc-2 usage contract, not a mid-main Config traceback.
    from howtotrainyourmamlpytorch_tpu.config import REMAT_POLICIES

    remat_knob = os.environ.get("BENCH_REMAT", "")
    if remat_knob not in REMAT_POLICIES:
        print(
            f"bench_serving: bad BENCH_REMAT {remat_knob!r} "
            f"(valid: {sorted(p for p in REMAT_POLICIES if p)})",
            file=sys.stderr,
        )
        return 2
    # BENCH_STRATEGY, same rc-2 contract: the serving bench measures the
    # FULL menu incl. the forward-only protonet tier (core/strategies.py) —
    # one recorded JSON line per arm is the latency ladder. "" = maml++,
    # the recipe exactly as before.
    from howtotrainyourmamlpytorch_tpu.config import SERVING_STRATEGIES

    strategy_knob = os.environ.get("BENCH_STRATEGY", "")
    if strategy_knob not in ("",) + tuple(SERVING_STRATEGIES):
        print(
            f"bench_serving: bad BENCH_STRATEGY {strategy_knob!r} "
            f"(valid: {sorted(SERVING_STRATEGIES)})",
            file=sys.stderr,
        )
        return 2
    strategy = strategy_knob or "maml++"
    # BENCH_TENANTS, same rc-2 contract: N > 0 spreads the SLO staircase
    # across N synthetic tenants (perturbed checkpoints behind an
    # in-process registry), measuring the weight pager in the same line.
    # "" / 0 = the single-tenant recipe exactly as before.
    tenants_knob = os.environ.get("BENCH_TENANTS", "")
    try:
        n_tenants = int(tenants_knob) if tenants_knob else 0
    except ValueError:
        n_tenants = -1
    if n_tenants < 0:
        print(
            f"bench_serving: bad BENCH_TENANTS {tenants_knob!r} "
            "(want a non-negative integer)",
            file=sys.stderr,
        )
        return 2
    # BENCH_REFINE, same rc-2 contract: N > 0 measures N guarded in-place
    # session refinements through the frontend (refine_p50_ms + the guard's
    # rollback count land in the line). "" / 0 = the stateless recipe
    # exactly as before — refine_enabled stays off, so the planned program
    # set and prewarm grid are untouched.
    refine_knob = os.environ.get("BENCH_REFINE", "")
    try:
        refine_reps = int(refine_knob) if refine_knob else 0
    except ValueError:
        refine_reps = -1
    if refine_reps < 0:
        print(
            f"bench_serving: bad BENCH_REFINE {refine_knob!r} "
            "(want a non-negative integer: refine reps, 0 = off)",
            file=sys.stderr,
        )
        return 2
    cfg = Config(
        num_classes_per_set=args.n_way,
        num_samples_per_class=args.k_shot,
        num_target_samples=max(args.n_query // args.n_way, 1),
        compute_dtype="bfloat16" if knob == "legacy" else "float32",
        precision={"enabled": knob == "bf16"},
        remat_policy=remat_knob,
        serving=ServingConfig(
            support_buckets=[support], query_buckets=[args.n_query],
            max_batch_size=args.batch,
            # the benched strategy is the deployment's (only) configured
            # one: the prewarm grid, planned set, and default all follow
            strategies=[strategy],
            refine_enabled=bool(refine_reps),
        ),
    )
    stages, filters = (2, 4) if args.tiny else (4, 64)
    system = MAMLSystem(
        cfg, model=build_vgg(img, args.n_way, num_stages=stages, cnn_num_filters=filters)
    )
    # collector-only compile ledger (observability/compile_ledger.py): the
    # serving programs' compile tax and per-program FLOPs ride the one-line
    # JSON, so the cold-start cost a fresh replica pays is a tracked number
    from howtotrainyourmamlpytorch_tpu.observability import costs as obs_costs
    from howtotrainyourmamlpytorch_tpu.observability.compile_ledger import (
        CompileLedger,
    )

    ledger = CompileLedger()
    state = system.init_train_state()
    registry = None
    if n_tenants:
        import tempfile

        from howtotrainyourmamlpytorch_tpu.serving.registry import (
            synthetic_registry,
        )

        registry = synthetic_registry(
            [f"t{i}" for i in range(n_tenants)], state,
            tempfile.mkdtemp(prefix="bench_tenants_"),
        )
    engine = AdaptationEngine(
        system, state, compile_ledger=ledger, registry=registry
    )

    def episode(seed):
        b = synthetic_batch(1, args.n_way, args.k_shot, cfg.num_target_samples, img, seed)
        return (
            b["x_support"][0],
            b["y_support"][0],
            b["x_target"][0].reshape((-1,) + img)[: args.n_query],
        )

    # --- warm up the compiled programs (excluded from every measurement):
    # the AOT prewarm compiles the full planned (bucket x batch-bucket)
    # grid through the ledger — the same pre-clock path a fresh replica
    # runs — then one real adapt/predict round settles the first request.
    prewarm_summary = engine.prewarm()
    x_s, y_s, x_q = episode(0)
    fw = engine.adapt(x_s, y_s)
    engine.predict(fw, x_q)
    cold_start_s = round(time.perf_counter() - _PROC_T0, 3)
    engine.adapt_batch([episode(i)[:2] for i in range(args.batch)])
    engine.predict_batch([(fw, x_q)] * args.batch)

    # phase instrumentation (observability/metrics.py): data-wait = request
    # payload assembly, dispatch = the headline predict engine call (host
    # arrays back, settle inside), adapt_dispatch/settle = the async adapt
    # launch and its drain — adapt and predict land in SEPARATE histograms
    # so neither population can mask a regression in the other. Same
    # registry machinery the run telemetry uses; the one-line BENCH json
    # reports p50/p95 per phase.
    from howtotrainyourmamlpytorch_tpu.observability import MetricsRegistry

    reg = MetricsRegistry()

    # --- adapt latency (uncached: a fresh support set every rep) ---
    adapt_ms = []
    weights = []
    for i in range(args.adapt_reps):
        with reg.timer("phase.data_wait"):
            x_s, y_s, _ = episode(100 + i)
        t0 = time.perf_counter()
        with reg.timer("phase.adapt_dispatch"):
            w = engine.adapt(x_s, y_s)
        with reg.timer("phase.settle"):
            jax.block_until_ready(w)
        adapt_ms.append((time.perf_counter() - t0) * 1e3)
        weights.append(w)

    # --- cached-predict latency (weights already adapted: predict only) ---
    predict_ms = []
    for i in range(args.predict_reps):
        with reg.timer("phase.data_wait"):
            _, _, x_q = episode(200 + i)
        t0 = time.perf_counter()
        with reg.timer("phase.dispatch"):
            engine.predict(weights[i % len(weights)], x_q)
        predict_ms.append((time.perf_counter() - t0) * 1e3)

    # --- predict throughput at the micro-batch size ---
    items = [(weights[i % len(weights)], episode(300 + i)[2]) for i in range(args.batch)]
    reps = max(args.predict_reps // args.batch, 1)
    t0 = time.perf_counter()
    for _ in range(reps):
        engine.predict_batch(items)
    elapsed = time.perf_counter() - t0
    queries_per_sec = reps * args.batch * args.n_query / elapsed

    result = {
        "metric": f"serving_cached_predict_queries_per_sec_{args.n_way}w{args.k_shot}s_b{args.batch}",
        "value": round(queries_per_sec, 2),
        "unit": "queries/sec",
        "vs_baseline": None,  # reference has no serving path to compare against
        "platform": jax.default_backend(),
        "adapt_p50_ms": round(float(np.percentile(adapt_ms, 50)), 3),
        "adapt_p95_ms": round(float(np.percentile(adapt_ms, 95)), 3),
        "cached_predict_p50_ms": round(float(np.percentile(predict_ms, 50)), 3),
        "cached_predict_p95_ms": round(float(np.percentile(predict_ms, 95)), 3),
        # the per-strategy latency-ladder fields (one recorded line per
        # BENCH_STRATEGY arm): predict_p50_ms aliases the cached-predict
        # p50 under the ladder's canonical name
        "strategy": strategy,
        "predict_p50_ms": round(float(np.percentile(predict_ms, 50)), 3),
        "n_way": args.n_way,
        "k_shot": args.k_shot,
        "n_query": args.n_query,
        "micro_batch": args.batch,
        "model": f"vgg{stages}x{filters}",
        # resolved policy name ("f32" | "legacy_bf16" | "bf16_inner") — a
        # capture from a precision arm must never read as the default number
        "precision": system.precision.name,
        "compiled": engine.compile_counts(),
        "phase_breakdown": {
            name: {"p50_ms": s["p50_ms"], "p95_ms": s["p95_ms"]}
            for name, s in reg.summaries("phase.").items()
        },
    }
    # cost model + compile tax: per-query FLOPs of the headline predict
    # program (batched dispatch FLOPs over its batch x query count) and the
    # ledger totals; mfu null-with-reason off-chip like bench.py
    summary = ledger.summary()
    result["compile_tax_s"] = summary["total_s"]
    # program-memory axes (ISSUE 12), same contract as bench.py: resolved
    # remat policy + biggest program's peak/donated bytes off the ledger
    result["remat_policy"] = cfg.resolved_remat_policy
    result["peak_program_bytes"] = summary.get("peak_program_bytes")
    result["donated_bytes"] = summary.get("donated_bytes")
    # process start -> first served request, plus the prewarm breakdown —
    # the replica-spawn tax as tracked numbers
    result["cold_start_s"] = cold_start_s
    result["prewarm"] = {
        "programs": prewarm_summary["programs"],
        "seconds": prewarm_summary["seconds"],
        "cache_hits": prewarm_summary["cache_hits"],
    }
    # program keys are serve_predict[@strategy]/<query-bucket>/<task-batch>;
    # take the widest-batch priced program (the headline's dispatch shape)
    flops_per_query = None
    best_batch = 0
    for name, p in summary["by_program"].items():
        if not (name.startswith("serve_predict") and p.get("flops")):
            continue
        _, bucket, b = name.split("/")
        if int(b) > best_batch:
            best_batch = int(b)
            flops_per_query = p["flops"] / (int(b) * int(bucket))
    result["predict_flops_per_query"] = flops_per_query

    # --- fleet staircase: sustained RPS within SLO through a replicated
    # frontend (BENCH_REPLICAS engine replicas behind the affinity router,
    # observability/slo.py open-loop schedule). BENCH_SLO_DURATION_S=0
    # skips it; the fields stay in the line either way so captures join.
    n_replicas = int(os.environ.get("BENCH_REPLICAS", "1"))
    slo_duration = float(os.environ.get("BENCH_SLO_DURATION_S", "6"))
    result["sustained_rps"] = None
    from howtotrainyourmamlpytorch_tpu.observability import slo
    from howtotrainyourmamlpytorch_tpu.serving.server import ServingFrontend

    # the frontend resolves BENCH_REPLICAS=0 to the actual per-device
    # count — the JSON line must carry the real denominator of the
    # scaling headline, not the raw env value
    frontend = ServingFrontend(engine, replicas=n_replicas)
    try:
        result["replicas"] = len(frontend.pool)
        if slo_duration > 0:
            stairs = [
                float(s)
                for s in os.environ.get("BENCH_SLO_STAIRS", "4,8").split(",")
                if s.strip()
            ]
            schedule = slo.generate_schedule(
                0, slo_duration, stairs,
                adapt_frac=0.25, query_sizes=(args.n_query,), query_weights=(1.0,),
                tenants=[f"t{i}" for i in range(n_tenants)] or None,
            )
            if schedule:
                run = slo.run_load(
                    frontend,
                    schedule,
                    lambda seed: episode(seed & 0x7FFFFFFF)[:2],
                    lambda seed, n_q: episode(seed & 0x7FFFFFFF)[2][:n_q],
                    log=lambda m: print(m, file=sys.stderr, flush=True),
                )
                slo_rep = slo.slo_report(
                    schedule, run, stairs_rps=stairs, duration_s=slo_duration,
                    seed=0, slo_p99_ms=2000.0, max_shed_rate=0.05,
                )
                result["sustained_rps"] = slo_rep["value"]
                result["slo_breaker_trips"] = slo_rep["breaker_trips"]
                if "per_replica" in slo_rep:
                    result["per_replica"] = slo_rep["per_replica"]
        # multi-tenant paging story (BENCH_TENANTS arm); the fields stay in
        # the line either way so single- and multi-tenant captures join
        result["tenants"] = n_tenants
        pager_stats = frontend.pool.pager_stats()
        result["page_in_p50_ms"] = (
            pager_stats["page_in_p50_ms"] if pager_stats else None
        )
        result["tenant_evictions"] = (
            pager_stats["evictions"] if pager_stats else None
        )
        # guarded in-place refinement (BENCH_REFINE arm): adapt one session,
        # warm-refine it once (the probe carve + baseline probe score settle
        # outside the clock), then time steady-state refinements of the same
        # support set. Rollbacks ride the frontend's honest counter; a
        # quarantine exits via explicit re-adapt, timed like any rep.
        if refine_reps:
            from howtotrainyourmamlpytorch_tpu.serving.errors import (
                SessionQuarantinedError,
            )

            x_rs, y_rs, _ = episode(400)
            sid = frontend.adapt(x_rs, y_rs)["adaptation_id"]
            frontend.refine(sid, x_rs, y_rs)
            refine_ms = []
            for _ in range(refine_reps):
                t0 = time.perf_counter()
                try:
                    frontend.refine(sid, x_rs, y_rs)
                except SessionQuarantinedError:
                    sid = frontend.adapt(x_rs, y_rs)["adaptation_id"]
                refine_ms.append((time.perf_counter() - t0) * 1e3)
            result["refine_reps"] = refine_reps
            result["refine_p50_ms"] = round(float(np.percentile(refine_ms, 50)), 3)
            result["refine_p95_ms"] = round(float(np.percentile(refine_ms, 95)), 3)
            result["rollbacks"] = int(frontend.counters.get("refine_rollbacks"))
    finally:
        frontend.close()
    # --- autoscale cycle (BENCH_AUTOSCALE=1): run the REAL fleet-surge
    # drill (resilience/campaign.py — subprocess gateway + backends +
    # supervisor) and lift the control-plane numbers off the supervisor's
    # decision log: scale_up_settle_s = spawn -> /healthz past warming ->
    # gateway admission (the warm gate the supervisor pays per scale-up),
    # surge_recovery_s = supervisor engaged -> surge capacity admitted.
    # The fields stay in the line either way so captures join.
    result["scale_up_settle_s"] = None
    result["surge_recovery_s"] = None
    if bench_autoscale:
        import tempfile

        from howtotrainyourmamlpytorch_tpu.resilience import campaign

        work = tempfile.mkdtemp(prefix="bench_autoscale_")
        template = campaign.make_serving_run_dir(work, "template")
        violations = campaign._run_gateway_episode(
            campaign.Episode(kind="fleet-surge", mode="gateway",
                             subprocess=True),
            work_dir=work, template_run=template,
        )
        if violations:
            # honest line: no timings rather than timings off a broken cycle
            print(f"bench_serving: autoscale cycle violations: {violations}",
                  file=sys.stderr)
        else:
            # the episode runs in its own chaos_fleet_surge_* subdir of work
            drill_dirs = sorted(
                d for d in os.listdir(work)
                if d.startswith("chaos_fleet_surge_")
            )
            events = []
            with open(os.path.join(
                work, drill_dirs[-1], "supervisor_events.jsonl"
            )) as f:
                for line in f:
                    try:
                        events.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue
            start = next(
                e for e in events if e.get("event") == "supervisor_start"
            )
            up = next(e for e in events if e.get("event") == "scale_up")
            result["scale_up_settle_s"] = up.get("settle_s")
            result["surge_recovery_s"] = round(up["ts"] - start["ts"], 2)
    device_kind = str(jax.devices()[0].device_kind)
    mfu_value, mfu_reason = obs_costs.mfu(
        flops_per_query, queries_per_sec, device_kind
    )
    if mfu_reason:
        print(f"bench_serving: mfu unavailable: {mfu_reason}", file=sys.stderr)
    result["mfu"] = mfu_value
    print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
