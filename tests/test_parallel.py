"""SPMD tests on the 8-device virtual CPU mesh (SURVEY.md §4 'distributed
without a cluster'): mesh construction, sharded meta-step numerical parity
with the single-device step, and the explicit shard_map psum path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from howtotrainyourmamlpytorch_tpu.config import ParallelConfig
from howtotrainyourmamlpytorch_tpu.core import MAMLSystem
from howtotrainyourmamlpytorch_tpu.data.synthetic import synthetic_batch
from howtotrainyourmamlpytorch_tpu.parallel import (
    batch_sharding,
    make_mesh,
    replicate,
    shard_batch,
)

from tests.test_maml_core import TINY_SHAPE, _as_jnp, tiny_config, tiny_linear_model


def test_virtual_devices_present():
    assert len(jax.devices()) == 8


def test_make_mesh_shapes():
    mesh = make_mesh(ParallelConfig(dp=-1, mp=1))
    assert mesh.shape == {"dp": 8, "mp": 1}
    mesh2 = make_mesh(ParallelConfig(dp=4, mp=2))
    assert mesh2.shape == {"dp": 4, "mp": 2}
    with pytest.raises(ValueError):
        make_mesh(ParallelConfig(dp=16, mp=1))


def test_sharded_train_step_matches_single_device():
    """The whole point of the pjit design: sharding the meta-batch over dp must
    not change the numbers (XLA inserts the psum mean of meta-grads)."""
    cfg = tiny_config(batch_size=8)
    system = MAMLSystem(cfg, model=tiny_linear_model())
    batch = _as_jnp(synthetic_batch(8, 3, 2, 2, TINY_SHAPE, seed=5))

    state_a = system.init_train_state()
    state_a, out_a = system.train_step(state_a, batch)

    mesh = make_mesh(ParallelConfig(dp=8))
    state_b = replicate(system.init_train_state(), mesh)
    sharded = shard_batch(batch, mesh)
    assert sharded["x_support"].sharding.spec == P("dp")
    state_b, out_b = system.train_step(state_b, sharded)

    np.testing.assert_allclose(float(out_a.loss), float(out_b.loss), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(state_a.params["w"]), np.asarray(state_b.params["w"]), rtol=1e-5, atol=1e-6
    )


@pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="needs jax.shard_map with VMA typing (newer jax); the installed "
    "jax only has experimental shard_map, whose rep-checker cannot infer the "
    "replication of a grad-of-replicated-arg output (auto-psum untestable)",
)
def test_explicit_shard_map_psum_meta_grad():
    """Unit test of the meta-grad collective (SURVEY.md §4). Under JAX's VMA
    typing, ``jax.grad`` w.r.t. a *replicated* arg inside ``shard_map``
    already inserts the cross-device psum (the transpose of the
    replicated->varying broadcast), so the per-shard loss is scaled by 1/dp to
    make that psum compute the global-batch *mean* gradient."""
    mesh = make_mesh(ParallelConfig(dp=8))
    dp = mesh.shape["dp"]
    xs = jnp.arange(8 * 4, dtype=jnp.float32).reshape(8, 4) / 10.0
    w = jnp.ones((4,))

    def loss(w, x):
        return jnp.mean((x @ w) ** 2)

    def per_shard(w, x):
        return jax.grad(lambda w: loss(w, x) / dp)(w)

    g_sharded = jax.jit(
        jax.shard_map(
            per_shard,
            mesh=mesh,
            in_specs=(P(), P("dp")),
            out_specs=P(),
        )
    )(w, xs)
    g_global = jax.grad(loss)(w, xs)
    np.testing.assert_allclose(np.asarray(g_sharded), np.asarray(g_global), rtol=1e-5)


def test_pp_hook_rejects_multi_stage():
    """SURVEY §2.11 PP row: the stage-partition hook exists in the mesh
    config and any pp != 1 is rejected with the documented non-goal."""
    import pytest
    from howtotrainyourmamlpytorch_tpu.config import ParallelConfig

    assert ParallelConfig().pp == 1
    with pytest.raises(ValueError, match="pipeline parallelism"):
        ParallelConfig(pp=2)


def test_tp_convs_sharded_meta_grads_match_single_device():
    """Conv tensor parallelism (parallel.tp_convs): with the patches-GEMM
    conv implementation, conv kernels shard output-channel-parallel over
    ``mp`` — the layout GSPMD's convolution handler rejects on the native
    conv path (parallel/mesh.py::_param_spec) — and the full second-order
    META-GRADIENT matches the single-device one.

    Two deliberate choices keep this a numerics test rather than a chaos
    test: (1) the backbone uses strided convs instead of max-pooling — a
    pooling argmax near a tie can flip under the reorder noise that sharded
    channel contractions legitimately introduce (~1e-7), discretely
    rerouting gradients; (2) we compare meta-grads, not post-Adam params —
    Adam's first step is ~sign(g)*lr, which amplifies reorder noise on
    noise-dominated entries of g into O(lr) param deltas. (Measured: a 1e-7
    param perturbation moves this program family's 2-inner-step loss by up
    to 1e-2 with pooling, while the sharded-vs-single meta-grad on the
    smooth variant agrees to ~1e-6.)"""
    import dataclasses

    from howtotrainyourmamlpytorch_tpu.models import build_vgg
    from howtotrainyourmamlpytorch_tpu.parallel import (
        shard_train_state,
        train_state_shardings,
    )

    n_way, k, t = 4, 2, 2
    cfg = dataclasses.replace(
        tiny_config(batch_size=4, num_classes_per_set=n_way),
        parallel=ParallelConfig(dp=4, mp=2, tp_convs=True),
    )
    assert cfg.conv_via_patches  # auto-enabled by tp_convs
    model = build_vgg(
        TINY_SHAPE, n_way, num_stages=2, cnn_num_filters=8, max_pooling=False,
        conv_via_patches=True,
    )
    system = MAMLSystem(cfg, model=model)
    batch = _as_jnp(synthetic_batch(4, n_way, k, t, TINY_SHAPE, seed=7))
    state = system.init_train_state()

    mesh = make_mesh(cfg.parallel)
    shardings = train_state_shardings(state, mesh, tp_convs=True)
    # conv kernels genuinely carry the mp axis now (HWIO output channels)
    assert shardings.params["stage_0"]["conv"]["w"].spec == P(None, None, None, "mp")
    assert shardings.params["fc"]["w"].spec == P(None, "mp")

    def meta_grads(st, b):
        trainables = {"params": st.params, "hparams": st.inner_hparams}

        def objective(tr):
            loss, _ = system._meta_objective(
                tr, st.bn_state, st.opt_state, b, 0, True,
                cfg.number_of_training_steps_per_iter, True,
            )
            return loss

        return jax.grad(objective)(trainables)

    g_single = jax.jit(meta_grads)(state, batch)

    state_sh = shard_train_state(state, mesh, tp_convs=True)
    # the sharded kernel is distributed, not just spec-tagged: each shard
    # holds 1/mp of the output channels
    shard = state_sh.params["stage_0"]["conv"]["w"].addressable_shards[0]
    assert shard.data.shape[3] == 8 // 2
    g_sharded = jax.jit(meta_grads)(state_sh, shard_batch(batch, mesh))

    flat_a = [np.asarray(x) for x in jax.tree.leaves(g_single)]
    flat_b = [np.asarray(x) for x in jax.tree.leaves(g_sharded)]
    # Reorder noise from the sharded channel contractions is absolute at the
    # scale of the LARGEST gradient entries flowing through the same sums,
    # so near-zero leaves are compared with an atol tied to the global grad
    # magnitude, not their own (their own would demand agreement below the
    # noise floor of the arithmetic itself).
    g_scale = max(float(np.max(np.abs(a))) for a in flat_a)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-5 * g_scale)

    # and the sharded train step itself executes with conv TP end-to-end
    state_sh2, out = system.train_step(state_sh, shard_batch(batch, mesh))
    assert np.isfinite(float(out.loss))
    assert state_sh2.params["stage_0"]["conv"]["w"].sharding.spec == P(
        None, None, None, "mp"
    )


def test_param_spec_shards_only_kernels_named_w():
    """ADVICE r5 #1: BOTH tensor-parallel branches key off the layer-zoo
    kernel name 'w', not shape alone — a future 2-D (or 4-D) non-kernel
    parameter whose trailing axis happens to divide mp must stay
    replicated."""
    from howtotrainyourmamlpytorch_tpu.parallel.mesh import _param_spec

    mp = 2
    # dense kernel: column-parallel
    assert _param_spec((16, 8), mp, leaf_name="w") == P(None, "mp")
    # 2-D non-kernel leaf with a divisible trailing axis: replicated
    assert _param_spec((16, 8), mp, leaf_name="embedding") == P()
    assert _param_spec((16, 8), mp, leaf_name=None) == P()
    # conv kernel: mp-sharded only under tp_convs, and only when named 'w'
    assert _param_spec((3, 3, 4, 8), mp, tp_convs=True, leaf_name="w") == P(
        None, None, None, "mp"
    )
    assert _param_spec((3, 3, 4, 8), mp, tp_convs=True, leaf_name="table") == P()
    # non-divisible axes always replicate
    assert _param_spec((16, 7), mp, leaf_name="w") == P()


def test_sharded_convergence_matches_single_device():
    """Multi-chip evidence upgraded from one-step parity to LEARNING
    (VERDICT r5 next-round #4): a short multi-epoch dp x mp + tp_convs run on
    the virtual mesh must (a) climb in val accuracy, (b) climb *identically*
    to the single-device run on the same episode stream, and (c) end in a
    state that matches single-device functionally.

    The inner loop is deliberately weakened (1 step, lr=0.01) so episode
    adaptation alone cannot solve the task — the accuracy climb is then
    attributable to the outer (meta) updates, which is exactly the path the
    dp-psum + mp tensor-parallel collectives sit on. Final-state comparison:
    val logits to f32 tolerance; raw params to a looser bound, since ~10
    Adam steps amplify sharded-contraction reorder noise on noise-dominated
    gradient entries into O(lr) param deltas that provably (see the logit
    check) don't change the learned function (same rationale as
    test_tp_convs_sharded_meta_grads_match_single_device)."""
    import dataclasses

    from howtotrainyourmamlpytorch_tpu.config import InnerOptimConfig
    from howtotrainyourmamlpytorch_tpu.data.synthetic import learnable_synthetic_batch
    from howtotrainyourmamlpytorch_tpu.models import build_vgg
    from howtotrainyourmamlpytorch_tpu.parallel import shard_train_state

    n_way, k, t = 4, 2, 2
    epochs, iters = 4, 4
    cfg = dataclasses.replace(
        tiny_config(
            batch_size=4,
            num_classes_per_set=n_way,
            number_of_training_steps_per_iter=1,
            number_of_evaluation_steps_per_iter=1,
            meta_learning_rate=0.003,
        ),
        inner_optim=InnerOptimConfig(kind="sgd", lr=0.01),
        parallel=ParallelConfig(dp=4, mp=2, tp_convs=True),
    )
    model = build_vgg(
        TINY_SHAPE, n_way, num_stages=2, cnn_num_filters=8, max_pooling=False,
        conv_via_patches=True,
    )
    system = MAMLSystem(cfg, model=model)
    mesh = make_mesh(cfg.parallel)
    val = _as_jnp(learnable_synthetic_batch(4, n_way, k, t, TINY_SHAPE, seed=100))

    def run(sharded: bool):
        state = system.init_train_state()
        if sharded:
            state = shard_train_state(state, mesh, tp_convs=True)
        vb = shard_batch(val, mesh) if sharded else val

        def val_acc(st):
            return float(np.mean(np.asarray(system.eval_step(st, vb).per_task_accuracies)))

        accs, step = [val_acc(state)], 0
        for epoch in range(epochs):
            for _ in range(iters):
                # the SAME deterministic stream for both arms
                batch = _as_jnp(
                    learnable_synthetic_batch(4, n_way, k, t, TINY_SHAPE, seed=step)
                )
                if sharded:
                    batch = shard_batch(batch, mesh)
                state, _ = system.train_step(state, batch, epoch=epoch)
                step += 1
            accs.append(val_acc(state))
        logits = np.asarray(system.eval_step(state, vb).per_task_target_logits)
        return state, accs, logits

    state_single, accs_single, logits_single = run(False)
    state_sharded, accs_sharded, logits_sharded = run(True)

    # (a) learning happened: val accuracy climbs well clear of the start
    assert accs_sharded[-1] >= accs_sharded[0] + 0.25, accs_sharded
    # (b) the sharded arm learns in lockstep with the single-device arm
    np.testing.assert_allclose(accs_sharded, accs_single, atol=0.05)
    # (c) final state matches: functionally to f32 tolerance...
    np.testing.assert_allclose(logits_sharded, logits_single, atol=1e-4)
    # ...and parameter-wise within the Adam-amplified reorder-noise bound
    p_scale = max(
        float(np.max(np.abs(np.asarray(x)))) for x in jax.tree.leaves(state_single.params)
    )
    for a, b in zip(
        jax.tree.leaves(state_single.params), jax.tree.leaves(state_sharded.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1.0, atol=5e-2 * p_scale
        )
    # the sharded arm really trained tensor-parallel all along
    assert state_sharded.params["stage_0"]["conv"]["w"].sharding.spec == P(
        None, None, None, "mp"
    )


def test_dp_mp_sharded_step_matches_single_device():
    """Real tensor parallelism (SURVEY §2.11 TP row): on a 4x2 dp x mp mesh
    the dense-head kernel shards column-parallel over ``mp`` (a P spec
    carrying "mp"; conv kernels stay replicated — XLA SPMD limits documented
    in parallel/mesh.py::_param_spec), and one full second-order train step
    reproduces the single-device numbers bit-closely."""
    from howtotrainyourmamlpytorch_tpu.models import build_vgg
    from howtotrainyourmamlpytorch_tpu.parallel import (
        shard_train_state,
        train_state_shardings,
    )

    n_way, k, t = 4, 2, 2
    # patches-GEMM convs: GSPMD's convolution handler CHECK-crashes on the
    # dp-sharded batch-grouped convs of this program family on this jaxlib
    # (see tests/test_runner.py::runner_config)
    cfg = tiny_config(batch_size=4, num_classes_per_set=n_way, conv_via_patches=True)
    model = build_vgg(TINY_SHAPE, n_way, num_stages=2, cnn_num_filters=8, conv_via_patches=True)
    system = MAMLSystem(cfg, model=model)
    batch = _as_jnp(synthetic_batch(4, n_way, k, t, TINY_SHAPE, seed=7))

    state_a = system.init_train_state()
    state_a, out_a = system.train_step(state_a, batch)

    mesh = make_mesh(ParallelConfig(dp=4, mp=2))
    shardings = train_state_shardings(system.init_train_state(), mesh)
    # the specs actually carry the mp axis where promised
    assert shardings.params["fc"]["w"].spec == P(None, "mp")
    assert shardings.params["stage_0"]["conv"]["w"].spec == P()
    assert shardings.params["fc"]["b"].spec == P()
    # and the optimizer moments mirror the param shardings
    mp_sharded = [
        s for s in jax.tree.leaves(
            shardings.opt_state, is_leaf=lambda x: hasattr(x, "spec")
        )
        if getattr(s, "spec", None) == P(None, "mp")
    ]
    assert len(mp_sharded) >= 2  # fc kernel in both mu and nu
    state_b = shard_train_state(system.init_train_state(), mesh)
    state_b, out_b = system.train_step(state_b, shard_batch(batch, mesh))

    np.testing.assert_allclose(float(out_a.loss), float(out_b.loss), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(state_a.params["stage_0"]["conv"]["w"]),
        np.asarray(state_b.params["stage_0"]["conv"]["w"]),
        rtol=1e-5, atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(state_a.params["fc"]["w"]),
        np.asarray(state_b.params["fc"]["w"]),
        rtol=1e-5, atol=1e-6,
    )
