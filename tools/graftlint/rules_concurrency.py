"""GL2xx — concurrency rules.

GL201  read-modify-write of shared state outside a lock in a threaded class
GL202  untimed blocking waits (``Future.result()`` / ``Queue.get()``)
GL210  lock acquisition order inverts the declared hierarchy
GL211  field written under a lock in one method, stored bare in another
GL212  blocking call made while holding a lock
GL213  import-light module transitively imports a heavy root

GL210–GL213 are the static half of the graftsan lock-discipline sanitizer
(``tools/graftsan``). GL210 reads the canonical acquisition hierarchy from
``tools/graftsan/order.toml`` (registry → pager → cache → batcher →
breaker) plus per-module ``# graftsan: order=a<b`` facts, and walks nested
``with`` acquisitions across the intra-class call graph (``self.m()``
transitively, plus one cross-class hop through tier-named attributes like
``self._pager``): acquiring an *earlier* tier while holding a *later* one
is the static shadow of the ABBA deadlock the armed runtime reports as a
``lock_order_cycle``. GL211 generalizes GL201 past single-method scope —
a field the class guards in one method but plainly rebinds in another is
either a missing guard or a misleading one (GL211 takes the plain-``Assign``
shapes; GL201 keeps the read-modify-writes). GL212 is the static twin of
the runtime held-across-blocking check: ``.result()``, queue ``.get()``,
``urlopen``, socket ops, ``time.sleep`` and engine ``dispatch`` inside a
``with <lock>:`` region stall every other thread behind the lock. GL213
replaces the three duplicated subprocess import-probe tests: modules carrying
the ``import-light`` marker comment must not reach ``jax``/the package root
through their *transitive* module-level import closure (imports inside
``try/except ImportError`` are optional by contract; function-local imports
are lazy and exempt).

A class is "threaded" when the linter can see concurrency in it: it starts a
``threading.Thread``/``Timer``, owns a ``ThreadPoolExecutor``, owns a lock
(``Lock``/``RLock``/``Condition``/``Semaphore`` assigned to ``self.*`` — the
author already declared the instance concurrent), or carries an explicit
``# graftlint: threaded`` marker on its ``class`` line.

GL201 deliberately flags only read-modify-write shapes — ``self.x += 1`` and
``self.d[k] = v`` — not plain rebinds (``self.x = v``), which are single
GIL-atomic stores. Lost-update counters were exactly the PR2 review bug class
(``FaultInjector`` call counters raced by loader-pool / batcher / HTTP
threads). Methods named ``*_locked`` (or marked ``# graftlint: holds-lock``)
are assumed to run under their caller's lock.

GL202 flags ``.result()`` with no timeout anywhere, and ``.get()`` with no
timeout on receivers the module visibly binds to ``queue.Queue``-family
constructors. A hung device call parks an untimed waiter forever — the
BENCH_r03–r05 wedge signature; every documented exception needs a
justification naming its supervisor.
"""

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .engine import Finding, Module, Project, Rule, call_name, dotted_name, register

LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
THREAD_CTORS = {"Thread", "Timer"}
EXECUTOR_CTORS = {"ThreadPoolExecutor", "ProcessPoolExecutor"}
QUEUE_CTORS = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue", "JoinableQueue"}
#: attribute names accepted as lock-like in a `with self.<attr>:` guard even
#: when their construction wasn't seen (subclasses, injected locks)
LOCKY_FRAGMENTS = ("lock", "cond", "wake", "mutex", "sem")


def _ctor_last(call: ast.Call) -> str:
    name = call_name(call) or ""
    return name.split(".")[-1]


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _ClassInfo:
    def __init__(self, module: Module, cls: ast.ClassDef):
        self.cls = cls
        self.lock_attrs: Set[str] = set()
        self.threaded = module.has_marker("threaded", cls.lineno)
        for node in ast.walk(cls):
            if isinstance(node, ast.Call):
                last = _ctor_last(node)
                if last in THREAD_CTORS or last in EXECUTOR_CTORS:
                    self.threaded = True
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                last = _ctor_last(node.value)
                for target in node.targets:
                    attr = _self_attr(target)
                    if attr and last in LOCK_CTORS:
                        self.lock_attrs.add(attr)
                        self.threaded = True

    def is_lock_guard(self, expr: ast.AST) -> bool:
        attr = _self_attr(expr)
        if attr is None:
            return False
        return attr in self.lock_attrs or any(
            frag in attr.lower() for frag in LOCKY_FRAGMENTS
        )


@register
class UnguardedSharedWrite(Rule):
    id = "GL201"
    title = "shared-state read-modify-write outside a lock"

    def check_module(self, module: Module, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        for cls in [
            n for n in ast.walk(module.tree) if isinstance(n, ast.ClassDef)
        ]:
            info = _ClassInfo(module, cls)
            if not info.threaded:
                continue
            for method in cls.body:
                if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if (
                    method.name in ("__init__", "__new__", "__del__")
                    or method.name.endswith("_locked")
                    or module.has_marker("holds-lock", method.lineno)
                ):
                    continue
                findings.extend(self._walk(module, cls.name, info, method.body, False))
        return findings

    def _walk(
        self,
        module: Module,
        cls_name: str,
        info: _ClassInfo,
        stmts: List[ast.stmt],
        guarded: bool,
    ) -> Iterable[Finding]:
        out: List[Finding] = []
        for stmt in stmts:
            if isinstance(stmt, ast.With):
                now_guarded = guarded or any(
                    info.is_lock_guard(item.context_expr) for item in stmt.items
                )
                out.extend(self._walk(module, cls_name, info, stmt.body, now_guarded))
                continue
            if not guarded:
                out.extend(self._check_stmt(module, cls_name, stmt))
            # nested blocks inherit the current guard state
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, attr, None)
                if sub and not isinstance(stmt, ast.With):
                    out.extend(self._walk(module, cls_name, info, sub, guarded))
            for handler in getattr(stmt, "handlers", []) or []:
                out.extend(self._walk(module, cls_name, info, handler.body, guarded))
        return out

    def _check_stmt(self, module, cls_name, stmt) -> Iterable[Finding]:
        shapes = []
        if isinstance(stmt, ast.AugAssign):
            attr = _self_attr(stmt.target)
            if attr:
                shapes.append((stmt, attr, f"self.{attr} {type(stmt.op).__name__}="))
        targets = stmt.targets if isinstance(stmt, ast.Assign) else (
            [stmt.target] if isinstance(stmt, ast.AugAssign) else []
        )
        for target in targets:
            if isinstance(target, ast.Subscript):
                attr = _self_attr(target.value)
                if attr:
                    shapes.append((stmt, attr, f"self.{attr}[...] ="))
        out = []
        for node, attr, shape in shapes:
            out.append(
                Finding(
                    self.id,
                    module.rel,
                    node.lineno,
                    node.col_offset,
                    f"`{shape}` in threaded class {cls_name} outside a "
                    "`with <lock>:` block — a read-modify-write racing "
                    "another thread loses updates; guard it (or mark the "
                    "method `*_locked` if the caller holds the lock)",
                )
            )
        return out


def _queue_names(module: Module) -> Set[str]:
    """Names (locals and self attrs, flattened) visibly bound to Queue
    constructors anywhere in the module (shared by GL202 / GL212)."""
    names: Set[str] = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _ctor_last(node.value) in QUEUE_CTORS:
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        names.add(target.id)
                    else:
                        attr = _self_attr(target)
                        if attr:
                            names.add(attr)
    return names


@register
class UntimedBlockingWait(Rule):
    id = "GL202"
    title = "untimed blocking wait"

    def check_module(self, module: Module, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        queue_names = _queue_names(module)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call) or not isinstance(
                node.func, ast.Attribute
            ):
                continue
            has_timeout = bool(node.args) or any(
                kw.arg in ("timeout", "block") for kw in node.keywords
            )
            if node.func.attr == "result" and not has_timeout:
                findings.append(
                    Finding(
                        self.id,
                        module.rel,
                        node.lineno,
                        node.col_offset,
                        ".result() with no timeout waits forever on a hung "
                        "device call (the wedge signature); pass timeout= "
                        "or document the supervising watchdog in a "
                        "suppression",
                    )
                )
            elif node.func.attr == "get" and not has_timeout and not node.keywords:
                recv = node.func.value
                recv_name = (
                    recv.id
                    if isinstance(recv, ast.Name)
                    else _self_attr(recv) or ""
                )
                if recv_name in queue_names:
                    findings.append(
                        Finding(
                            self.id,
                            module.rel,
                            node.lineno,
                            node.col_offset,
                            f"`{recv_name}.get()` with no timeout blocks "
                            "forever if the producer died; pass timeout= "
                            "and handle Empty",
                        )
                    )
        return findings


# ---------------------------------------------------------------------------
# GL210 — lock-order inversion (static half of graftsan)
# ---------------------------------------------------------------------------

#: per-module order facts: `# graftsan: order=a<b` (a acquired before b)
GRAFTSAN_ORDER_RE = re.compile(
    r"#\s*graftsan:\s*order=([A-Za-z_]\w*)\s*<\s*([A-Za-z_]\w*)"
)


def _locky(attr: str) -> bool:
    return any(frag in attr.lower() for frag in LOCKY_FRAGMENTS)


def _funcs(cls: ast.ClassDef) -> Dict[str, ast.AST]:
    return {
        n.name: n
        for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


class _Hierarchy:
    """Ranks from tools/graftsan/order.toml: outermost tier = rank 0."""

    def __init__(self, data: Optional[dict]):
        self.order: List[str] = []
        self.class_rank: Dict[str, int] = {}
        self.attr_rank: Dict[str, int] = {}
        if not data:
            return
        self.order = list(data.get("order") or [])
        tiers = data.get("tiers") or {}
        for tier, spec in tiers.items():
            if tier not in self.order:
                continue
            rank = self.order.index(tier)
            for cls in spec.get("classes") or []:
                self.class_rank[cls] = rank
            for attr in spec.get("attrs") or []:
                self.attr_rank[attr] = rank

    def tier(self, rank: int) -> str:
        return self.order[rank] if 0 <= rank < len(self.order) else "?"


def _load_hierarchy(project: Project) -> _Hierarchy:
    cached = getattr(project, "_graftsan_hierarchy", None)
    if cached is not None:
        return cached
    data = None
    try:
        from ..graftsan.runtime import load_order

        data = load_order(
            os.path.join(project.repo_root, "tools", "graftsan", "order.toml")
        )
    except ImportError:  # graftsan not importable: rank checks degrade off
        data = None
    hier = _Hierarchy(data)
    project._graftsan_hierarchy = hier
    return hier


def _tier_class_index(project: Project, hier: _Hierarchy) -> Dict[str, Set[str]]:
    """Class name (tier classes only) -> method names that acquire a lock
    via `with self.<locky>:` anywhere in their body. Powers the one-hop
    cross-class check (`self._pager.evict()` while holding a later tier)."""
    cached = getattr(project, "_graftsan_tier_classes", None)
    if cached is not None:
        return cached
    index: Dict[str, Set[str]] = {}
    for mod in project.modules:
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef) or cls.name not in hier.class_rank:
                continue
            acquiring: Set[str] = set()
            for name, fn in _funcs(cls).items():
                for node in ast.walk(fn):
                    if isinstance(node, ast.With):
                        for item in node.items:
                            attr = _self_attr(item.context_expr)
                            if attr and _locky(attr):
                                acquiring.add(name)
            index[cls.name] = acquiring
    project._graftsan_tier_classes = index
    return index


class _Acq:
    """One lock acquisition, attributed to a hierarchy tier where possible."""

    __slots__ = ("rank", "labels", "line", "col", "desc")

    def __init__(self, rank, labels, line, col, desc):
        self.rank = rank
        self.labels = labels
        self.line = line
        self.col = col
        self.desc = desc


@register
class LockOrderInversion(Rule):
    id = "GL210"
    title = "lock acquisition inverts the declared hierarchy"

    def check_module(self, module: Module, project: Project) -> Iterable[Finding]:
        hier = _load_hierarchy(project)
        facts: List[Tuple[str, str]] = []
        for text in module.lines:
            m = GRAFTSAN_ORDER_RE.search(text)
            if m:
                facts.append((m.group(1), m.group(2)))
        if not hier.order and not facts:
            return ()
        tier_classes = _tier_class_index(project, hier)
        findings: List[Finding] = []
        for cls in [n for n in ast.walk(module.tree) if isinstance(n, ast.ClassDef)]:
            info = _ClassInfo(module, cls)
            analyzer = _LockOrderWalker(
                self, module, cls, info, hier, facts, tier_classes
            )
            findings.extend(analyzer.check())
        return findings


class _LockOrderWalker:
    def __init__(self, rule, module, cls, info, hier, facts, tier_classes):
        self.rule = rule
        self.module = module
        self.cls = cls
        self.info = info
        self.hier = hier
        self.facts = facts
        self.tier_classes = tier_classes
        self.methods = _funcs(cls)
        self._summaries: Dict[str, List[_Acq]] = {}
        self.findings: List[Finding] = []

    # -- attribution --------------------------------------------------------

    def _attribute(self, expr: ast.AST) -> Optional[_Acq]:
        """Map a with-item to an acquisition; None when it isn't lock-like."""
        attr = _self_attr(expr)
        if attr is not None:
            if not (attr in self.info.lock_attrs or _locky(attr)):
                return None
            labels = {attr.lstrip("_"), self.cls.name}
            rank = self.hier.class_rank.get(self.cls.name)
            if rank is not None:
                labels.add(self.hier.tier(rank))
            return _Acq(
                rank, labels, expr.lineno, expr.col_offset, f"self.{attr}"
            )
        dotted = dotted_name(expr)
        if dotted is None or not isinstance(expr, ast.Attribute):
            return None
        parts = [p for p in dotted.split(".") if p != "self"]
        if len(parts) < 2 or not _locky(parts[-1]):
            return None
        labels = {p.lstrip("_") for p in parts}
        rank = None
        for owner in parts[:-1]:
            owner_rank = self.hier.attr_rank.get(owner.lstrip("_"))
            if owner_rank is not None:
                rank = owner_rank
                labels.add(self.hier.tier(owner_rank))
                break
        return _Acq(rank, labels, expr.lineno, expr.col_offset, dotted)

    # -- inversion checks ---------------------------------------------------

    def _check_pair(self, held: _Acq, new: _Acq, line: int, col: int, via: str):
        if (
            held.rank is not None
            and new.rank is not None
            and new.rank < held.rank
        ):
            self.findings.append(
                Finding(
                    self.rule.id,
                    self.module.rel,
                    line,
                    col,
                    f"acquires {new.desc} (tier '{self.hier.tier(new.rank)}')"
                    f" while holding {held.desc} (tier "
                    f"'{self.hier.tier(held.rank)}'){via} — inverts the "
                    "canonical hierarchy in tools/graftsan/order.toml; the "
                    "moment another thread runs the canonical direction this "
                    "is an ABBA deadlock",
                )
            )
            return
        for a, b in self.facts:
            if a in new.labels and b in held.labels:
                self.findings.append(
                    Finding(
                        self.rule.id,
                        self.module.rel,
                        line,
                        col,
                        f"acquires {new.desc} while holding {held.desc}"
                        f"{via} — inverts the declared module fact "
                        f"`# graftsan: order={a}<{b}`",
                    )
                )
                return

    def _check_acq(self, held: List[_Acq], new: _Acq, line: int, col: int, via=""):
        for h in held:
            self._check_pair(h, new, line, col, via)

    # -- cross-class one-hop ------------------------------------------------

    def _cross_class(self, call: ast.Call) -> Optional[_Acq]:
        """`self.<attr>.m()` where <attr> names a hierarchy tier and some
        class of that tier visibly acquires its own lock inside `m`."""
        if not isinstance(call.func, ast.Attribute):
            return None
        owner = call.func.value
        attr = _self_attr(owner)
        if attr is None:
            return None
        rank = self.hier.attr_rank.get(attr.lstrip("_"))
        if rank is None:
            return None
        method = call.func.attr
        tier = self.hier.tier(rank)
        for cls_name, acquiring in self.tier_classes.items():
            if self.hier.class_rank.get(cls_name) == rank and method in acquiring:
                return _Acq(
                    rank,
                    {attr.lstrip("_"), tier, cls_name},
                    call.lineno,
                    call.col_offset,
                    f"self.{attr}.{method}() (acquires {cls_name}'s lock)",
                )
        return None

    # -- interprocedural summary (self.m() transitively) --------------------

    def _summary(self, name: str, stack: Set[str]) -> List[_Acq]:
        if name in self._summaries:
            return self._summaries[name]
        if name in stack or name not in self.methods:
            return []
        stack = stack | {name}
        acqs: List[_Acq] = []
        for node in ast.walk(self.methods[name]):
            if isinstance(node, ast.With):
                for item in node.items:
                    acq = self._attribute(item.context_expr)
                    if acq is not None:
                        acqs.append(acq)
            elif isinstance(node, ast.Call):
                callee = self._self_call(node)
                if callee is not None:
                    acqs.extend(self._summary(callee, stack))
                else:
                    hop = self._cross_class(node)
                    if hop is not None:
                        acqs.append(hop)
        self._summaries[name] = acqs
        return acqs

    @staticmethod
    def _self_call(call: ast.Call) -> Optional[str]:
        if (
            isinstance(call.func, ast.Attribute)
            and isinstance(call.func.value, ast.Name)
            and call.func.value.id == "self"
        ):
            return call.func.attr
        return None

    # -- the walk -----------------------------------------------------------

    def check(self) -> List[Finding]:
        for name, fn in self.methods.items():
            self._walk(fn.body, [])
        return self.findings

    def _walk(self, stmts: List[ast.stmt], held: List[_Acq]):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs run later, under their own discipline
            if isinstance(stmt, ast.With):
                pushed = 0
                for item in stmt.items:
                    acq = self._attribute(item.context_expr)
                    if acq is not None:
                        self._check_acq(held, acq, acq.line, acq.col)
                        held.append(acq)
                        pushed += 1
                self._walk(stmt.body, held)
                del held[len(held) - pushed : len(held)]
                continue
            if held:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    callee = self._self_call(node)
                    if callee is not None:
                        for acq in self._summary(callee, set()):
                            self._check_acq(
                                held,
                                acq,
                                node.lineno,
                                node.col_offset,
                                via=f" via self.{callee}()",
                            )
                    else:
                        hop = self._cross_class(node)
                        if hop is not None:
                            self._check_acq(held, hop, node.lineno, node.col_offset)
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, attr, None)
                if sub:
                    self._walk(sub, held)
            for handler in getattr(stmt, "handlers", []) or []:
                self._walk(handler.body, held)


# ---------------------------------------------------------------------------
# GL211 — guarded field stored bare in a sibling method
# ---------------------------------------------------------------------------


@register
class GuardedFieldBareWrite(Rule):
    id = "GL211"
    title = "lock-guarded field stored bare in a sibling method"

    def check_module(self, module: Module, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        for cls in [n for n in ast.walk(module.tree) if isinstance(n, ast.ClassDef)]:
            info = _ClassInfo(module, cls)
            if not info.threaded:
                continue
            guarded_by: Dict[str, Set[str]] = {}  # field -> methods guarding it
            bare: List[Tuple[str, str, ast.stmt]] = []  # (field, method, node)
            for name, fn in _funcs(cls).items():
                exempt = (
                    name in ("__init__", "__new__", "__del__")
                    or name.endswith("_locked")
                    or module.has_marker("holds-lock", fn.lineno)
                )
                self._scan(module, info, fn.body, False, name, exempt, guarded_by, bare)
            for field, method, node in bare:
                others = guarded_by.get(field, set()) - {method}
                if not others:
                    continue
                findings.append(
                    Finding(
                        self.id,
                        module.rel,
                        node.lineno,
                        node.col_offset,
                        f"`self.{field} = ...` in {cls.name}.{method} without "
                        f"the lock, but {', '.join(sorted(others))} writes it "
                        "under `with <lock>:` — either the guard is missing "
                        "here or misleading there; take the lock (or mark the "
                        "method `*_locked` if the caller holds it)",
                    )
                )
        return findings

    def _scan(self, module, info, stmts, guarded, method, exempt, guarded_by, bare):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(stmt, ast.With):
                now = guarded or any(
                    info.is_lock_guard(item.context_expr) for item in stmt.items
                )
                self._scan(module, info, stmt.body, now, method, exempt, guarded_by, bare)
                continue
            if isinstance(stmt, ast.Assign):
                for target in stmt.targets:
                    attr = _self_attr(target)
                    if attr is None or attr in info.lock_attrs:
                        continue
                    if guarded:
                        guarded_by.setdefault(attr, set()).add(method)
                    elif not exempt:
                        # exempt methods (__init__, *_locked, holds-lock)
                        # neither prove a guard nor violate one
                        bare.append((attr, method, stmt))
            elif isinstance(stmt, ast.AugAssign):
                attr = _self_attr(stmt.target)
                if attr is not None and guarded:
                    # RMW under lock marks the field guarded; the bare-RMW
                    # case is GL201's finding, not ours
                    guarded_by.setdefault(attr, set()).add(method)
            for attr_name in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, attr_name, None)
                if sub and not isinstance(stmt, ast.With):
                    self._scan(module, info, sub, guarded, method, exempt, guarded_by, bare)
            for handler in getattr(stmt, "handlers", []) or []:
                self._scan(module, info, handler.body, guarded, method, exempt, guarded_by, bare)


# ---------------------------------------------------------------------------
# GL212 — blocking call while holding a lock
# ---------------------------------------------------------------------------

#: socket-family method names that park the calling thread on the network
SOCKET_BLOCKERS = {"connect", "accept", "recv", "recv_into", "sendall"}


@register
class LockHeldAcrossBlocking(Rule):
    id = "GL212"
    title = "blocking call while holding a lock"

    def check_module(self, module: Module, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        queue_names = _queue_names(module)
        for cls in [n for n in ast.walk(module.tree) if isinstance(n, ast.ClassDef)]:
            info = _ClassInfo(module, cls)
            for name, fn in _funcs(cls).items():
                self._walk(module, cls.name, info, fn.body, False, queue_names, findings)
        return findings

    def _walk(self, module, cls_name, info, stmts, guarded, queue_names, findings):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # closures run later, usually outside the lock
            if isinstance(stmt, ast.With):
                now = guarded or any(
                    info.is_lock_guard(item.context_expr) for item in stmt.items
                )
                self._walk(module, cls_name, info, stmt.body, now, queue_names, findings)
                continue
            if guarded:
                for node in ast.walk(stmt):
                    if isinstance(node, ast.Call):
                        why = self._blocking(module, node, queue_names)
                        if why:
                            findings.append(
                                Finding(
                                    self.id,
                                    module.rel,
                                    node.lineno,
                                    node.col_offset,
                                    f"{why} inside a `with <lock>:` block in "
                                    f"{cls_name} — every thread needing the "
                                    "lock stalls behind this call (the armed "
                                    "graftsan runtime reports the same shape "
                                    "as held_across_blocking); move the call "
                                    "outside the guarded region",
                                )
                            )
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, attr, None)
                if sub and not isinstance(stmt, ast.With):
                    self._walk(module, cls_name, info, sub, guarded, queue_names, findings)
            for handler in getattr(stmt, "handlers", []) or []:
                self._walk(module, cls_name, info, handler.body, guarded, queue_names, findings)

    def _blocking(self, module: Module, call: ast.Call, queue_names) -> Optional[str]:
        func = call.func
        if isinstance(func, ast.Attribute):
            attr = func.attr
            if attr == "result":
                return "`.result()` (Future wait)"
            if attr == "dispatch":
                return "engine `.dispatch()`"
            if attr in SOCKET_BLOCKERS:
                return f"socket `.{attr}()`"
            if attr == "get":
                recv = func.value
                recv_name = (
                    recv.id if isinstance(recv, ast.Name) else _self_attr(recv) or ""
                )
                if recv_name in queue_names:
                    return f"`{recv_name}.get()` (queue wait)"
        dotted = dotted_name(func)
        if dotted:
            root = dotted.split(".")[0]
            resolved = module.resolve_root(root)
            full = resolved + dotted[len(root):] if resolved != root else dotted
            if full == "time.sleep" or dotted == "time.sleep":
                return "`time.sleep()`"
            if full.endswith("urlopen") or dotted.endswith("urlopen"):
                return "`urlopen()` (HTTP I/O)"
        return None


# ---------------------------------------------------------------------------
# GL213 — import-light transitive closure
# ---------------------------------------------------------------------------

#: roots an import-light module must never reach at module scope: importing
#: jax (or the package root, whose __init__ pulls config -> jax) on a
#: gateway-only host is exactly what the old subprocess probes banned
HEAVY_ROOTS = ("jax", "jaxlib", "howtotrainyourmamlpytorch_tpu")


def _module_is_import_light(module: Module) -> bool:
    return any("import-light" in marks for marks in module.markers.values())


def _required_imports(module: Module) -> List[Tuple[str, int, int]]:
    """(dotted, line, col) imports that RUN at import time: module scope and
    class bodies, descending into plain If/With/For/While blocks. Imports
    inside a try whose handlers catch ImportError are optional by contract;
    imports inside functions are lazy. Mirrors the runtime probe semantics
    (a banned `__import__` only fired for module-level imports)."""
    out: List[Tuple[str, int, int]] = []

    def guards_import_error(handlers) -> bool:
        for handler in handlers:
            if handler.type is None:
                return True
            names = []
            if isinstance(handler.type, ast.Tuple):
                names = [dotted_name(e) or "" for e in handler.type.elts]
            else:
                names = [dotted_name(handler.type) or ""]
            for name in names:
                if name.split(".")[-1] in (
                    "ImportError",
                    "ModuleNotFoundError",
                    "Exception",
                    "BaseException",
                ):
                    return True
        return False

    def visit(stmts):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(stmt, ast.Import):
                for alias in stmt.names:
                    out.append((alias.name, stmt.lineno, stmt.col_offset))
            elif isinstance(stmt, ast.ImportFrom):
                base = stmt.module or ""
                if stmt.level:
                    pkg = module.rel[: -len(".py")].replace("/", ".")
                    if pkg.endswith(".__init__"):
                        pkg = pkg[: -len(".__init__")]
                    else:
                        pkg = pkg.rsplit(".", 1)[0] if "." in pkg else ""
                    for _ in range(stmt.level - 1):
                        pkg = pkg.rsplit(".", 1)[0] if "." in pkg else ""
                    base = f"{pkg}.{base}" if base else pkg
                if base:
                    for alias in stmt.names:
                        out.append(
                            (f"{base}.{alias.name}", stmt.lineno, stmt.col_offset)
                        )
            elif isinstance(stmt, ast.Try):
                if not guards_import_error(stmt.handlers):
                    visit(stmt.body)
                visit(stmt.orelse)
                visit(stmt.finalbody)
                for handler in stmt.handlers:
                    visit(handler.body)
            elif isinstance(stmt, ast.If):
                test = dotted_name(stmt.test) or ""
                if "TYPE_CHECKING" not in test:
                    visit(stmt.body)
                visit(stmt.orelse)
            else:
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, attr, None)
                    if sub:
                        visit(sub)

    visit(module.tree.body)
    return out


@register
class ImportLightClosure(Rule):
    id = "GL213"
    title = "import-light module reaches a heavy root"

    def check_project(self, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        by_rel = {m.rel: m for m in project.modules}

        def resolve(dotted: str) -> Optional[Module]:
            # `from a.b import name` may target module a.b.name or attr
            # `name` of a/b.py — try the deeper path first
            path = dotted.replace(".", "/")
            for cand in (path + ".py", path + "/__init__.py"):
                mod = by_rel.get(cand)
                if mod is not None:
                    return mod
            if "." in dotted:
                return resolve(dotted.rsplit(".", 1)[0])
            return None

        def heavy(dotted: str) -> bool:
            root = dotted.split(".")[0]
            return root in HEAVY_ROOTS

        # closure cache: module rel -> offending chain (list of dotted) or None
        chains: Dict[str, Optional[List[str]]] = {}

        def chase(mod: Module, stack: Set[str]) -> Optional[List[str]]:
            if mod.rel in chains:
                return chains[mod.rel]
            if mod.rel in stack:
                return None
            stack = stack | {mod.rel}
            result: Optional[List[str]] = None
            for dotted, _line, _col in _required_imports(mod):
                if heavy(dotted):
                    result = [dotted]
                    break
                target = resolve(dotted)
                if target is not None and target.rel != mod.rel:
                    sub = chase(target, stack)
                    if sub is not None:
                        result = [dotted] + sub
                        break
            chains[mod.rel] = result
            return result

        for mod in project.modules:
            if not _module_is_import_light(mod):
                continue
            for dotted, line, col in _required_imports(mod):
                if heavy(dotted):
                    findings.append(
                        Finding(
                            self.id,
                            mod.rel,
                            line,
                            col,
                            f"import-light module imports `{dotted}` at module "
                            "scope — it must load on a gateway-only host with "
                            "no jax and without executing the package "
                            "__init__; lazy-import it inside the function "
                            "that needs it, or guard with try/except "
                            "ImportError",
                        )
                    )
                    continue
                target = resolve(dotted)
                if target is None or target.rel == mod.rel:
                    continue
                chain = chase(target, {mod.rel})
                if chain is not None:
                    findings.append(
                        Finding(
                            self.id,
                            mod.rel,
                            line,
                            col,
                            f"import-light module imports `{dotted}`, whose "
                            "transitive module-scope closure reaches "
                            f"`{chain[-1]}` (chain: {dotted} -> "
                            f"{' -> '.join(chain)}) — the heavy root loads "
                            "on every host that imports this module",
                        )
                    )
        return findings
