"""Device-time breakdown from a ``jax.profiler`` trace.

The reference has no profiling at all (SURVEY.md §5.1); here a trace window is
first-class (runner ``profile_dir``) and this module turns the written
``*.xplane.pb`` into a 3-line device-time breakdown (compute / data-movement /
other) without TensorBoard: the tensorboard profile plugin is incompatible
with the installed TF in this image, so the xplane proto is parsed directly
via ``tensorflow.tsl`` under the pure-python protobuf implementation.
"""

import glob
import os
from typing import Any, Dict, Optional

# Op-name prefixes that are data movement (HBM<->HBM/infeed DMA), not MXU/VPU
# compute. copy/slice dominate when layouts force relayout between ops.
_DMA_PREFIXES = (
    "copy",
    "slice",
    "dynamic-slice",
    "dynamic-update-slice",
    "transpose",
    "reshape",
    "bitcast",
    "concatenate",
    "infeed",
    "outfeed",
    "all-to-all",
)
_COMPUTE_PREFIXES = (
    "fusion",
    "convolution",
    "dot",
    "loop",
    "scatter",
    "gather",
    "reduce",
    "rng",
    "select",
    "while",
    "custom-call",
)


def _categorize(op_name: str) -> str:
    name = op_name.lower()
    for p in _DMA_PREFIXES:
        if name.startswith(p):
            return "dma"
    for p in _COMPUTE_PREFIXES:
        if name.startswith(p):
            return "compute"
    return "other"


def device_time_breakdown(trace_dir: str) -> Optional[Dict[str, Any]]:
    """Aggregate per-op device busy time from the newest xplane in trace_dir.

    Returns ``{"compute_frac", "dma_frac", "other_frac", "device_busy_ms",
    "top_ops"}`` over the whole trace window, or None when no xplane / no
    device plane is found. Fractions are of device *busy* time (events on the
    device plane); wall time per step is the caller's to measure.
    """
    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
    paths = sorted(
        glob.glob(os.path.join(trace_dir, "plugins", "profile", "*", "*.xplane.pb")),
        key=os.path.getmtime,
    )
    if not paths:
        return None
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2  # type: ignore
    except Exception:
        try:
            from tsl.profiler.protobuf import xplane_pb2  # type: ignore
        except Exception:
            return None

    xspace = xplane_pb2.XSpace()
    with open(paths[-1], "rb") as f:
        xspace.ParseFromString(f.read())

    device_planes = [
        p
        for p in xspace.planes
        if p.name.startswith("/device:TPU:") or p.name.startswith("/device:CPU:0")
    ]
    # prefer TPU planes when both exist
    tpu = [p for p in device_planes if "TPU" in p.name]
    planes = tpu or device_planes
    if not planes:
        return None

    per_op_ps: Dict[str, int] = {}
    for plane in planes:
        meta = plane.event_metadata
        # device planes carry hierarchical lines ('XLA Modules', 'Steps')
        # whose events span the same device time as the op-level 'XLA Ops'
        # line — summing them all would double/triple-count busy time
        op_lines = [l for l in plane.lines if l.name == "XLA Ops"] or list(plane.lines)
        for line in op_lines:
            for event in line.events:
                name = meta[event.metadata_id].name if event.metadata_id in meta else "?"
                per_op_ps[name] = per_op_ps.get(name, 0) + event.duration_ps

    total_ps = sum(per_op_ps.values())
    if total_ps == 0:
        return None
    cat_ps = {"compute": 0, "dma": 0, "other": 0}
    for name, ps in per_op_ps.items():
        cat_ps[_categorize(name)] += ps
    top = sorted(per_op_ps.items(), key=lambda kv: -kv[1])[:8]
    return {
        "compute_frac": round(cat_ps["compute"] / total_ps, 4),
        "dma_frac": round(cat_ps["dma"] / total_ps, 4),
        "other_frac": round(cat_ps["other"] / total_ps, 4),
        "device_busy_ms": round(total_ps / 1e9, 3),
        "top_ops": [
            {"op": name, "ms": round(ps / 1e9, 3)} for name, ps in top
        ],
    }
