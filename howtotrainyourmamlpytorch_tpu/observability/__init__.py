"""Unified observability: span tracing, metrics registry, run telemetry.

The first subsystem that spans both stacks: the training runner and the
serving frontend instrument their hot paths through the same three pieces —

- :mod:`trace` — ``SpanTracer``: low-overhead thread-safe span recorder
  (bounded ring, injectable clock, per-thread nesting) with Chrome
  trace-event / Perfetto JSON export and a balance validator the chaos
  campaign runs over every exported trace;
- :mod:`metrics` — ``MetricsRegistry``: counters, gauges, and windowed
  histograms with exact percentiles (window copied under the lock, numpy
  math outside it). ``serving/metrics.py``'s ``LatencyStats`` /
  ``EventCounters`` are thin adapters over it, ``/metrics`` schema
  unchanged;
- :mod:`telemetry` — ``TelemetryHub``: snapshots the registry to
  ``logs/telemetry.jsonl`` per epoch / per-N steps (episodes/s throughput,
  step-phase histograms, provider snapshots: recompile guard, watchdog beat
  age, breaker state).

The performance layer (ISSUE 7) builds on those three:

- :mod:`costs` — robust XLA cost-model access (program FLOPs/bytes via a
  never-raise fallback chain), the chip-peak table, and MFU arithmetic;
- :mod:`compile_ledger` — every XLA compile timed (lower/compile split),
  priced, and attributed to ``logs/compile_ledger.jsonl`` with
  persistent-cache hit accounting (the AOT/cold-start evidence base);
- :mod:`memory` — per-device HBM watermarks as a snapshot provider plus a
  latched low-headroom event;
- :mod:`donation` — the buffer-donation audit table (per planned program:
  donatable vs donated bytes) and the runtime aliasing self-check gating
  ``donate_train_state`` (the ``scripts/donation_probe.py`` verdict
  productized — ISSUE 12);
- :mod:`slo` — deterministic open-loop load schedules + the SLO report
  (CLI: ``scripts/loadgen.py``).

And the request-scoped layer (ISSUE 10):

- :mod:`context` — ``RequestContext`` (W3C ``traceparent`` ids, minted when
  absent) threaded HTTP thread -> batcher queue -> worker flush -> engine
  dispatch, exported as Chrome flow events so one request renders as one
  linked arc; plus the sampled structured access log
  (``logs/access.jsonl``). Cross-process merge: ``scripts/trace_merge.py``;
  live console: ``scripts/obs_top.py``.

Knobs: ``Config.observability`` (``config.py::ObservabilityConfig``) —
fully inert and bit-identical when disabled. Report CLI:
``scripts/obs_report.py``; howto: ``docs/OPERATIONS.md`` "Reading a run",
"Performance triage", and "Tracing a request".
"""

from .compile_ledger import CompileLedger  # noqa: F401
from .context import (  # noqa: F401
    AccessLog,
    RequestContext,
    format_traceparent,
    new_request_context,
    parse_traceparent,
    read_access_log,
)
from .costs import (  # noqa: F401
    jit_cost,
    mfu,
    peak_flops_per_sec,
    program_cost,
    program_memory,
)
from .donation import donation_audit, donation_selfcheck  # noqa: F401
from .memory import MemoryWatermarks, device_memory_stats  # noqa: F401
from .metrics import MetricsRegistry  # noqa: F401
from .telemetry import NULL_HUB, TelemetryHub  # noqa: F401
from .trace import (  # noqa: F401
    NULL_TRACER,
    NullTracer,
    SpanTracer,
    load_and_validate_trace,
    validate_chrome_trace,
)
