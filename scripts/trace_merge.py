#!/usr/bin/env python
"""Merge per-process Chrome traces + access logs + fleet events into ONE
Perfetto timeline.

A ``fleet_run`` (or a train + serve pair) leaves one ``logs/trace.json`` per
process, each with its own wall-clock anchor (``otherData.epoch_unix``) and
real pid, plus per-request ``logs/access.jsonl`` lines and the scheduler's
``fleet_events.jsonl``. Debugging a cross-process request (or a fleet-wide
stall) means eyeballing them TOGETHER — so this tool aligns every input onto
one wall-clock zero, keeps each process on its own pid track (re-assigning
only on collision or the legacy ``pid: 0``), renders access-log lines as
complete events on a per-process ``access_log`` track (args carry the trace
id — searchable in the Perfetto UI), renders fleet events as instants on a
``fleet`` track, and validates the result with the repo's own
``validate_chrome_trace`` before writing it.

Usage::

    python scripts/trace_merge.py --out merged.json A/logs/trace.json B/logs/trace.json
    python scripts/trace_merge.py --root exps/<fleet-dir> --out merged.json

``--root`` discovers every ``*/logs/trace*.json`` + sibling ``access.jsonl``
under the root (any directory of runs: a fleet exps root, or a parent
holding a train run and a serving run) and a root-level
``fleet_events.jsonl`` when present. Prints ONE JSON summary line on stdout;
rc 0 ok, 1 when the merged trace fails validation, 2 usage.

Import-light by design (stdlib + the file-path-loaded trace module; no jax):
merging finished runs must never touch a backend.
"""

import argparse
import glob
import importlib.util
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PKG = os.path.join(_REPO_ROOT, "howtotrainyourmamlpytorch_tpu")


def _load_by_path(name: str, path: str):
    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


try:
    _exit_codes = _load_by_path("htymp_exit_codes", os.path.join(_PKG, "exit_codes.py"))
    _RC_OK, _RC_USAGE = _exit_codes.OK, _exit_codes.USAGE
except Exception:  # standalone copy of scripts/: the historical literals hold
    _RC_OK, _RC_USAGE = 0, 2
#: merged trace failed validation — the lint.py "findings" convention
_RC_INVALID = 1

#: synthetic tid for the per-process access-log track (far above the span
#: exporter's dense 0..n thread ids)
ACCESS_TID = 9999


def _read_jsonl(path: str) -> Tuple[List[Dict[str, Any]], int]:
    records, torn = [], 0
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                torn += 1
    return records, torn


class _PidAllocator:
    """Keep each input's real pid when possible; remap on collision (two
    traces from recycled pids) or the legacy ``pid: 0`` export."""

    def __init__(self):
        self._used = set()
        self._next_synthetic = 1_000_000

    def assign(self, wanted: Optional[int]) -> int:
        if wanted and wanted > 0 and wanted not in self._used:
            self._used.add(wanted)
            return wanted
        pid = self._next_synthetic
        while pid in self._used:
            pid += 1
        self._next_synthetic = pid + 1
        self._used.add(pid)
        return pid


def _trace_pid(trace: Dict[str, Any]) -> Optional[int]:
    pid = (trace.get("otherData") or {}).get("pid")
    if isinstance(pid, int):
        return pid
    for ev in trace.get("traceEvents", []):
        if isinstance(ev, dict) and isinstance(ev.get("pid"), int):
            return ev["pid"]
    return None


def _label_for(path: str) -> str:
    """Process label: the run-dir name (trace lives in <run>/logs/) or the
    file's own stem for loose inputs."""
    parent = os.path.dirname(os.path.abspath(path))
    if os.path.basename(parent) == "logs":
        return os.path.basename(os.path.dirname(parent))
    return os.path.splitext(os.path.basename(path))[0]


def merge(
    trace_paths: List[str],
    access_paths: Optional[List[str]] = None,
    fleet_events_path: Optional[str] = None,
) -> Dict[str, Any]:
    """Build the merged Chrome-trace object (no I/O besides reads).

    Alignment: every input with an ``epoch_unix`` anchor is shifted onto the
    EARLIEST anchor across inputs; anchor-less traces (or wall-stamped
    records with no trace sibling) stay at their own zero — visibly
    unaligned beats silently wrong."""
    inputs: List[Dict[str, Any]] = []
    for path in trace_paths:
        with open(path) as f:
            trace = json.load(f)
        inputs.append({"path": path, "trace": trace,
                       "epoch_unix": (trace.get("otherData") or {}).get("epoch_unix")})
    anchors = [i["epoch_unix"] for i in inputs if isinstance(i["epoch_unix"], (int, float))]
    base = min(anchors) if anchors else None

    pids = _PidAllocator()
    events: List[Dict[str, Any]] = []
    dropped_spans = 0
    open_spans = 0
    label_to_pid: Dict[str, int] = {}
    for item in inputs:
        trace = item["trace"]
        label = _label_for(item["path"])
        pid = pids.assign(_trace_pid(trace))
        label_to_pid[label] = pid
        shift_us = 0.0
        if base is not None and isinstance(item["epoch_unix"], (int, float)):
            shift_us = (item["epoch_unix"] - base) * 1e6
        for ev in trace.get("traceEvents", []):
            if not isinstance(ev, dict):
                continue
            out = dict(ev)
            out["pid"] = pid
            if isinstance(out.get("ts"), (int, float)):
                out["ts"] = round(out["ts"] + shift_us, 3)
            events.append(out)
        events.append(
            {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": label}}
        )
        other = trace.get("otherData") or {}
        dropped_spans += int(other.get("dropped_spans", 0) or 0)
        open_spans += int(other.get("open_spans", 0) or 0)

    access_lines = 0
    for path in access_paths or []:
        label = _label_for(path)
        pid = label_to_pid.get(label)
        if pid is None:
            pid = pids.assign(None)
            label_to_pid[label] = pid
            events.append(
                {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                 "args": {"name": label}}
            )
        records, _ = _read_jsonl(path)
        for rec in records:
            ts_wall = rec.get("ts")
            if base is None or not isinstance(ts_wall, (int, float)):
                continue
            total_ms = rec.get("total_ms") or 0.0
            # access ts stamps request COMPLETION; draw the slice over the
            # request's actual window so it overlaps its span chain
            events.append(
                {
                    "name": f"{rec.get('verb')} {rec.get('outcome')}",
                    "cat": "access",
                    "ph": "X",
                    # clamp: a request begun before the earliest trace
                    # anchor must not export a (schema-invalid) negative ts
                    "ts": max(
                        0.0,
                        round(((ts_wall - base) * 1e6) - total_ms * 1e3, 3),
                    ),
                    "dur": round(total_ms * 1e3, 3),
                    "pid": pid,
                    "tid": ACCESS_TID,
                    "args": {
                        k: v
                        for k, v in rec.items()
                        if isinstance(v, (int, float, bool, str, type(None)))
                    },
                }
            )
            access_lines += 1
        events.append(
            {"name": "thread_name", "ph": "M", "pid": pid, "tid": ACCESS_TID,
             "args": {"name": "access_log"}}
        )

    fleet_events = 0
    if fleet_events_path and os.path.exists(fleet_events_path):
        pid = pids.assign(None)
        events.append(
            {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": "fleet"}}
        )
        records, _ = _read_jsonl(fleet_events_path)
        for rec in records:
            ts_wall = rec.get("ts")
            if base is None or not isinstance(ts_wall, (int, float)):
                continue
            events.append(
                {
                    "name": str(rec.get("event", "fleet_event")),
                    "cat": "fleet",
                    "ph": "i",
                    "s": "g",  # global-scope instant: a fleet-wide mark
                    "ts": round((ts_wall - base) * 1e6, 3),
                    "pid": pid,
                    "tid": 0,
                    "args": {
                        k: v
                        for k, v in rec.items()
                        if isinstance(v, (int, float, bool, str, type(None)))
                    },
                }
            )
            fleet_events += 1

    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "merged_from": [i["path"] for i in inputs],
            "epoch_unix": base,
            "open_spans": open_spans,
            "dropped_spans": dropped_spans,
            "access_lines": access_lines,
            "fleet_events": fleet_events,
        },
    }


def discover(root: str) -> Tuple[List[str], List[str], Optional[str]]:
    """``--root`` inputs: every ``*/logs/trace*.json`` (one level of run
    dirs, archived sessions included) + sibling ``access.jsonl`` files +
    the root-level ``fleet_events.jsonl`` when the scheduler wrote one."""
    traces = sorted(glob.glob(os.path.join(root, "*", "logs", "trace*.json")))
    access = sorted(glob.glob(os.path.join(root, "*", "logs", "access.jsonl")))
    fleet = os.path.join(root, "fleet_events.jsonl")
    return traces, access, fleet if os.path.exists(fleet) else None


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("traces", nargs="*", help="per-process trace*.json files")
    parser.add_argument("--root", default=None,
                        help="discover */logs/trace*.json + access.jsonl + "
                        "fleet_events.jsonl under this directory")
    parser.add_argument("--out", required=True, help="merged trace output path")
    parser.add_argument("--access", action="append", default=[],
                        help="access.jsonl file(s) to add as request tracks")
    parser.add_argument("--fleet-events", default=None,
                        help="fleet_events.jsonl to add as an instant track")
    args = parser.parse_args(argv)

    traces = list(args.traces)
    access = list(args.access)
    fleet = args.fleet_events
    if args.root:
        found_traces, found_access, found_fleet = discover(args.root)
        traces += found_traces
        access += found_access
        fleet = fleet or found_fleet
    if not traces:
        print("trace_merge: no input traces (pass files or --root)", file=sys.stderr)
        return _RC_USAGE

    try:
        merged = merge(traces, access_paths=access, fleet_events_path=fleet)
    except (OSError, json.JSONDecodeError) as exc:
        print(f"trace_merge: unreadable input: {exc}", file=sys.stderr)
        return _RC_USAGE

    trace_mod = _load_by_path(
        "htymp_trace", os.path.join(_PKG, "observability", "trace.py")
    )
    violations = trace_mod.validate_chrome_trace(merged)
    with open(args.out, "w") as f:
        json.dump(merged, f)
    print(
        json.dumps(
            {
                "out": args.out,
                "traces": len(traces),
                "access_files": len(access),
                "events": len(merged["traceEvents"]),
                "access_lines": merged["otherData"]["access_lines"],
                "fleet_events": merged["otherData"]["fleet_events"],
                "violations": violations,
                "ok": not violations,
            }
        ),
        flush=True,
    )
    return _RC_OK if not violations else _RC_INVALID


if __name__ == "__main__":
    sys.exit(main())
