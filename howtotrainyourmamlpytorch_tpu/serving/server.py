"""The serving front-end: in-process API + a thin stdlib HTTP JSON layer.

``ServingFrontend`` wires the pieces together — engine (compiled adapt /
predict), adapted-weight cache, micro-batchers, latency metrics — behind the
request API a client sees:

- ``adapt(x_support, y_support) -> {adaptation_id, cached, ...}``: run (or
  skip, on cache hit) the inner loop; the returned id names the cached
  adapted weights.
- ``predict(adaptation_id, x_query) -> probs``: forward queries through the
  cached adapted weights.
- ``adapt_predict(...)``: both in one call, for one-shot clients.
- ``metrics() / healthz()``: the observability surface.

The HTTP layer (``ThreadingHTTPServer`` + JSON bodies) is deliberately
stdlib-only — no framework dependency — and thin: every handler parses JSON,
calls the frontend, serializes the result. Concurrency comes from the
threaded server (one thread per in-flight request) feeding the batchers,
whose single worker serializes device dispatch.
"""

import json
import os
import signal
import sys
import threading
import time
import urllib.parse
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from ..config import Config, ResilienceConfig, ServingConfig
from ..exit_codes import DRAIN_DEADLINE, HTTP_DEADLINE, HTTP_UNAVAILABLE, OK
from ..observability import TelemetryHub
from ..observability.context import (
    AccessLog,
    RequestContext,
    flow_start,
    format_traceparent,
    new_request_context,
    parse_traceparent,
)
from ..observability.metrics import prometheus_text
from ..observability.trace import NULL_TRACER
from ..core.strategies import validate_request_strategy
from ..resilience.retry import DeadlineExceededError
from ..resilience.watchdog import HeartbeatWatchdog
from .cache import support_digest
from .engine import AdaptationEngine

# historical home of the request-path error taxonomy: re-exported so every
# ``from .server import ServiceUnavailableError`` keeps resolving to the
# one class the pool/router layers now raise from below the frontend
from .errors import (  # noqa: F401
    ServiceUnavailableError,
    SessionQuarantinedError,
    UnknownAdaptationError,
)
from .metrics import EventCounters, LatencyStats
from .pool import EnginePool
from .router import Router, rendezvous_score
from .tenancy import QuotaExceededError, validate_request_tenant

from ..utils.locks import san_condition, san_lock


class _LazyTenantFingerprints:
    """Mapping view the session rehydrator hands ``SessionStore.load_all``:
    ``get(tenant)`` resolves a REGISTERED tenant's checkpoint fingerprint on
    demand (loading its master into host RAM only then), so rehydrating a
    run dir with zero spilled tenant sessions never touches a tenant
    checkpoint — the registry stays lazy."""

    def __init__(self, registry):
        self._registry = registry

    def get(self, tenant, default=None):
        if tenant not in self._registry:
            return default
        try:
            return self._registry.fingerprint(tenant)
        except Exception:  # noqa: BLE001 — an unloadable tenant is foreign
            return default


class ServingFrontend:
    def __init__(
        self,
        engine: AdaptationEngine,
        serving_cfg: Optional[ServingConfig] = None,
        resilience_cfg: Optional[ResilienceConfig] = None,
        clock=time.monotonic,
        wedge_exit=None,
        hub: Optional[TelemetryHub] = None,
        access_log_dir: Optional[str] = None,
        replicas: Optional[int] = None,
    ):
        self.engine = engine
        self.serving = serving_cfg or engine.serving
        # fleet size: explicit arg > Config.serving.replicas; 0 = one
        # replica per visible local device (serving/pool.py)
        self._n_replicas = (
            int(replicas)
            if replicas is not None
            else int(getattr(self.serving, "replicas", 1))
        )
        # resilience knobs ride the run config like the serving knobs do;
        # clock is injectable so breaker tests walk cooldowns without waiting
        self.resilience = resilience_cfg or engine.cfg.resilience
        # graftsan: arm the lock-discipline sanitizer BEFORE this frontend
        # constructs its locks/pool/batchers, so they come out instrumented.
        # (Locks built earlier — e.g. the engine's jit lock — stay plain
        # unless HTYMP_GRAFTSAN=1 armed the whole process at import time.)
        self._graftsan = None
        if getattr(self.resilience, "sanitizer", False) or os.environ.get(
            "HTYMP_GRAFTSAN"
        ) == "1":
            try:
                from tools.graftsan import runtime as _graftsan_runtime

                _graftsan_runtime.arm()
                self._graftsan = _graftsan_runtime
            except ImportError:  # packaged without tools/: sanitizer off
                self._graftsan = None
        # close-audit baseline: threads alive before this frontend spawned
        # any — whatever non-daemon thread outlives close() beyond these is
        # a leak this frontend owns
        self._graftsan_thread_baseline = {
            t.ident for t in threading.enumerate()
        }
        # one TelemetryHub per frontend (no logs dir — a server owns no run
        # directory; tracer + registry only, snapshot on demand). The SAME
        # registry backs the LatencyStats/EventCounters adapters, so the
        # /metrics payload and the hub read one set of numbers.
        self.hub = (
            hub
            if hub is not None
            else TelemetryHub.from_config(
                getattr(engine.cfg, "observability", None)
            )
        )
        self.latency = LatencyStats(
            self.serving.latency_window, registry=self.hub.registry
        )
        self.counters = EventCounters(registry=self.hub.registry)
        self._memory = None
        # structured access log (observability/context.py): one JSON line
        # per request in <access_log_dir>/access.jsonl. Only built when the
        # caller names a directory (a frontend owns no run dir by itself;
        # from_run_dir / serve.py / loadgen pass one) AND observability is
        # on — disabled, the request path stays zero-file.
        self.access_log: Optional[AccessLog] = None
        # structured serving events (replica deaths, drain milestones,
        # session spill/rehydrate) land in <access_log_dir>/events.jsonl —
        # same zero-file contract as the access log: only with a log dir
        # AND observability on, so the disabled build stays bit-identical
        self.events = None
        if self.hub.enabled and access_log_dir:
            obs_cfg = getattr(engine.cfg, "observability", None)
            if getattr(obs_cfg, "access_log", True):
                self.access_log = AccessLog(
                    access_log_dir,
                    sample=getattr(obs_cfg, "access_log_sample", 1.0),
                )
            from ..experiment.storage import EventLog

            self.events = EventLog(access_log_dir)
        if self._graftsan is not None and self.events is not None:
            # sanitizer findings land as structured graftsan_violation
            # records next to the serving events they implicate
            self._graftsan.add_sink(self.events.append)
        if self.hub.enabled:
            # trace the engine's device dispatches and both batchers' flushes
            # through the hub's tracer (engines built standalone keep their
            # own tracer if one was injected)
            if self.engine.tracer is NULL_TRACER:
                self.engine.tracer = self.hub.tracer
            self.hub.add_provider("breaker", lambda: self.breaker.snapshot())
            obs_cfg = getattr(engine.cfg, "observability", None)
            # collector-only compile ledger (a server owns no run dir): the
            # per-bucket program compiles show up in /metrics.compiled and
            # in every hub snapshot, so the serving cold-start tax is a
            # number, not a vibe
            if self.engine.compile_ledger is None and getattr(
                obs_cfg, "compile_ledger", True
            ):
                from ..observability.compile_ledger import CompileLedger

                self.engine.compile_ledger = CompileLedger(
                    session=self.hub.session_id
                )
            if self.engine.compile_ledger is not None:
                self.hub.add_provider(
                    "compile_ledger", self.engine.compile_ledger.summary
                )
            if getattr(obs_cfg, "memory_watermarks", True):
                from ..observability.memory import MemoryWatermarks

                self._memory = MemoryWatermarks(
                    getattr(obs_cfg, "hbm_headroom_warn_frac", 0.05)
                )
                self.hub.add_provider("memory", self._memory.snapshot)
        # --- the fleet: pool + router (serving/pool.py, serving/router.py)
        # One EngineReplica per requested replica, each with its own
        # batchers (continuous batching), circuit breaker, and adapted-
        # weight cache; the router keeps sessions affine to the replica
        # holding their fast weights and sheds at admission. With one
        # replica everything below collapses to the pre-fleet behavior.
        self.pool = EnginePool.build(
            engine,
            self._n_replicas,
            serving_cfg=self.serving,
            resilience_cfg=self.resilience,
            counters=self.counters,
            tracer=self.hub.tracer,
            clock=clock,
        )
        self.router = Router(
            self.pool.replicas,
            max_queued_per_replica=getattr(
                self.serving, "router_max_queued_per_replica", 0
            ),
            shed_retry_after_s=self.resilience.shed_retry_after_s,
        )
        # --- multi-tenant serving (serving/registry.py + tenancy.py) ------
        # quotas + the HBM-watermark eviction signal only exist when the
        # engine carries a registry; single-tenant frontends pay nothing
        self.quotas = None
        if engine.registry is not None:
            from .tenancy import TenantQuotas

            self.quotas = TenantQuotas(
                max_inflight=getattr(self.serving, "tenant_max_inflight", 0),
                rate_rps=getattr(self.serving, "tenant_rate_rps", 0.0),
                max_resident_bytes=getattr(
                    self.serving, "tenant_max_resident_bytes", 0
                ),
            )
            if self._memory is not None:
                # PR 7's watermark provider is the pagers' eviction signal:
                # real per-device HBM pressure preempts the static budget
                for e in self.pool.engines():
                    if getattr(e, "pager", None) is not None:
                        e.pager.watermarks = self._memory
        # back-compat views: the single-replica surface tests, the SLO
        # harness, and operator tools read — all primary-replica objects
        primary = self.pool.replicas[0]
        self.breaker = primary.breaker
        self.cache = primary.cache
        self._adapt_batcher = primary.adapt_batcher
        self._predict_batcher = primary.predict_batcher
        if self.hub.enabled and len(self.pool) > 1:
            self.hub.add_provider("router", self.router.stats)
        self._started = time.monotonic()
        self._closed = False
        # --- graceful drain state (begin_drain / run_server) -------------
        # one lock guards the draining flag and the in-flight request count;
        # the condition lets the drain thread sleep until the count reaches
        # zero instead of polling
        self._drain_lock = san_lock("ServingFrontend._drain_lock")
        self._drain_zero = san_condition("ServingFrontend._drain_zero", self._drain_lock)
        self._draining = False
        self._inflight = 0
        self._drain_info: Dict[str, Any] = {}
        # set once the FIRST drain fully completes (verdict recorded) —
        # a second SIGTERM blocks on it instead of racing ahead with an
        # empty verdict and shutting the server down mid-drain
        self._drain_done = threading.Event()
        # --- refinement lineage (serving/cache.py::SessionLineage) --------
        # per-session refinement history, keyed by the FULL cache key (so
        # tenant A's lineage can never guard tenant B's session). Bounded
        # LRU: a lineage evicted here costs nothing but history — the next
        # refine re-seeds a baseline. Stays empty with refine_enabled=false,
        # so the refine-off request path never pays for it.
        self._lineage_lock = san_lock("ServingFrontend._lineage_lock")
        self._lineages: "OrderedDict[Tuple[str, str, str], Any]" = OrderedDict()
        self._max_lineages = 4096
        # --- session spill/rehydrate (serving/sessions.py) ----------------
        # run-dir engines spill hot adapted sessions at drain and rehydrate
        # them here at startup, so a rolling restart keeps its sessions warm
        self.session_store = None
        self._session_stats: Dict[str, int] = {}
        if getattr(self.serving, "session_spill", True) and getattr(
            engine, "save_dir", None
        ):
            from .sessions import SessionStore

            self.session_store = SessionStore(
                os.path.join(engine.save_dir, "sessions")
            )
            self._rehydrate_sessions()
        # --- AOT prewarm (Config.aot; compile/aot.py) --------------------
        # compile the full (bucket x batch-bucket) serving grid before (or,
        # background, while) the frontend accepts work: /healthz answers
        # 503 "warming" until the set is compiled — DISTINCT from the
        # breaker's "degraded" — so an orchestrator holds traffic off a
        # replica that would eat cold XLA compiles on its first requests.
        self._prewarm_lock = san_lock("ServingFrontend._prewarm_lock")
        self._prewarm: Dict[str, Any] = {"status": "disabled"}
        self._prewarm_thread: Optional[threading.Thread] = None
        aot_cfg = getattr(engine.cfg, "aot", None)
        if (
            aot_cfg is not None
            and getattr(aot_cfg, "enabled", False)
            and hasattr(self.engine, "prewarm")
        ):
            with self._prewarm_lock:
                self._prewarm = {"status": "warming"}
            if getattr(aot_cfg, "serving_background", True):
                self._prewarm_thread = threading.Thread(
                    target=self._run_prewarm, name="serving-prewarm", daemon=True
                )
                self._prewarm_thread.start()
            else:
                self._run_prewarm()
        # wedge watchdogs over the batcher flush workers (poll mode): work
        # pending (queued or mid-flush) with zero completed flushes across
        # the whole deadline means that worker is parked in a hung device
        # dispatch. The breaker already fail-fasts *clients* on that
        # signature; it cannot un-hang the worker thread — only a process
        # restart can, so the watchdog dumps stacks and exits rc=76 for the
        # supervisor. ONE WATCHDOG PER BATCHER: progress is per-worker, so a
        # hung adapt worker is never masked by a predict worker that keeps
        # completing flushes. Disabled (watchdog.serve_enabled=false) it
        # costs nothing; ``wedge_exit`` is injectable for drills.
        self._watchdogs: list = []
        wd_cfg = getattr(self.resilience, "watchdog", None)
        if wd_cfg is not None and wd_cfg.enabled and wd_cfg.serve_enabled:
            batchers = [
                b
                for r in self.pool.replicas
                for b in (r.adapt_batcher, r.predict_batcher, r.refine_batcher)
                if b is not None
            ]
            for batcher in batchers:
                wd = HeartbeatWatchdog(
                    deadline_s=wd_cfg.serve_deadline_s,
                    poll_s=wd_cfg.poll_s,
                    on_wedge=self._on_wedge,
                    exit_code=wd_cfg.wedge_exit_code,
                    exit_fn=wedge_exit if wedge_exit is not None else os._exit,
                    progress_fn=batcher.flushes_completed,
                    pending_fn=batcher.pending,
                    name=f"serving-{batcher.name}",
                )
                wd.arm(batcher.name)
                self._watchdogs.append(wd)

    def _run_prewarm(self) -> None:
        """Compile the planned serving grid (engine.prewarm) and publish the
        outcome. Runs on the background prewarm thread (or inline when
        ``aot.serving_background=false``); a failure degrades to lazy
        compiles with the error visible in /metrics, never a dead server."""
        t0 = time.monotonic()
        try:
            # per-replica warm gating (compile/aot.py::prewarm_pool): every
            # DISTINCT engine behind the pool is warmed; same-device
            # replicas share the primary's warm set for free
            summary = (
                self.pool.prewarm()
                if len(self.pool) > 1
                else self.engine.prewarm()
            )
            result = {
                "status": "warm",
                "programs": summary["programs"],
                "seconds": summary["seconds"],
                "cache_hits": summary["cache_hits"],
                "store_hits": summary.get("store_hits", 0),
                "compile_errors": summary["errors"],
            }
            if "replicas" in summary:
                result["replicas"] = summary["replicas"]
        except Exception as exc:  # noqa: BLE001 — prewarm must not kill serving
            result = {
                "status": "error",
                "seconds": round(time.monotonic() - t0, 3),
                "error": f"{type(exc).__name__}: {exc}",
            }
        with self._prewarm_lock:
            self._prewarm = result
        print(
            f"serving prewarm: {result['status']} in {result['seconds']}s"
            + (
                f" ({result['programs']} programs, "
                f"{result['cache_hits']} persistent-cache hits)"
                if result["status"] == "warm"
                else f" ({result.get('error')})"
            ),
            flush=True,
        )

    def prewarm_status(self) -> Dict[str, Any]:
        with self._prewarm_lock:
            return dict(self._prewarm)

    def wait_prewarm(self, timeout_s: float = 600.0) -> Dict[str, Any]:
        """Block until the background prewarm settles (bounded), then return
        its status — the readiness hook for supervisors and tests."""
        thread = self._prewarm_thread
        if thread is not None:
            thread.join(timeout=timeout_s)
        return self.prewarm_status()

    def _on_wedge(self, info: Dict[str, Any]) -> None:
        """Serving wedge post-mortem: one structured JSON line + per-thread
        stacks on stderr (a server has no run dir to own an events.jsonl),
        then the watchdog exits with the wedge code."""
        self.counters.inc("wedged")
        print(
            json.dumps(
                {
                    "event": "wedged",
                    "component": "serving",
                    "stage": info["stage"],
                    "stall_s": info["stall_s"],
                    "adapt_batcher": self.pool.batcher_stats("adapt"),
                    "predict_batcher": self.pool.batcher_stats("predict"),
                }
            ),
            file=sys.stderr,
            flush=True,
        )
        for label, stack in info["threads"].items():
            print(f"--- thread {label} ---", file=sys.stderr)
            for line in stack:
                print(line, file=sys.stderr)
        sys.stderr.flush()

    # ------------------------------------------------------------------

    def _cache_key(
        self, digest: str, strategy: str, tenant: Optional[str] = None
    ) -> Tuple[str, str, str]:
        """Adapted-session cache key: (checkpoint fingerprint, strategy,
        adaptation id). The strategy is an explicit component — a ProtoNet
        prototype table and a MAML fast-weight tree for the same support
        set must never collide — on top of being folded into the digest
        itself (serving/cache.py::support_digest). A non-default tenant's
        key carries THAT tenant's checkpoint fingerprint, so a cross-tenant
        predict (tenant B naming tenant A's adaptation id) misses into the
        honest 404 — it can never resolve to another tenant's weights."""
        fp = (
            self.engine.registry.fingerprint(tenant)
            if tenant is not None
            else self.engine.fingerprint
        )
        return (fp, strategy, digest)

    # -- refinement lineage (serving/cache.py::SessionLineage) ----------

    def _lineage_for(self, key: Tuple[str, str, str], create: bool = False):
        """The session's lineage record (None when it has none and
        ``create`` is false). Creation binds the configured snapshot-ring
        bound; the table is a bounded LRU — an evicted lineage costs only
        history, never correctness (the next refine re-seeds a baseline)."""
        with self._lineage_lock:
            lineage = self._lineages.get(key)
            if lineage is not None:
                self._lineages.move_to_end(key)
            elif create:
                from .cache import SessionLineage

                lineage = SessionLineage(
                    snapshot_ring=int(
                        getattr(self.serving, "refine_snapshot_ring", 2)
                    )
                )
                self._lineages[key] = lineage
                while len(self._lineages) > self._max_lineages:
                    self._lineages.popitem(last=False)
            return lineage

    def _pop_lineage(self, key: Tuple[str, str, str]):
        with self._lineage_lock:
            return self._lineages.pop(key, None)

    def _quarantined(self, key: Tuple[str, str, str]) -> bool:
        with self._lineage_lock:
            lineage = self._lineages.get(key)
        return lineage is not None and lineage.quarantined

    def _count_strategy(self, strategy: str, verb: str, outcome: str) -> None:
        """Per-strategy outcome tally (the /metrics ``strategies`` block and
        obs_top's live strategy mix read these): one increment per request,
        keyed ``serving.strategy.<name>.<verb>.<outcome>``."""
        self.hub.registry.inc(f"serving.strategy.{strategy}.{verb}.{outcome}")

    def _count_tenant(self, tenant: Optional[str], verb: str, outcome: str) -> None:
        """Per-tenant outcome tally, keyed
        ``serving.tenant.<id>.<verb>.<outcome>`` — only in tenant mode, so
        a single-tenant deployment's counter namespace is unchanged."""
        if self.engine.registry is None:
            return
        self.hub.registry.inc(
            f"serving.tenant.{tenant or 'default'}.{verb}.{outcome}"
        )

    def _acquire_quota(self, tenant: Optional[str]) -> Optional[str]:
        """Per-tenant admission (rate + inflight token): returns the quota
        label to release, or None when quotas are off. A breach becomes the
        existing shed contract — 429 + honest ``Retry-After`` — and is
        per-tenant by construction: other tenants' admission never sees it."""
        if self.quotas is None or not self.quotas.enabled:
            return None
        label = tenant or "default"
        try:
            self.quotas.acquire(label)
        except QuotaExceededError as exc:
            self.counters.inc("tenant_quota_rejected")
            raise ServiceUnavailableError(
                str(exc), retry_after_s=exc.retry_after_s, status=429
            ) from exc
        return label

    def _check_resident_quota(self, tenant: Optional[str], fingerprint: str) -> None:
        """Before an adapt inserts NEW bytes: the tenant's live adapted-
        session bytes (summed over every replica cache, honest — from the
        entries, not counters) must fit its quota."""
        if self.quotas is None or not self.quotas.max_resident_bytes:
            return
        resident = sum(
            r.cache.bytes_for_fingerprint(fingerprint)
            for r in self.pool.replicas
        )
        try:
            self.quotas.check_resident_bytes(tenant or "default", resident)
        except QuotaExceededError as exc:
            self.counters.inc("tenant_quota_rejected")
            raise ServiceUnavailableError(
                str(exc), retry_after_s=exc.retry_after_s, status=429
            ) from exc

    def _sweep_pagers(self) -> None:
        """HBM-watermark eviction sweep: ask each engine's pager to evict
        its LRU tenant while the tightest per-device headroom sits below the
        configured floor. Free when the knob is off (the pager returns
        immediately); called after tenant-mode dispatches."""
        for e in self.pool.engines():
            pager = getattr(e, "pager", None)
            if pager is None:
                continue
            pager.check_watermark()
            for rec in pager.drain_events():
                if rec["event"] == "tenant_evicted":
                    self.counters.inc("tenant_evictions")
                    if rec.get("reason") == "hbm_watermark":
                        self.counters.inc("tenant_watermark_evictions")
                self._event(rec.pop("event"), **rec)

    def _request_ctx(self, ctx: Optional[RequestContext]) -> Optional[RequestContext]:
        """The per-request trace identity: adopt the caller's (HTTP layer,
        loadgen), mint one when observability is on, stay None (and
        zero-overhead) when it is off."""
        if ctx is not None or not self.hub.enabled:
            return ctx
        return new_request_context()

    def _record_access(
        self,
        ctx: Optional[RequestContext],
        verb: str,
        outcome: str,
        status: int,
        total_s: float,
    ) -> None:
        if ctx is None or self.access_log is None:
            return
        self.access_log.record(
            ctx, verb, outcome, status, total_s, breaker=self.breaker.state
        )

    def log_http_access(
        self,
        ctx: Optional[RequestContext],
        verb: str,
        outcome: str,
        status: int,
        total_s: float,
    ) -> None:
        """HTTP-layer seam for requests the frontend methods never saw —
        parse errors, unknown paths, handler-level faults, degraded
        /healthz. ``ctx.access_logged`` guards double-logging the ones the
        frontend already recorded."""
        if ctx is None or ctx.access_logged:
            return
        self._record_access(ctx, verb, outcome, status, total_s)

    @staticmethod
    def _failure_of(exc: BaseException) -> Tuple[str, int]:
        """Map a request-path exception to its (outcome, HTTP status) pair
        — the access log's taxonomy, identical in-process and over HTTP."""
        if isinstance(exc, SessionQuarantinedError):
            # the refinement guard's honest refusal: 409 + Retry-After,
            # never a silently-stale answer through a poisoned session
            return "quarantined", exc.status
        if isinstance(exc, ServiceUnavailableError):
            # 503 for replica-side refusals, 429 for router admission —
            # the error carries its own wire status (serving/errors.py)
            return "shed", exc.status
        if isinstance(exc, DeadlineExceededError):
            return "deadline", HTTP_DEADLINE
        if isinstance(exc, UnknownAdaptationError):
            return "unknown_id", 404
        return "error", 500

    def _dispatch(self, batcher, bucket, payload, ctx=None):
        """Back-compat seam: the guarded dispatch (breaker + shed +
        deadline + timeout attribution) lives on
        :class:`~.pool.EngineReplica` now; this delegates to the primary
        replica's guard for callers (tests, tools) that drive it with an
        arbitrary batcher."""
        return self.pool.replicas[0].dispatch(batcher, bucket, payload, ctx)

    def _note_padding(
        self, verb: str, true_size: int, bucket, strategy: Optional[str] = None
    ) -> None:
        """Padding-waste accounting (ROADMAP 4d): forward FLOPs scale with
        the PADDED sample count, so the wasted-FLOPs fraction over traffic
        is ``1 - true_samples / padded_samples``. Called AFTER a dispatch
        returns, so only FLOPs actually spent are counted (cache hits,
        sheds, breaker rejections, and deadline expiries pad nothing); the
        live ``padding_waste_frac`` gauge rides the one registry /metrics,
        the hub, and the prom exposition read."""
        if not isinstance(bucket, (int, np.integer)) or bucket <= 0:
            return
        reg = self.hub.registry
        reg.inc(f"serving.padding.{verb}.true_samples", int(true_size))
        reg.inc(f"serving.padding.{verb}.padded_samples", int(bucket))
        # per-bucket tallies: the bucket-granular traffic histogram the
        # auto-tuner (serving/buckets.py::traffic_from_metrics) consumes
        # when no access log was recorded
        reg.inc(f"serving.padding.{verb}.bucket.{int(bucket)}.count", 1)
        reg.inc(
            f"serving.padding.{verb}.bucket.{int(bucket)}.true_samples",
            int(true_size),
        )
        if strategy:
            # per-strategy tallies under their own prefix (the legacy
            # per-verb keys above stay the aggregate the tuner reads)
            reg.inc(
                f"serving.padding.strategy.{strategy}.{verb}.true_samples",
                int(true_size),
            )
            reg.inc(
                f"serving.padding.strategy.{strategy}.{verb}.padded_samples",
                int(bucket),
            )
        true_total = sum(
            reg.counter(f"serving.padding.{v}.true_samples")
            for v in ("adapt", "predict", "refine")
        )
        padded_total = sum(
            reg.counter(f"serving.padding.{v}.padded_samples")
            for v in ("adapt", "predict", "refine")
        )
        if padded_total:
            reg.set_gauge(
                "serving.padding_waste_frac",
                round(1.0 - true_total / padded_total, 4),
            )

    def padding_stats(self) -> Dict[str, Any]:
        """The /metrics ``padding`` block: per-verb true vs padded sample
        totals and waste fractions — the number bucket-edge tuning reads."""
        reg = self.hub.registry
        out: Dict[str, Any] = {}
        true_total = padded_total = 0
        # the refine verb joins the block only once refine traffic exists —
        # a refine-off deployment's padding schema is byte-identical
        verbs = ["adapt", "predict"]
        if reg.counter("serving.padding.refine.padded_samples"):
            verbs.append("refine")
        for verb in verbs:
            t = reg.counter(f"serving.padding.{verb}.true_samples")
            p = reg.counter(f"serving.padding.{verb}.padded_samples")
            true_total += t
            padded_total += p
            out[verb] = {
                "true_samples": t,
                "padded_samples": p,
                "padding_waste_frac": round(1.0 - t / p, 4) if p else None,
            }
        out["padding_waste_frac"] = (
            round(1.0 - true_total / padded_total, 4) if padded_total else None
        )
        # per-(verb, bucket) request counts + true-sample totals — what
        # scripts/bucket_tune.py tunes edges from via /metrics
        by_bucket: Dict[str, Dict[str, Dict[str, int]]] = {}
        for verb in verbs:
            prefix = f"serving.padding.{verb}.bucket."
            rows: Dict[str, Dict[str, int]] = {}
            for name, value in reg.counters(prefix).items():  # prefix-stripped
                bucket_id, _, field = name.partition(".")
                if field in ("count", "true_samples"):
                    rows.setdefault(bucket_id, {})[field] = value
            if rows:
                by_bucket[verb] = rows
        if by_bucket:
            out["by_bucket"] = by_bucket
        # per-strategy true/padded totals + waste — "which tier pads most"
        by_strategy: Dict[str, Dict[str, Any]] = {}
        for name, value in reg.counters("serving.padding.strategy.").items():
            s, _, rest = name.partition(".")  # rest = "<verb>.<field>"
            _, _, field = rest.partition(".")
            row = by_strategy.setdefault(
                s, {"true_samples": 0, "padded_samples": 0}
            )
            if field in row:
                row[field] += value
        for row in by_strategy.values():
            row["padding_waste_frac"] = (
                round(1.0 - row["true_samples"] / row["padded_samples"], 4)
                if row["padded_samples"]
                else None
            )
        if by_strategy:
            out["by_strategy"] = by_strategy
        return out

    def strategy_stats(self) -> Dict[str, Any]:
        """The /metrics ``strategies`` block: per-strategy request/outcome
        tallies (one ``<verb>.<outcome>`` counter bump per request) — the
        live "which tier is eating the fleet" mix obs_top renders."""
        out: Dict[str, Any] = {}
        for name, value in self.hub.registry.counters("serving.strategy.").items():
            s, _, rest = name.partition(".")  # rest = "<verb>.<outcome>"
            row = out.setdefault(s, {"requests": 0})
            row[rest] = row.get(rest, 0) + value
            row["requests"] += value
        return out

    def tenant_stats(self) -> Dict[str, Any]:
        """Per-tenant request/outcome tallies (same schema as
        :meth:`strategy_stats`, keyed ``serving.tenant.<id>.<verb>.<outcome>``)
        — the ``by_tenant`` half of the /metrics ``tenants`` block."""
        out: Dict[str, Any] = {}
        for name, value in self.hub.registry.counters("serving.tenant.").items():
            t, _, rest = name.partition(".")  # rest = "<verb>.<outcome>"
            row = out.setdefault(t, {"requests": 0})
            row[rest] = row.get(rest, 0) + value
            row["requests"] += value
        return out

    def kill_replica(self, index: int, reason: str = "operator") -> None:
        """Mark one replica dead (chaos drills, operator action): the
        router stops routing to it from the next request on, the rest of
        the fleet keeps serving, and the death lands in the access log as
        a synthetic ``replica_death`` line — the access-log-resolvable
        event the chaos invariant greps for (non-``ok`` outcomes bypass
        sampling by contract)."""
        replica = self.pool.replicas[index]
        replica.kill(reason)
        self.counters.inc("replica_deaths")
        # the death is an events.jsonl event too (not only an access line):
        # obs_report answers "when did r1 die and who absorbed it" from the
        # run dir after the fact, long after /metrics is gone
        self._event(
            "replica_death",
            replica=index,
            reason=reason,
            routable=sum(1 for r in self.pool.replicas if r.routable()),
        )
        if self.access_log is not None:
            ctx = new_request_context()
            ctx.replica = index
            self.access_log.record(
                ctx, "replica_death", "dead", None, None,
                replica=index, reason=reason,
            )

    # ------------------------------------------------------------------
    # graceful drain + session spill/rehydrate
    # ------------------------------------------------------------------

    def _event(self, name: str, **fields: Any) -> None:
        """One structured serving event into <logs>/events.jsonl (no-op
        without a log dir); a failed append must never fail a request."""
        if self.events is None:
            return
        try:
            self.events.append(
                {"ts": time.time(), "event": name, "component": "serving", **fields}
            )
        except OSError:
            pass

    def _enter_request(self) -> None:
        """Admission gate + in-flight accounting: a request arriving after
        drain began is refused 503 + Retry-After (the gateway/load balancer
        already stopped routing here; this catches the race)."""
        with self._drain_lock:
            if self._draining:
                raise ServiceUnavailableError(
                    "backend is draining (shutting down); retry against "
                    "another backend",
                    retry_after_s=self.resilience.shed_retry_after_s,
                )
            self._inflight += 1

    def _exit_request(self) -> None:
        with self._drain_lock:
            self._inflight -= 1
            if self._inflight <= 0:
                self._drain_zero.notify_all()

    def _wait_inflight_drained(self, deadline_s: float) -> bool:
        """Block until every in-flight request (queued work included — its
        caller blocks in ``Future.result`` and so counts) completes, bounded
        by ``deadline_s``. True = drained clean."""
        end = time.monotonic() + deadline_s
        with self._drain_lock:
            while self._inflight > 0:
                remaining = end - time.monotonic()
                if remaining <= 0:
                    return False
                self._drain_zero.wait(timeout=remaining)
        return True

    def draining(self) -> bool:
        with self._drain_lock:
            return self._draining

    def http_scope(self):
        """Context manager the HTTP handler wraps one WHOLE request in
        (parse -> frontend call -> response write): the drain's in-flight
        gate must cover the socket write, or a drain completing between
        the frontend method returning and the handler serializing the body
        would let the process exit mid-write — a request counted completed
        that the client saw as a connection reset. Nested with the
        frontend methods' own gate (both count; both release)."""
        import contextlib

        @contextlib.contextmanager
        def _scope():
            self._enter_request()
            try:
                yield
            finally:
                self._exit_request()

        return _scope()

    def begin_drain(
        self, deadline_s: Optional[float] = None, reason: str = "sigterm"
    ) -> Dict[str, Any]:
        """Graceful drain: stop taking new work (healthz flips to
        ``draining`` — 503 — and new requests get 503 + Retry-After), let
        in-flight AND queued work complete under ``deadline_s`` (default
        ``serving.drain_deadline_s``), spill hot sessions to the run dir,
        close batchers/telemetry/logs cleanly. Idempotent: a second SIGTERM
        returns the first drain's verdict. Returns the drain info dict;
        ``deadline_exceeded`` means the supervisor should exit
        ``exit_codes.DRAIN_DEADLINE``."""
        if deadline_s is None:
            deadline_s = float(getattr(self.serving, "drain_deadline_s", 30.0))
        with self._drain_lock:
            already = self._draining
            self._draining = True
        if already:
            # a second SIGTERM must WAIT for the first drain's verdict —
            # returning early with an empty dict would let run_server shut
            # the server down mid-drain and report a lossy exit as clean
            self._drain_done.wait(timeout=deadline_s + 30.0)
            with self._drain_lock:
                return dict(self._drain_info)
        t0 = time.monotonic()
        inflight_at_start = self._inflight
        self._event(
            "drain_begin", reason=reason, deadline_s=deadline_s,
            inflight=inflight_at_start,
        )
        drained = self._wait_inflight_drained(deadline_s)
        spilled = 0
        spill_error = None
        if self.session_store is not None:
            try:
                spilled = self._spill_sessions()
            except Exception as exc:  # noqa: BLE001 — spill is best-effort
                spill_error = f"{type(exc).__name__}: {exc}"
        info: Dict[str, Any] = {
            "ok": drained and spill_error is None,
            "deadline_exceeded": not drained,
            "deadline_s": deadline_s,
            "inflight_at_drain": inflight_at_start,
            "spilled_sessions": spilled,
            "seconds": round(time.monotonic() - t0, 3),
        }
        if spill_error is not None:
            info["spill_error"] = spill_error
        with self._drain_lock:
            self._drain_info = info
        self._drain_done.set()
        self._event("drain_complete", **info)
        # bounded close on the deadline path: a worker parked in a hung
        # dispatch must not also hang the exiting process
        self.close(join_timeout_s=None if drained else 2.0)
        return info

    def _spill_sessions(self) -> int:
        """Spill every live adapted session (all replicas' caches) to the
        run dir, content-addressed + digest-wrapped (serving/sessions.py)."""
        from .sessions import encode_lineage

        count = 0
        ttl_s = float(self.serving.cache_ttl_s)
        # reverse fingerprint -> tenant map: only a LOADED tenant master can
        # have adapted sessions in any cache, so hosted_fingerprints covers
        # every spillable tenant entry without touching cold checkpoints
        tenant_by_fp: Dict[str, str] = {}
        if self.engine.registry is not None:
            tenant_by_fp = {
                fp: t
                for t, fp in self.engine.registry.hosted_fingerprints().items()
            }
        for replica in self.pool.replicas:
            for key, tree, age_s in replica.cache.snapshot_entries():
                fingerprint, strategy, digest = key
                tenant = None
                if fingerprint != self.engine.fingerprint:
                    tenant = tenant_by_fp.get(fingerprint)
                    if tenant is None:
                        continue
                if strategy == "protonet":
                    # a prototype table is one forward pass to recompute —
                    # not worth a spill file (and the rehydrate template is
                    # the parameter tree, which it doesn't match)
                    continue
                # a refined session's lineage (score trail, rollback ring,
                # quarantine flag) rides its spill file, so guard state
                # survives the restart with the weights it guards
                lineage = self._lineage_for(key)
                self.session_store.spill(
                    digest, tree, fingerprint, age_s=age_s, ttl_s=ttl_s,
                    strategy=strategy, tenant=tenant,
                    lineage=(
                        encode_lineage(lineage) if lineage is not None else None
                    ),
                )
                count += 1
        if count:
            self._event("sessions_spilled", count=count, dir=self.session_store.root)
        return count

    def _rehydrate_sessions(self) -> None:
        """Load spilled sessions (digest-verified, fingerprint-matched,
        TTL-honored — serving/sessions.py) into the replica each key is
        rendezvous-affine to, so the router finds them exactly where it
        will look. Anything unsafe is ignored: the fallback is the honest
        404 + re-adapt, never a stale answer."""
        from .sessions import decode_lineage

        lineage_raw: Dict[str, Dict[str, Any]] = {}
        entries, stats = self.session_store.load_all(
            fingerprint=self.engine.fingerprint,
            template=self.engine.state.params,
            tenant_fingerprints=(
                _LazyTenantFingerprints(self.engine.registry)
                if self.engine.registry is not None
                else None
            ),
            lineage_sink=lineage_raw,
        )
        for digest, tree, lived_s, strategy, tenant in entries:
            replica = max(
                self.pool.replicas,
                key=lambda r: rendezvous_score(digest, r.index),
            )
            # back-date by the TTL budget already consumed: a restart must
            # never extend a session's original expiry
            key = self._cache_key(digest, strategy, tenant)
            replica.cache.put(key, tree, age_s=lived_s)
            raw = lineage_raw.get(digest)
            if raw is not None:
                # restore the refinement guard's memory with the weights it
                # guards; an undecodable lineage rehydrates as lineage-free
                # (decode_lineage returns None), never with made-up history
                lineage = decode_lineage(raw, self.engine.state.params)
                if lineage is not None:
                    with self._lineage_lock:
                        self._lineages[key] = lineage
        self._session_stats = dict(stats, rehydrated=stats["loaded"])
        if any(stats.values()):
            self._event("sessions_rehydrated", **stats)
            print(
                "serving sessions: rehydrated "
                f"{stats['loaded']} (stale {stats['stale']}, corrupt "
                f"{stats['corrupt']}, foreign {stats['foreign']})",
                flush=True,
            )

    def adapt(
        self,
        x_support,
        y_support,
        ctx: Optional[RequestContext] = None,
        strategy: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> Dict[str, Any]:
        # strategy/tenant resolution BEFORE the logged/gated section: an
        # unknown name raises ValueError here, which the HTTP layer maps to
        # 400 + its own bad_request access line (a valid-but-unconfigured
        # strategy passes — strict mode rejects its unplanned program
        # downstream; an unregistered tenant never does)
        strategy = validate_request_strategy(strategy, self.engine.strategies)
        tenant = validate_request_tenant(tenant, self.engine.registry)
        ctx = self._request_ctx(ctx)
        if ctx is not None:
            ctx.strategy = strategy
            ctx.tenant = tenant
        t0 = time.monotonic()
        entered = False
        quota_label = None
        try:
            # drain gate + in-flight accounting: a request that passes here
            # is guaranteed to complete (or fail honestly) before a
            # graceful drain lets the process exit
            self._enter_request()
            entered = True
            # per-tenant admission rides next: a tenant over its rate or
            # inflight quota sheds 429 HERE, before any queue or dispatch,
            # so it cannot degrade other tenants' p99
            quota_label = self._acquire_quota(tenant)
            # the request's flow STARTS here (ph "s"); the batcher flush
            # steps it ("t") and the engine dispatch finishes it ("f") — one
            # linked arc HTTP thread -> worker flush -> device dispatch
            with self.hub.span(
                "serve.adapt", flows=flow_start(ctx),
                trace=ctx.trace_id if ctx else None,
            ):
                x, y = self.engine._flatten_support(x_support, y_support)
                digest = support_digest(
                    x, y, self.engine.num_steps, strategy, tenant=tenant
                )
                key = self._cache_key(digest, strategy, tenant)
                # affinity on the cache key: this session's fast weights
                # live (or will live) on exactly this replica's cache (the
                # digest folds the tenant in, so tenants spread + stick
                # independently)
                replica = self.router.route(digest, ctx=ctx)
                cached = replica.cache.get(key, ctx=ctx) is not None
                if cached and self._quarantined(key):
                    # the one exit from quarantine: an explicit re-adapt
                    # from the masters. The hit is treated as a miss — the
                    # poisoned entry is recomputed below and its lineage
                    # (streak, quarantine flag, rollback ring) is discarded
                    cached = False
                    if ctx is not None:
                        ctx.cache_hit = False
                    self._pop_lineage(key)
                    self.counters.inc("session_readapts")
                    self._event(
                        "session_readapted", session=digest, strategy=strategy
                    )
                if not cached:
                    # shed at the router BEFORE the request queues at the
                    # replica (a cache hit above costs nothing — only real
                    # work passes admission)
                    self.router.admit(replica)
                    self._check_resident_quota(tenant, key[0])
                    bucket = self.engine.support_bucket(x.shape[0])
                    if ctx is not None:
                        ctx.bucket = bucket
                        ctx.true_size = int(x.shape[0])
                    # the batcher group key carries the strategy (and, for
                    # non-default tenants, the tenant): requests of
                    # different strategies compile different programs, and
                    # different tenants adapt against different masters —
                    # neither may ever share a flush
                    group = (
                        (tenant, strategy, bucket)
                        if tenant is not None
                        else (strategy, bucket)
                    )
                    fast_weights = replica.dispatch(
                        replica.adapt_batcher, group, (x, y), ctx
                    )
                    self._note_padding("adapt", x.shape[0], bucket, strategy)
                    replica.cache.put(key, fast_weights)
                    # a fresh adapt is version zero: any lineage left from a
                    # previous (expired or re-adapted) life of this key
                    # must not guard the new weights
                    self._pop_lineage(key)
                    if tenant is not None:
                        self._sweep_pagers()
        except BaseException as exc:
            outcome, status = self._failure_of(exc)
            self._count_strategy(strategy, "adapt", outcome)
            self._count_tenant(tenant, "adapt", outcome)
            self._record_access(ctx, "adapt", outcome, status, time.monotonic() - t0)
            raise
        finally:
            if quota_label is not None:
                self.quotas.release(quota_label)
            if entered:
                self._exit_request()
        elapsed = time.monotonic() - t0
        self.latency.record("adapt_cached" if cached else "adapt", elapsed)
        if strategy != self.engine.strategies[0]:
            # non-default strategies get their own latency phase on top of
            # the aggregate (the default keeps the historical schema alone)
            self.latency.record(f"adapt@{strategy}", elapsed)
        self._count_strategy(strategy, "adapt", "ok")
        self._count_tenant(tenant, "adapt", "ok")
        self._record_access(ctx, "adapt", "ok", 200, elapsed)
        out = {
            "adaptation_id": digest,
            "cached": cached,
            "strategy": strategy,
            "support_size": int(x.shape[0]),
            "latency_ms": round(elapsed * 1e3, 3),
        }
        if tenant is not None:
            out["tenant"] = tenant
        if ctx is not None:
            out["trace_id"] = ctx.trace_id
            out["timing"] = ctx.timing_ms(elapsed)
        return out

    def _probe_score(
        self, replica, fast_weights, x_probe, y_probe, strategy, tenant, ctx
    ) -> float:
        """Held-out cross-entropy of ``fast_weights`` on the session's
        probe — the refinement guard's yardstick. Scored through the
        ordinary predict batcher (a PLANNED program: the guard costs zero
        extra compiles and the sealed strict-mode invariant holds).
        Non-finite weights score non-finite honestly: numpy's max
        propagates NaN, so a poisoned tree can never look like a pass."""
        bucket = self.engine.query_bucket(x_probe.shape[0])
        group = (
            (tenant, strategy, bucket)
            if tenant is not None
            else (strategy, bucket)
        )
        probs = replica.dispatch(
            replica.predict_batcher, group, (fast_weights, x_probe), ctx
        )
        p = np.asarray(probs, np.float64)
        idx = np.asarray(y_probe, np.int64)
        picked = p[np.arange(idx.shape[0]), idx]
        return float(np.mean(-np.log(np.maximum(picked, 1e-12))))

    def refine(
        self,
        session_id: str,
        x_support,
        y_support,
        ctx: Optional[RequestContext] = None,
        strategy: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Guarded online refinement of a persistent session (ISSUE 17):
        continue the K-step rollout from the session's CACHED fast weights
        (``engine.refine_batch``) instead of re-adapting from the masters,
        then score the candidate on the session's held-out probe before
        committing. A non-finite or regressed (past
        ``serving.refine_regress_tol``) candidate is discarded — the cache
        keeps the last-good version, the response says ``rolled_back:
        true`` — and ``serving.refine_quarantine_after`` consecutive
        regressions quarantine the session (409 + Retry-After; the only
        exit is an explicit re-adapt). ProtoNet sessions have no
        fast-weight rollout: their refresh recomputes prototypes through
        the (planned) adapt program against the new support set, under the
        same guard."""
        if not getattr(self.serving, "refine_enabled", False):
            # -> the HTTP 400 branch: refinement must be configured on
            raise ValueError(
                "refinement is disabled (serving.refine_enabled=false)"
            )
        strategy = validate_request_strategy(strategy, self.engine.strategies)
        tenant = validate_request_tenant(tenant, self.engine.registry)
        ctx = self._request_ctx(ctx)
        if ctx is not None:
            ctx.strategy = strategy
            ctx.tenant = tenant
        t0 = time.monotonic()
        entered = False
        quota_label = None
        rolled_back = False
        score: Optional[float] = None
        try:
            self._enter_request()
            entered = True
            quota_label = self._acquire_quota(tenant)
            with self.hub.span(
                "serve.refine", flows=flow_start(ctx),
                trace=ctx.trace_id if ctx else None,
            ):
                key = self._cache_key(session_id, strategy, tenant)
                # same affinity key as the adapt that cached the session:
                # the refine lands on the replica holding its fast weights
                replica = self.router.route(session_id, ctx=ctx)
                fast_weights = replica.cache.get(key, ctx=ctx)
                if fast_weights is None:
                    raise UnknownAdaptationError(
                        f"unknown or expired session {session_id!r} for "
                        f"strategy {strategy!r}; re-send the support set "
                        "via /adapt"
                    )
                lineage = self._lineage_for(key, create=True)
                if lineage.quarantined:
                    raise SessionQuarantinedError(
                        f"session {session_id!r} is quarantined after "
                        f"{lineage.consecutive_regressions} consecutive "
                        "regressed refinements; re-adapt from the masters "
                        "via /adapt",
                        retry_after_s=self.resilience.shed_retry_after_s,
                    )
                self.router.admit(replica)
                x, y = self.engine._flatten_support(x_support, y_support)
                with self._lineage_lock:
                    if lineage.probe is None:
                        # first refine: carve a persistent held-out probe
                        # from THIS support set — every later refinement
                        # scores against the same yardstick. Evenly spaced
                        # indices, because support sets arrive class-major:
                        # holding out a contiguous tail would hold out a
                        # whole class, and the train slice losing a class
                        # makes every first refinement look like a
                        # regression
                        n = int(x.shape[0])
                        if n < 2:
                            raise ValueError(
                                "refinement needs >= 2 support samples "
                                "(one must be held out for the guard)"
                            )
                        n_hold = min(
                            max(1, int(round(n * float(getattr(
                                self.serving, "refine_holdout_frac", 0.25
                            ))))),
                            n - 1,
                        )
                        stride = max(1, n // n_hold)
                        hold = np.zeros(n, bool)
                        hold[np.arange(n)[stride - 1::stride][:n_hold]] = True
                        lineage.probe = (
                            np.asarray(x[hold]), np.asarray(y[hold])
                        )
                        x_train, y_train = x[~hold], y[~hold]
                    else:
                        # later refines train on the full new support set;
                        # the probe stays the session's fixed yardstick
                        x_train, y_train = x, y
                    probe_x, probe_y = lineage.probe
                if lineage.last_good_score is None:
                    # baseline: what the CURRENT weights score — the first
                    # guard comparison needs a last-good to regress against
                    base = self._probe_score(
                        replica, fast_weights, probe_x, probe_y, strategy,
                        tenant, ctx,
                    )
                    if np.isfinite(base):
                        with self._lineage_lock:
                            lineage.set_baseline(base)
                bucket = self.engine.support_bucket(x_train.shape[0])
                if ctx is not None:
                    ctx.bucket = bucket
                    ctx.true_size = int(x_train.shape[0])
                group = (
                    (tenant, strategy, bucket)
                    if tenant is not None
                    else (strategy, bucket)
                )
                if strategy == "protonet":
                    # no fast-weight rollout to continue: the refresh
                    # recomputes prototypes from the new support set
                    # through the planned adapt program
                    refined = replica.dispatch(
                        replica.adapt_batcher, group, (x_train, y_train), ctx
                    )
                else:
                    refined = replica.dispatch(
                        replica.refine_batcher, group,
                        (fast_weights, x_train, y_train), ctx,
                    )
                self._note_padding(
                    "refine", x_train.shape[0], bucket, strategy
                )
                score = self._probe_score(
                    replica, refined, probe_x, probe_y, strategy, tenant, ctx
                )
                tol = float(getattr(self.serving, "refine_regress_tol", 0.5))
                last_good = lineage.last_good_score
                regressed = (not np.isfinite(score)) or (
                    last_good is not None and score > last_good + tol
                )
                if regressed:
                    with self._lineage_lock:
                        streak = lineage.reject()
                    rolled_back = True
                    self.counters.inc("refine_rollbacks")
                    self._event(
                        "refine_rollback",
                        session=session_id,
                        strategy=strategy,
                        score=(float(score) if np.isfinite(score) else None),
                        last_good=last_good,
                        streak=streak,
                        **({"tenant": tenant} if tenant else {}),
                    )
                    if streak >= int(getattr(
                        self.serving, "refine_quarantine_after", 3
                    )):
                        with self._lineage_lock:
                            lineage.quarantined = True
                        self.counters.inc("session_quarantines")
                        self._event(
                            "session_quarantined",
                            session=session_id,
                            strategy=strategy,
                            streak=streak,
                        )
                        raise SessionQuarantinedError(
                            f"session {session_id!r} quarantined after "
                            f"{streak} consecutive regressed refinements; "
                            "re-adapt from the masters via /adapt",
                            retry_after_s=self.resilience.shed_retry_after_s,
                        )
                else:
                    with self._lineage_lock:
                        lineage.commit(fast_weights, score)
                    replica.cache.put(key, refined)
                    self.counters.inc("refines")
                    self._event(
                        "refine_commit",
                        session=session_id,
                        strategy=strategy,
                        score=float(score),
                        refine_count=lineage.refine_count,
                        **({"tenant": tenant} if tenant else {}),
                    )
                if tenant is not None:
                    self._sweep_pagers()
        except BaseException as exc:
            outcome, status = self._failure_of(exc)
            self._count_strategy(strategy, "refine", outcome)
            self._count_tenant(tenant, "refine", outcome)
            self._record_access(
                ctx, "refine", outcome, status, time.monotonic() - t0
            )
            raise
        finally:
            if quota_label is not None:
                self.quotas.release(quota_label)
            if entered:
                self._exit_request()
        elapsed = time.monotonic() - t0
        self.latency.record("refine", elapsed)
        if strategy != self.engine.strategies[0]:
            self.latency.record(f"refine@{strategy}", elapsed)
        self._count_strategy(strategy, "refine", "ok")
        self._count_tenant(tenant, "refine", "ok")
        self._record_access(ctx, "refine", "ok", 200, elapsed)
        out = {
            "adaptation_id": session_id,
            "refined": True,
            # honest verdict: a rolled-back refinement is still a 200 (the
            # session is SERVABLE, at its last-good version) but says so
            "rolled_back": rolled_back,
            "refine_count": lineage.refine_count,
            "consecutive_regressions": lineage.consecutive_regressions,
            "score": (
                float(score)
                if score is not None and np.isfinite(score)
                else None
            ),
            "strategy": strategy,
            "support_size": int(x_train.shape[0]),
            "latency_ms": round(elapsed * 1e3, 3),
        }
        if tenant is not None:
            out["tenant"] = tenant
        if ctx is not None:
            out["trace_id"] = ctx.trace_id
            out["timing"] = ctx.timing_ms(elapsed)
        return out

    def predict(
        self,
        adaptation_id: str,
        x_query,
        ctx: Optional[RequestContext] = None,
        strategy: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> np.ndarray:
        strategy = validate_request_strategy(strategy, self.engine.strategies)
        tenant = validate_request_tenant(tenant, self.engine.registry)
        ctx = self._request_ctx(ctx)
        if ctx is not None:
            ctx.strategy = strategy
            ctx.tenant = tenant
        t0 = time.monotonic()
        entered = False
        quota_label = None
        try:
            self._enter_request()
            entered = True
            quota_label = self._acquire_quota(tenant)
            with self.hub.span(
                "serve.predict", flows=flow_start(ctx),
                trace=ctx.trace_id if ctx else None,
            ):
                # same affinity key as the adapt that cached these weights
                # (the adaptation id IS the support digest), so the session
                # lands on the replica whose cache holds them. After a
                # replica death the key remaps and the miss below is the
                # honest failover answer: re-adapt, never a stale result.
                # A predict naming the WRONG strategy — or the wrong TENANT
                # (the key carries the tenant's checkpoint fingerprint) —
                # for its id misses the (fingerprint, strategy, id) key the
                # same honest way: a prototype table is never pushed
                # through a gradient strategy's predict program, and tenant
                # B can never resolve tenant A's weights.
                replica = self.router.route(adaptation_id, ctx=ctx)
                key = self._cache_key(adaptation_id, strategy, tenant)
                fast_weights = replica.cache.get(key, ctx=ctx)
                if fast_weights is None:
                    raise UnknownAdaptationError(
                        f"unknown or expired adaptation_id {adaptation_id!r} "
                        f"for strategy {strategy!r}; re-send the support set "
                        "via /adapt"
                    )
                if self._quarantined(key):
                    # a quarantined session's weights are untrusted —
                    # refusing to predict through them is the honest
                    # alternative to serving a silently-poisoned answer
                    raise SessionQuarantinedError(
                        f"session {adaptation_id!r} is quarantined after "
                        "consecutive regressed refinements; re-adapt from "
                        "the masters via /adapt",
                        retry_after_s=self.resilience.shed_retry_after_s,
                    )
                self.router.admit(replica)
                x = np.asarray(x_query, np.float32)
                bucket = self.engine.query_bucket(x.shape[0])
                if ctx is not None:
                    ctx.bucket = bucket
                    ctx.true_size = int(x.shape[0])
                group = (
                    (tenant, strategy, bucket)
                    if tenant is not None
                    else (strategy, bucket)
                )
                probs = replica.dispatch(
                    replica.predict_batcher, group, (fast_weights, x), ctx,
                )
                self._note_padding("predict", x.shape[0], bucket, strategy)
                if tenant is not None:
                    self._sweep_pagers()
        except BaseException as exc:
            outcome, status = self._failure_of(exc)
            self._count_strategy(strategy, "predict", outcome)
            self._count_tenant(tenant, "predict", outcome)
            self._record_access(ctx, "predict", outcome, status, time.monotonic() - t0)
            raise
        finally:
            if quota_label is not None:
                self.quotas.release(quota_label)
            if entered:
                self._exit_request()
        elapsed = time.monotonic() - t0
        self.latency.record("predict", elapsed)
        if strategy != self.engine.strategies[0]:
            self.latency.record(f"predict@{strategy}", elapsed)
        self._count_strategy(strategy, "predict", "ok")
        self._count_tenant(tenant, "predict", "ok")
        self._record_access(ctx, "predict", "ok", 200, elapsed)
        return probs

    def adapt_predict(
        self,
        x_support,
        y_support,
        x_query,
        ctx: Optional[RequestContext] = None,
        strategy: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> Dict[str, Any]:
        # one client call, two hops: both access-log lines (verb adapt +
        # verb predict) share the request's trace id
        ctx = self._request_ctx(ctx)
        t0 = time.monotonic()
        info = self.adapt(
            x_support, y_support, ctx=ctx, strategy=strategy, tenant=tenant
        )
        if ctx is not None:
            ctx.access_logged = False  # the predict hop logs its own line
        probs = self.predict(
            info["adaptation_id"], x_query, ctx=ctx, strategy=strategy,
            tenant=tenant,
        )
        if ctx is not None:
            # adapt() stamped an adapt-hop-only breakdown into info; the
            # response must describe the WHOLE request (queue/dispatch from
            # the predict hop — the adapt hop's detail is its access line)
            info["timing"] = ctx.timing_ms(time.monotonic() - t0)
        return {**info, "probs": probs}

    # ------------------------------------------------------------------

    def healthz(self) -> Dict[str, Any]:
        # degraded = serving, but in a mode a load balancer / operator should
        # react to: the engine breaker is open (device dispatch failing) or
        # half-open (probing). The HTTP layer returns 503 only while OPEN so
        # orchestrators drain traffic away; half-open stays 200 (body still
        # says degraded) because the breaker can only close via real requests
        # passing as probes — a drained backend would stay degraded forever.
        # OPERATIONS.md "Degraded modes".
        solo = len(self.pool) == 1
        degraded = []
        for replica in self.pool.replicas:
            tag = "" if solo else f":r{replica.index}"
            if not replica.alive:
                degraded.append(f"replica_dead{tag}")
            elif replica.breaker.state != "closed":
                degraded.append(f"breaker_{replica.breaker.state}{tag}")
        routable = sum(1 for r in self.pool.replicas if r.routable())
        prewarm = self.prewarm_status()
        # the status field is the MACHINE-READABLE membership contract a
        # gateway/orchestrator switches on — exactly one of four values,
        # schema-pinned by test: "ok" (route), "degraded" (route unless
        # routable==0 — the body names what is down), "warming" (alive,
        # hold NEW traffic until the AOT prewarm lands), "draining" (alive,
        # finishing in-flight work, never route NEW work — takes precedence
        # over everything: a draining replica is leaving no matter how
        # healthy it looks)
        if self.draining():
            status = "draining"
        elif prewarm["status"] == "warming":
            status = "warming"
        else:
            status = "degraded" if degraded else "ok"
        return {
            "status": status,
            "degraded": degraded,
            "replicas": len(self.pool),
            # the HTTP layer's 503 signal: zero routable replicas means no
            # request can be served — drain traffic; a PARTIALLY degraded
            # fleet keeps answering 200 with the body naming what is down
            "routable": routable,
            "prewarm": prewarm,
            "breaker": self.breaker.snapshot(),
            "platform": jax.default_backend(),
            "checkpoint_fingerprint": self.engine.fingerprint,
            "model": self.engine.system.model.name,
            "num_classes": self.engine.num_classes,
            "adapt_steps": self.engine.num_steps,
            "uptime_s": round(time.monotonic() - self._started, 1),
        }

    def metrics(self) -> Dict[str, Any]:
        out = {
            "prewarm": self.prewarm_status(),
            "latency": self.latency.summary(),
            # fleet aggregates under the historical single-replica keys
            # (counts summed, rates recomputed) — scrapers keep working;
            # the per-replica story is the "replicas" block below
            "cache": self.pool.cache_stats(),
            "adapt_batcher": self.pool.batcher_stats("adapt"),
            "predict_batcher": self.pool.batcher_stats("predict"),
            "compiled": self.engine.compile_counts(),
            "router": self.router.stats(),
            "replicas": self.pool.stats(),
            "padding": self.padding_stats(),
            "strategies": self.strategy_stats(),
            "resilience": {
                **self.counters.snapshot(),
                "breaker": self.breaker.snapshot(),
                "injected_faults": self.engine.injector.stats(),
            },
            "uptime_s": round(time.monotonic() - self._started, 1),
        }
        if self.engine.registry is not None:
            # the multi-tenant surface (ISSUE: registry + pager + quotas +
            # per-tenant tallies) under one scrape-able block — obs_top's
            # live tenant row and obs_report's tenant table read this
            tenants_block: Dict[str, Any] = {
                "registry": self.engine.registry.stats(),
                "by_tenant": self.tenant_stats(),
            }
            pager = self.pool.pager_stats()
            if pager is not None:
                tenants_block["pager"] = pager
            if self.quotas is not None and self.quotas.enabled:
                tenants_block["quotas"] = self.quotas.stats()
            out["tenants"] = tenants_block
        with self._drain_lock:
            out["drain"] = {
                "draining": self._draining,
                "inflight": self._inflight,
                **self._drain_info,
            }
        if self.session_store is not None:
            # rehydrate verdicts + what is parked on disk right now — the
            # spill->rehydrate round-trip is a scrape-able number
            out["sessions"] = {
                **self._session_stats,
                "pending_on_disk": self.session_store.pending(),
            }
        if getattr(self.serving, "refine_enabled", False):
            # the refinement guard's scoreboard (only with the feature on:
            # a refine-off /metrics payload is byte-identical). Lives under
            # "sessions" — refinement is session state — created here even
            # without a session store (in-memory-only deployments refine too)
            events = self.counters.snapshot()
            with self._lineage_lock:
                lineages = list(self._lineages.values())
            out.setdefault("sessions", {})["refine"] = {
                "refines": events.get("refines", 0),
                "rollbacks": events.get("refine_rollbacks", 0),
                "quarantines": events.get("session_quarantines", 0),
                "readapts": events.get("session_readapts", 0),
                "active_lineages": len(lineages),
                "quarantined": sum(1 for l in lineages if l.quarantined),
                "snapshot_bytes": sum(l.snapshot_bytes() for l in lineages),
            }
            out["refine_batcher"] = self.pool.batcher_stats("refine")
        if self.access_log is not None:
            out["access_log"] = self.access_log.stats()
        if self._memory is not None:
            # HBM watermarks on the scrape surface too (obs_top reads them
            # live), not only inside hub snapshots
            out["memory"] = self._memory.snapshot()
        return out

    def metrics_prometheus(self) -> str:
        """The ``/metrics?format=prom`` body: OpenMetrics text over the one
        registry that backs every serving number."""
        return prometheus_text(self.hub.registry)

    def close(self, join_timeout_s: float = None) -> None:
        if self._closed:
            return
        self._closed = True
        for wd in self._watchdogs:
            wd.stop()
        self.pool.close(join_timeout_s)
        if self._graftsan is not None:
            # thread-leak audit: workers/watchdogs this frontend spawned
            # must all be joined by now (reported as thread_leak events)
            self._graftsan.audit_thread_leaks(
                "ServingFrontend.close",
                baseline=self._graftsan_thread_baseline,
            )
        if self.access_log is not None:
            self.access_log.close()
        if self.events is not None:
            self.events.close()


def frontend_from_run_dir(
    run_dir: str,
    checkpoint_idx="best",
    cfg: Optional[Config] = None,
    replicas: Optional[int] = None,
) -> ServingFrontend:
    engine = AdaptationEngine.from_run_dir(run_dir, checkpoint_idx, cfg=cfg)
    # a run-dir frontend owns the run's logs/: access.jsonl lands next to
    # telemetry.jsonl and events.jsonl so trace_merge finds them together
    return ServingFrontend(
        engine, access_log_dir=os.path.join(run_dir, "logs"), replicas=replicas
    )


# ---------------------------------------------------------------------------
# HTTP layer
# ---------------------------------------------------------------------------


class _Handler(BaseHTTPRequestHandler):
    # the frontend is attached to the server instance by make_http_server
    protocol_version = "HTTP/1.1"
    # per-request context/clock, reset by _begin_request at the top of every
    # handler (one instance serves a keep-alive connection sequentially)
    _ctx: Optional[RequestContext] = None
    _t0: float = 0.0

    def _send_json(
        self, code: int, payload: Dict[str, Any], headers: Optional[Dict[str, str]] = None
    ) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if self._ctx is not None:
            # every response names its request: the grep handle joining the
            # wire, access.jsonl, and the exported trace flows
            self.send_header("X-Request-Id", self._ctx.trace_id)
            self.send_header("traceparent", format_traceparent(self._ctx))
        for name, value in (headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, code: int, body: str, content_type: str) -> None:
        raw = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(raw)))
        if self._ctx is not None:
            self.send_header("X-Request-Id", self._ctx.trace_id)
        self.end_headers()
        self.wfile.write(raw)

    def _read_json(self) -> Dict[str, Any]:
        length = int(self.headers.get("Content-Length", 0))
        if length <= 0:
            return {}
        return json.loads(self.rfile.read(length))

    def _begin_request(self, frontend: "ServingFrontend"):
        """Adopt/mint the request context (W3C ``traceparent``) and start
        the per-request clock. None when observability is off — the
        request path must stay bit-identical to the un-instrumented build
        (no extra headers, no body keys, no files)."""
        self._t0 = time.monotonic()
        self._ctx = (
            parse_traceparent(self.headers.get("traceparent"))
            if frontend.hub.enabled
            else None
        )
        return self._ctx

    def _log_http(self, frontend, outcome: str, status: int) -> None:
        """Access-log a terminal HTTP outcome the frontend methods never
        saw (no-op for the ones they did — ``ctx.access_logged``)."""
        frontend.log_http_access(
            self._ctx, self.path, outcome, status, time.monotonic() - self._t0
        )

    def log_message(self, fmt, *args):
        # quiet by default: the STRUCTURED access log (logs/access.jsonl,
        # observability/context.py) carries what these lines would, plus
        # the trace id / timing breakdown stdlib lines cannot
        pass

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        frontend: ServingFrontend = self.server.frontend  # type: ignore[attr-defined]
        ctx = self._begin_request(frontend)
        try:
            split = urllib.parse.urlsplit(self.path)
            path = split.path
            query = urllib.parse.parse_qs(split.query)
            if path == "/healthz":
                health = frontend.healthz()
                # 503 while NO replica is routable (every breaker OPEN or
                # every replica dead — drain traffic) or while the AOT
                # prewarm is still compiling (hold traffic off a cold
                # replica — body status "warming", distinct from
                # "degraded"); half-open replicas stay routable (probes are
                # real requests) and a PARTIALLY degraded fleet keeps
                # answering 200 — the body names exactly what is down
                code = (
                    HTTP_UNAVAILABLE
                    if health["routable"] == 0
                    or health["status"] in ("warming", "draining")
                    else 200
                )
                if code != 200:
                    # the chaos invariant: every non-200 response has an
                    # access-log line carrying its request id
                    self._log_http(frontend, health["status"], code)
                self._send_json(code, health)
            elif path == "/metrics":
                if query.get("format") == ["prom"]:
                    self._send_text(
                        200,
                        frontend.metrics_prometheus(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                else:
                    self._send_json(200, frontend.metrics())
            else:
                self._log_http(frontend, "not_found", 404)
                self._send_json(404, {"error": f"unknown path {self.path}"})
        except Exception as exc:  # noqa: BLE001 — keep the server alive
            self._log_http(frontend, "error", 500)
            self._send_json(500, {"error": f"internal error: {exc!r}"})

    def do_POST(self):  # noqa: N802
        frontend: ServingFrontend = self.server.frontend  # type: ignore[attr-defined]
        ctx = self._begin_request(frontend)
        try:
            # the whole request — body parse through RESPONSE WRITE — sits
            # inside the drain gate: a graceful drain cannot declare this
            # request complete until its bytes are on the wire
            with frontend.http_scope():
                # fault seam for handler-level drills (raise -> 500, delay)
                # — fired AFTER the body is drained so an injected 500 on a
                # keep-alive connection doesn't leave unread body bytes to
                # be misparsed as the client's next request
                req = self._read_json()
                frontend.engine.injector.fire("serving.http")
                # optional per-request strategy (core/strategies.py) and
                # tenant (serving/tenancy.py): absent = the deployment
                # default; unknown name => ValueError => the 400 branch
                # below — the wire contract for a typo'd tier or tenant
                strategy = req.get("strategy")
                tenant = req.get("tenant")
                if self.path == "/adapt":
                    if req.get("refine"):
                        # refinement rides /adapt (same wire verb, same
                        # gateway affinity path): a truthy "refine" +
                        # "session_id" continues the named session's
                        # rollout in place. A request WITHOUT the field
                        # takes the branch below byte-identically.
                        out = frontend.refine(
                            req["session_id"], req["x_support"],
                            req["y_support"], ctx=ctx,
                            strategy=strategy, tenant=tenant,
                        )
                    else:
                        out = frontend.adapt(
                            req["x_support"], req["y_support"], ctx=ctx,
                            strategy=strategy, tenant=tenant,
                        )
                    self._send_json(200, out)
                elif self.path == "/predict":
                    probs = frontend.predict(
                        req["adaptation_id"], req["x_query"], ctx=ctx,
                        strategy=strategy, tenant=tenant,
                    )
                    body = {"probs": probs.tolist()}
                    if ctx is not None:
                        body["trace_id"] = ctx.trace_id
                        body["timing"] = ctx.timing_ms(time.monotonic() - self._t0)
                    self._send_json(200, body)
                elif self.path == "/adapt_predict":
                    out = frontend.adapt_predict(
                        req["x_support"], req["y_support"], req["x_query"],
                        ctx=ctx, strategy=strategy, tenant=tenant,
                    )
                    out["probs"] = out["probs"].tolist()
                    self._send_json(200, out)
                else:
                    self._log_http(frontend, "not_found", 404)
                    self._send_json(404, {"error": f"unknown path {self.path}"})
        except SessionQuarantinedError as exc:
            # refinement-guard quarantine: honest 409 + Retry-After — the
            # client must re-adapt from the masters, never read through a
            # poisoned session
            self._send_json(
                exc.status,
                {
                    "error": str(exc),
                    "retry_after_s": exc.retry_after_s,
                    "quarantined": True,
                },
                headers={"Retry-After": str(max(1, int(round(exc.retry_after_s))))},
            )
        except ServiceUnavailableError as exc:
            # load shed / breaker open (503) or router admission (429):
            # tell the client when to come back
            self._send_json(
                exc.status,
                {"error": str(exc), "retry_after_s": exc.retry_after_s},
                # Retry-After is integer seconds (RFC 9110); round up so a
                # sub-second hint doesn't become an immediate retry storm
                headers={"Retry-After": str(max(1, int(round(exc.retry_after_s))))},
            )
        except DeadlineExceededError as exc:
            self._send_json(HTTP_DEADLINE, {"error": str(exc)})
        except UnknownAdaptationError as exc:
            self._send_json(404, {"error": str(exc)})
        except (KeyError, ValueError, TypeError) as exc:
            self._log_http(frontend, "bad_request", 400)
            self._send_json(400, {"error": f"bad request: {exc!r}"})
        except Exception as exc:  # noqa: BLE001 — keep the server alive
            self._log_http(frontend, "error", 500)
            self._send_json(500, {"error": f"internal error: {exc!r}"})


def make_http_server(
    frontend: ServingFrontend, host: str = "127.0.0.1", port: int = 0
) -> ThreadingHTTPServer:
    """Bind (port 0 = ephemeral, for tests) but do not serve; the caller owns
    ``serve_forever`` / ``shutdown``."""
    server = ThreadingHTTPServer((host, port), _Handler)
    server.frontend = frontend  # type: ignore[attr-defined]
    server.daemon_threads = True
    return server


def serve_forever(frontend: ServingFrontend, host: str, port: int) -> None:
    server = make_http_server(frontend, host, port)
    addr = server.server_address
    print(
        f"serving on http://{addr[0]}:{addr[1]} "
        f"(checkpoint {frontend.engine.fingerprint[:12]}, "
        f"platform {jax.default_backend()}, "
        f"{len(frontend.pool)} replica(s))",
        flush=True,
    )
    try:
        server.serve_forever()
    finally:
        server.server_close()
        frontend.close()


def drain_exit_code(info: Dict[str, Any]) -> int:
    """The process rc for one drain verdict: 0 for a clean drain,
    ``exit_codes.DRAIN_DEADLINE`` when in-flight work outlived the deadline
    (the supervisor must treat the replica's last seconds as lossy)."""
    return DRAIN_DEADLINE if info.get("deadline_exceeded") else OK


def run_server(
    frontend: ServingFrontend,
    host: str,
    port: int,
    install_signal_handlers: bool = True,
    on_bound=None,
) -> int:
    """Serve until SIGTERM/SIGINT, then drain gracefully and return the
    process rc (``drain_exit_code``): the signal flips /healthz to
    ``draining`` (the gateway stops routing new work), in-flight + queued
    requests complete under ``serving.drain_deadline_s``, hot sessions
    spill to the run dir, logs close, and a clean drain exits 0.
    ``on_bound(host, port)`` fires after bind — the ephemeral-port
    discovery hook for drills and supervisors."""
    server = make_http_server(frontend, host, port)
    addr = server.server_address
    rc_box = {"rc": OK}

    def _drain_and_stop(reason: str) -> None:
        info = frontend.begin_drain(reason=reason)
        rc_box["rc"] = drain_exit_code(info)
        print(
            f"serving drain: {'clean' if info.get('ok') else 'DEADLINE EXCEEDED'} "
            f"in {info.get('seconds')}s "
            f"({info.get('spilled_sessions', 0)} session(s) spilled)",
            flush=True,
        )
        server.shutdown()

    def _on_signal(signum, frame):  # noqa: ARG001 — signal contract
        name = signal.Signals(signum).name.lower()
        # the handler must return immediately; the drain runs on its own
        # thread while serve_forever keeps answering in-flight work
        threading.Thread(
            target=_drain_and_stop, args=(name,), name="serving-drain", daemon=True
        ).start()

    if install_signal_handlers:
        signal.signal(signal.SIGTERM, _on_signal)
        signal.signal(signal.SIGINT, _on_signal)
    print(
        f"serving on http://{addr[0]}:{addr[1]} "
        f"(checkpoint {frontend.engine.fingerprint[:12]}, "
        f"platform {jax.default_backend()}, "
        f"{len(frontend.pool)} replica(s))",
        flush=True,
    )
    if on_bound is not None:
        on_bound(addr[0], addr[1])
    try:
        server.serve_forever()
    finally:
        server.server_close()
        frontend.close()
    return rc_box["rc"]
