"""Bounded retry with exponential backoff + jitter, fake-clock injectable.

One retry policy for every transient-failure seam (loader episode I/O,
serving clients, scripts talking through the wedging tunnel) instead of
ad-hoc ``for attempt in range(...)`` loops. Everything time-shaped is
injectable (``sleep``, ``clock``, ``rng``) so tests drive the full backoff
schedule with a fake clock and zero real sleeping.
"""

import time
from typing import Callable, Optional, Sequence, Tuple, Type

import numpy as np


class DeadlineExceededError(TimeoutError):
    """A call (or retry budget) ran past its deadline. The serving layer maps
    this to HTTP 504."""


def backoff_schedule(
    retries: int,
    backoff_s: float,
    max_backoff_s: float = 2.0,
    jitter: float = 0.5,
    rng: Optional[np.random.RandomState] = None,
) -> Tuple[float, ...]:
    """The delays ``retry_call`` would sleep between attempts: exponential
    doubling from ``backoff_s`` capped at ``max_backoff_s``, each inflated by
    up to ``jitter`` fraction (pass a seeded rng for determinism in tests).
    Exposed separately so callers can budget deadlines against it. The
    default rng is OS-seeded: jitter exists to DEcorrelate the retries of
    many threads/processes hitting the same blip, so they must not all draw
    the identical inflation sequence."""
    rng = rng if rng is not None else np.random.RandomState()
    delays = []
    for attempt in range(retries):
        base = min(backoff_s * (2.0 ** attempt), max_backoff_s)
        delays.append(base * (1.0 + jitter * float(rng.random_sample())))
    return tuple(delays)


def retry_call(
    fn: Callable,
    *args,
    retries: int = 2,
    backoff_s: float = 0.05,
    max_backoff_s: float = 2.0,
    jitter: float = 0.5,
    retry_on: Sequence[Type[BaseException]] = (OSError,),
    deadline_s: Optional[float] = None,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    rng: Optional[np.random.RandomState] = None,
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
):
    """Call ``fn(*args)``; on an exception in ``retry_on``, sleep the next
    backoff delay and try again, up to ``retries`` retries (so ``retries + 1``
    attempts total). The final failure re-raises the original exception.

    ``deadline_s`` bounds the whole affair against ``clock``: a retry that
    would start past the deadline raises :class:`DeadlineExceededError`
    chained to the last failure instead of sleeping toward it.
    ``on_retry(attempt, exc)`` observes each scheduled retry (logging,
    counters)."""
    delays = backoff_schedule(retries, backoff_s, max_backoff_s, jitter, rng)
    start = clock()
    for attempt in range(retries + 1):
        try:
            return fn(*args)
        except tuple(retry_on) as exc:
            if attempt >= retries:
                raise
            delay = delays[attempt]
            if deadline_s is not None and clock() - start + delay > deadline_s:
                raise DeadlineExceededError(
                    f"retry budget exhausted after {attempt + 1} attempts "
                    f"({clock() - start:.3f}s elapsed, deadline {deadline_s}s)"
                ) from exc
            if on_retry is not None:
                on_retry(attempt, exc)
            if delay > 0:
                sleep(delay)
