"""Persistent adaptation sessions — guarded online refinement (ISSUE 17).

Covers the refinement contract end to end at both layers:

- engine: ``refine_batch`` continues the K-step rollout from cached fast
  weights and matches the core ``refine_fast_weights`` primitive bit-for-bit
  through the same predict program; batched == single.
- frontend: commit / rollback / quarantine / re-adapt ladder (the cache is
  untouched by a rollback — last-good predictions stay bit-identical), an
  isolated regression never quarantines, lineage rides the SessionStore
  spill -> rehydrate round trip, the refine-off surface stays absent, and
  the sealed strict-mode guard sees ZERO outside-prewarm compiles under
  mixed adapt/refine/predict traffic across two strategies.
"""

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.config import Config, ServingConfig
from howtotrainyourmamlpytorch_tpu.core import MAMLSystem
from howtotrainyourmamlpytorch_tpu.data.synthetic import synthetic_batch
from howtotrainyourmamlpytorch_tpu.models import build_vgg
from howtotrainyourmamlpytorch_tpu.resilience.faults import FaultInjector, FaultSpec
from howtotrainyourmamlpytorch_tpu.serving.engine import AdaptationEngine
from howtotrainyourmamlpytorch_tpu.serving.errors import (
    SessionQuarantinedError,
    UnknownAdaptationError,
)
from howtotrainyourmamlpytorch_tpu.serving.server import ServingFrontend

_IMG = (14, 14, 1)


def _config(**kwargs):
    serving = kwargs.pop(
        "serving",
        ServingConfig(
            support_buckets=[16], query_buckets=[16], max_batch_size=2,
            refine_enabled=True,
        ),
    )
    return Config(
        num_classes_per_set=5,
        num_samples_per_class=2,
        num_target_samples=3,
        batch_size=2,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        serving=serving,
        **kwargs,
    )


@pytest.fixture(scope="module")
def tiny_system_state():
    cfg = _config()
    system = MAMLSystem(
        cfg,
        model=build_vgg(
            _IMG, cfg.num_classes_per_set, num_stages=2, cnn_num_filters=4
        ),
    )
    return system, system.init_train_state()


def _episode(seed):
    batch = synthetic_batch(1, 5, 2, 3, _IMG, seed=seed)
    x_s, y_s = batch["x_support"][0], batch["y_support"][0]
    x_q = batch["x_target"][0].reshape((-1,) + _IMG)
    return x_s, y_s, x_q


def _refine_frontend(tiny_system_state, **serving_kwargs):
    system, state = tiny_system_state
    serving = ServingConfig(
        support_buckets=[16], query_buckets=[16], max_batch_size=2,
        refine_enabled=True, **serving_kwargs,
    )
    engine = AdaptationEngine(system, state, serving_cfg=serving)
    return engine, ServingFrontend(engine)


# ---------------------------------------------------------------------------
# engine layer: refine continues the rollout from the cached weights
# ---------------------------------------------------------------------------


def test_engine_refine_matches_core_primitive(tiny_system_state):
    """``engine.refine`` (the bucketed/batched serving program) must score
    exactly like the core ``refine_fast_weights`` rollout it compiles."""
    system, state = tiny_system_state
    engine = AdaptationEngine(
        system, state,
        serving_cfg=ServingConfig(
            support_buckets=[16], query_buckets=[16], max_batch_size=2,
            refine_enabled=True,
        ),
    )
    x_s, y_s, x_q = _episode(seed=3)
    fw = engine.adapt(x_s, y_s)
    via_engine = engine.predict(engine.refine(fw, x_s, y_s), x_q)
    # the core primitive takes the flattened [N, H, W, C] support the
    # engine's batchers normalise to
    x_flat, y_flat = engine._flatten_support(x_s, y_s)
    core_fw = system.refine_fast_weights(state, fw, x_flat, y_flat)
    via_core = engine.predict(core_fw, x_q)
    np.testing.assert_allclose(
        np.asarray(via_engine), np.asarray(via_core), atol=1e-5
    )
    # refine-vs-fresh parity: refining a K-step session on the SAME
    # support is the same trajectory as one 2K-step rollout — at this
    # checkpoint the LSLR schedule is per-step uniform, so continuing the
    # rollout and stretching it coincide (within f32 tolerance)
    k = engine.cfg.number_of_evaluation_steps_per_iter
    fw_2k = system.adapt_fast_weights(state, x_flat, y_flat, num_steps=2 * k)
    np.testing.assert_allclose(
        np.asarray(via_engine), np.asarray(engine.predict(fw_2k, x_q)),
        atol=1e-4,
    )
    # refining moved the weights: a refined session is NOT a fresh adapt
    assert not np.allclose(
        np.asarray(via_engine), np.asarray(engine.predict(fw, x_q))
    )


def test_engine_refine_batch_matches_single(tiny_system_state):
    """A micro-batched refine flush returns exactly the per-session
    results — same contract the adapt/predict batchers already pin."""
    system, state = tiny_system_state
    engine = AdaptationEngine(
        system, state,
        serving_cfg=ServingConfig(
            support_buckets=[16], query_buckets=[16], max_batch_size=4,
            refine_enabled=True,
        ),
    )
    episodes = [_episode(seed=s) for s in (11, 12)]
    fws = [engine.adapt(x_s, y_s) for x_s, y_s, _ in episodes]
    batched = engine.refine_batch(
        [(fw, x_s, y_s) for fw, (x_s, y_s, _) in zip(fws, episodes)]
    )
    for fw, refined, (x_s, y_s, x_q) in zip(fws, batched, episodes):
        single = engine.refine(fw, x_s, y_s)
        np.testing.assert_allclose(
            np.asarray(engine.predict(refined, x_q)),
            np.asarray(engine.predict(single, x_q)),
            atol=1e-5,
        )


def test_protonet_refine_recomputes_prototypes(tiny_system_state):
    """ProtoNet sessions have no fast-weight rollout: a refine recomputes
    the prototype table from the new support through the planned adapt
    program. The first refine trains on the probe-carved subset; the
    SECOND sees the full support again, so its table — and hence its
    predictions — must be bit-identical to the fresh adapt's."""
    system, state = tiny_system_state
    engine = AdaptationEngine(
        system, state,
        serving_cfg=ServingConfig(
            support_buckets=[16], query_buckets=[16], max_batch_size=2,
            strategies=["maml++", "protonet"], refine_enabled=True,
            # streak semantics aren't under test here: a huge tolerance
            # keeps the natural first-refine score wiggle (the candidate
            # never trained on the probe points; the baseline did) from
            # making this seed-dependent
            refine_regress_tol=100.0,
        ),
    )
    frontend = ServingFrontend(engine)
    x_s, y_s, x_q = _episode(seed=5)
    sid = frontend.adapt(x_s, y_s, strategy="protonet")["adaptation_id"]
    before = np.asarray(frontend.predict(sid, x_q, strategy="protonet"))
    r1 = frontend.refine(sid, x_s, y_s, strategy="protonet")
    assert r1["refined"] and not r1["rolled_back"] and r1["refine_count"] == 1
    r2 = frontend.refine(sid, x_s, y_s, strategy="protonet")
    assert not r2["rolled_back"] and r2["refine_count"] == 2
    after = np.asarray(frontend.predict(sid, x_q, strategy="protonet"))
    np.testing.assert_array_equal(before, after)


# ---------------------------------------------------------------------------
# frontend layer: the guard ladder
# ---------------------------------------------------------------------------


def test_refine_commit_rollback_quarantine_readapt_ladder(tiny_system_state):
    """The full guarded lifecycle: healthy refine commits; a poisoned
    (nan-loss) refinement rolls back with the cache untouched; a burst of
    consecutive regressions quarantines (409 on refine AND predict); the
    only exit is an explicit re-adapt, which resets the lineage."""
    # a huge regress tolerance makes the injected non-finite faults the
    # ONLY rollback source — the ladder under test is streak bookkeeping,
    # not the natural score wiggle of a random-init network
    engine, frontend = _refine_frontend(
        tiny_system_state, refine_quarantine_after=2, refine_regress_tol=100.0
    )
    x_s, y_s, x_q = _episode(seed=1)

    with pytest.raises(UnknownAdaptationError):
        frontend.refine("no-such-session", x_s, y_s)

    sid = frontend.adapt(x_s, y_s)["adaptation_id"]
    r1 = frontend.refine(sid, x_s, y_s)
    assert r1["refined"] and not r1["rolled_back"]
    assert r1["refine_count"] == 1 and r1["score"] is not None
    p_good = np.asarray(frontend.predict(sid, x_q))

    engine.injector = FaultInjector(
        [FaultSpec.parse("serving.refine=nan-loss:times=3")]
    )
    r2 = frontend.refine(sid, x_s, y_s)
    assert r2["rolled_back"] and r2["score"] is None
    assert r2["refine_count"] == 1 and r2["consecutive_regressions"] == 1
    # rollback discarded the candidate: last-good predictions bit-identical
    np.testing.assert_array_equal(p_good, np.asarray(frontend.predict(sid, x_q)))

    with pytest.raises(SessionQuarantinedError) as exc_info:
        frontend.refine(sid, x_s, y_s)
    assert exc_info.value.status == 409
    assert exc_info.value.retry_after_s > 0
    # a quarantined session refuses refine AND predict until re-adapted
    with pytest.raises(SessionQuarantinedError):
        frontend.predict(sid, x_q)
    with pytest.raises(SessionQuarantinedError):
        frontend.refine(sid, x_s, y_s)

    engine.injector = FaultInjector()
    info = frontend.adapt(x_s, y_s)
    assert info["adaptation_id"] == sid and not info["cached"]
    assert np.isfinite(np.asarray(frontend.predict(sid, x_q))).all()
    r3 = frontend.refine(sid, x_s, y_s)
    assert not r3["rolled_back"] and r3["refine_count"] == 1

    m = frontend.metrics()
    refine = m["sessions"]["refine"]
    assert refine["refines"] == 2
    assert refine["rollbacks"] == 2
    assert refine["quarantines"] == 1
    assert refine["readapts"] == 1
    assert "refine_batcher" in m


def test_isolated_rollback_does_not_quarantine(tiny_system_state):
    """Quarantine is a CONSECUTIVE-regression breaker: a single rollback
    followed by a healthy commit resets the streak, so isolated blips
    never take a session out of service."""
    engine, frontend = _refine_frontend(
        tiny_system_state, refine_quarantine_after=2, refine_regress_tol=100.0
    )
    x_s, y_s, _ = _episode(seed=2)
    sid = frontend.adapt(x_s, y_s)["adaptation_id"]
    frontend.refine(sid, x_s, y_s)

    for _ in range(2):  # rollback -> commit, twice: never two in a row
        engine.injector = FaultInjector(
            [FaultSpec.parse("serving.refine=nan-loss:times=1")]
        )
        r = frontend.refine(sid, x_s, y_s)
        assert r["rolled_back"] and r["consecutive_regressions"] == 1
        engine.injector = FaultInjector()
        r = frontend.refine(sid, x_s, y_s)
        assert not r["rolled_back"] and r["consecutive_regressions"] == 0

    assert frontend.metrics()["sessions"]["refine"]["quarantines"] == 0


# ---------------------------------------------------------------------------
# lineage rides spill -> rehydrate
# ---------------------------------------------------------------------------


def test_spill_rehydrate_carries_lineage(tiny_system_state, tmp_path):
    """A drained frontend spills refined sessions WITH their lineage; the
    successor rehydrates them bit-identically and the next refine
    CONTINUES the count instead of restarting a fresh lineage."""
    system, state = tiny_system_state
    serving = ServingConfig(
        support_buckets=[16], query_buckets=[16], max_batch_size=2,
        refine_enabled=True,
    )
    x_s, y_s, x_q = _episode(seed=4)

    engine_a = AdaptationEngine(system, state, serving_cfg=serving)
    engine_a.save_dir = str(tmp_path)
    front_a = ServingFrontend(engine_a)
    sid = front_a.adapt(x_s, y_s)["adaptation_id"]
    assert front_a.refine(sid, x_s, y_s)["refine_count"] == 1
    p_before = np.asarray(front_a.predict(sid, x_q))
    drain = front_a.begin_drain(reason="test")
    assert drain["spilled_sessions"] == 1, drain

    engine_b = AdaptationEngine(system, state, serving_cfg=serving)
    engine_b.save_dir = str(tmp_path)
    front_b = ServingFrontend(engine_b)
    assert front_b._session_stats.get("rehydrated") == 1
    lineage = front_b._lineage_for(front_b._cache_key(sid, "maml++", None))
    assert lineage is not None and lineage.refine_count == 1
    assert lineage.probe is not None and lineage.scores
    np.testing.assert_array_equal(p_before, np.asarray(front_b.predict(sid, x_q)))
    assert front_b.refine(sid, x_s, y_s)["refine_count"] == 2


# ---------------------------------------------------------------------------
# refine-off: the surface is absent, not dormant
# ---------------------------------------------------------------------------


def test_refine_disabled_surface_is_absent(tiny_system_state):
    system, state = tiny_system_state
    engine = AdaptationEngine(
        system, state,
        serving_cfg=ServingConfig(
            support_buckets=[16], query_buckets=[16], max_batch_size=2,
        ),
    )
    frontend = ServingFrontend(engine)
    x_s, y_s, _ = _episode(seed=6)
    with pytest.raises(ValueError, match="refine_enabled"):
        frontend.refine("x", x_s, y_s)
    m = frontend.metrics()
    assert "refine_batcher" not in m and "sessions" not in m
    assert frontend.pool.replicas[0].refine_batcher is None


# ---------------------------------------------------------------------------
# obs_report: the per-session refinement table replays events.jsonl
# ---------------------------------------------------------------------------


def test_obs_report_refinement_table_from_events(tiny_system_state, tmp_path):
    """scripts/obs_report.py builds the per-session refinement lifecycle
    (commits / rollbacks / quarantines / re-adapts + score trend) from the
    events the frontend actually writes."""
    import importlib.util
    import os

    system, state = tiny_system_state
    engine = AdaptationEngine(
        system, state,
        serving_cfg=ServingConfig(
            support_buckets=[16], query_buckets=[16], max_batch_size=2,
            refine_enabled=True, refine_quarantine_after=2,
            refine_regress_tol=100.0,
        ),
    )
    frontend = ServingFrontend(engine, access_log_dir=str(tmp_path))
    x_s, y_s, _ = _episode(seed=7)
    sid = frontend.adapt(x_s, y_s)["adaptation_id"]
    frontend.refine(sid, x_s, y_s)
    frontend.refine(sid, x_s, y_s)
    engine.injector = FaultInjector(
        [FaultSpec.parse("serving.refine=nan-loss:times=3")]
    )
    r = frontend.refine(sid, x_s, y_s)
    assert r["rolled_back"]
    with pytest.raises(SessionQuarantinedError):
        frontend.refine(sid, x_s, y_s)
    engine.injector = FaultInjector()
    frontend.adapt(x_s, y_s)  # re-adapt: the quarantine exit
    frontend.close()

    spec = importlib.util.spec_from_file_location(
        "obs_report_mod",
        os.path.join(os.path.dirname(os.path.dirname(__file__)),
                     "scripts", "obs_report.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    events, torn = mod._read_jsonl(str(tmp_path / "events.jsonl"))
    assert torn == 0
    table = mod._refinement_from_events(events)
    assert table is not None and len(table) == 1
    row = table[sid[:12]]
    assert row["refines"] == 2 and row["rollbacks"] == 2
    assert row["quarantines"] == 1 and row["readapts"] == 1
    assert row["strategy"] == "maml++"
    assert row["first_score"] is not None and row["last_score"] is not None
    assert row["best_score"] <= max(row["first_score"], row["last_score"])
    # refine-free events produce NO table (the section stays absent)
    assert mod._refinement_from_events(
        [{"event": "session_readapted", "session": "abc"}]
    ) is None


# ---------------------------------------------------------------------------
# sealed guard: mixed refine traffic compiles NOTHING outside the prewarm
# ---------------------------------------------------------------------------


def test_sealed_guard_zero_compiles_under_mixed_refine_traffic(tmp_path):
    """ACCEPTANCE (ISSUE 17): with the strict guard sealed after prewarm,
    mixed adapt/refine/predict traffic across TWO strategies — including a
    guard-probe score per refine — lowers zero new programs."""
    cfg = _config(
        strict_recompile_guard=True,
        serving=ServingConfig(
            support_buckets=[16], query_buckets=[16], max_batch_size=2,
            strategies=["maml++", "anil"], refine_enabled=True,
            refine_regress_tol=100.0,
        ),
    )
    system = MAMLSystem(
        cfg,
        model=build_vgg(
            _IMG, cfg.num_classes_per_set, num_stages=2, cnn_num_filters=4
        ),
    )
    engine = AdaptationEngine(system, system.init_train_state())
    summary = engine.prewarm(max_workers=1)
    assert summary["errors"] == 0
    sealed = engine.recompile_guard.snapshot()
    assert sealed["prewarmed"]

    frontend = ServingFrontend(engine)
    x_s, y_s, x_q = _episode(seed=9)
    for strategy in ("maml++", "anil"):
        sid = frontend.adapt(x_s, y_s, strategy=strategy)["adaptation_id"]
        for _ in range(2):
            r = frontend.refine(sid, x_s, y_s, strategy=strategy)
            assert r["refined"] and not r["rolled_back"]
        probs = frontend.predict(sid, x_q, strategy=strategy)
        assert np.isfinite(np.asarray(probs)).all()

    snap = engine.recompile_guard.snapshot()
    assert snap["violations"] == []
    assert snap["lowerings"] == sealed["lowerings"], (
        "mixed adapt/refine/predict traffic compiled outside the "
        "prewarmed grid"
    )
