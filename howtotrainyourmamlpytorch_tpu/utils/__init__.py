from . import trees, seeding  # noqa: F401
