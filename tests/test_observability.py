"""Observability subsystem: tracer semantics under a fake clock, registry
thread-safety, Chrome-trace schema/balance validation, hub snapshot cadence,
and the runner e2e contract — all five step phases in telemetry.jsonl with
plausible durations, a loadable balanced trace, and bit-identical training
with the subsystem switched off."""

import json
import os
import threading

import numpy as np
import pytest
from PIL import Image

from howtotrainyourmamlpytorch_tpu.config import (
    Config,
    DatasetConfig,
    ObservabilityConfig,
    ParallelConfig,
)
from howtotrainyourmamlpytorch_tpu.core import MAMLSystem
from howtotrainyourmamlpytorch_tpu.experiment import ExperimentRunner
from howtotrainyourmamlpytorch_tpu.experiment.storage import load_statistics
from howtotrainyourmamlpytorch_tpu.models import build_vgg
from howtotrainyourmamlpytorch_tpu.observability import (
    NULL_HUB,
    MetricsRegistry,
    SpanTracer,
    TelemetryHub,
    validate_chrome_trace,
)
from howtotrainyourmamlpytorch_tpu.serving.metrics import EventCounters, LatencyStats


class FakeClock:
    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ---------------------------------------------------------------------------
# tracer
# ---------------------------------------------------------------------------


def test_tracer_nesting_and_durations_fake_clock():
    clock = FakeClock()
    tracer = SpanTracer(capacity=16, clock=clock)
    with tracer.span("outer", epoch=0):
        clock.advance(1.0)
        with tracer.span("inner"):
            clock.advance(0.25)
        clock.advance(0.5)
    recs = {r["name"]: r for r in tracer.records()}
    assert recs["inner"]["depth"] == 1
    assert recs["outer"]["depth"] == 0
    assert recs["inner"]["dur_s"] == pytest.approx(0.25)
    assert recs["outer"]["dur_s"] == pytest.approx(1.75)
    assert recs["outer"]["tags"] == {"epoch": 0}
    # inner completed first: ring order is completion order
    assert [r["name"] for r in tracer.records()] == ["inner", "outer"]
    assert tracer.open_spans() == 0
    assert tracer.durations_s("inner") == pytest.approx([0.25])


def test_tracer_ring_eviction_bounded_and_counted():
    clock = FakeClock()
    tracer = SpanTracer(capacity=3, clock=clock)
    for i in range(5):
        with tracer.span(f"s{i}"):
            clock.advance(0.1)
    recs = tracer.records()
    assert len(recs) == 3  # bounded: oldest evicted, never unbounded growth
    assert [r["name"] for r in recs] == ["s2", "s3", "s4"]
    assert tracer.dropped == 2
    # eviction is visible in the export too
    assert tracer.to_chrome_trace()["otherData"]["dropped_spans"] == 2


def test_tracer_thread_spans_keep_independent_nesting():
    clock = FakeClock()
    tracer = SpanTracer(capacity=64, clock=clock)
    errors = []

    def worker():
        try:
            with tracer.span("w_outer"):
                with tracer.span("w_inner"):
                    pass
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    with tracer.span("main_outer"):
        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert not errors
    recs = tracer.records()
    # worker nesting never inherits the main thread's open span
    assert all(r["depth"] == 0 for r in recs if r["name"] == "w_outer")
    assert all(r["depth"] == 1 for r in recs if r["name"] == "w_inner")
    assert tracer.open_spans() == 0


# ---------------------------------------------------------------------------
# chrome-trace export + validation
# ---------------------------------------------------------------------------


def test_chrome_trace_schema_valid_and_balanced(tmp_path):
    clock = FakeClock()
    tracer = SpanTracer(capacity=16, clock=clock)
    with tracer.span("a", bucket=(25, 8)):  # non-scalar tag must stringify
        clock.advance(0.5)
    path = str(tmp_path / "trace.json")
    tracer.export(path)
    with open(path) as f:
        trace = json.load(f)
    assert validate_chrome_trace(trace) == []
    (event,) = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert event["dur"] == pytest.approx(0.5e6)  # microseconds
    assert event["args"]["bucket"] == "(25, 8)"
    # the REAL pid, not the old hardcoded 0 — trace_merge keys process
    # tracks off it
    assert isinstance(event["tid"], int) and event["pid"] == os.getpid()


def test_chrome_trace_validator_rejects_malformed():
    assert validate_chrome_trace({"traceEvents": "nope"}) != []
    bad_dur = {"traceEvents": [
        {"name": "x", "ph": "X", "ts": 0, "dur": -1, "pid": 0, "tid": 0}
    ]}
    assert any("dur" in p for p in validate_chrome_trace(bad_dur))
    missing = {"traceEvents": [{"name": "x", "ph": "X", "ts": 0}]}
    assert any("missing keys" in p for p in validate_chrome_trace(missing))
    unbalanced = {"traceEvents": [
        {"name": "x", "ph": "B", "ts": 0, "pid": 0, "tid": 0}
    ]}
    assert any("unclosed" in p for p in validate_chrome_trace(unbalanced))
    open_spans = {"traceEvents": [], "otherData": {"open_spans": 2}}
    assert any("still open" in p for p in validate_chrome_trace(open_spans))


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


def test_registry_thread_safety_exact_counts():
    reg = MetricsRegistry()
    n_threads, n_iters = 8, 500

    def worker(tid):
        for i in range(n_iters):
            reg.inc("hits")
            reg.observe("lat", 0.001 * (i + 1), window=64)
            reg.set_gauge(f"g{tid}", i)

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.counter("hits") == n_threads * n_iters  # no lost updates
    summary = reg.summaries()["lat"]
    assert summary["count"] == n_threads * n_iters  # cumulative past eviction
    assert summary["window"] == 64


def test_registry_summaries_window_and_cumulative_sum():
    reg = MetricsRegistry()
    for v in (0.010, 0.020, 0.030, 0.040):
        reg.observe("phase.settle", v, window=2)
    s = reg.summaries("phase.")["settle"]
    # window keeps the last 2 (30ms, 40ms); count/sum are cumulative
    assert s["count"] == 4
    assert s["window"] == 2
    assert s["p50_ms"] == pytest.approx(35.0)
    assert s["max_ms"] == pytest.approx(40.0)
    assert s["sum_ms"] == pytest.approx(100.0)


def test_latency_stats_adapter_schema_unchanged():
    """The /metrics contract: per-phase keys exactly as the pre-registry
    LatencyStats emitted them (no registry-internal keys leaking out)."""
    stats = LatencyStats(window=8)
    stats.record("adapt", 0.010)
    with stats.time("predict"):
        pass
    out = stats.summary()
    assert set(out) == {"adapt", "predict"}
    assert set(out["adapt"]) == {
        "count", "window", "mean_ms", "p50_ms", "p95_ms", "p99_ms", "max_ms"
    }
    assert out["adapt"]["count"] == 1


def test_event_counters_adapter_shares_registry():
    reg = MetricsRegistry()
    counters = EventCounters(registry=reg)
    latency = LatencyStats(window=4, registry=reg)
    counters.inc("shed")
    counters.inc("shed", 2)
    latency.record("adapt", 0.005)
    assert counters.get("shed") == 3
    assert counters.snapshot() == {"shed": 3}
    # namespaces keep the two adapters collision-free on one registry
    assert "adapt" in latency.summary()


# ---------------------------------------------------------------------------
# hub
# ---------------------------------------------------------------------------


def test_null_hub_is_inert(tmp_path):
    assert not NULL_HUB.enabled
    with NULL_HUB.phase("dispatch"):
        pass
    with NULL_HUB.span("x"):
        pass
    NULL_HUB.step_completed(8)
    assert NULL_HUB.snapshot("epoch") == {}
    NULL_HUB.close()
    disabled = TelemetryHub(enabled=False, logs_dir=str(tmp_path))
    disabled.snapshot("epoch")
    disabled.close()
    assert os.listdir(tmp_path) == []  # no file ever created


def test_hub_step_snapshot_cadence_and_throughput(tmp_path):
    clock = FakeClock()
    hub = TelemetryHub(
        enabled=True, logs_dir=str(tmp_path), snapshot_every_steps=2, clock=clock
    )
    for _ in range(5):
        with hub.phase("dispatch"):
            clock.advance(0.5)
        hub.step_completed(episodes=4)
    hub.close()
    records = [
        json.loads(line) for line in open(tmp_path / "telemetry.jsonl")
    ]
    kinds = [r["kind"] for r in records]
    assert kinds == ["step", "step", "final"]  # every 2 steps, 5 % 2 -> final
    assert records[0]["steps"] == 2 and records[0]["episodes"] == 8
    # 4 episodes per 0.5s fake-clock step
    assert records[0]["episodes_per_s"] == pytest.approx(8.0)
    assert records[-1]["steps"] == 5
    assert records[-1]["phases"]["dispatch"]["count"] == 5
    assert os.path.exists(tmp_path / "trace.json")


def test_hub_provider_errors_are_contained():
    hub = TelemetryHub(enabled=True)

    def broken():
        raise RuntimeError("boom")

    hub.add_provider("ok", lambda: {"x": 1})
    hub.add_provider("broken", broken)
    snap = hub.snapshot("epoch")
    assert snap["providers"]["ok"] == {"x": 1}
    assert "boom" in snap["providers"]["broken"]["provider_error"]


# ---------------------------------------------------------------------------
# runner e2e
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def toy_dataset(tmp_path_factory):
    root = tmp_path_factory.mktemp("data") / "omniglot_toy"
    rng = np.random.RandomState(0)
    for a in range(4):
        for c in range(5):
            d = root / f"alpha{a}" / f"char{c}"
            d.mkdir(parents=True)
            base = (rng.rand(28, 28) > 0.5).astype(np.uint8) * 255
            for i in range(6):
                noisy = base ^ (rng.rand(28, 28) > 0.95).astype(np.uint8) * 255
                Image.fromarray(noisy, mode="L").convert("1").save(d / f"{i}.png")
    return str(root)


def _toy_config(toy_dataset, tmp_path, name, **overrides):
    base = dict(
        dataset=DatasetConfig(name="omniglot_toy", path=toy_dataset),
        num_classes_per_set=3,
        num_samples_per_class=2,
        num_target_samples=2,
        batch_size=2,
        parallel=ParallelConfig(dp=2),
        total_epochs=2,
        total_iter_per_epoch=3,
        num_evaluation_tasks=4,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        experiment_root=str(tmp_path),
        experiment_name=name,
        load_into_memory=True,
        num_dataprovider_workers=2,
        train_val_test_split=(0.6, 0.2, 0.2),
        conv_via_patches=True,  # the dp-sharded native-conv GSPMD crash dodge
    )
    base.update(overrides)
    return Config(**base)


def _toy_system(cfg):
    return MAMLSystem(
        cfg,
        model=build_vgg(
            (28, 28, 1), cfg.num_classes_per_set, num_stages=2,
            cnn_num_filters=4, conv_via_patches=True,
        ),
    )


RUNNER_PHASES = ("data_wait", "dispatch", "settle", "eval", "checkpoint")


def test_runner_e2e_all_five_phases_with_plausible_durations(
    toy_dataset, tmp_path
):
    cfg = _toy_config(
        toy_dataset, tmp_path, "obs_e2e",
        observability=ObservabilityConfig(snapshot_every_steps=2),
    )
    runner = ExperimentRunner(cfg, system=_toy_system(cfg))
    runner.run_experiment()
    logs = os.path.join(runner.run_dir, "logs")

    records = [json.loads(line) for line in open(os.path.join(logs, "telemetry.jsonl"))]
    kinds = [r["kind"] for r in records]
    assert "epoch" in kinds and "step" in kinds and kinds[-1] == "final"
    epoch_snaps = [r for r in records if r["kind"] == "epoch"]
    assert [r["epoch"] for r in epoch_snaps] == [0, 1]

    last = records[-1]
    phases = last["phases"]
    assert set(RUNNER_PHASES) <= set(phases), sorted(phases)
    for name in RUNNER_PHASES:
        s = phases[name]
        assert s["count"] > 0
        assert 0.0 <= s["p50_ms"] <= s["max_ms"]
        assert s["sum_ms"] <= last["elapsed_s"] * 1e3  # no phase exceeds the run
    # 2 epochs x 3 iters, each dispatched and (guard on) settled exactly once
    assert phases["dispatch"]["count"] == 6
    assert phases["settle"]["count"] == 6
    assert last["steps"] == 6
    assert last["episodes"] == 6 * cfg.batch_size
    # train-loop phases cover the epoch wall-clock (the obs_report honesty
    # check; generous lower bound for a 1-core CI box)
    train_wall = sum(r["train_wall_s"] for r in epoch_snaps)
    loop_sum = sum(phases[p]["sum_ms"] / 1e3 for p in ("data_wait", "dispatch", "settle"))
    assert loop_sum <= train_wall * 1.10
    assert loop_sum >= train_wall * 0.5
    # providers rode along
    assert last["providers"]["loader"]["train_episodes_produced"] == 12
    assert "watchdog_beat_age_s" in last["providers"]

    # exported trace loads, validates, and carries every runner phase
    from howtotrainyourmamlpytorch_tpu.observability import load_and_validate_trace

    trace_path = os.path.join(logs, "trace.json")
    assert load_and_validate_trace(trace_path) == []
    with open(trace_path) as f:
        names = {e["name"] for e in json.load(f)["traceEvents"] if e["ph"] == "X"}
    assert set(RUNNER_PHASES) <= names
    # the first dispatch (the compile) is tagged cold
    with open(trace_path) as f:
        dispatches = [
            e for e in json.load(f)["traceEvents"]
            if e.get("name") == "dispatch"
        ]
    cold = [e for e in dispatches if (e.get("args") or {}).get("cold")]
    assert len(cold) >= 1

    # obs_report runs over the fresh dir (human + oneline + json contract)
    import subprocess
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo_root, "scripts", "obs_report.py"),
         runner.run_dir, "--oneline"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    line = json.loads(proc.stdout)
    assert line["report"] == "obs"
    assert line["epochs"] == 2
    assert 0.9 <= line["phase_coverage"] <= 1.1, line


def test_observability_off_is_bit_identical_and_fileless(toy_dataset, tmp_path):
    """The off switch: identical final losses with the subsystem disabled vs
    enabled (same seeds, same stream), and zero observability artifacts."""
    results = {}
    for label, obs in (
        ("on", ObservabilityConfig(enabled=True)),
        ("off", ObservabilityConfig(enabled=False)),
    ):
        cfg = _toy_config(
            toy_dataset, tmp_path, f"obs_bitident_{label}", observability=obs
        )
        runner = ExperimentRunner(cfg, system=_toy_system(cfg))
        runner.run_experiment()
        logs = os.path.join(runner.run_dir, "logs")
        rows = load_statistics(logs)
        results[label] = [
            (r["train_loss_mean"], r["val_loss_mean"], r["train_accuracy_mean"])
            for r in rows
        ]
        has_tel = os.path.exists(os.path.join(logs, "telemetry.jsonl"))
        has_trace = os.path.exists(os.path.join(logs, "trace.json"))
        assert has_tel == (label == "on")
        assert has_trace == (label == "on")
    # bit-identical: the CSV strings themselves match, not just approx
    assert results["on"] == results["off"]


def test_runner_disabled_hub_multi_dispatch_path(toy_dataset, tmp_path):
    """The K>1 chunked-dispatch loop runs through the same hub hooks; with
    observability off it must stay inert there too."""
    cfg = _toy_config(
        toy_dataset, tmp_path, "obs_off_multi",
        train_steps_per_dispatch=3,
        observability=ObservabilityConfig(enabled=False),
    )
    runner = ExperimentRunner(cfg, system=_toy_system(cfg))
    result = runner.run_experiment()
    assert "test_accuracy_mean" in result
    assert not os.path.exists(os.path.join(runner.run_dir, "logs", "telemetry.jsonl"))
