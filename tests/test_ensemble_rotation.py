"""best_val checkpoint rotation + top-K test ensembling (SURVEY.md §2.9 item
4: upstream MAML++ kept its best-5 val checkpoints and ensembled them at test
time) and the jax.profiler trace window."""

import os

import numpy as np
import pytest
from PIL import Image

from howtotrainyourmamlpytorch_tpu.config import Config, DatasetConfig, ParallelConfig
from howtotrainyourmamlpytorch_tpu.core import MAMLSystem
from howtotrainyourmamlpytorch_tpu.experiment import ExperimentRunner
from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt
from howtotrainyourmamlpytorch_tpu.experiment.storage import load_statistics
from howtotrainyourmamlpytorch_tpu.models import build_vgg


@pytest.fixture(scope="module")
def toy_dataset(tmp_path_factory):
    root = tmp_path_factory.mktemp("data") / "omniglot_toy"
    rng = np.random.RandomState(0)
    for a in range(4):
        for c in range(5):
            d = root / f"alpha{a}" / f"char{c}"
            d.mkdir(parents=True)
            base = (rng.rand(28, 28) > 0.5).astype(np.uint8) * 255
            for i in range(6):
                noisy = base ^ (rng.rand(28, 28) > 0.95).astype(np.uint8) * 255
                Image.fromarray(noisy, mode="L").convert("1").save(d / f"{i}.png")
    return str(root)


def make_runner(toy_dataset, tmp_path, **overrides):
    base = dict(
        dataset=DatasetConfig(name="omniglot_toy", path=toy_dataset),
        num_classes_per_set=3,
        num_samples_per_class=2,
        num_target_samples=2,
        batch_size=2,
        parallel=ParallelConfig(dp=2),
        total_epochs=4,
        total_iter_per_epoch=2,
        num_evaluation_tasks=4,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        experiment_root=str(tmp_path),
        load_into_memory=True,
        num_dataprovider_workers=2,
        train_val_test_split=(0.6, 0.2, 0.2),
        # patches-GEMM convs: GSPMD's convolution handler CHECK-crashes on
        # the dp-sharded batch-grouped convs of this program family on this
        # jaxlib (see tests/test_runner.py::runner_config)
        conv_via_patches=True,
    )
    base.update(overrides)
    cfg = Config(**base)
    system = MAMLSystem(
        cfg, model=build_vgg((28, 28, 1), cfg.num_classes_per_set, num_stages=2, cnn_num_filters=4, conv_via_patches=True)
    )
    return cfg, ExperimentRunner(cfg, system=system)


def test_best_val_rotation_keeps_top_epochs(tmp_path):
    # pure checkpoint-layer behavior: rotation by recorded val accuracy
    from howtotrainyourmamlpytorch_tpu.core.train_state import TrainState

    save_dir = str(tmp_path)
    state = TrainState(
        params={"w": np.zeros(2, np.float32)}, bn_state={}, inner_hparams={},
        opt_state={}, step=np.int32(0),
    )
    val = {0: 0.2, 1: 0.9, 2: 0.5, 3: 0.1, 4: 0.7}
    for epoch in range(5):
        ckpt.save_checkpoint(save_dir, state, {}, epoch, max_models_to_save=2,
                             val_acc_by_epoch=val)
    # top-2 by val acc: epochs 1 (0.9) and 4 (0.7)
    assert ckpt.available_epochs(save_dir) == [1, 4]
    assert ckpt.checkpoint_exists(save_dir, "latest")


def test_rotation_latest_default(tmp_path):
    from howtotrainyourmamlpytorch_tpu.core.train_state import TrainState

    save_dir = str(tmp_path)
    state = TrainState(
        params={"w": np.zeros(2, np.float32)}, bn_state={}, inner_hparams={},
        opt_state={}, step=np.int32(0),
    )
    for epoch in range(5):
        ckpt.save_checkpoint(save_dir, state, {}, epoch, max_models_to_save=2)
    assert ckpt.available_epochs(save_dir) == [3, 4]


def test_config_rejects_bad_rotation():
    with pytest.raises(ValueError, match="checkpoint_rotation"):
        Config(checkpoint_rotation="newest")


def test_ensemble_test_evaluation(toy_dataset, tmp_path):
    cfg, runner = make_runner(
        toy_dataset, tmp_path,
        experiment_name="toy_ens",
        checkpoint_rotation="best_val",
        test_ensemble_top_k=3,
        max_models_to_save=3,
    )
    result = runner.run_experiment()
    assert "test_accuracy_mean" in result
    rows = load_statistics(os.path.join(runner.run_dir, "logs"), "test_summary.csv")
    assert float(rows[-1]["test_ensemble_size"]) >= 2
    # kept checkpoints are exactly the top-val ones the ensemble used
    kept = ckpt.available_epochs(os.path.join(runner.run_dir, "saved_models"))
    used = [int(e) for e in rows[-1]["test_ensemble_epochs"].split()]
    assert set(used).issubset(set(kept))
    # val_acc_by_epoch survives the checkpoint round-trip
    cfg2, runner2 = make_runner(
        toy_dataset, tmp_path,
        experiment_name="toy_ens",
        checkpoint_rotation="best_val",
        test_ensemble_top_k=3,
        max_models_to_save=3,
        total_epochs=4,
    )
    assert runner2.val_acc_by_epoch == runner.val_acc_by_epoch


def test_save_statistics_reconciles_changed_columns(tmp_path):
    from howtotrainyourmamlpytorch_tpu.experiment import storage

    log_dir = str(tmp_path)
    storage.save_statistics(log_dir, {"a": 1.0, "b": 2.0}, filename="t.csv")
    storage.save_statistics(log_dir, {"a": 3.0, "c": 4.0}, filename="t.csv")
    rows = load_statistics(log_dir, "t.csv")
    assert rows[0] == {"a": "1.0", "b": "2.0", "c": ""}
    assert rows[1] == {"a": "3.0", "b": "", "c": "4.0"}


def test_ensemble_requires_best_val_rotation():
    with pytest.raises(ValueError, match="test_ensemble_top_k"):
        Config(test_ensemble_top_k=3)


def test_profile_window_writes_trace(toy_dataset, tmp_path):
    prof_dir = str(tmp_path / "traces")
    cfg, runner = make_runner(
        toy_dataset, tmp_path,
        experiment_name="toy_prof",
        total_epochs=1,
        profile_dir=prof_dir,
    )
    runner.run_experiment()
    assert runner._profiled
    # jax.profiler writes plugins/profile/<ts>/*.xplane.pb under the trace dir
    found = [
        os.path.join(dp, f)
        for dp, _, fs in os.walk(prof_dir)
        for f in fs
        if f.endswith(".xplane.pb") or f.endswith(".trace.json.gz")
    ]
    assert found, "no profiler trace written"
