"""Chaos campaign smoke (tier-1) + full soak (slow).

The fast smoke runs a seeded in-process slice of the campaign — every
invariant checked, subprocess episodes (rc=76 wedge, device-shrink) excluded
for speed since tests/test_wedge_watchdog.py drills those bit-for-bit. The
full soak (``-m slow``) runs ``scripts/chaos_soak.py --episodes 21 --seed 0``
end to end and pins the one-JSON-line CLI contract."""

import json
import os
import subprocess
import sys

import pytest

from howtotrainyourmamlpytorch_tpu.resilience.campaign import (
    DOCUMENTED_RCS,
    episode_menu,
    run_campaign,
    sample_episodes,
)

from tests.test_runner import toy_dataset  # noqa: F401

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_episode_sampling_is_seeded_and_covers_every_seam():
    import numpy as np

    menu = episode_menu(np.random.RandomState(0))
    seams = set()
    for ep in menu:
        for f in ep.faults:
            seams.add(f.split("=", 1)[0])
    # serve episodes carry their seams inside _run_serve_episode
    seams |= {"serving.dispatch", "serving.http", "serving.refine"}
    assert seams >= {
        "runner.step", "loader.episode", "checkpoint.read",
        "checkpoint.write", "serving.dispatch", "serving.http",
        "serving.refine",
    }
    # the full menu covers the ISSUE 17 refinement drills and the ISSUE 18
    # fleet-supervisor drills
    kinds = {e.kind for e in menu}
    assert {
        "serve-refine-rollback", "serve-refine-across-drain",
        "fleet-surge", "fleet-crashloop",
    } <= kinds
    assert len(menu) == 21
    # deterministic in seed; jittered across seeds
    a = [e.kind for e in sample_episodes(7, 21)]
    b = [e.kind for e in sample_episodes(7, 21)]
    assert a == b
    assert len(sample_episodes(0, 21, include_subprocess=False)) == 21
    assert not any(
        e.subprocess for e in sample_episodes(0, 21, include_subprocess=False)
    )


def test_chaos_smoke_campaign_all_invariants_green(toy_dataset, tmp_path):
    """A fixed-seed 4-episode in-process campaign: documented rcs only,
    loadable checkpoints, well-formed events, honest serving — and the
    verdict itself is one JSON-serializable line."""
    verdict = run_campaign(
        str(tmp_path),
        episodes=4,
        seed=0,
        data_root=toy_dataset,
        include_subprocess=False,
        log=lambda m: None,
    )
    assert verdict["ok"], verdict["violations"]
    assert verdict["episodes"] == 4
    for result in verdict["episode_results"]:
        assert not result.get("violations")
        for rc in result.get("rcs", []):
            assert rc in DOCUMENTED_RCS
    line = json.dumps(verdict)
    assert "\n" not in line and json.loads(line)["ok"] is True
    # sanitizer off (the default) = no verdict block and ZERO new files —
    # the graftsan log only exists when --sanitize asked for it
    assert verdict["sanitizer"] is None
    assert not os.path.exists(os.path.join(str(tmp_path), "graftsan.jsonl"))


@pytest.mark.slow
def test_full_chaos_soak_cli(tmp_path):
    """The acceptance command: ``python scripts/chaos_soak.py --episodes 21
    --seed 0`` (one full menu pass, including the ISSUE 6 grow-back /
    SIGTERM-during-async-save episodes, the ISSUE 11 replica-death episode,
    the ISSUE 14 cross-process gateway drills, the ISSUE 17 refinement
    rollback / across-drain drills, and the ISSUE 18 fleet surge /
    crash-loop drills) reports every invariant green in ONE JSON line,
    rc 0 — with the ISSUE 19 graftsan lock-discipline sanitizer armed
    across all of it (``--sanitize``), reporting zero violations."""
    proc = subprocess.run(
        [
            sys.executable, "scripts/chaos_soak.py",
            "--episodes", "21", "--seed", "0",
            "--work-dir", str(tmp_path),
            "--sanitize",
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=3600,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, lines
    verdict = json.loads(lines[0])
    assert verdict["ok"] is True
    assert verdict["episodes"] == 21
    assert verdict["violations"] == []
    assert verdict["sanitizer"]["armed"] is True
    assert verdict["sanitizer"]["violations"] == 0, verdict["sanitizer"]
    kinds = {r["kind"] for r in verdict["episode_results"]}
    assert {
        "device-grow-resume", "sigterm-during-async-save",
        "serve-replica-death", "serve-tenant-thrash", "gateway-kill9-backend",
        "gateway-drain-rehydrate", "gateway-rolling-restart",
        "serve-refine-rollback", "serve-refine-across-drain",
        "fleet-surge", "fleet-crashloop",
    } <= kinds
