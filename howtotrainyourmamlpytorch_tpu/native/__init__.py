"""Build + ctypes bindings for the native episode-assembly engine.

The shared library is compiled from ``episode_engine.cpp`` on first use with
the system ``g++`` (no pybind11 in this environment — plain C ABI + ctypes)
and cached next to the source; it is rebuilt whenever the source is newer.
``load_engine()`` returns None when no compiler/toolchain is available, and
callers fall back to the pure-numpy path — the native engine is a fast path,
never a requirement.
"""

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "episode_engine.cpp")
_LIB = os.path.join(os.path.dirname(__file__), "episode_engine.so")
_lock = threading.Lock()
_engine = None
_engine_failed = False


def _build() -> bool:
    # compile to a process-unique temp path, then atomically os.replace into
    # place: concurrent builders race harmlessly, and an interrupted g++ can
    # never leave a partial .so at the canonical path
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
        _SRC, "-o", tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False


def load_engine() -> Optional[ctypes.CDLL]:
    """The compiled engine with argtypes set, or None if unavailable."""
    global _engine, _engine_failed
    with _lock:
        if _engine is not None:
            return _engine
        if _engine_failed:
            return None
        stale = not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
        if stale and not _build():
            _engine_failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            # e.g. a corrupt .so from an older interrupted writer: remove and
            # rebuild once before giving up on the native path
            try:
                os.remove(_LIB)
            except OSError:
                pass
            if not _build():
                _engine_failed = True
                return None
            try:
                lib = ctypes.CDLL(_LIB)
            except OSError:
                _engine_failed = True
                return None
        f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        lib.assemble_episodes.restype = ctypes.c_int
        lib.assemble_episodes.argtypes = [
            f32p,  # cache
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),  # image_idx
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),  # rot_k
            f32p,  # out
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,  # B, n_way, n_samples
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,  # H, W, C
            f32p, f32p,  # mean, std
            ctypes.c_int,  # has_norm
            ctypes.c_int,  # num_threads
        ]
        _engine = lib
        return _engine


_NO_NORM = np.zeros(1, np.float32), np.ones(1, np.float32)


def assemble_episodes(
    cache: np.ndarray,       # [total_images, H, W, C] float32
    image_idx: np.ndarray,   # [B, n_way, n_samples] int64
    rot_k: np.ndarray,       # [B, n_way] int32
    mean: Optional[np.ndarray] = None,
    std: Optional[np.ndarray] = None,
    num_threads: int = 4,
) -> Optional[np.ndarray]:
    """One native call: gather + rot90 + normalize + pack a whole meta-batch.

    Returns ``[B, n_way, n_samples, H, W, C]`` float32, or None when the
    native engine is unavailable (caller falls back to numpy).
    """
    lib = load_engine()
    if lib is None:
        return None
    B, n_way, n_samples = image_idx.shape
    _, H, W, C = cache.shape
    out = np.empty((B, n_way, n_samples, H, W, C), np.float32)
    has_norm = int(mean is not None)
    m, s = (mean, std) if has_norm else _NO_NORM
    rc = lib.assemble_episodes(
        np.ascontiguousarray(cache),
        np.ascontiguousarray(image_idx, np.int64),
        np.ascontiguousarray(rot_k, np.int32),
        out, B, n_way, n_samples, H, W, C,
        np.ascontiguousarray(m, np.float32),
        np.ascontiguousarray(s, np.float32),
        has_norm, num_threads,
    )
    if rc != 0:
        raise ValueError("assemble_episodes: odd rotation of non-square images")
    return out
