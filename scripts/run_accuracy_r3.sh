#!/bin/bash
# Round-3 accuracy matrix (VERDICT r2 item 1): the reference's published
# Omniglot configs (BASELINE.md / nbs cells 9-11), full 150-epoch budget,
# seed 0, run serially on the attached TPU chip.
# Reference anchors: vgg+SGD 5w1s 99.62+-0.08, 5w5s 99.86+-0.02,
# 20w1s 97.21+-0.11, 20w5s 99.13+-0.13; resnet-4+SGD 5w1s 99.91+-0.05.
set -u
cd /root/repo
COMMON="dataset=omniglot inner_optim=gd seed=0 train_seed=0 val_seed=0 \
 dataset.path=/root/reference/datasets/omniglot_dataset \
 index_cache_dir=/tmp/omniglot_idx load_into_memory=true \
 total_epochs=150 remat_inner_steps=false"

run () {
  name=$1; shift
  echo "=== $(date -u +%H:%M:%S) start $name" >> exps/sweep_r3.log
  python train_maml_system.py $COMMON experiment_name="$name" "$@" \
    >> "exps/${name}.out" 2>&1
  echo "=== $(date -u +%H:%M:%S) done $name rc=$?" >> exps/sweep_r3.log
}

run omniglot.5.1.vgg.gd.s0      num_classes_per_set=5  num_samples_per_class=1 net=vgg
run omniglot.20.1.vgg.gd.s0     num_classes_per_set=20 num_samples_per_class=1 net=vgg
run omniglot.5.5.vgg.gd.s0      num_classes_per_set=5  num_samples_per_class=5 net=vgg
run omniglot.20.5.vgg.gd.s0     num_classes_per_set=20 num_samples_per_class=5 net=vgg
run omniglot.5.1.resnet-4.gd.s0 num_classes_per_set=5  num_samples_per_class=1 net=resnet-4
echo "=== $(date -u +%H:%M:%S) ALL DONE" >> exps/sweep_r3.log
