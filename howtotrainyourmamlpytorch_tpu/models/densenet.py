"""Stem-less DenseNet-BC for few-shot learning (reference ``models.py:153-220``).

No first convolution: the dense blocks start directly from the input channels
(``models.py:180``). ``growth_rate=8``, ``bn_size=2``; densenet-8/12 map to
``block_config=[2]*4 / [3]*4`` (reference ``few_shot_learning_system.py:74-77``).
Each dense layer (torchvision ``_DenseLayer``) is
BN -> ReLU -> Conv1x1(bn_size*growth) -> BN -> ReLU -> Conv3x3(growth, pad 1),
output concatenated onto the running feature stack; transitions are
BN -> ReLU -> Conv1x1(features//2) -> AvgPool2x2. Final BN -> ReLU -> global
avg pool -> Linear (zero bias, ``models.py:211-212``); convs use
kaiming-normal fan_in (torch ``kaiming_normal_`` default, ``models.py:206-207``).
"""

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from . import layers
from .model import Model


def _init_dense_layer(key, cin, growth_rate, bn_size):
    k1, k2 = jax.random.split(key)
    bottleneck = bn_size * growth_rate
    n1_p, n1_s = layers.init_batch_norm(cin)
    n2_p, n2_s = layers.init_batch_norm(bottleneck)
    params = {
        "norm1": n1_p,
        "conv1": layers.init_conv(k1, 1, 1, cin, bottleneck, bias=False, init="kaiming_normal_fan_in"),
        "norm2": n2_p,
        "conv2": layers.init_conv(k2, 3, 3, bottleneck, growth_rate, bias=False, init="kaiming_normal_fan_in"),
    }
    state = {"norm1": n1_s, "norm2": n2_s}
    return params, state


def _apply_dense_layer(params, state, x, use_batch_stats, update_running, via_patches=False,
                       sample_weight=None, stat_dtype=None):
    out, n1_s = layers.batch_norm(
        params["norm1"], state["norm1"], x, use_batch_stats, update_running,
        sample_weight=sample_weight, stat_dtype=stat_dtype,
    )
    out = layers.relu(out)
    out = layers.conv2d(params["conv1"], out, stride=1, padding=0, via_patches=via_patches)
    out, n2_s = layers.batch_norm(
        params["norm2"], state["norm2"], out, use_batch_stats, update_running,
        sample_weight=sample_weight, stat_dtype=stat_dtype,
    )
    out = layers.relu(out)
    out = layers.conv2d(params["conv2"], out, stride=1, padding=1, via_patches=via_patches)
    return out, {"norm1": n1_s, "norm2": n2_s}


def build_densenet(
    image_shape: Tuple[int, int, int],
    num_classes: int,
    block_config: Sequence[int] = (3, 3, 3, 3),
    growth_rate: int = 8,
    bn_size: int = 2,
    conv_via_patches: bool = False,
) -> Model:
    """``conv_via_patches`` bakes the conv implementation into this model's
    apply (explicit parameter, not a process global — see layers.conv2d).
    No max-pool knob: transitions use average pooling."""
    h, w, c = image_shape

    def init(key):
        params, state = {}, {}
        num_features = c
        n_keys = sum(block_config) + len(block_config)
        keys = jax.random.split(key, n_keys)
        ki = 0
        for i, num_layers in enumerate(block_config):
            block_p, block_s = {}, {}
            for li in range(num_layers):
                lp, ls = _init_dense_layer(
                    keys[ki], num_features + li * growth_rate, growth_rate, bn_size
                )
                ki += 1
                block_p[f"layer_{li}"] = lp
                block_s[f"layer_{li}"] = ls
            params[f"denseblock{i + 1}"] = block_p
            state[f"denseblock{i + 1}"] = block_s
            num_features = num_features + num_layers * growth_rate
            if i != len(block_config) - 1:
                tn_p, tn_s = layers.init_batch_norm(num_features)
                params[f"transition{i + 1}"] = {
                    "norm": tn_p,
                    "conv": layers.init_conv(
                        keys[ki], 1, 1, num_features, num_features // 2,
                        bias=False, init="kaiming_normal_fan_in",
                    ),
                }
                state[f"transition{i + 1}"] = {"norm": tn_s}
                ki += 1
                num_features = num_features // 2
        n5_p, n5_s = layers.init_batch_norm(num_features)
        params["norm5"] = n5_p
        state["norm5"] = n5_s
        params["classifier"] = layers.init_linear(
            keys[-1], num_features, num_classes, zero_bias=True
        )
        return params, state

    def apply(params, state, x, *, use_batch_stats=True, update_running=False,
              sample_weight=None, stat_dtype=None):
        new_state = {}
        for i, num_layers in enumerate(block_config):
            bname = f"denseblock{i + 1}"
            block_s = {}
            for li in range(num_layers):
                lname = f"layer_{li}"
                new_feat, ls = _apply_dense_layer(
                    params[bname][lname], state[bname][lname], x,
                    use_batch_stats, update_running, conv_via_patches,
                    sample_weight, stat_dtype,
                )
                block_s[lname] = ls
                x = jnp.concatenate([x, new_feat], axis=-1)
            new_state[bname] = block_s
            if i != len(block_config) - 1:
                tname = f"transition{i + 1}"
                x, tn_s = layers.batch_norm(
                    params[tname]["norm"], state[tname]["norm"], x,
                    use_batch_stats, update_running, sample_weight=sample_weight,
                    stat_dtype=stat_dtype,
                )
                x = layers.relu(x)
                x = layers.conv2d(
                    params[tname]["conv"], x, stride=1, padding=0,
                    via_patches=conv_via_patches,
                )
                x = layers.avg_pool(x)
                new_state[tname] = {"norm": tn_s}
        x, n5_s = layers.batch_norm(
            params["norm5"], state["norm5"], x, use_batch_stats, update_running,
            sample_weight=sample_weight, stat_dtype=stat_dtype,
        )
        new_state["norm5"] = n5_s
        x = layers.relu(x)
        x = layers.global_avg_pool(x)
        return layers.linear(params["classifier"], x), new_state

    # reduce_window_pool=None: transitions use average pooling, so the
    # max-pool tie-subgradient convention does not apply
    return Model(
        init=init, apply=apply, name="densenet", conv_via_patches=conv_via_patches
    )
