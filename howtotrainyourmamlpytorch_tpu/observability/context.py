"""Request-scoped tracing: trace ids end to end + the structured access log.

Aggregate observability (PRs 5 and 7) answers "where does time go" for the
*population*; this module answers it for *one request* — the Dapper lesson
(Sigelman et al., 2010) that sampled per-request traces, not histograms, are
what debug tail latency in a batched serving tier. Continuous batching makes
the need sharper: one request's latency is a function of its flush-mates
(Orca; Yu et al., OSDI 2022), so a bad p99 can only be explained by seeing
*that request's* queue wait and flush batch, not the percentile it landed in.

Three pieces:

- :class:`RequestContext` — the per-request identity (128-bit trace id +
  64-bit span id, W3C ``traceparent``-compatible) and the mutable timing
  slots the serving path fills in as the request moves HTTP thread ->
  batcher queue -> worker flush -> engine dispatch. Accepted/echoed via the
  ``traceparent`` header (:func:`parse_traceparent` /
  :func:`format_traceparent`), minted when absent
  (:func:`new_request_context` — ``os.urandom``, no seeded RNG).
- Flow helpers (:func:`flow_start` etc.) — build the ``flows`` argument
  ``SpanTracer.span`` records so the Chrome/Perfetto export links a
  request's spans across threads as one arc (``ph: s/t/f`` flow events).
- :class:`AccessLog` — the sampled structured access log
  (``logs/access.jsonl``, one JSON line per request: trace id, verb,
  bucket, flush batch, queue-wait/dispatch/total ms, cache hit, outcome,
  breaker state). Sampling is deterministic on the trace id so every
  process of a fleet keeps or drops the same request; non-``ok`` outcomes
  are ALWAYS logged regardless of the sample rate — the chaos-campaign
  invariant "every non-200 response has an access line" must hold at any
  sampling level.

``scripts/trace_merge.py`` joins per-process traces + access logs into one
Perfetto timeline; OPERATIONS.md "Tracing a request" is the runbook.
"""

import dataclasses
import json
import os
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..utils.locks import san_lock

#: W3C traceparent: version "00" - 16-byte trace id - 8-byte parent span id
#: - 2-hex flags (bit 0 = sampled). All-zero ids are invalid per spec.
_TRACEPARENT_RE = re.compile(
    r"^00-(?!0{32})([0-9a-f]{32})-(?!0{16})([0-9a-f]{16})-([0-9a-f]{2})$"
)


@dataclasses.dataclass
class RequestContext:
    """One request's identity + the timing slots each hop fills in.

    The identity fields are immutable in spirit; the timing slots are
    written by exactly one later hop each (batcher worker: queue wait /
    flush batch; engine: dispatch seconds; cache: hit flag), each a single
    GIL-atomic attribute store. For a request that RESOLVES, the
    ``Future.result`` edge is the happens-before and the reader sees every
    stamp. For a request the caller abandons at its deadline, the worker
    may still be stamping while the failure line is logged — that line
    shows whichever hops had completed by logging time (e.g. queue wait
    without dispatch), which is the honest journey of an abandoned
    request, so no lock is spent on it."""

    trace_id: str  # 32 lowercase hex chars (16 bytes)
    span_id: str  # 16 lowercase hex chars (8 bytes), minted per server hop
    parent_id: Optional[str] = None  # upstream span id from traceparent
    sampled: bool = True  # traceparent sampled flag, echoed downstream
    # -- filled in as the request moves through the serving path --------
    bucket: Any = None  # shape bucket the frontend routed to
    true_size: Optional[int] = None  # pre-padding sample count (waste acct)
    strategy: Optional[str] = None  # adaptation strategy the request named
    tenant: Optional[str] = None  # tenant the request named (None = default)
    replica: Optional[int] = None  # pool replica the router chose
    flush_batch: Optional[int] = None  # requests sharing the flush
    queue_wait_s: Optional[float] = None  # submit -> worker pickup
    dispatch_s: Optional[float] = None  # engine device dispatch
    cache_hit: Optional[bool] = None  # adapted-weight cache verdict
    access_logged: bool = False  # the double-log guard (HTTP layer)

    def timing_ms(self, total_s: Optional[float] = None) -> Dict[str, Any]:
        """The per-request breakdown returned in response bodies and logged
        to access.jsonl (``None`` for hops the request never reached)."""

        def ms(v):
            return round(v * 1e3, 3) if v is not None else None

        return {
            "queue_wait_ms": ms(self.queue_wait_s),
            "dispatch_ms": ms(self.dispatch_s),
            "total_ms": ms(total_s),
        }


def new_request_context() -> RequestContext:
    """Mint a fresh root context (``os.urandom`` — collision-safe across
    processes, never a seeded RNG)."""
    return RequestContext(
        trace_id=os.urandom(16).hex(), span_id=os.urandom(8).hex()
    )


def parse_traceparent(header: Optional[str]) -> RequestContext:
    """Adopt an incoming ``traceparent`` (the caller's trace id becomes
    ours, their span id becomes our parent) or mint a fresh context when
    the header is absent or malformed — a bad header must never 4xx a
    request over plumbing the client may not even know it sends."""
    if header:
        m = _TRACEPARENT_RE.match(header.strip().lower())
        if m:
            trace_id, parent_id, flags = m.groups()
            return RequestContext(
                trace_id=trace_id,
                span_id=os.urandom(8).hex(),
                parent_id=parent_id,
                sampled=bool(int(flags, 16) & 1),
            )
    return new_request_context()


def format_traceparent(ctx: RequestContext) -> str:
    """The outgoing header: our span id is the downstream's parent."""
    return f"00-{ctx.trace_id}-{ctx.span_id}-{'01' if ctx.sampled else '00'}"


# ---------------------------------------------------------------------------
# flow helpers: the ``flows`` argument SpanTracer.span records
# ---------------------------------------------------------------------------


def flow_start(ctx: Optional[RequestContext]) -> Optional[Tuple]:
    """Flow origin (``ph: "s"``) — the request's entry span (HTTP thread).
    A start with no finish is legitimate: the request never reached a
    device dispatch (cache hit, shed, breaker rejection)."""
    return ((ctx.trace_id, "s"),) if ctx is not None else None


def flow_step(ctxs: Sequence[Optional[RequestContext]]) -> Optional[Tuple]:
    """Flow step (``ph: "t"``) — the batcher flush span, one step per
    request the flush carries (two requests, one flush span, two flows)."""
    steps = tuple((c.trace_id, "t") for c in ctxs if c is not None)
    return steps or None


def flow_end(ctxs: Sequence[Optional[RequestContext]]) -> Optional[Tuple]:
    """Flow finish (``ph: "f"``) — the engine dispatch span."""
    ends = tuple((c.trace_id, "f") for c in ctxs if c is not None)
    return ends or None


# ---------------------------------------------------------------------------
# the structured access log
# ---------------------------------------------------------------------------


class AccessLog:
    """Sampled per-request JSON lines in ``<log_dir>/access.jsonl``.

    Storage rides :class:`~..experiment.storage.EventLog` (whole-line
    writes, flushed per append, lock-protected — HTTP handler threads and
    the in-process API log concurrently), so a hard-killed server leaves at
    worst one torn final line. Sampling is a deterministic function of the
    trace id — every process of a fleet keeps or drops the SAME request, so
    a cross-process ``trace_merge`` never sees half a journey — and
    non-``ok`` outcomes bypass it entirely."""

    def __init__(
        self,
        log_dir: str,
        sample: float = 1.0,
        filename: str = "access.jsonl",
        wall_clock: Callable[[], float] = time.time,
    ):
        from ..experiment.storage import EventLog

        os.makedirs(log_dir, exist_ok=True)
        self._log = EventLog(log_dir, filename=filename)
        self.path = self._log.path
        self.sample = float(sample)
        self._wall_clock = wall_clock
        self._lock = san_lock("AccessLog._lock")
        self.lines = 0
        self.sampled_out = 0

    def should_sample(self, trace_id: str) -> bool:
        """Deterministic keep/drop from the id's leading 32 bits."""
        if self.sample >= 1.0:
            return True
        if self.sample <= 0.0:
            return False
        return int(trace_id[:8], 16) / float(1 << 32) < self.sample

    def record(
        self,
        ctx: RequestContext,
        verb: str,
        outcome: str,
        status: Optional[int],
        total_s: Optional[float],
        **fields: Any,
    ) -> bool:
        """Append one line (or count it sampled out). Marks the context
        logged either way so the HTTP layer never double-logs. Returns
        whether a line was written."""
        ctx.access_logged = True
        if outcome == "ok" and not self.should_sample(ctx.trace_id):
            with self._lock:
                self.sampled_out += 1
            return False
        rec: Dict[str, Any] = {
            "ts": self._wall_clock(),
            "trace_id": ctx.trace_id,
            "parent_id": ctx.parent_id,
            "verb": verb,
            "outcome": outcome,
            "status": status,
            "bucket": ctx.bucket,
            "true_size": ctx.true_size,
            "strategy": ctx.strategy,
            "tenant": ctx.tenant,
            "replica": ctx.replica,
            "flush_batch": ctx.flush_batch,
            "cache_hit": ctx.cache_hit,
            **ctx.timing_ms(total_s),
        }
        rec.update(fields)
        self._log.append(rec)
        with self._lock:
            self.lines += 1
        return True

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "path": self.path,
                "lines": self.lines,
                "sampled_out": self.sampled_out,
                "sample": self.sample,
            }

    def close(self) -> None:
        self._log.close()


def read_access_log(path: str) -> Tuple[List[Dict[str, Any]], int]:
    """Parse an access.jsonl, skipping (and counting) torn lines — readers
    (SLO report join, trace_merge, the chaos invariant) must degrade on a
    hard-killed server's log, never die on it."""
    records: List[Dict[str, Any]] = []
    torn = 0
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                torn += 1
    return records, torn
