"""Results analysis — the reference's plotting notebook as a library.

The reference ships its analysis as ``nbs/2019.09.14.plot.ipynb``: cells 2-6
load each sweep run's ``config.yaml`` + ``logs/*.csv``, cells 8-11 aggregate
meta-test accuracy over seeds per (dataset, n_way, k_shot, model, inner_optim)
into a LaTeX table (keeping only groups where all seeds finished — cell 8
filters ``count == 3``), and cells 13-14 plot the learned per-tensor inner-opt
learning rates / Adam betas over epochs from ``lrs.csv`` / ``betas.csv``.

This module is the same pipeline as importable functions over the identical
artifact contract (experiment/storage.py), plus a CLI (``analyze_results.py``)
that emits markdown + LaTeX tables and PNG plots instead of notebook cells.
"""

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import yaml

from .experiment import storage


@dataclasses.dataclass
class RunRecord:
    """Everything the notebook reads from one run directory."""

    run_dir: str
    config: Dict[str, Any]
    # one dict per epoch from logs/summary_statistics.csv
    summary: List[Dict[str, float]]
    # rows of logs/test_summary.csv (usually one)
    test: List[Dict[str, float]]
    # [epochs, n_tensors] learned per-tensor lrs; None if not recorded
    lrs: Optional[np.ndarray] = None
    # [epochs, 2*n_tensors] interleaved (b1, b2) per tensor; None unless Adam
    betas: Optional[np.ndarray] = None

    # -- the ablation axes the notebook groups by (cells 8-11) --------------
    @property
    def dataset(self) -> str:
        return self.config.get("dataset", {}).get("name", "?")

    @property
    def n_way(self) -> int:
        return int(self.config.get("num_classes_per_set", 0))

    @property
    def k_shot(self) -> int:
        return int(self.config.get("num_samples_per_class", 0))

    @property
    def net(self) -> str:
        return self.config.get("net", "?")

    @property
    def inner_optim(self) -> str:
        opt = self.config.get("inner_optim", {})
        return opt.get("kind", "?") if isinstance(opt, dict) else str(opt)

    @property
    def seed(self) -> int:
        return int(self.config.get("seed", 0))

    @property
    def group_key(self) -> Tuple[str, int, int, str, str]:
        return (self.dataset, self.n_way, self.k_shot, self.net, self.inner_optim)

    @property
    def test_accuracy(self) -> Optional[float]:
        if not self.test:
            return None
        return float(self.test[-1]["test_accuracy_mean"])


def _read_csv_rows(path: str) -> List[Dict[str, float]]:
    if not os.path.exists(path):
        return []
    rows = storage.load_statistics(os.path.dirname(path), os.path.basename(path))

    def scalar_or_none(v):
        # header-reconciled CSVs back-fill missing cells with '' — map exactly
        # those to None so the 'is not None' filters (and matplotlib) skip
        # them; legitimately-string columns (e.g. test_ensemble_epochs) pass
        # through unchanged
        return None if v == "" else storage._scalar(v)

    return [{k: scalar_or_none(v) for k, v in row.items()} for row in rows]


def _read_hparam_csv(path: str) -> Optional[np.ndarray]:
    """lrs.csv / betas.csv: header-less comma-joined floats, one row per epoch
    (storage.append_hparam_row; reference few_shot_learning_system.py:366-376)."""
    if not os.path.exists(path):
        return None
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append([float(v) for v in line.split(",")])
    if not rows:
        return None
    width = max(len(r) for r in rows)
    return np.array([r + [np.nan] * (width - len(r)) for r in rows], np.float64)


def load_run(run_dir: str) -> Optional[RunRecord]:
    """Load one run directory (notebook cells 2-3); None if it has no config."""
    cfg_path = os.path.join(run_dir, "config.yaml")
    if not os.path.exists(cfg_path):
        return None
    with open(cfg_path) as f:
        config = yaml.safe_load(f) or {}
    logs = os.path.join(run_dir, "logs")
    return RunRecord(
        run_dir=run_dir,
        config=config,
        summary=_read_csv_rows(os.path.join(logs, "summary_statistics.csv")),
        test=_read_csv_rows(os.path.join(logs, "test_summary.csv")),
        lrs=_read_hparam_csv(os.path.join(run_dir, "lrs.csv")),
        betas=_read_hparam_csv(os.path.join(run_dir, "betas.csv")),
    )


def collect_runs(exps_root: str) -> List[RunRecord]:
    """Walk an experiments root and load every run dir (one with config.yaml)."""
    runs = []
    for dirpath, dirnames, filenames in os.walk(exps_root):
        if "config.yaml" in filenames:
            run = load_run(dirpath)
            if run is not None:
                runs.append(run)
            dirnames[:] = []  # run dirs don't nest
    return sorted(runs, key=lambda r: r.run_dir)


# ---------------------------------------------------------------------------
# Aggregation (notebook cells 8-11)
# ---------------------------------------------------------------------------

# The reference's published test accuracies — mean ± std over 3 seeds, from
# the committed outputs of its analysis notebook (BASELINE.md; reference
# ``nbs/2019.09.14.plot.ipynb`` cell 11). Keyed like ``RunRecord.group_key``
# (inner-optimizer kind in this framework's lowercase spelling) so every
# aggregated row can carry its reference target and Δ automatically.
REFERENCE_TEST_ACCURACY: Dict[Tuple[str, int, int, str, str], Tuple[float, float]] = {
    ("mini_imagenet_full_size", 5, 1, "densenet-8", "sgd"): (46.08, 1.40),
    ("mini_imagenet_full_size", 5, 1, "resnet-12", "sgd"): (51.06, 1.51),
    ("mini_imagenet_full_size", 5, 1, "resnet-4", "adam"): (49.71, 3.71),
    ("mini_imagenet_full_size", 5, 1, "resnet-4", "sgd"): (54.36, 0.23),
    ("mini_imagenet_full_size", 5, 1, "resnet-8", "sgd"): (54.16, 1.35),
    ("mini_imagenet_full_size", 5, 1, "vgg", "adam"): (47.93, 11.64),
    ("mini_imagenet_full_size", 5, 1, "vgg", "sgd"): (56.33, 0.27),
    ("mini_imagenet_full_size", 5, 5, "densenet-8", "sgd"): (65.29, 0.98),
    ("mini_imagenet_full_size", 5, 5, "resnet-12", "adam"): (37.40, 3.64),
    ("mini_imagenet_full_size", 5, 5, "resnet-12", "sgd"): (69.14, 3.19),
    ("mini_imagenet_full_size", 5, 5, "resnet-4", "adam"): (76.33, 0.71),
    ("mini_imagenet_full_size", 5, 5, "resnet-4", "sgd"): (74.48, 0.77),
    ("mini_imagenet_full_size", 5, 5, "resnet-8", "adam"): (68.03, 15.19),
    ("mini_imagenet_full_size", 5, 5, "resnet-8", "sgd"): (76.73, 0.52),
    ("mini_imagenet_full_size", 5, 5, "vgg", "adam"): (72.82, 2.36),
    ("mini_imagenet_full_size", 5, 5, "vgg", "sgd"): (75.13, 0.67),
    ("omniglot_dataset", 5, 1, "densenet-8", "sgd"): (99.54, 0.33),
    ("omniglot_dataset", 5, 1, "resnet-4", "sgd"): (99.91, 0.05),
    ("omniglot_dataset", 5, 1, "vgg", "adam"): (99.62, 0.08),
    ("omniglot_dataset", 5, 1, "vgg", "sgd"): (99.62, 0.08),
    ("omniglot_dataset", 5, 5, "densenet-8", "sgd"): (99.86, 0.05),
    ("omniglot_dataset", 5, 5, "resnet-4", "sgd"): (99.87, 0.03),
    ("omniglot_dataset", 5, 5, "vgg", "adam"): (99.86, 0.04),
    ("omniglot_dataset", 5, 5, "vgg", "sgd"): (99.86, 0.02),
    ("omniglot_dataset", 20, 1, "densenet-8", "sgd"): (93.20, 0.32),
    ("omniglot_dataset", 20, 1, "resnet-12", "sgd"): (99.00, 0.33),
    ("omniglot_dataset", 20, 1, "resnet-4", "adam"): (98.31, 0.09),
    ("omniglot_dataset", 20, 1, "resnet-4", "sgd"): (96.31, 0.15),
    ("omniglot_dataset", 20, 1, "resnet-8", "sgd"): (98.50, 0.15),
    ("omniglot_dataset", 20, 1, "vgg", "adam"): (96.15, 0.16),
    ("omniglot_dataset", 20, 1, "vgg", "sgd"): (97.21, 0.11),
    ("omniglot_dataset", 20, 5, "densenet-8", "sgd"): (97.24, 0.26),
    ("omniglot_dataset", 20, 5, "resnet-12", "sgd"): (99.69, 0.17),
    ("omniglot_dataset", 20, 5, "resnet-4", "adam"): (99.44, 0.23),
    ("omniglot_dataset", 20, 5, "resnet-4", "sgd"): (99.71, 0.03),
    ("omniglot_dataset", 20, 5, "resnet-8", "sgd"): (99.76, 0.01),
    ("omniglot_dataset", 20, 5, "vgg", "adam"): (98.74, 0.04),
    ("omniglot_dataset", 20, 5, "vgg", "sgd"): (99.13, 0.13),
}


@dataclasses.dataclass
class AggregateRow:
    dataset: str
    n_way: int
    k_shot: int
    net: str
    inner_optim: str
    mean: float  # test accuracy, percent
    std: float
    count: int  # seeds aggregated
    # the reference's published number for the same ablation cell (None when
    # the reference never ran it, e.g. any rprop cell)
    ref_mean: Optional[float] = None
    ref_std: Optional[float] = None

    @property
    def delta_vs_ref(self) -> Optional[float]:
        return None if self.ref_mean is None else self.mean - self.ref_mean


def aggregate_test_accuracy(
    runs: Sequence[RunRecord], min_seeds: int = 1
) -> List[AggregateRow]:
    """Mean/std of meta-test accuracy over seeds per ablation cell, each row
    carrying the reference's published number for the same cell.

    The notebook keeps only cells where all 3 seeds finished (cell 8 filters
    ``count == 3``); ``min_seeds`` generalizes that threshold.
    Accuracies are reported in percent (the baseline-table convention).
    """
    groups: Dict[Tuple[str, int, int, str, str], List[float]] = {}
    for run in runs:
        acc = run.test_accuracy
        if acc is None:
            continue
        groups.setdefault(run.group_key, []).append(acc * 100.0)
    rows = []
    for key in sorted(groups):
        accs = np.asarray(groups[key], np.float64)
        if len(accs) < min_seeds:
            continue
        ref = REFERENCE_TEST_ACCURACY.get(key)
        rows.append(
            AggregateRow(
                *key,
                mean=float(accs.mean()),
                std=float(accs.std()),
                count=len(accs),
                ref_mean=ref[0] if ref else None,
                ref_std=ref[1] if ref else None,
            )
        )
    return rows


_TABLE_HEADER = [
    "Dataset", "N-way", "K-shot", "Model", "Inner opt",
    "Test acc (%)", "Std", "Seeds", "Ref (3 seeds)", "Δ vs ref",
]


def to_markdown(rows: Sequence[AggregateRow]) -> str:
    lines = [
        "| " + " | ".join(_TABLE_HEADER) + " |",
        "|" + "|".join("---" for _ in _TABLE_HEADER) + "|",
    ]
    for r in rows:
        ref = (
            f"{r.ref_mean:.2f} ± {r.ref_std:.2f}" if r.ref_mean is not None else "—"
        )
        delta = (
            f"{r.delta_vs_ref:+.2f}" if r.delta_vs_ref is not None else "—"
        )
        lines.append(
            f"| {r.dataset} | {r.n_way} | {r.k_shot} | {r.net} | {r.inner_optim} "
            f"| {r.mean:.2f} | {r.std:.2f} | {r.count} | {ref} | {delta} |"
        )
    return "\n".join(lines) + "\n"


def _tex(s: str) -> str:
    """Escape text-mode LaTeX specials in names ('omniglot_dataset' etc.)."""
    for ch in "&%$#_{}":
        s = s.replace(ch, "\\" + ch)
    return s


def to_latex(rows: Sequence[AggregateRow]) -> str:
    """The notebook cell-11 style LaTeX table (mean ± std per ablation cell),
    extended with the reference-baseline columns so all three report formats
    (markdown / JSON / LaTeX) agree on schema (ADVICE r5 #2): each row
    carries the reference's published mean ± std for the same cell and the
    signed delta, or ``--`` where the reference never ran that cell."""
    lines = [
        "\\begin{tabular}{llllllll}",
        "\\toprule",
        "Dataset & N-way & K-shot & Model & Inner opt & Test acc (\\%) & "
        "Ref (3 seeds) & $\\Delta$ vs ref \\\\",
        "\\midrule",
    ]
    for r in rows:
        ref = (
            f"${r.ref_mean:.2f} \\pm {r.ref_std:.2f}$"
            if r.ref_mean is not None
            else "--"
        )
        delta = f"${r.delta_vs_ref:+.2f}$" if r.delta_vs_ref is not None else "--"
        lines.append(
            f"{_tex(r.dataset)} & {r.n_way} & {r.k_shot} & {_tex(r.net)} & "
            f"{_tex(r.inner_optim)} & ${r.mean:.2f} \\pm {r.std:.2f}$ & "
            f"{ref} & {delta} \\\\"
        )
    lines += ["\\bottomrule", "\\end{tabular}"]
    return "\n".join(lines) + "\n"


def best_per_config(rows: Sequence[AggregateRow]) -> List[AggregateRow]:
    """Best (model, inner_optim) per (dataset, n_way, k_shot) — the headline
    'Best' column of the baseline table (notebook cells 9-10)."""
    best: Dict[Tuple[str, int, int], AggregateRow] = {}
    for r in rows:
        key = (r.dataset, r.n_way, r.k_shot)
        if key not in best or r.mean > best[key].mean:
            best[key] = r
    return [best[k] for k in sorted(best)]


# ---------------------------------------------------------------------------
# Plots (notebook cells 4-6, 13-14) — matplotlib, headless
# ---------------------------------------------------------------------------


def _plt():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


def plot_learning_curves(run: RunRecord, out_path: str) -> Optional[str]:
    """Train/val accuracy + loss vs epoch (notebook cells 4-6)."""
    if not run.summary:
        return None
    plt = _plt()
    epochs = [r.get("epoch", i) for i, r in enumerate(run.summary)]
    fig, (ax_acc, ax_loss) = plt.subplots(1, 2, figsize=(11, 4))
    for split, style in (("train", "-"), ("val", "--")):
        acc = [r.get(f"{split}_accuracy_mean") for r in run.summary]
        loss = [r.get(f"{split}_loss_mean") for r in run.summary]
        if any(v is not None for v in acc):
            ax_acc.plot(epochs, acc, style, label=split)
        if any(v is not None for v in loss):
            ax_loss.plot(epochs, loss, style, label=split)
    ax_acc.set_xlabel("epoch"), ax_acc.set_ylabel("accuracy"), ax_acc.legend()
    ax_loss.set_xlabel("epoch"), ax_loss.set_ylabel("loss"), ax_loss.legend()
    fig.suptitle(os.path.basename(run.run_dir.rstrip("/")))
    fig.tight_layout()
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


def plot_inner_opt_stats(run: RunRecord, out_path: str) -> Optional[str]:
    """Learned per-tensor lrs (and Adam betas) over epochs (cells 13-14)."""
    if run.lrs is None:
        return None
    plt = _plt()
    n_panels = 1 + (run.betas is not None)
    fig, axes = plt.subplots(1, n_panels, figsize=(6 * n_panels, 4), squeeze=False)
    ax = axes[0][0]
    for j in range(run.lrs.shape[1]):
        ax.plot(run.lrs[:, j], lw=0.8)
    ax.set_xlabel("epoch"), ax.set_ylabel("inner lr"), ax.set_title("learned per-tensor lrs")
    if run.betas is not None:
        ax = axes[0][1]
        for j in range(run.betas.shape[1]):
            # interleaved b1, b2 per tensor (runner.write_inner_opt_stats)
            ax.plot(run.betas[:, j], lw=0.8, ls="-" if j % 2 == 0 else ":")
        ax.set_xlabel("epoch"), ax.set_ylabel("beta"), ax.set_title("learned Adam betas (b1 solid, b2 dotted)")
    fig.tight_layout()
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


# ---------------------------------------------------------------------------
# End-to-end report (what the notebook produces, as files)
# ---------------------------------------------------------------------------


def write_report(exps_root: str, out_dir: str, min_seeds: int = 1) -> Dict[str, Any]:
    """Analyze every run under ``exps_root`` into ``out_dir``: aggregate
    markdown/LaTeX/JSON tables + per-run curve and inner-opt-stat plots.

    An empty run set (nothing under ``exps_root`` has a config.yaml, or no
    cell met ``min_seeds``) is stamped explicitly — "0 runs matched" /
    "0 aggregate rows" — instead of emitting header-only tables that read as
    a silently-successful analysis (VERDICT r5 weak #6)."""
    os.makedirs(out_dir, exist_ok=True)
    runs = collect_runs(exps_root)
    rows = aggregate_test_accuracy(runs, min_seeds=min_seeds)
    empty_stamp = None
    if not runs:
        empty_stamp = f"0 runs matched under {exps_root!r} — nothing to aggregate.\n"
    elif not rows:
        empty_stamp = (
            f"0 aggregate rows: {len(runs)} run(s) found under {exps_root!r} "
            f"but none with a finished test_summary.csv met min_seeds="
            f"{min_seeds}.\n"
        )
    with open(os.path.join(out_dir, "test_accuracy.md"), "w") as f:
        if empty_stamp:
            f.write(empty_stamp)
        else:
            f.write(to_markdown(rows))
            best = best_per_config(rows)
            if best:
                f.write("\nBest (model, inner-opt) per config:\n\n" + to_markdown(best))
    with open(os.path.join(out_dir, "test_accuracy.tex"), "w") as f:
        f.write(f"% {empty_stamp}" if empty_stamp else to_latex(rows))
    with open(os.path.join(out_dir, "test_accuracy.json"), "w") as f:
        # the JSON carries the empty stamp too (an unmarked bare [] is the
        # same silently-successful-empty artifact the md/tex stamps prevent);
        # shape: list of rows normally, {"warning", "rows": []} when empty
        json.dump(
            {"warning": empty_stamp.strip(), "rows": []}
            if empty_stamp
            else [{**dataclasses.asdict(r), "delta_vs_ref": r.delta_vs_ref} for r in rows],
            f,
            indent=1,
        )
    plots = []
    for run in runs:
        # stem from the run dir's path relative to the sweep root, so
        # same-basename runs in different sweep subdirs don't collide
        rel = os.path.relpath(run.run_dir, exps_root).replace(os.sep, ".")
        stem = rel + f".seed{run.seed}"
        p = plot_learning_curves(run, os.path.join(out_dir, f"{stem}.curves.png"))
        q = plot_inner_opt_stats(run, os.path.join(out_dir, f"{stem}.inner_opt.png"))
        plots += [x for x in (p, q) if x]
    return {
        "runs": len(runs),
        "table_rows": len(rows),
        "plots": plots,
        "out_dir": out_dir,
        **({"warning": empty_stamp.strip()} if empty_stamp else {}),
    }
