#!/usr/bin/env python
"""Training entry point (reference ``train_maml_system.py``).

Usage:
    python train_maml_system.py [--config configs/omniglot_5way_1shot.yaml] \
        [key=value ...]

(No --config runs the reference default, Omniglot 20-way 5-shot —
``configs/default.yaml`` spells it out.)

Overrides use dotted paths, e.g.::

    python train_maml_system.py net=resnet-4 inner_optim=adam \
        num_classes_per_set=5 num_samples_per_class=1 dataset=omniglot

Unlike the reference (hydra 0.x chdir + hard-coded ``torch.device('cuda')``,
``train_maml_system.py:16,23``), this runs against whatever JAX platform is
visible (TPU chip(s), or CPU with ``JAX_PLATFORMS=cpu``) and writes artifacts
under ``exps/{dataset}.{n_way}.{k_shot}`` without changing directory.
"""

import argparse
import sys


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--config", default=None, help="YAML config file")
    parser.add_argument("overrides", nargs="*", help="key=value config overrides")
    args = parser.parse_args(argv)

    import os

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        # Site hooks (e.g. a TPU-tunnel plugin) may override the platform
        # selection after capturing the env; re-assert the user's choice.
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    # Persistent XLA compilation cache: re-runs of the same (shape, config)
    # programs skip the 20-40s first compile. One shared helper
    # (utils/compcache.py) owns the setup; Config.compilation_cache_dir >
    # JAX_COMPILATION_CACHE_DIR env > the shared default.
    from howtotrainyourmamlpytorch_tpu.config import load_config
    from howtotrainyourmamlpytorch_tpu.experiment import ExperimentRunner
    from howtotrainyourmamlpytorch_tpu.utils.compcache import setup_compilation_cache

    cfg = load_config(args.config, args.overrides)
    setup_compilation_cache(cfg.compilation_cache_dir)
    runner = ExperimentRunner(cfg)
    print(f"run dir: {runner.run_dir}")
    print(f"n_params: {runner.system.num_params(runner.state)}")
    result = runner.run_experiment()
    print(result)
    return 0


if __name__ == "__main__":
    sys.exit(main())
