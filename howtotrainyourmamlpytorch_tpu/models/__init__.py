from . import layers  # noqa: F401
from .densenet import build_densenet  # noqa: F401
from .model import Model  # noqa: F401
from .registry import MODEL_NAMES, build_model  # noqa: F401
from .resnet import build_resnet  # noqa: F401
from .vgg import build_vgg  # noqa: F401
