"""Shared metrics registry: counters, gauges, windowed histograms.

One registry instance backs every telemetry surface of a process — the
runner's step-phase histograms, serving's request-latency percentiles
(``serving/metrics.py``'s ``LatencyStats``/``EventCounters`` are thin
adapters over this), resilience event counts — so a snapshot is one call and
one schema instead of three islands.

Histograms keep a bounded window of raw observations (exact percentiles over
the recent window, cold-start outliers forgotten at window pace — the same
design ``LatencyStats`` shipped with) plus *cumulative* count and sum, so
rate/coverage math over a whole run survives window eviction. Percentile
math happens OUTSIDE the registry lock: ``summaries()`` copies each window
under the lock and releases it before numpy runs, so recorder threads never
block behind ``/metrics`` percentile crunching.
"""

import re
import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..utils.locks import san_lock

DEFAULT_WINDOW = 2048


class _Histogram:
    """Mutated only under the registry lock."""

    __slots__ = ("window", "values", "count", "total")

    def __init__(self, window: int):
        self.window = int(window)
        self.values: deque = deque(maxlen=self.window)
        self.count = 0  # cumulative observations (window evicts, this doesn't)
        self.total = 0.0  # cumulative sum, same lifetime


class MetricsRegistry:
    def __init__(self, default_window: int = DEFAULT_WINDOW):
        if default_window < 1:
            raise ValueError(f"default_window must be >= 1, got {default_window}")
        self.default_window = int(default_window)
        self._lock = san_lock("MetricsRegistry._lock")
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, Any] = {}
        self._hists: Dict[str, _Histogram] = {}

    # -- counters ------------------------------------------------------

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def counters(self, prefix: str = "") -> Dict[str, int]:
        """Counters whose name starts with ``prefix`` (stripped off keys)."""
        with self._lock:
            items = list(self._counters.items())
        return {k[len(prefix):]: v for k, v in items if k.startswith(prefix)}

    # -- gauges --------------------------------------------------------

    def set_gauge(self, name: str, value: Any) -> None:
        with self._lock:
            self._gauges[name] = value

    def gauge(self, name: str, default: Any = None) -> Any:
        with self._lock:
            return self._gauges.get(name, default)

    def gauges(self) -> Dict[str, Any]:
        """All gauges (copy) — cheap, no histogram math (snapshot() would
        recompute every percentile summary just to reach this dict)."""
        with self._lock:
            return dict(self._gauges)

    # -- histograms ----------------------------------------------------

    def observe(self, name: str, value: float, window: Optional[int] = None) -> None:
        """Record one observation. ``window`` only applies on first use of
        ``name`` (a histogram's window is fixed at creation)."""
        with self._lock:
            hist = self._hists.get(name)
            if hist is None:
                hist = self._hists[name] = _Histogram(window or self.default_window)
            hist.values.append(float(value))
            hist.count += 1
            hist.total += float(value)

    def timer(self, name: str, clock=None) -> "_Timer":
        """``with registry.timer("phase.settle"): ...`` — records seconds."""
        import time

        return _Timer(self, name, clock or time.monotonic)

    def window_values(self, name: str) -> List[float]:
        with self._lock:
            hist = self._hists.get(name)
            return list(hist.values) if hist is not None else []

    def summaries(self, prefix: str = "", scale: float = 1e3, suffix: str = "_ms") -> Dict[str, Dict[str, Any]]:
        """Percentile summaries of every histogram under ``prefix`` (prefix
        stripped from keys). The window copy happens under the lock; the
        numpy percentile math runs after it is released, so threads recording
        observations never serialize behind a metrics scrape."""
        with self._lock:
            copies: List[Tuple[str, List[float], int, float]] = [
                (name[len(prefix):], list(h.values), h.count, h.total)
                for name, h in self._hists.items()
                if name.startswith(prefix)
            ]
        out: Dict[str, Dict[str, Any]] = {}
        for key, values, count, total in copies:
            if not values:
                continue
            arr = np.asarray(values, np.float64) * scale
            p50, p95, p99 = np.percentile(arr, [50, 95, 99])
            out[key] = {
                "count": count,
                "window": len(arr),
                f"mean{suffix}": round(float(arr.mean()), 3),
                f"p50{suffix}": round(float(p50), 3),
                f"p95{suffix}": round(float(p95), 3),
                f"p99{suffix}": round(float(p99), 3),
                f"max{suffix}": round(float(arr.max()), 3),
                f"sum{suffix}": round(float(total * scale), 3),
            }
        return out

    def histogram_windows(self) -> List[Tuple[str, List[float], int, float]]:
        """``(name, window copy, cumulative count, cumulative sum)`` per
        histogram, copied under the lock — the raw-value surface for
        renderers (``prometheus_text``) that need full precision rather
        than ``summaries()``'s rounded ms table."""
        with self._lock:
            return [
                (name, list(h.values), h.count, h.total)
                for name, h in sorted(self._hists.items())
            ]

    def snapshot(self) -> Dict[str, Any]:
        """Whole-registry snapshot: counters + gauges verbatim, histograms as
        ms-scaled percentile summaries."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
        return {
            "counters": counters,
            "gauges": gauges,
            "histograms": self.summaries(),
        }


#: Prometheus metric names: [a-zA-Z_:][a-zA-Z0-9_:]*; everything else
#: (the registry's dotted names) collapses to underscores
_PROM_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")
#: summary quantiles exposed per histogram (matches summaries()'s p50/95/99)
_PROM_QUANTILES = (0.5, 0.95, 0.99)


def _prom_name(namespace: str, name: str) -> str:
    sanitized = _PROM_BAD_CHARS.sub("_", name)
    if sanitized and sanitized[0].isdigit():
        sanitized = "_" + sanitized
    return f"{namespace}_{sanitized}" if namespace else sanitized


def prometheus_text(registry: "MetricsRegistry", namespace: str = "htymp") -> str:
    """Prometheus/OpenMetrics text exposition of the registry — counters
    (``_total``), numeric gauges, and histograms as summaries (quantile
    series in base-unit SECONDS, plus ``_count``/``_sum``), each with a
    ``# TYPE`` line. Serves ``/metrics?format=prom`` alongside the JSON
    form; the key set is schema-pinned by test. Non-numeric gauges (state
    strings, nested snapshots) are JSON-only — Prometheus samples are
    float64, full stop."""
    lines: List[str] = []
    for name, value in sorted(registry.counters().items()):
        metric = _prom_name(namespace, name) + "_total"
        lines.append(f"# TYPE {metric} counter")
        lines.append(f"{metric} {value}")
    for name, value in sorted(registry.gauges().items()):
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            continue
        metric = _prom_name(namespace, name)
        lines.append(f"# TYPE {metric} gauge")
        lines.append(f"{metric} {value}")
    # windows copied under the registry lock (inside histogram_windows),
    # percentile math outside it — the same discipline as summaries()
    for name, values, count, total in registry.histogram_windows():
        metric = _prom_name(namespace, name)
        lines.append(f"# TYPE {metric} summary")
        if values:
            arr = np.asarray(values, np.float64)
            for q, v in zip(_PROM_QUANTILES, np.percentile(arr, [100 * q for q in _PROM_QUANTILES])):
                lines.append(f'{metric}{{quantile="{q}"}} {round(float(v), 9)}')
        lines.append(f"{metric}_count {count}")
        lines.append(f"{metric}_sum {round(total, 9)}")
    return "\n".join(lines) + "\n"


class _Timer:
    __slots__ = ("_registry", "_name", "_clock", "_t0")

    def __init__(self, registry: MetricsRegistry, name: str, clock: Callable[[], float]):
        self._registry = registry
        self._name = name
        self._clock = clock

    def __enter__(self):
        self._t0 = self._clock()
        return self

    def __exit__(self, *exc):
        self._registry.observe(self._name, self._clock() - self._t0)
        return False
