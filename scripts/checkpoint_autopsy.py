#!/usr/bin/env python
"""Autopsy of a (collapsed) run's saved TrainState checkpoints, entirely on
CPU — no tunnel needed.

The round-3 20-way on-chip collapse left epoch-9..13 checkpoints behind
(exps/omniglot.20.5.vgg.gd.s0). This script loads them and answers the
discriminating question the chip can't be asked while the tunnel is down:

  **Does the collapsed state fail on CPU too?**

- If CPU inner-adaptation from the checkpointed params also scores ~chance,
  the *state itself* is destroyed — the on-chip outer updates walked it
  somewhere unrecoverable (training-dynamics / platform-computed-update
  issue, but a real state, faithfully saved).
- If CPU adaptation from the same state scores well, the chip's *execution*
  of the adaptation/eval program is numerically wrong (platform bug), since
  the identical program on the identical state gives different answers.

Also dumps per-tensor param/BN/Adam-moment/LSLR statistics per checkpoint to
show *what* degraded and when.

Usage:
  JAX_PLATFORMS=cpu python scripts/checkpoint_autopsy.py <run_dir> [epoch ...]
  (defaults: all available epochs + 'best'; eval on 3 real val batches)
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np

from howtotrainyourmamlpytorch_tpu.config import load_config
from howtotrainyourmamlpytorch_tpu.core import MAMLSystem
from howtotrainyourmamlpytorch_tpu.data import MetaLearningDataLoader
from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt


def tensor_stats(tree, label):
    leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    print(f"  {label}:")
    for path, leaf in leaves:
        a = np.asarray(leaf, np.float64)
        name = jax.tree_util.keystr(path)
        print(
            f"    {name:55s} shape={str(a.shape):18s} "
            f"|x|={np.linalg.norm(a):10.3e} max|x|={np.abs(a).max():10.3e} "
            f"mean={a.mean():+9.3e}"
        )


def main():
    run_dir = sys.argv[1]
    save_dir = os.path.join(run_dir, "saved_models")
    idxs = sys.argv[2:] or [str(e) for e in ckpt.available_epochs(save_dir)] + ["best"]

    import dataclasses

    cfg = load_config(os.path.join(run_dir, "config.yaml"))
    # CPU-friendly program family: rolled scan compiles fast; math identical
    # (rolled-vs-unrolled parity is pinned by tests/test_maml_core.py). Point
    # the dataset at the read-only reference copy regardless of what the
    # run dir recorded.
    cfg = dataclasses.replace(
        cfg,
        unroll_inner_steps=False,
        remat_inner_steps=True,
        load_into_memory=False,
        index_cache_dir="/tmp/omniglot_idx",
    )
    system = MAMLSystem(cfg)
    template = system.init_train_state()

    loader = MetaLearningDataLoader(cfg, current_iter=0, data_root="/root/reference")
    n_eval_batches = int(os.environ.get("AUTOPSY_EVAL_BATCHES", "3"))
    batches = []
    for b in loader.val_batches(n_eval_batches):
        batches.append({k: jnp.asarray(v) for k, v in b.items()})
        if len(batches) == n_eval_batches:
            break

    for idx in idxs:
        if not ckpt.checkpoint_exists(save_dir, idx):
            print(f"== checkpoint {idx}: missing, skipped")
            continue
        state, book = ckpt.load_checkpoint(save_dir, idx, template)
        print(f"== checkpoint {idx} (epoch={book.get('epoch')}, step={int(state.step)})")
        tensor_stats(state.params, "params")
        tensor_stats(state.bn_state, "bn_state")
        if state.inner_hparams:
            tensor_stats(state.inner_hparams, "inner_hparams (learned lrs)")
        losses, accs = [], []
        for b in batches:
            out = system.eval_step(state, b)
            losses.append(float(out.loss))
            accs.append(float(out.accuracy))
        print(
            f"  CPU eval ({len(batches)} real val batches, "
            f"{cfg.number_of_evaluation_steps_per_iter} inner steps): "
            f"loss={np.mean(losses):.4f} acc={np.mean(accs):.4f} "
            f"(per-batch acc: {', '.join(f'{a:.3f}' for a in accs)})"
        )


if __name__ == "__main__":
    main()
