"""Full-train-state checkpointing.

Fixes the reference's resume gap (SURVEY.md §5.4): its ``save_model`` writes
only ``state_dict()`` — outer Adam moments and scheduler position are lost on
resume (reference ``few_shot_learning_system.py:409-432``). Here the checkpoint
is the complete ``TrainState`` pytree (params + BN state + learned inner-opt
hyperparams + outer optimizer state + step counter) plus runner bookkeeping
(epoch, data cursor, best-val tracking), serialized with flax msgpack.

File naming mirrors the reference ("{name}_{idx}" with idx = epoch or
'latest'); ``max_models_to_save`` rotation matches ``config.yaml:12``.

Integrity (resilience subsystem): every checkpoint since format 2 wraps the
msgpack body with its sha256 digest; every load verifies it. A mismatch (torn
write, bit rot, truncation — or an injected ``checkpoint.read`` fault) raises
:class:`CheckpointCorruptError`; :func:`quarantine` renames the bad file to
``*.corrupt`` so rotation and epoch discovery never see it again, and
:func:`load_latest_with_fallback` walks latest -> newest valid epoch so a
corrupt ``train_model_latest`` degrades a resume by one epoch instead of
crashing it. Pre-format-2 files (no digest) still load, unverified.

Format 3 (elastic-recovery subsystem): a *sharded* checkpoint — the state's
leaves split across ``N`` per-shard files plus a checksummed manifest under
the checkpoint's own name, so a dp x mp save stops funneling through one
host-side blob. The manifest (format-2-style digest-wrapped, carrying each
shard file's sha256) is the COMMIT POINT: it is renamed into place only
after every shard landed, so a kill mid-save leaves invisible stray shards,
never a loadable-but-torn checkpoint. All three formats load through the
same ``load_checkpoint`` / fallback chain, and :class:`AsyncCheckpointWriter`
moves the whole save (device fetch included) onto a background thread with a
one-save lag, mirroring the runner's one-dispatch-lag pipeline.
"""

import hashlib
import json
import os
import re
import threading
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import jax
import numpy as np
from flax import serialization

from ..core.train_state import TrainState
from ..resilience.faults import NULL_INJECTOR

MODEL_NAME = "train_model"

CHECKPOINT_FORMAT = 2  # 1 (implicit): bare payload; 2: sha256-wrapped body
SHARDED_FORMAT = 3  # per-shard leaf files + digest-wrapped manifest


class CheckpointCorruptError(RuntimeError):
    """The file failed its embedded-digest check or cannot be decoded."""


class InferenceState(NamedTuple):
    """The checkpoint subset a serving process needs: meta-parameters, BN
    state, learned inner-opt hyperparams, and the step counter — WITHOUT the
    outer optimizer moments (for the flagship config the optimizer state is
    ~2/3 of the checkpoint, and a server never takes an outer step).
    ``fingerprint`` is a content hash of the checkpoint file, the cache-key
    component that invalidates adapted-weight cache entries across model
    pushes (serving/cache.py)."""

    params: Any
    bn_state: Any
    inner_hparams: Any
    step: Any
    fingerprint: str


def _path(save_dir: str, idx) -> str:
    return os.path.join(save_dir, f"{MODEL_NAME}_{idx}")


def _serialize(state: TrainState, bookkeeping: Dict[str, Any]) -> bytes:
    body = serialization.msgpack_serialize(
        {
            "network": serialization.to_bytes(jax.tree.map(np.asarray, state)),
            "bookkeeping": bookkeeping,
        }
    )
    # format 2: the body's digest rides inside the file, so a load can tell
    # "file I wrote" from "file something mangled" without a sidecar
    return serialization.msgpack_serialize(
        {
            "format": CHECKPOINT_FORMAT,
            "sha256": hashlib.sha256(body).hexdigest(),
            "body": body,
        }
    )


def _read_payload(path: str, injector=NULL_INJECTOR) -> Tuple[Dict[str, Any], bytes]:
    """Read + digest-verify one checkpoint file -> (payload dict, raw blob).
    Decode failures and digest mismatches both raise
    :class:`CheckpointCorruptError` (a truncated msgpack and a bit-flipped one
    deserve the same quarantine)."""
    with open(path, "rb") as f:
        blob = f.read()
    blob = injector.fire_bytes("checkpoint.read", blob)
    try:
        outer = serialization.msgpack_restore(blob)
    except Exception as exc:
        raise CheckpointCorruptError(f"{path}: undecodable checkpoint ({exc!r})") from exc
    if isinstance(outer, dict) and "body" in outer and "sha256" in outer:
        body = outer["body"]
        digest = hashlib.sha256(body).hexdigest()
        if digest != outer["sha256"]:
            raise CheckpointCorruptError(
                f"{path}: sha256 mismatch (stored {outer['sha256'][:12]}…, "
                f"computed {digest[:12]}…) — corrupt checkpoint"
            )
        try:
            payload = serialization.msgpack_restore(body)
        except Exception as exc:
            raise CheckpointCorruptError(f"{path}: undecodable body ({exc!r})") from exc
    else:
        # pre-format-2 file: no digest to verify — accept as-is so old runs
        # (and their forensic tooling, scripts/checkpoint_autopsy.py) keep
        # loading
        payload = outer
    if not isinstance(payload, dict) or (
        "network" not in payload and "shards" not in payload
    ):
        raise CheckpointCorruptError(
            f"{path}: payload missing 'network' (blob) or 'shards' (manifest)"
        )
    return payload, blob


# ---------------------------------------------------------------------------
# format 3: sharded checkpoints (per-shard leaf files + manifest commit point)
# ---------------------------------------------------------------------------


#: reserved leaf name marking a structurally-present-but-empty subtree (a
#: BN-free model's ``bn_state``): without it the flatten/unflatten cycle
#: would drop the empty dict and the restore would fail its structure check
_EMPTY_MARK = "__empty_dict__"


def _flatten_state_dict(nested, prefix: str = "") -> Dict[str, Any]:
    """``serialization.to_state_dict`` output (pure nested string-keyed
    dicts of ndarrays) -> flat ``{'params/conv0/w': ndarray}``. The key
    grammar is stable across processes because the state dict's keys are
    field/layer names and stringified tuple indices."""
    flat: Dict[str, Any] = {}
    for key, value in nested.items():
        name = f"{prefix}/{key}" if prefix else str(key)
        if isinstance(value, dict):
            if value:
                flat.update(_flatten_state_dict(value, name))
            else:
                flat[f"{name}/{_EMPTY_MARK}"] = np.zeros(0, np.uint8)
        else:
            flat[name] = value
    return flat


def _unflatten_state_dict(flat: Dict[str, Any]) -> Dict[str, Any]:
    nested: Dict[str, Any] = {}
    for key, value in flat.items():
        node = nested
        parts = key.split("/")
        if parts[-1] == _EMPTY_MARK:
            for part in parts[:-1]:
                node = node.setdefault(part, {})
            continue
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = value
    return nested


def _partition_keys(flat: Dict[str, Any], num_shards: int) -> List[List[str]]:
    """Greedy byte-balanced partition of the leaf keys into ``num_shards``
    groups (largest leaf first into the lightest bin) so shard files come
    out near-equal — the point of sharding is that no single file carries
    the whole state. Deterministic: ties break on key order."""
    sized = sorted(
        flat.items(),
        key=lambda kv: (-int(getattr(kv[1], "nbytes", 0) or 0), kv[0]),
    )
    bins: List[List[str]] = [[] for _ in range(num_shards)]
    weights = [0] * num_shards
    for key, value in sized:
        i = weights.index(min(weights))
        bins[i].append(key)
        weights[i] += int(getattr(value, "nbytes", 0) or 0) + 1
    return bins


def _shard_path(path: str, k: int) -> str:
    return f"{path}.shard{k}"


def _shard_files(path: str) -> List[str]:
    """LIVE shard files of one checkpoint path — exactly ``<path>.shard<N>``.
    Quarantined forensics (``.shardN.corrupt``) and stray write temps must
    never match: the stale-shard sweep and rotation delete what this
    returns, and a second quarantine renames it."""
    pattern = re.compile(re.escape(os.path.basename(path)) + r"\.shard\d+$")
    parent = os.path.dirname(path) or "."
    if not os.path.isdir(parent):
        return []
    return sorted(
        os.path.join(parent, name)
        for name in os.listdir(parent)
        if pattern.fullmatch(name)
    )


def _sharded_serialize(
    state: TrainState, num_shards: int
) -> Tuple[List[bytes], Dict[str, Any]]:
    """-> (shard blobs, manifest body dict minus bookkeeping). Each shard is
    a msgpack map of flat-key -> ndarray; the manifest records each shard's
    sha256 of the FINAL file bytes, so the manifest's own digest transitively
    covers the whole checkpoint."""
    flat = _flatten_state_dict(
        serialization.to_state_dict(jax.tree.map(np.asarray, state))
    )
    blobs = [
        serialization.msgpack_serialize({key: flat[key] for key in keys})
        for keys in _partition_keys(flat, num_shards)
    ]
    return blobs, {"num_leaves": len(flat)}


def _read_shards(path: str, payload: Dict[str, Any], injector=NULL_INJECTOR) -> Dict[str, Any]:
    """Read + digest-verify every shard a manifest names -> merged flat leaf
    dict. Any missing/corrupt shard fails the WHOLE checkpoint (the manifest
    is all-or-nothing), with the same error class as a torn blob so the
    fallback chain quarantines and walks on."""
    save_dir = os.path.dirname(path)
    flat: Dict[str, Any] = {}
    for entry in payload["shards"]:
        shard_path = os.path.join(save_dir, entry["file"])
        try:
            with open(shard_path, "rb") as f:
                blob = f.read()
        except OSError as exc:
            raise CheckpointCorruptError(
                f"{path}: missing shard {entry['file']} ({exc!r})"
            ) from exc
        blob = injector.fire_bytes("checkpoint.read", blob)
        digest = hashlib.sha256(blob).hexdigest()
        if digest != entry["sha256"]:
            raise CheckpointCorruptError(
                f"{path}: shard {entry['file']} sha256 mismatch (stored "
                f"{entry['sha256'][:12]}…, computed {digest[:12]}…)"
            )
        try:
            flat.update(serialization.msgpack_restore(blob))
        except Exception as exc:
            raise CheckpointCorruptError(
                f"{path}: undecodable shard {entry['file']} ({exc!r})"
            ) from exc
    if len(flat) != payload.get("num_leaves", len(flat)):
        raise CheckpointCorruptError(
            f"{path}: manifest promises {payload.get('num_leaves')} leaves, "
            f"shards hold {len(flat)}"
        )
    return flat


def _restore_network(payload: Dict[str, Any], path: str, template, injector=NULL_INJECTOR):
    """Format dispatch for the state restore: blob formats hand flax the
    serialized bytes; format 3 reassembles the state dict from shards."""
    if "shards" in payload:
        nested = _unflatten_state_dict(_read_shards(path, payload, injector))
        return serialization.from_state_dict(template, nested)
    return serialization.from_bytes(template, payload["network"])


def _write_atomic(target: str, blob: bytes, injector=NULL_INJECTOR) -> None:
    blob = injector.fire_bytes("checkpoint.write", blob)
    # unique temp per (thread, call): the async epoch writer and the wedge
    # watchdog's emergency save can both target train_model_latest at the
    # same instant — a shared fixed '.tmp' would let one thread rename the
    # other's half-written temp into place. With unique temps every rename
    # moves a COMPLETE file; last-rename-wins is then always loadable.
    tmp = f"{target}.tmp-{os.getpid()}-{threading.get_ident()}"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, target)  # atomic: preemption-safe (SURVEY.md §5.3)


def quarantine(save_dir: str, idx) -> Optional[str]:
    """Rename a corrupt checkpoint to ``*.corrupt`` (kept for forensics,
    invisible to ``available_epochs``/``checkpoint_exists``). A format-3
    checkpoint quarantines its shard files alongside the manifest — the
    manifest names them, so leaving them behind would strand orphan shards a
    later same-idx save could partially overwrite. Returns the new manifest
    path, or None if the file was already gone."""
    path = _path(save_dir, idx)
    if not os.path.exists(path):
        return None
    for shard in _shard_files(path):
        os.replace(shard, shard + ".corrupt")
    target = path + ".corrupt"
    os.replace(path, target)
    return target


def save_named(
    save_dir: str, state: TrainState, bookkeeping: Dict[str, Any], idx,
    injector=NULL_INJECTOR,
) -> str:
    """Write a single checkpoint file under any idx (e.g. 'best')."""
    path = _path(save_dir, idx)
    _write_atomic(path, _serialize(state, bookkeeping), injector)
    return path


def _manifest_blob(
    shard_entries: List[Dict[str, Any]], bookkeeping: Dict[str, Any], num_leaves: int
) -> bytes:
    body = serialization.msgpack_serialize(
        {
            "shards": shard_entries,
            "num_leaves": num_leaves,
            "bookkeeping": bookkeeping,
        }
    )
    return serialization.msgpack_serialize(
        {
            "format": SHARDED_FORMAT,
            "sha256": hashlib.sha256(body).hexdigest(),
            "body": body,
        }
    )


def _save_sharded(
    save_dir: str,
    state: TrainState,
    bookkeeping: Dict[str, Any],
    epoch: int,
    num_shards: int,
    injector=NULL_INJECTOR,
) -> str:
    """Format-3 save: shards first (atomic each), manifest last — the
    manifest rename is the commit point, so a kill at ANY instant leaves
    either the previous complete checkpoint or the new complete one, never a
    readable half. ``latest`` reuses the epoch's shard bytes via hardlinks
    (same content, no second serialization pass); its manifest names the
    latest-prefixed links, so epoch-file rotation can never strand it."""
    path = _path(save_dir, epoch)
    latest = _path(save_dir, "latest")
    blobs, extra = _sharded_serialize(state, num_shards)
    entries, latest_entries = [], []
    for k, blob in enumerate(blobs):
        shard = _shard_path(path, k)
        _write_atomic(shard, blob, injector)
        # the digest is of the bytes as WRITTEN (injector included): an
        # injected torn write must be detected at load, exactly like rot
        with open(shard, "rb") as f:
            written = f.read()
        digest = hashlib.sha256(written).hexdigest()
        entries.append({"file": os.path.basename(shard), "sha256": digest})
        link = _shard_path(latest, k)
        tmp = f"{link}.tmp-{os.getpid()}-{threading.get_ident()}"
        try:
            os.link(shard, tmp)
        except OSError:  # cross-device / no-hardlink filesystem: plain copy
            with open(tmp, "wb") as f:
                f.write(written)
        os.replace(tmp, link)
        latest_entries.append({"file": os.path.basename(link), "sha256": digest})
    _write_atomic(
        path, _manifest_blob(entries, bookkeeping, extra["num_leaves"]), injector
    )
    _write_atomic(
        latest,
        _manifest_blob(latest_entries, bookkeeping, extra["num_leaves"]),
        injector,
    )
    # a previous save under the same idx with MORE shards leaves stale
    # higher-index files the fresh manifests no longer name — sweep them
    # once both manifests are committed
    named = {os.path.basename(_shard_path(p, k)) for p in (path, latest)
             for k in range(len(blobs))}
    for target in (path, latest):
        for stale in _shard_files(target):
            if os.path.basename(stale) not in named:
                os.remove(stale)
    return path


def save_checkpoint(
    save_dir: str,
    state: TrainState,
    bookkeeping: Dict[str, Any],
    epoch: int,
    max_models_to_save: int = 5,
    val_acc_by_epoch: Optional[Dict[int, float]] = None,
    injector=NULL_INJECTOR,
    num_shards: int = 1,
) -> str:
    """Write ``train_model_{epoch}`` + ``train_model_latest`` and rotate.

    ``num_shards >= 2`` writes checkpoint format 3 (per-shard files + a
    manifest commit point — see :func:`_save_sharded`); 1 keeps the
    single-blob format 2. Rotation keeps ``max_models_to_save`` per-epoch
    files: the most recent ones by default, or — when ``val_acc_by_epoch``
    is given — the top ones by validation accuracy (upstream MAML++ kept its
    best-5 val models for test ensembling; SURVEY.md §2.9 item 4)."""
    if num_shards >= 2:
        path = _save_sharded(save_dir, state, bookkeeping, epoch, num_shards, injector)
    else:
        blob = _serialize(state, bookkeeping)
        path = _path(save_dir, epoch)
        for target in (path, _path(save_dir, "latest")):
            _write_atomic(target, blob, injector)
    _rotate(save_dir, max_models_to_save, val_acc_by_epoch)
    return path


def _rotate(save_dir: str, keep: int, val_acc_by_epoch: Optional[Dict[int, float]] = None) -> None:
    if keep <= 0:
        return
    epochs = available_epochs(save_dir)
    if val_acc_by_epoch is not None:
        # drop lowest-val-acc first; epochs missing a recorded val acc (e.g.
        # from an older run) rank lowest, ties broken oldest-first
        epochs = sorted(epochs, key=lambda e: (val_acc_by_epoch.get(e, -1.0), e))
    for epoch in epochs[:-keep]:
        path = _path(save_dir, epoch)
        # a format-3 epoch's shards go with its manifest ('latest' holds its
        # own hardlinked copies, so this never strands the resume chain)
        for shard in _shard_files(path):
            os.remove(shard)
        os.remove(path)


def load_checkpoint(
    save_dir: str, idx, template_state: TrainState, injector=NULL_INJECTOR
) -> Tuple[TrainState, Dict[str, Any]]:
    """``idx`` is an epoch number or 'latest' (reference load_model API,
    ``few_shot_learning_system.py:419-432``). ``template_state`` supplies the
    pytree structure (an ``init_train_state()`` result). Digest-verified
    (manifest AND every shard for format 3): raises
    :class:`CheckpointCorruptError` on a bad file."""
    path = _path(save_dir, idx)
    payload, _ = _read_payload(path, injector)
    template = jax.tree.map(np.asarray, template_state)
    state = _restore_network(payload, path, template, injector)
    return TrainState(*state), payload["bookkeeping"]


def load_latest_with_fallback(
    save_dir: str, template_state: TrainState, injector=NULL_INJECTOR
) -> Tuple[TrainState, Dict[str, Any], Any]:
    """Resume chain: ``latest`` first, then per-epoch files newest-first.
    Every corrupt candidate is quarantined (``*.corrupt``) and the chain moves
    on, so one torn write costs one epoch of progress, not the run. Returns
    ``(state, bookkeeping, idx_used)``; raises
    :class:`CheckpointCorruptError` only when NO candidate survives."""
    candidates = ["latest"] + [
        e for e in reversed(available_epochs(save_dir))
    ]
    errors = []
    for idx in candidates:
        if not checkpoint_exists(save_dir, idx):
            continue
        try:
            state, bookkeeping = load_checkpoint(save_dir, idx, template_state, injector)
            return state, bookkeeping, idx
        except CheckpointCorruptError as exc:
            quarantined = quarantine(save_dir, idx)
            errors.append(str(exc))
            print(
                f"warning: checkpoint {MODEL_NAME}_{idx} is corrupt — "
                f"quarantined to {quarantined}; falling back",
                flush=True,
            )
    raise CheckpointCorruptError(
        f"no valid checkpoint under {save_dir}: " + "; ".join(errors)
    )


def load_for_inference(
    save_dir: str, idx, injector=NULL_INJECTOR
) -> Tuple[InferenceState, Dict[str, Any]]:
    """Restore params / BN state / inner hyperparams / step for serving,
    dropping the outer optimizer state (serving never takes an outer step;
    note this also means an inner-Adam config with
    ``warm_start_inner_opt_from_outer`` adapts from cold inner moments when
    loaded this way — the warm start is a training-time coupling to the
    outer Adam that a standalone server deliberately does not carry).

    Unlike :func:`load_checkpoint` this needs no template state: the flax
    msgpack payload stores the TrainState by field name with plain
    dict-of-ndarray subtrees, which restore structurally as-is. A format-3
    fingerprint hashes the manifest blob — it embeds every shard's digest,
    so it is content-addressed transitively, exactly like the blob hash."""
    path = _path(save_dir, idx)
    payload, blob = _read_payload(path, injector)
    if "shards" in payload:
        net = _unflatten_state_dict(_read_shards(path, payload, injector))
    else:
        # "network" is itself msgpack bytes (see _serialize): decode the
        # inner layer to the field-name-keyed TrainState dict
        net = serialization.msgpack_restore(payload["network"])
    state = InferenceState(
        params=net["params"],
        bn_state=net["bn_state"],
        inner_hparams=net["inner_hparams"],
        step=np.asarray(net["step"]),
        fingerprint=hashlib.sha256(blob).hexdigest(),
    )
    return state, payload["bookkeeping"]


class AsyncCheckpointWriter:
    """One-save-lag background checkpoint writer.

    The runner's step loop must never block on checkpoint serialization —
    the save (device fetch, msgpack, shard writes) runs on a background
    thread, and the caller blocks only on the *previous* save at the next
    save point, mirroring the one-dispatch-lag device pipeline. jax arrays
    are immutable, so the background thread can ``device_get`` a state the
    main thread has long since stepped past (donation — which invalidates
    buffers — is the documented exception; the runner keeps async saves off
    when ``donate_train_state`` is on).

    A failed save re-raises on the next :meth:`wait`/:meth:`submit`/
    :meth:`close`, so a dead disk surfaces one save late, never silently.
    At most one save is in flight; the writes themselves stay atomic
    (tmp+rename; format-3 manifest as the commit point), so killing the
    process mid-save can never leave a loadable-but-torn checkpoint."""

    def __init__(self, name: str = "ckpt-writer"):
        self._name = name
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def submit(self, fn: Callable[[], None]) -> None:
        """Block on the previous save (the one-save lag), then start ``fn``
        on the writer thread."""
        self.wait()

        def run():
            try:
                fn()
            except BaseException as exc:  # noqa: BLE001 — carried to wait()
                self._error = exc

        self._thread = threading.Thread(target=run, name=self._name, daemon=True)
        self._thread.start()

    def wait(self) -> None:
        """Join the in-flight save, if any; re-raise its failure."""
        thread = self._thread
        if thread is not None:
            thread.join()
            self._thread = None
        if self._error is not None:
            error = self._error
            self._error = None
            raise error

    @property
    def busy(self) -> bool:
        thread = self._thread
        return thread is not None and thread.is_alive()

    def close(self) -> None:
        self.wait()


# ---------------------------------------------------------------------------
# AOT executable-store manifest (compile/aot.py) — co-located with the
# checkpoints so the warm-start contract travels with the run: a restarted
# process (or a fleet scheduler about to spawn one) reads it HERE to verify
# it will hit the persistent compilation cache warm before accepting work.
# ---------------------------------------------------------------------------

PREWARM_MANIFEST = "prewarm_manifest.json"


def prewarm_manifest_path(save_dir: str) -> str:
    return os.path.join(save_dir, PREWARM_MANIFEST)


def save_prewarm_manifest(save_dir: str, manifest: Dict[str, Any]) -> str:
    """Atomic write (same tmp+rename discipline as the checkpoints — a
    kill mid-write must leave the previous manifest, never a torn one)."""
    os.makedirs(save_dir, exist_ok=True)
    path = prewarm_manifest_path(save_dir)
    _write_atomic(path, json.dumps(manifest, indent=1).encode())
    return path


def load_prewarm_manifest(save_dir: str) -> Optional[Dict[str, Any]]:
    """None when absent or unreadable — a bad manifest degrades the reader
    to a cold start, exactly like no manifest at all."""
    path = prewarm_manifest_path(save_dir)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        return None


def latest_checkpoint_exists(save_dir: str) -> bool:
    return checkpoint_exists(save_dir, "latest")


def checkpoint_exists(save_dir: str, idx) -> bool:
    return os.path.exists(_path(save_dir, idx))


def available_epochs(save_dir: str):
    pattern = re.compile(rf"^{MODEL_NAME}_(\d+)$")
    if not os.path.isdir(save_dir):
        return []
    return sorted(
        int(m.group(1)) for name in os.listdir(save_dir) if (m := pattern.match(name))
    )
