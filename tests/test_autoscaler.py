"""Fleet supervisor decision matrix (serving/autoscaler.py) on a fake
clock with zero subprocesses: hysteresis windows + per-direction cooldowns,
min/max clamps, the crash-loop backoff ladder -> quarantine, dead-backend
replacement, write-ahead journaling + adopt-on-restart per interrupted-action
kind, forecast -> retune -> prewarm-on-next-spawn arithmetic, and the
Policy <-> config.AutoscaleConfig defaults cross-pin (plus the off-switch:
importing the package never loads the supervisor).

Every collaborator (clock, wall, sleep, fetch, spawn, drain, probe,
pid_alive, kill9, port_pid) is injected, so each test drives the real
control loop deterministically.
"""

import dataclasses
import importlib.util
import json
import os
import socket
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SERVING = os.path.join(REPO, "howtotrainyourmamlpytorch_tpu", "serving")


def _load_by_path(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


autoscaler = _load_by_path(
    "t_autoscaler", os.path.join(_SERVING, "autoscaler.py")
)
fleetctl = autoscaler.fleetctl

GW_URL = "http://127.0.0.1:9099"
BASE_PORT = 9100
# fake pids above the Linux default pid_max (4194304): they can never name a
# real process, so the few real liveness probes (fleetctl.wait_pid_gone in
# the kill9 escalation paths) resolve "gone" instantly
FAKE_PID_BASE = 4_500_000


class FakeClock:
    def __init__(self):
        self.t = 0.0
        self.wall0 = 1_000_000.0

    def clock(self):
        return self.t

    def wall(self):
        return self.wall0 + self.t

    def sleep(self, s):
        self.t += s


class FakeFleet:
    """A fake process estate: scripted per-slot spawn behavior
    ('ok' | 'die' | 'never_warm'), pid liveness/health sets, canned gateway
    and backend /metrics payloads, and a journal snapshot taken at every
    spawn call (to prove write-ahead ordering)."""

    def __init__(self):
        self.next_pid = FAKE_PID_BASE
        self.behavior = {}
        self.slot_pid = {}
        self.alive = set()
        self.healthy = set()
        self.force_healthy_ports = set()
        self.spawns = []
        self.drains = []
        self.kills = []
        self.gateway_metrics = None
        self.backend_metrics = {}
        self.journal_at_spawn = []
        self.state_path = None

    def preoccupy(self, slot_id):
        """A backend that is already running + healthy on this slot."""
        pid = self.next_pid
        self.next_pid += 1
        self.slot_pid[slot_id] = pid
        self.alive.add(pid)
        self.healthy.add(pid)
        return pid

    def die(self, slot_id):
        """kill -9 the backend on this slot (unasked, out of band)."""
        pid = self.slot_pid[slot_id]
        self.alive.discard(pid)
        self.healthy.discard(pid)
        return pid

    def spawn(self, entry, extra):
        self.spawns.append((entry["slot"], list(extra) if extra else None))
        if self.state_path and os.path.exists(self.state_path):
            with open(self.state_path) as f:
                self.journal_at_spawn.append(json.load(f))
        pid = self.next_pid
        self.next_pid += 1
        self.slot_pid[entry["slot"]] = pid
        behavior = self.behavior.get(entry["slot"], "ok")
        if behavior != "die":
            self.alive.add(pid)
        if behavior == "ok":
            self.healthy.add(pid)
        return pid

    def drain(self, entry, timeout_s):
        self.drains.append(entry["slot"])
        self.alive.discard(entry.get("pid"))
        self.healthy.discard(entry.get("pid"))
        return {"url": entry["url"], "old_pid": entry.get("pid"),
                "drain": "sigterm_sent", "drain_rc": 0, "drain_s": 0.1}

    def pid_alive(self, pid):
        return pid in self.alive

    def kill9(self, pid):
        self.kills.append(pid)
        self.alive.discard(pid)
        self.healthy.discard(pid)

    def probe(self, url):
        port = int(url.rstrip("/").rsplit(":", 1)[1])
        if port in self.force_healthy_ports:
            return 200, {"status": "ok"}
        pid = self.slot_pid.get(port - BASE_PORT)
        if pid is not None and pid in self.healthy:
            return 200, {"status": "ok"}
        return None, {}

    def fetch(self, url):
        if url.startswith(GW_URL):
            return self.gateway_metrics
        port = int(url.split("//", 1)[1].split("/", 1)[0].rsplit(":", 1)[1])
        return self.backend_metrics.get(port - BASE_PORT)


def gw(requests=0, shed=0, backends=None, backends_in=None):
    return {"gateway": True, "requests": requests, "admission_shed": shed,
            "no_backend": 0, "backends_in": backends_in,
            "backends": backends or []}


def _slots(n):
    return [
        {"url": f"http://127.0.0.1:{BASE_PORT + i}", "port": BASE_PORT + i,
         "respawn": ["python", "scripts/serve.py", "exps/run",
                     "--port", str(BASE_PORT + i)]}
        for i in range(n)
    ]


def make_supervisor(tmp_path, fleet=None, clk=None, n_slots=3, pids_for=(),
                    port_pid=None, access_log=None, support=None, query=None,
                    **policy):
    clk = clk or FakeClock()
    fleet = fleet or FakeFleet()
    slots = _slots(n_slots)
    for i in pids_for:
        slots[i]["pid"] = fleet.preoccupy(i)
    state_path = os.path.join(str(tmp_path), "fleet_state.json")
    fleet.state_path = state_path
    sup = autoscaler.Supervisor(
        state_path,
        autoscaler.Policy(**policy),
        GW_URL,
        events_path=os.path.join(str(tmp_path), "events.jsonl"),
        access_log=access_log,
        current_support=support,
        current_query=query,
        clock=clk.clock, wall=clk.wall, sleep=clk.sleep,
        fetch=fleet.fetch, spawn=fleet.spawn, drain=fleet.drain,
        probe=fleet.probe, pid_alive=fleet.pid_alive, kill9=fleet.kill9,
        port_pid=port_pid or (lambda port: None),
        log=lambda m: None,
    )
    return sup, fleet, clk, slots


def _events(tmp_path, name=None):
    path = os.path.join(str(tmp_path), "events.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        out = [json.loads(line) for line in f if line.strip()]
    return [e for e in out if name is None or e["event"] == name]


def _disk_state(sup):
    with open(sup.state_path) as f:
        return json.load(f)


def _queue(fleet, depth, *slots):
    for i in slots:
        fleet.backend_metrics[i] = {"adapt_batcher": {"queue_depth": depth}}


# ---------------------------------------------------------------------------
# policy + config cross-pins
# ---------------------------------------------------------------------------


def test_policy_validates_knobs():
    autoscaler.Policy()  # defaults are self-consistent
    with pytest.raises(ValueError, match="unknown policy knobs"):
        autoscaler.Policy(replicas=3)
    with pytest.raises(ValueError, match="min_backends"):
        autoscaler.Policy(min_backends=-1)
    with pytest.raises(ValueError, match="max_backends"):
        autoscaler.Policy(min_backends=3, max_backends=2)
    with pytest.raises(ValueError, match="up_polls"):
        autoscaler.Policy(up_polls=0)
    with pytest.raises(ValueError, match="poll_interval_s"):
        autoscaler.Policy(poll_interval_s=0)


def test_policy_defaults_pinned_to_autoscale_config():
    """The import-light Policy and the yaml-facing config.AutoscaleConfig
    document the same knobs with the same defaults — pinned so they cannot
    drift apart."""
    from howtotrainyourmamlpytorch_tpu.config import AutoscaleConfig

    cfg = AutoscaleConfig()
    assert cfg.enabled is False  # the off-switch default
    cfg_fields = {f.name for f in dataclasses.fields(AutoscaleConfig)}
    assert cfg_fields - {"enabled"} == set(autoscaler.Policy.DEFAULTS)
    for knob, default in autoscaler.Policy.DEFAULTS.items():
        assert getattr(cfg, knob) == default, knob


def test_off_switch_package_import_never_loads_supervisor():
    """Disabled-by-default means disabled-by-construction: importing the
    package (or its serving subpackage) must not load the supervisor — with
    autoscaling off there is no new module, file, thread, or process."""
    import howtotrainyourmamlpytorch_tpu  # noqa: F401
    import howtotrainyourmamlpytorch_tpu.serving  # noqa: F401

    assert "howtotrainyourmamlpytorch_tpu.serving.autoscaler" not in sys.modules
    assert "howtotrainyourmamlpytorch_tpu.serving.fleetctl" not in sys.modules
    assert not hasattr(howtotrainyourmamlpytorch_tpu.serving, "Supervisor")


# ---------------------------------------------------------------------------
# fleetctl: the shared fleet-state schema
# ---------------------------------------------------------------------------


def test_fleet_state_legacy_list_normalizes_and_round_trips(tmp_path):
    legacy = [{"url": "http://a", "pid": 11, "respawn": ["x"]},
              {"url": "http://b", "pid": 22, "respawn": ["y"]}]
    state = fleetctl.normalize_fleet_state(legacy)
    assert state["version"] == fleetctl.FLEET_STATE_VERSION
    assert [s["slot"] for s in state["slots"]] == [0, 1]
    assert all(s["state"] == "up" for s in state["slots"])
    assert state["intent"] is None
    path = str(tmp_path / "fleet_state.json")
    fleetctl.save_fleet_state(path, state)
    again = fleetctl.load_fleet_state(path)
    assert [s["url"] for s in again["slots"]] == ["http://a", "http://b"]


def test_fleet_state_rejects_garbage():
    with pytest.raises(ValueError, match="non-empty"):
        fleetctl.normalize_fleet_state([])
    with pytest.raises(ValueError, match="version"):
        fleetctl.normalize_fleet_state({"version": 99, "slots": [{}]})
    with pytest.raises(ValueError, match="unknown state"):
        fleetctl.normalize_fleet_state({
            "version": 1, "slots": [{"url": "http://a", "state": "zombie"}],
        })
    with pytest.raises(ValueError, match="list or dict"):
        fleetctl.normalize_fleet_state("nope")


def test_find_pid_by_port_locates_our_listener():
    sock = socket.socket()
    try:
        sock.bind(("127.0.0.1", 0))
        sock.listen(1)
        port = sock.getsockname()[1]
        found = autoscaler.find_pid_by_port(port)
        if found is None:
            pytest.skip("/proc scan unavailable on this platform")
        assert found == os.getpid()
    finally:
        sock.close()
    assert autoscaler.find_pid_by_port(1) is None  # nothing listens there


# ---------------------------------------------------------------------------
# reactive loop: capacity, hysteresis, cooldowns, clamps
# ---------------------------------------------------------------------------


def test_bootstrap_spawns_up_to_min_backends(tmp_path):
    sup, fleet, clk, slots = make_supervisor(tmp_path, min_backends=2)
    assert sup.load_or_init(slots) == "initialized"
    assert sup.tick() == "spawn_retry"
    assert sup.tick() == "spawn_retry"
    assert sup.tick() == "idle"
    assert [s for s, _ in fleet.spawns] == [0, 1]
    disk = _disk_state(sup)
    assert disk["intent"] is None
    assert [s["state"] for s in disk["slots"]] == ["up", "up", "down"]
    assert disk["slots"][0]["pid"] == fleet.slot_pid[0]


def test_scale_up_needs_consecutive_breach_polls(tmp_path):
    sup, fleet, clk, _slots_ = make_supervisor(
        tmp_path, pids_for=(0,), up_polls=2, queue_high=8.0,
    )
    sup.load_or_init(_slots_)
    fleet.gateway_metrics = gw()
    _queue(fleet, 10, 0)  # breach
    assert sup.tick() == "idle"  # streak 1/2
    _queue(fleet, 0, 0)  # a clear tick resets the streak
    assert sup.tick() == "idle"
    _queue(fleet, 10, 0)
    assert sup.tick() == "idle"  # streak back to 1/2
    assert sup.tick() == "scale_up"
    assert [s for s, _ in fleet.spawns] == [1]
    (event,) = _events(tmp_path, "scale_up")
    assert "queue_depth" in event["reason"]
    assert event["signals"]["queue_depth"] == 10
    assert event["pid"] == fleet.slot_pid[1]
    assert isinstance(event["settle_s"], float)


def test_scale_up_cooldown_blocks_then_releases(tmp_path):
    sup, fleet, clk, _slots_ = make_supervisor(
        tmp_path, pids_for=(0,), up_polls=1, cooldown_up_s=10.0,
    )
    sup.load_or_init(_slots_)
    fleet.gateway_metrics = gw()
    _queue(fleet, 10, 0, 1, 2)
    assert sup.tick() == "scale_up"
    for _ in range(3):  # still breaching, but inside the cooldown
        assert sup.tick() == "idle"
    assert len(fleet.spawns) == 1
    clk.sleep(10.1)
    assert sup.tick() == "scale_up"
    assert [s for s, _ in fleet.spawns] == [1, 2]


def test_scale_up_never_exceeds_max_backends(tmp_path):
    sup, fleet, clk, _slots_ = make_supervisor(
        tmp_path, n_slots=2, pids_for=(0, 1), max_backends=2, up_polls=1,
    )
    sup.load_or_init(_slots_)
    fleet.gateway_metrics = gw()
    _queue(fleet, 50, 0, 1)
    for _ in range(5):
        assert sup.tick() == "idle"
    assert fleet.spawns == []


def test_scale_down_clear_polls_victim_rank_and_min_floor(tmp_path):
    sup, fleet, clk, _slots_ = make_supervisor(
        tmp_path, pids_for=(0, 1, 2), min_backends=1, down_polls=3,
        cooldown_down_s=5.0,
    )
    sup.load_or_init(_slots_)
    fleet.gateway_metrics = gw()
    _queue(fleet, 0, 0, 1, 2)  # clear on every backend
    assert sup.tick() == "idle"
    assert sup.tick() == "idle"
    assert sup.tick() == "scale_down"
    assert fleet.drains == [2]  # the lowest-ranked backend (highest slot)
    assert sup.tick() == "idle"  # streak restarts at 1; cooldown active
    clk.sleep(5.1)
    assert sup.tick() == "idle"  # streak 2 (a cooldown does not reset it)
    assert sup.tick() == "scale_down"  # streak 3, cooldown elapsed
    assert fleet.drains == [2, 1]
    clk.sleep(5.1)
    for _ in range(6):  # at the min_backends floor: never drains the last
        assert sup.tick() == "idle"
    assert fleet.drains == [2, 1]
    down_events = _events(tmp_path, "scale_down")
    assert [e["slot"] for e in down_events] == [2, 1]
    assert all(e["drain_rc"] == 0 for e in down_events)


def test_breach_reasons_cover_all_signals(tmp_path):
    sup, _, _, _ = make_supervisor(tmp_path, page_in_p50_high_ms=50.0)
    base = {"queue_depth": None, "shed_rate": None, "shed_delta": 0,
            "evict_delta": 0, "page_in_p50_ms": None}
    assert sup._breach_reasons(base) == []
    reasons = sup._breach_reasons({
        **base, "queue_depth": 9.0, "shed_rate": 0.5, "shed_delta": 3,
        "evict_delta": 7, "page_in_p50_ms": 80.0,
    })
    assert len(reasons) == 4
    assert any("queue_depth" in r for r in reasons)
    assert any("shed_rate" in r for r in reasons)
    assert any("evictions" in r for r in reasons)
    assert any("page_in" in r for r in reasons)
    # shed below threshold or with no shed volume is not a breach
    assert sup._breach_reasons({**base, "shed_rate": 0.01, "shed_delta": 1}) == []
    assert sup._breach_reasons({**base, "shed_rate": 1.0, "shed_delta": 0}) == []


def test_collect_signals_gateway_deltas_and_out_urls(tmp_path):
    sup, fleet, clk, _slots_ = make_supervisor(tmp_path, pids_for=(0,))
    sup.load_or_init(_slots_)
    fleet.gateway_metrics = gw(requests=100, shed=0)
    first = sup.collect_signals()
    assert first["gateway"] and first["shed_rate"] is None  # no delta yet
    fleet.gateway_metrics = gw(
        requests=120, shed=6,
        backends=[{"url": "http://127.0.0.1:9101", "state": "out",
                   "flaps": 2}],
    )
    second = sup.collect_signals()
    assert second["requests_delta"] == 20
    assert second["shed_delta"] == 6
    assert second["shed_rate"] == 0.3
    assert second["out_urls"] == ["http://127.0.0.1:9101"]


# ---------------------------------------------------------------------------
# crash-loop ladder + dead-backend replacement
# ---------------------------------------------------------------------------


def test_crash_loop_backoff_ladder_then_quarantine(tmp_path):
    sup, fleet, clk, _slots_ = make_supervisor(
        tmp_path, n_slots=1, min_backends=1, crash_max=3,
        backoff_base_s=0.5, backoff_max_s=30.0, crash_window_s=60.0,
    )
    fleet.behavior[0] = "die"
    sup.load_or_init(_slots_)
    assert sup.tick() == "spawn_retry"  # attempt 1 dies
    assert len(fleet.spawns) == 1
    assert sup.tick() == "idle"  # backoff not elapsed: NO hot respawn
    assert len(fleet.spawns) == 1
    disk = _disk_state(sup)
    assert disk["slots"][0]["next_spawn_ts"] == pytest.approx(
        clk.wall() + 0.5, abs=1e-6
    )
    clk.sleep(0.6)
    assert sup.tick() == "spawn_retry"  # attempt 2: backoff doubled
    assert _disk_state(sup)["slots"][0]["next_spawn_ts"] == pytest.approx(
        clk.wall() + 1.0, abs=1e-6
    )
    clk.sleep(1.1)
    sup.tick()  # attempt 3 -> quarantine
    assert len(fleet.spawns) == 3
    assert _disk_state(sup)["slots"][0]["state"] == "quarantined"
    assert sup.counters["quarantines"] == 1
    for _ in range(5):  # quarantined is never respawned hot
        clk.sleep(60.0)
        assert sup.tick() == "idle"
    assert len(fleet.spawns) == 3
    assert len(_events(tmp_path, "spawn_crash")) == 2
    (q,) = _events(tmp_path, "quarantine")
    assert q["slot"] == 0 and q["crashes"] == 3


def test_warm_timeout_walks_the_same_ladder(tmp_path):
    sup, fleet, clk, _slots_ = make_supervisor(
        tmp_path, n_slots=1, min_backends=1,
        warm_timeout_s=2.0, warm_poll_s=0.5,
    )
    fleet.behavior[0] = "never_warm"
    sup.load_or_init(_slots_)
    sup.tick()
    pid = fleet.spawns and fleet.slot_pid[0]
    assert fleet.kills == [pid]  # a never-warm spawn is cleared hard
    (crash,) = _events(tmp_path, "spawn_crash")
    assert "warm_timeout" in crash["reason"]
    assert _disk_state(sup)["slots"][0]["state"] == "down"


def test_dead_backend_is_replaced_through_capacity_repair(tmp_path):
    sup, fleet, clk, _slots_ = make_supervisor(
        tmp_path, pids_for=(0, 1), min_backends=1,
    )
    sup.load_or_init(_slots_)
    old_pid = fleet.die(0)  # kill -9, out of band
    assert sup.tick() == "replace"
    (died,) = _events(tmp_path, "backend_died")
    assert died["slot"] == 0 and died["pid"] == old_pid
    assert sup.counters["replacements"] == 1
    assert sup.tick() == "spawn_retry"  # running 1 < target 2, no cooldown
    assert _disk_state(sup)["slots"][0]["state"] == "up"
    assert _disk_state(sup)["slots"][0]["pid"] != old_pid


def test_wedged_backend_gateway_out_probe_dead_is_killed_and_replaced(tmp_path):
    """A pid that still answers kill(pid, 0) but is OUT at the gateway and
    unreachable over HTTP (wedged / unreapable zombie) must be cleared
    hard and replaced."""
    sup, fleet, clk, _slots_ = make_supervisor(tmp_path, pids_for=(0, 1))
    sup.load_or_init(_slots_)
    pid = fleet.slot_pid[0]
    fleet.healthy.discard(pid)  # alive but wedged: probe now fails
    fleet.gateway_metrics = gw(
        backends=[{"url": "http://127.0.0.1:9100", "state": "out", "flaps": 1}],
    )
    assert sup.tick() == "replace"
    assert fleet.kills == [pid]
    (died,) = _events(tmp_path, "backend_died")
    assert died["slot"] == 0


# ---------------------------------------------------------------------------
# crash-safe control: write-ahead journal + adopt-on-restart
# ---------------------------------------------------------------------------


def test_spawn_is_journaled_write_ahead(tmp_path):
    """At the moment the spawn actually happens, the intent is already on
    disk — a supervisor killed inside spawn() leaves a rollable journal."""
    sup, fleet, clk, _slots_ = make_supervisor(tmp_path, min_backends=1)
    sup.load_or_init(_slots_)
    sup.tick()
    (snap,) = fleet.journal_at_spawn
    assert snap["intent"]["action"] == "spawn"
    assert snap["intent"]["slot"] == 0
    assert snap["slots"][0]["state"] == "spawning"
    assert snap["slots"][0]["pid"] is None  # pid lands right after Popen
    assert _disk_state(sup)["intent"] is None  # settled after warm


def test_interrupted_spawn_leaves_journal_and_next_supervisor_settles(tmp_path):
    """Stop mid-warm (SIGTERM during a spawn): the backend is NOT killed,
    the intent + pid stay journaled, and the next supervisor's adopt rolls
    the spawn forward without double-spawning."""
    sup, fleet, clk, _slots_ = make_supervisor(
        tmp_path, n_slots=1, min_backends=1,
    )
    fleet.behavior[0] = "never_warm"
    sup.load_or_init(_slots_)
    sup.stop()
    sup.tick()
    pid = fleet.slot_pid[0]
    disk = _disk_state(sup)
    assert disk["intent"]["action"] == "spawn"
    assert disk["slots"][0]["pid"] == pid
    assert pid in fleet.alive  # never killed on supervisor exit
    # --- restart: the backend has finished warming in the meantime
    fleet.healthy.add(pid)
    sup2, fleet, clk, _ = make_supervisor(
        tmp_path, fleet=fleet, n_slots=1, min_backends=1,
    )
    assert sup2.load_or_init(None) == "adopted"
    assert len(fleet.spawns) == 1  # no double-spawn
    assert _disk_state(sup2)["intent"] is None
    assert _disk_state(sup2)["slots"][0]["state"] == "up"
    (rf,) = _events(tmp_path, "adopt_rollforward")
    assert rf["outcome"] == "spawn_settled" and rf["pid"] == pid


def _craft_state(tmp_path, slots, intent=None, target=None):
    path = os.path.join(str(tmp_path), "fleet_state.json")
    fleetctl.save_fleet_state(path, {
        "version": 1, "slots": slots, "intent": intent,
        "target": target if target is not None else None,
    })
    return path


def test_adopt_live_and_dead_backends(tmp_path):
    fleet = FakeFleet()
    live_pid = fleet.preoccupy(0)
    slots = _slots(2)
    slots[0].update(slot=0, pid=live_pid, state="up")
    slots[1].update(slot=1, pid=FAKE_PID_BASE + 77, state="up")  # dead
    _craft_state(tmp_path, slots, target=2)
    sup, fleet, clk, _ = make_supervisor(tmp_path, fleet=fleet, min_backends=1)
    assert sup.load_or_init(None) == "adopted"
    assert sup.counters["adopted"] == 1
    disk = _disk_state(sup)
    assert disk["slots"][0]["state"] == "up"
    assert disk["slots"][1]["state"] == "down"
    assert disk["slots"][1]["pid"] is None
    (dead,) = _events(tmp_path, "adopt_found_dead")
    assert dead["slot"] == 1
    (start,) = _events(tmp_path, "supervisor_start")
    assert start["mode"] == "adopted" and start["found_dead"] == 1
    assert sup.tick() == "spawn_retry"  # target 2: the gap is repaired


def test_rollforward_spawn_with_no_pid_and_silent_port_respawns(tmp_path):
    """Killed between intent-write and Popen: nothing listens on the slot's
    port -> the spawn never happened; capacity repair re-spawns it."""
    slots = _slots(1)
    slots[0].update(slot=0, state="spawning", pid=None)
    _craft_state(tmp_path, slots, intent={"id": 0, "action": "spawn",
                                          "slot": 0, "ts": 1.0}, target=1)
    sup, fleet, clk, _ = make_supervisor(tmp_path, n_slots=1, min_backends=1)
    sup.load_or_init(None)
    (rf,) = _events(tmp_path, "adopt_rollforward")
    assert rf["outcome"] == "respawn_pending"
    assert sup.tick() == "spawn_retry"
    assert len(fleet.spawns) == 1


def test_rollforward_adopts_orphan_by_port(tmp_path):
    """Killed between Popen and journaling the pid: the orphan is found by
    port -> pid probe and adopted — never spawned on top of."""
    orphan_pid = FAKE_PID_BASE + 900
    slots = _slots(1)
    slots[0].update(slot=0, state="spawning", pid=None)
    _craft_state(tmp_path, slots, intent={"id": 0, "action": "spawn",
                                          "slot": 0, "ts": 1.0}, target=1)
    fleet = FakeFleet()
    fleet.force_healthy_ports.add(BASE_PORT)
    fleet.alive.add(orphan_pid)
    sup, fleet, clk, _ = make_supervisor(
        tmp_path, fleet=fleet, n_slots=1, min_backends=1,
        port_pid=lambda port: orphan_pid if port == BASE_PORT else None,
    )
    sup.load_or_init(None)
    (rf,) = _events(tmp_path, "adopt_rollforward")
    assert rf["outcome"] == "orphan_adopted" and rf["pid"] == orphan_pid
    disk = _disk_state(sup)
    assert disk["slots"][0]["state"] == "up"
    assert disk["slots"][0]["pid"] == orphan_pid
    assert fleet.spawns == []


def test_rollforward_unmanageable_orphan_quarantines_the_slot(tmp_path):
    """Something answers on the slot's port but its pid is beyond reach:
    never spawn onto an occupied port."""
    slots = _slots(1)
    slots[0].update(slot=0, state="spawning", pid=None)
    _craft_state(tmp_path, slots, intent={"id": 0, "action": "spawn",
                                          "slot": 0, "ts": 1.0}, target=1)
    fleet = FakeFleet()
    fleet.force_healthy_ports.add(BASE_PORT)
    sup, fleet, clk, _ = make_supervisor(
        tmp_path, fleet=fleet, n_slots=1, min_backends=1,
    )
    sup.load_or_init(None)
    (rf,) = _events(tmp_path, "adopt_rollforward")
    assert rf["outcome"] == "orphan_unmanaged"
    assert _disk_state(sup)["slots"][0]["state"] == "quarantined"
    assert sup.tick() == "idle"  # never spawns over it
    assert fleet.spawns == []


def test_rollforward_reissues_interrupted_drain(tmp_path):
    fleet = FakeFleet()
    pid = fleet.preoccupy(1)
    slots = _slots(2)
    slots[0].update(slot=0, pid=fleet.preoccupy(0), state="up")
    slots[1].update(slot=1, pid=pid, state="draining")
    _craft_state(tmp_path, slots, intent={"id": 3, "action": "drain",
                                          "slot": 1, "ts": 1.0}, target=1)
    sup, fleet, clk, _ = make_supervisor(tmp_path, fleet=fleet, min_backends=1)
    sup.load_or_init(None)
    assert fleet.drains == [1]
    (rf,) = _events(tmp_path, "adopt_rollforward")
    assert rf["outcome"] == "drain_reissued" and rf["pid"] == pid
    disk = _disk_state(sup)
    assert disk["slots"][1]["state"] == "down"
    assert disk["intent"] is None


# ---------------------------------------------------------------------------
# predictive loop: forecast -> retune -> prewarm on next spawn
# ---------------------------------------------------------------------------


def _write_access(tmp_path, rows):
    path = os.path.join(str(tmp_path), "access.jsonl")
    with open(path, "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    return path


def test_forecast_retune_parks_and_prewarms_next_spawn(tmp_path):
    clk = FakeClock()
    # 30 ok adapt requests of true size 2 against a [16] grid: waste 0.875;
    # the tuned [2] grid wastes 0 — far past the 0.10 improvement gate
    access = _write_access(tmp_path, [
        {"ts": clk.wall0, "verb": "adapt", "true_size": 2, "outcome": "ok"}
        for _ in range(30)
    ])
    sup, fleet, clk, _slots_ = make_supervisor(
        tmp_path, clk=clk, n_slots=1, min_backends=1,
        access_log=access, support=[16], query=[16],
        forecast_interval_s=5.0, forecast_min_requests=20,
    )
    sup.load_or_init(_slots_)
    sup.tick()  # forecast runs first, then capacity repair spawns slot 0
    (retune,) = _events(tmp_path, "retune")
    assert retune["overrides"] == ["serving.support_buckets=[2]"]
    assert retune["requests"] == 30
    assert retune["improvement"] == pytest.approx(0.875, abs=1e-4)
    # the tuned grid rode the spawn argv — prewarm, never a live recompile
    assert fleet.spawns == [(0, ["serving.support_buckets=[2]"])]
    # the applied grid is the new forecast baseline; nothing stays parked
    assert sup.current_support == [2]
    assert sup._pending_overrides == []
    assert _disk_state(sup)["slots"][0]["overrides"] == [
        "serving.support_buckets=[2]"
    ]


def test_forecast_below_improvement_or_volume_parks_nothing(tmp_path):
    clk = FakeClock()
    # marginal win: sizes 15/16 on a [16] grid -> ~0.03 improvement < 0.10
    access = _write_access(tmp_path, (
        [{"ts": clk.wall0, "verb": "adapt", "true_size": 15, "outcome": "ok"}]
        * 15
        + [{"ts": clk.wall0, "verb": "adapt", "true_size": 16, "outcome": "ok"}]
        * 15
    ))
    sup, fleet, clk, _ = make_supervisor(
        tmp_path, clk=clk, n_slots=1, access_log=access, support=[16],
        query=[16],
    )
    assert sup.forecast_and_retune() is None
    assert sup._pending_overrides == []
    # volume gate: plenty of waste but too few requests to trust
    access2 = _write_access(tmp_path, [
        {"ts": clk.wall0, "verb": "adapt", "true_size": 2, "outcome": "ok"}
        for _ in range(5)
    ])
    sup.access_log = access2
    assert sup.forecast_and_retune() is None
    assert _events(tmp_path, "retune") == []


def test_forecast_window_excludes_stale_traffic(tmp_path):
    clk = FakeClock()
    clk.t = 1000.0  # so wall() - window stays positive and meaningful
    stale_ts = clk.wall() - 10_000.0
    access = _write_access(tmp_path, [
        {"ts": stale_ts, "verb": "adapt", "true_size": 2, "outcome": "ok"}
        for _ in range(50)
    ] + [
        {"ts": clk.wall(), "verb": "adapt", "true_size": 8, "outcome": "ok"}
        for _ in range(25)
    ])
    sup, fleet, clk, _ = make_supervisor(
        tmp_path, clk=clk, n_slots=1, access_log=access, support=[16],
        query=[16], forecast_window_s=300.0,
    )
    hist = sup._forecast_histograms()
    assert hist["adapt"] == {8: 25}  # the stale size-2 burst is gone
    result = sup.forecast_and_retune()
    assert result is not None
    assert sup._pending_overrides == ["serving.support_buckets=[8]"]


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------


def test_metrics_snapshot_shape_and_marker(tmp_path):
    sup, fleet, clk, _slots_ = make_supervisor(
        tmp_path, pids_for=(0,), min_backends=1, up_polls=1,
    )
    sup.load_or_init(_slots_)
    fleet.gateway_metrics = gw()
    _queue(fleet, 10, 0)
    sup.tick()  # scale_up
    snap = sup.metrics_snapshot()
    assert snap["supervisor"] is True  # the obs_top auto-detect marker
    assert snap["running"] == 2 and snap["target"] == 2
    assert snap["last_decision"]["event"] == "scale_up"
    assert snap["cooldowns"]["up_remaining_s"] > 0
    assert snap["counters"]["scale_ups"] == 1
    assert snap["intent"] is None
    states = {s["slot"]: s["state"] for s in snap["slots"]}
    assert states == {0: "up", 1: "up", 2: "down"}
    json.dumps(snap)  # the whole payload must be wire-serializable


def test_obs_report_scaling_table_from_supervisor_events(tmp_path):
    """ISSUE 18: obs_report --fleet-events replays the supervisor's
    events.jsonl into a chronological scaling-decision table (decision,
    trigger signals, outcome, settle time) — and degrades to exactly that
    table against a telemetry-free dir instead of dying on the missing
    logs/telemetry.jsonl."""
    obs_report = _load_by_path(
        "t_obs_report", os.path.join(REPO, "scripts", "obs_report.py")
    )
    events = os.path.join(tmp_path, "events.jsonl")
    rows = [
        {"ts": 1.0, "event": "supervisor_start", "component": "supervisor",
         "slots": 2, "target": 1, "mode": "initialized"},
        {"ts": 2.0, "event": "scale_up", "component": "supervisor",
         "slot": 1, "reason": "queue_depth_max 9.0 > 8.0",
         "signals": {"queue_depth_max": 9.0, "shed_rate": 0.0},
         "outcome": "up", "settle_s": 4.2, "pid": 4500001},
        # supervisor chatter that is NOT a decision stays out of the table
        {"ts": 2.5, "event": "adopt", "component": "supervisor", "slot": 0},
        # someone else's record in a shared stream stays out entirely
        {"ts": 2.7, "event": "scale_up", "component": "gateway"},
        {"ts": 3.0, "event": "spawn_crash", "component": "supervisor",
         "slot": 1, "reason": "died warming", "crashes": 1,
         "backoff_s": 0.5},
        {"ts": 4.0, "event": "quarantine", "component": "supervisor",
         "slot": 1, "reason": "died warming", "crashes": 3,
         "window_s": 60.0},
        {"ts": 5.0, "event": "scale_down", "component": "supervisor",
         "slot": 1, "reason": "clear 5 polls",
         "signals": {"queue_depth_max": 0.0}, "outcome": "down",
         "settle_s": 1.1, "drain": "sigterm_sent", "drain_rc": 0,
         "spilled_sessions": 2},
    ]
    with open(events, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
        f.write('{"torn')  # a hard-killed supervisor leaves a torn tail

    run_dir = os.path.join(tmp_path, "not_a_run")
    os.makedirs(run_dir)
    report = obs_report.build_report(run_dir, fleet_events=events)
    assert "error" in report  # no telemetry.jsonl — honest about it
    assert report["torn_fleet_event_lines"] == 1
    table = report["scaling"]
    assert [r["event"] for r in table] == [
        "supervisor_start", "scale_up", "spawn_crash", "quarantine",
        "scale_down",
    ]
    assert [r["ts"] for r in table] == sorted(r["ts"] for r in table)
    up = table[1]
    assert up["reason"] == "queue_depth_max 9.0 > 8.0"
    assert up["signals"] == {"queue_depth_max": 9.0, "shed_rate": 0.0}
    assert up["outcome"] == "up" and up["settle_s"] == 4.2
    down = table[-1]
    assert down["drain_rc"] == 0 and down["spilled_sessions"] == 2

    rendered = obs_report.render_human(report)
    assert "fleet scaling decisions" in rendered
    assert "queue_depth_max 9.0 > 8.0" in rendered
    assert "scale_down" in rendered and "drain_rc=0" in rendered
    # a run with NO supervisor records gains no scaling key at all
    empty = obs_report.build_report(run_dir, fleet_events=None)
    assert "scaling" not in empty


def test_bench_serving_rejects_bad_autoscale_knob():
    """BENCH_AUTOSCALE typos exit the rc-2 usage contract (one stderr
    line) BEFORE any device or fleet work starts, matching the adjacent
    BENCH_REMAT/BENCH_PRECISION knobs — never a mid-main traceback an
    armed sweep can't classify."""
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_AUTOSCALE="yes")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench_serving.py"), "--tiny"],
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
    )
    assert out.returncode == 2, out.stderr
    assert "BENCH_AUTOSCALE" in out.stderr
