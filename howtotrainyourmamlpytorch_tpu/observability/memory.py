"""Per-device HBM watermarks: live/peak bytes-in-use, limit, headroom.

OOMs on the chip are post-hoc mysteries today: nothing records how close a
run sat to the HBM limit before it died. ``MemoryWatermarks`` reads
``device.memory_stats()`` (the PJRT allocator's own counters) into a
telemetry-provider snapshot — embedded in every ``telemetry.jsonl`` record
when observability is on — plus an optional one-shot low-headroom event per
device, so a run that is *about* to OOM says so in ``events.jsonl`` while
it can still speak.

``memory_stats()`` availability varies by platform (older CPU backends
return None, some plugins raise); every path degrades to an explicit
``{"available": false, "reason": ...}`` row rather than raising — memory
telemetry must never be able to kill the run it watches.
"""

import time
from typing import Any, Callable, Dict, List, Optional


def _device_rows(devices) -> List[Dict[str, Any]]:
    rows: List[Dict[str, Any]] = []
    for d in devices:
        row: Dict[str, Any] = {
            "device": int(getattr(d, "id", len(rows))),
            "kind": str(getattr(d, "device_kind", "?")),
        }
        try:
            stats = d.memory_stats()
        except Exception as exc:
            row.update({"available": False, "reason": f"{type(exc).__name__}: {exc}"})
            rows.append(row)
            continue
        if not stats:
            row.update({"available": False, "reason": "memory_stats() returned none"})
            rows.append(row)
            continue
        in_use = stats.get("bytes_in_use")
        peak = stats.get("peak_bytes_in_use")
        limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
        row.update(
            {
                "available": True,
                "bytes_in_use": in_use,
                "peak_bytes_in_use": peak,
                "bytes_limit": limit,
                "headroom_frac": (
                    round((limit - in_use) / limit, 4)
                    if limit and in_use is not None
                    else None
                ),
            }
        )
        rows.append(row)
    return rows


def device_memory_stats() -> List[Dict[str, Any]]:
    """One row per local device; never raises (an unreachable backend
    yields a single unavailable row)."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception as exc:
        return [
            {
                "device": -1,
                "kind": "?",
                "available": False,
                "reason": f"{type(exc).__name__}: {exc}",
            }
        ]
    return _device_rows(devices)


class MemoryWatermarks:
    """TelemetryHub provider + low-headroom event latch.

    ``snapshot()`` (the provider) returns the per-device rows plus the
    fleet-level aggregates readers actually key on (max peak, min
    headroom). ``maybe_warn(event_log)`` appends one ``hbm_headroom_low``
    event per device the first time its headroom drops below
    ``warn_headroom_frac`` — latched, so a run hovering at the threshold
    doesn't flood events.jsonl. ``stats_fn`` is injectable for tests."""

    def __init__(
        self,
        warn_headroom_frac: float = 0.05,
        stats_fn: Callable[[], List[Dict[str, Any]]] = device_memory_stats,
    ):
        self.warn_headroom_frac = float(warn_headroom_frac)
        self._stats_fn = stats_fn
        self._warned: set = set()

    def snapshot(self) -> Dict[str, Any]:
        rows = self._stats_fn()
        live = [r for r in rows if r.get("available")]
        peaks = [r["peak_bytes_in_use"] for r in live if r.get("peak_bytes_in_use")]
        headrooms = [
            r["headroom_frac"] for r in live if r.get("headroom_frac") is not None
        ]
        return {
            "devices": rows,
            "available_devices": len(live),
            "peak_bytes_in_use_max": max(peaks) if peaks else None,
            "headroom_frac_min": min(headrooms) if headrooms else None,
        }

    def maybe_warn(self, event_log=None) -> List[Dict[str, Any]]:
        """Check headroom against the threshold; returns (and appends to
        ``event_log`` when given) the newly-fired events. Never raises."""
        fired: List[Dict[str, Any]] = []
        try:
            for row in self._stats_fn():
                headroom = row.get("headroom_frac")
                dev = row.get("device")
                if (
                    headroom is None
                    or dev in self._warned
                    or headroom >= self.warn_headroom_frac
                ):
                    continue
                self._warned.add(dev)
                event = {
                    "ts": time.time(),
                    "event": "hbm_headroom_low",
                    "device": dev,
                    "kind": row.get("kind"),
                    "headroom_frac": headroom,
                    "bytes_in_use": row.get("bytes_in_use"),
                    "bytes_limit": row.get("bytes_limit"),
                    "threshold": self.warn_headroom_frac,
                }
                fired.append(event)
                if event_log is not None:
                    event_log.append(event)
        except Exception:
            pass
        return fired
