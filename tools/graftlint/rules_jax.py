"""GL1xx — JAX/TPU hazard rules.

GL101  tracer concretization inside jit-reachable code
GL102  Python control flow on a traced value
GL110  host sync on a designated hot path
GL120  wall-clock-seeded RNG
GL121  unseeded module-level RNG in library code
GL122  set-iteration ordering feeding construction
GL130  donation-after-use (reading an argument passed through a
       ``donate_argnums`` position)
GL140  float-dtype cast outside the precision policy (hot-path modules
       must route casts through ``ops/precision.py``)

GL101/GL102 run a module-local taint analysis: parameters of functions
passed to ``jit``/``pjit``/``shard_map`` (and of functions those call, via
the arguments actually passed) are tracers; concretizing one (``float()``,
``np.asarray()``, ``.item()``) or branching Python control flow on one is a
trace-time error or — worse — a silent per-call recompile. Heuristics that
keep the rule quiet on correct code:

- ``self``/``cls`` and keyword-only parameters are NOT tainted: this
  codebase binds static program switches (``second_order``, ``msl_active``)
  keyword-only via ``functools.partial`` at the jit boundary.
- ``.shape``/``.ndim``/``.dtype``/``.size``, ``len()``, ``isinstance()``,
  ``hasattr()`` and ``x is (not) None`` are static under tracing and
  sanitize taint.
"""

import ast
from collections import deque
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .engine import (
    Finding,
    Module,
    Project,
    Rule,
    call_name,
    const_int,
    dotted_name,
    register,
)

JIT_WRAPPERS = {"jit", "pjit", "shard_map"}
UNWRAPPERS = {"partial", "grad", "value_and_grad", "vmap", "pmap", "checkpoint", "remat"}
SANITIZER_CALLS = {"len", "hasattr", "isinstance", "getattr", "callable", "type"}
STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
CONCRETIZERS = {"float", "int", "bool", "complex"}
CONCRETIZING_METHODS = {"item", "tolist", "numpy"}

#: Designated hot paths for GL110: the dispatch/settle machinery where one
#: stray host sync serializes the pipeline. Functions can also opt in with a
#: ``# graftlint: hot-path`` marker on (or above) their ``def`` line.
HOT_PATHS: Dict[str, Set[str]] = {
    "experiment/runner.py": {"_train_epoch"},
    "serving/engine.py": {"adapt_batch", "predict_batch"},
    "serving/server.py": {"_dispatch"},
}

HOST_SYNC_METHODS = {"block_until_ready", "item", "tolist"}

#: np.random module-level draws that consult (and mutate) the hidden global
#: generator — unseeded unless someone called np.random.seed, and shared
#: across threads either way.
NP_GLOBAL_RNG = {
    "rand", "randn", "randint", "random", "random_sample", "ranf", "sample",
    "choice", "shuffle", "permutation", "uniform", "normal", "standard_normal",
    "beta", "binomial", "poisson", "exponential", "bytes",
}
STDLIB_RNG = {
    "random", "randint", "randrange", "choice", "choices", "shuffle", "sample",
    "uniform", "gauss", "betavariate", "expovariate", "normalvariate",
}
WALL_CLOCKS = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "datetime.datetime.utcnow",
}
RNG_CTORS = {"RandomState", "default_rng", "seed", "Generator", "PRNGKey", "key"}


def _parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _enclosing(parents, node, kinds) -> Optional[ast.AST]:
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, kinds):
            return cur
        cur = parents.get(cur)
    return None


def _is_numpy_call(module: Module, call: ast.Call, fn_names: Set[str]) -> bool:
    """True for ``np.asarray(...)``-style calls where the root alias resolves
    to numpy and the attribute is one of ``fn_names``."""
    name = call_name(call)
    if not name or "." not in name:
        return False
    root, rest = name.split(".", 1)
    return module.resolve_root(root).startswith("numpy") and rest in fn_names


class _FuncIndex:
    """Top-level defs + methods of one module, with jit-target resolution."""

    def __init__(self, module: Module):
        self.module = module
        self.parents = _parent_map(module.tree)
        self.top: Dict[str, ast.FunctionDef] = {}
        self.methods: Dict[Tuple[str, str], ast.FunctionDef] = {}
        for node in module.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.top[node.name] = node
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self.methods[(node.name, sub.name)] = sub

    def enclosing_class(self, node: ast.AST) -> Optional[str]:
        cls = _enclosing(self.parents, node, (ast.ClassDef,))
        return cls.name if cls is not None else None

    def resolve_name(self, name: str, at: ast.AST) -> Optional[ast.FunctionDef]:
        """A bare callable name, searched through enclosing function bodies
        (nested defs) and then module top level."""
        fn = _enclosing(self.parents, at, (ast.FunctionDef, ast.AsyncFunctionDef))
        while fn is not None:
            for stmt in ast.walk(fn):
                if (
                    isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and stmt.name == name
                ):
                    return stmt
            fn = _enclosing(self.parents, fn, (ast.FunctionDef, ast.AsyncFunctionDef))
        return self.top.get(name)

    def resolve_method(self, cls_name: Optional[str], name: str):
        if cls_name is None:
            return None
        return self.methods.get((cls_name, name))

    def resolve_callable(
        self, expr: ast.AST, at: ast.AST, bound_kws: Set[str]
    ) -> Optional[Tuple[ast.AST, Set[str]]]:
        """Resolve the callable handed to a jit wrapper: a def/lambda plus
        the set of keyword names statically bound via functools.partial."""
        if isinstance(expr, ast.Lambda):
            return expr, bound_kws
        if isinstance(expr, ast.Name):
            target = self.resolve_name(expr.id, at)
            return (target, bound_kws) if target is not None else None
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and expr.value.id in ("self", "cls"):
                target = self.resolve_method(self.enclosing_class(at), expr.attr)
                return (target, bound_kws) if target is not None else None
            return None
        if isinstance(expr, ast.Call):
            name = call_name(expr)
            last = name.split(".")[-1] if name else ""
            if last in UNWRAPPERS and expr.args:
                kws = set(bound_kws)
                if last == "partial":
                    kws |= {kw.arg for kw in expr.keywords if kw.arg}
                return self.resolve_callable(expr.args[0], at, kws)
        return None


def _seed_taint(fn: ast.AST, bound_kws: Set[str]) -> Set[str]:
    """Tracer-tainted parameter names of a directly-jitted callable."""
    args = fn.args
    names = [a.arg for a in getattr(args, "posonlyargs", [])] + [
        a.arg for a in args.args
    ]
    tainted = {n for n in names if n not in ("self", "cls")}
    # keyword-only params are static switches by convention (partial-bound)
    return tainted - bound_kws


class _Analysis:
    """One pass over a function body with a given tainted-parameter set.

    Collects GL101/GL102 findings and (callee, tainted-params) propagations
    for the module-level fixpoint."""

    def __init__(self, module: Module, index: _FuncIndex, rule_ids: Tuple[str, str]):
        self.module = module
        self.index = index
        self.gl_concrete, self.gl_flow = rule_ids
        self.findings: List[Finding] = []
        self.calls_out: List[Tuple[ast.AST, frozenset]] = []

    # -- taint of an expression ----------------------------------------

    def t(self, node: ast.AST, env: Dict[str, bool]) -> bool:
        if isinstance(node, ast.Name):
            return env.get(node.id, False)
        if isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self.t(node.value, env)
        if isinstance(node, ast.Subscript):
            return self.t(node.value, env) or self.t(node.slice, env)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops) and all(
                isinstance(c, ast.Constant) and c.value is None
                for c in node.comparators
            ):
                return False  # pytree-structure test, static under tracing
            return self.t(node.left, env) or any(
                self.t(c, env) for c in node.comparators
            )
        if isinstance(node, (ast.BinOp,)):
            return self.t(node.left, env) or self.t(node.right, env)
        if isinstance(node, ast.BoolOp):
            return any(self.t(v, env) for v in node.values)
        if isinstance(node, ast.UnaryOp):
            return self.t(node.operand, env)
        if isinstance(node, ast.IfExp):
            return (
                self.t(node.test, env)
                or self.t(node.body, env)
                or self.t(node.orelse, env)
            )
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.t(e, env) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(self.t(v, env) for v in node.values if v is not None)
        if isinstance(node, ast.Starred):
            return self.t(node.value, env)
        if isinstance(node, ast.Call):
            name = call_name(node) or ""
            last = name.split(".")[-1]
            if last in SANITIZER_CALLS or name in ("jnp.shape", "jnp.ndim"):
                return False
            root = name.split(".")[0] if name else ""
            resolved = self.module.resolve_root(root)
            if resolved.startswith("jax") or resolved in ("jax.numpy", "jax.lax"):
                return True  # tracer-producing library call
            return any(self.t(a, env) for a in node.args) or any(
                self.t(kw.value, env) for kw in node.keywords
            )
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)):
            return any(self.t(g.iter, env) for g in node.generators)
        if isinstance(node, ast.JoinedStr):
            return False
        if isinstance(node, ast.Lambda):
            return False
        return False

    # -- statement walk -------------------------------------------------

    def run(self, fn: ast.AST, tainted: Set[str]) -> None:
        env: Dict[str, bool] = {name: True for name in tainted}
        body = fn.body if not isinstance(fn, ast.Lambda) else [ast.Expr(fn.body)]
        self._block(body, env)

    def _bind_target(self, target: ast.AST, value_tainted: bool, env) -> None:
        if isinstance(target, ast.Name):
            env[target.id] = value_tainted
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind_target(elt, value_tainted, env)
        elif isinstance(target, ast.Starred):
            self._bind_target(target.value, value_tainted, env)

    def _flag(self, rule: str, node: ast.AST, msg: str) -> None:
        self.findings.append(
            Finding(rule, self.module.rel, node.lineno, node.col_offset, msg)
        )

    def _check_call(self, call: ast.Call, env) -> None:
        name = call_name(call) or ""
        # concretizers: float(x) / int(x) / np.asarray(x) on a tracer
        if isinstance(call.func, ast.Name) and call.func.id in CONCRETIZERS:
            if any(self.t(a, env) for a in call.args):
                self._flag(
                    self.gl_concrete,
                    call,
                    f"{call.func.id}() concretizes a traced value inside a "
                    "jit-compiled function (trace-time error or silent "
                    "host sync)",
                )
        elif _is_numpy_call(self.module, call, {"asarray", "array", "copy"}):
            if any(self.t(a, env) for a in call.args):
                self._flag(
                    self.gl_concrete,
                    call,
                    f"{name}() pulls a traced value to the host inside a "
                    "jit-compiled function",
                )
        elif (
            isinstance(call.func, ast.Attribute)
            and call.func.attr in CONCRETIZING_METHODS
            and self.t(call.func.value, env)
        ):
            self._flag(
                self.gl_concrete,
                call,
                f".{call.func.attr}() concretizes a traced value inside a "
                "jit-compiled function",
            )
        # propagation into module-local callees
        target = None
        skip_self = 0
        if isinstance(call.func, ast.Name):
            target = self.index.resolve_name(call.func.id, call)
        elif (
            isinstance(call.func, ast.Attribute)
            and isinstance(call.func.value, ast.Name)
            and call.func.value.id in ("self", "cls")
        ):
            target = self.index.resolve_method(
                self.index.enclosing_class(call), call.func.attr
            )
            skip_self = 1
        if target is not None:
            params = [a.arg for a in getattr(target.args, "posonlyargs", [])] + [
                a.arg for a in target.args.args
            ]
            params = params[skip_self:]
            callee_tainted: Set[str] = set()
            for i, arg in enumerate(call.args):
                if isinstance(arg, ast.Starred):
                    continue
                if i < len(params) and self.t(arg, env):
                    callee_tainted.add(params[i])
            kw_params = set(params) | {a.arg for a in target.args.kwonlyargs}
            for kw in call.keywords:
                if kw.arg and kw.arg in kw_params and self.t(kw.value, env):
                    callee_tainted.add(kw.arg)
            if callee_tainted:
                self.calls_out.append((target, frozenset(callee_tainted)))

    def _expr(self, node: ast.AST, env) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._check_call(sub, env)

    def _block(self, stmts: List[ast.stmt], env: Dict[str, bool]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child = dict(env)
                # nested defs inside traced code are called with tracers
                # (scan/vmap bodies, loss closures): taint their params
                for a in (
                    list(getattr(stmt.args, "posonlyargs", []))
                    + stmt.args.args
                    + stmt.args.kwonlyargs
                ):
                    child[a.arg] = True
                self._block(stmt.body, child)
            elif isinstance(stmt, ast.Assign):
                self._expr(stmt.value, env)
                tainted = self.t(stmt.value, env)
                for target in stmt.targets:
                    self._bind_target(target, tainted, env)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                self._expr(stmt.value, env)
                self._bind_target(stmt.target, self.t(stmt.value, env), env)
            elif isinstance(stmt, ast.AugAssign):
                self._expr(stmt.value, env)
                if isinstance(stmt.target, ast.Name):
                    env[stmt.target.id] = env.get(stmt.target.id, False) or self.t(
                        stmt.value, env
                    )
            elif isinstance(stmt, (ast.If, ast.While)):
                self._expr(stmt.test, env)
                if self.t(stmt.test, env):
                    kind = "if" if isinstance(stmt, ast.If) else "while"
                    self._flag(
                        self.gl_flow,
                        stmt,
                        f"Python `{kind}` on a traced value inside a "
                        "jit-compiled function — use lax.cond/lax.select "
                        "(or hoist the switch to a static argument)",
                    )
                self._block(stmt.body, env)
                self._block(stmt.orelse, env)
            elif isinstance(stmt, ast.For):
                self._expr(stmt.iter, env)
                self._bind_target(stmt.target, self.t(stmt.iter, env), env)
                self._block(stmt.body, env)
                self._block(stmt.orelse, env)
            elif isinstance(stmt, ast.With):
                for item in stmt.items:
                    self._expr(item.context_expr, env)
                self._block(stmt.body, env)
            elif isinstance(stmt, ast.Try):
                self._block(stmt.body, env)
                for handler in stmt.handlers:
                    self._block(handler.body, env)
                self._block(stmt.orelse, env)
                self._block(stmt.finalbody, env)
            elif isinstance(stmt, ast.Return) and stmt.value is not None:
                self._expr(stmt.value, env)
            elif isinstance(stmt, (ast.Expr, ast.Assert, ast.Raise, ast.Delete)):
                self._expr(stmt, env)
            # Pass/Break/Continue/Import/Global/Nonlocal: nothing to do


def _jit_seeds(module: Module, index: _FuncIndex):
    """(funcdef-or-lambda, tainted-params) for every jit/pjit/shard_map
    call site in the module."""
    seeds = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node) or ""
        if name.split(".")[-1] not in JIT_WRAPPERS or not node.args:
            continue
        resolved = index.resolve_callable(node.args[0], node, set())
        if resolved is None:
            continue
        target, bound_kws = resolved
        if isinstance(target, ast.Lambda):
            tainted = {
                a.arg
                for a in list(getattr(target.args, "posonlyargs", []))
                + target.args.args
            }
            seeds.append((target, frozenset(tainted - bound_kws)))
        else:
            seeds.append((target, frozenset(_seed_taint(target, bound_kws))))
    return seeds


def _tracer_findings(module: Module) -> List[Finding]:
    """Run the shared GL101/GL102 taint fixpoint once per module (memoized on
    the Module instance so selecting both rules doesn't pay twice) and return
    ALL its findings; each rule class filters to its own id."""
    cached = getattr(module, "_graftlint_tracer_findings", None)
    if cached is not None:
        return cached
    findings: List[Finding] = []
    index = _FuncIndex(module)
    seeds = _jit_seeds(module, index)
    if seeds:
        contexts: Dict[ast.AST, Set[str]] = {}
        work = deque(seeds)
        iterations = 0
        while work and iterations < 10_000:
            iterations += 1
            fn, params = work.popleft()
            have = contexts.get(fn)
            if have is not None and set(params) <= have:
                continue
            contexts[fn] = (have or set()) | set(params)
            probe = _Analysis(module, index, ("GL101", "GL102"))
            probe.run(fn, contexts[fn])
            for callee, cparams in probe.calls_out:
                work.append((callee, cparams))
        seen = set()
        for fn, tainted in contexts.items():
            final = _Analysis(module, index, ("GL101", "GL102"))
            final.run(fn, tainted)
            for f in final.findings:
                key = (f.rule, f.line, f.col)
                if key not in seen:
                    seen.add(key)
                    findings.append(f)
    module._graftlint_tracer_findings = findings  # type: ignore[attr-defined]
    return findings


@register
class TracerHazards(Rule):
    id = "GL101"
    title = "tracer concretization inside jit-reachable code"

    def check_module(self, module: Module, project: Project) -> Iterable[Finding]:
        return [f for f in _tracer_findings(module) if f.rule == self.id]


@register
class ControlFlowOnTracer(Rule):
    id = "GL102"
    title = "Python control flow on a traced value"

    def check_module(self, module: Module, project: Project) -> Iterable[Finding]:
        return [f for f in _tracer_findings(module) if f.rule == self.id]


@register
class HostSyncInHotPath(Rule):
    id = "GL110"
    title = "host sync on a hot path"

    def _hot_functions(self, module: Module):
        declared: Set[str] = set()
        for suffix, names in HOT_PATHS.items():
            if module.rel.endswith(suffix):
                declared |= names
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
                node.name in declared or module.has_marker("hot-path", node.lineno)
            ):
                yield node

    def check_module(self, module: Module, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        for fn in self._hot_functions(module):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                msg = None
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in HOST_SYNC_METHODS
                ):
                    msg = f".{node.func.attr}() blocks on the device"
                elif _is_numpy_call(module, node, {"asarray", "array"}) and not (
                    node.args
                    and isinstance(node.args[0], (ast.List, ast.Tuple, ast.Constant))
                ):
                    # a literal display is host data by construction
                    msg = f"{call_name(node)}() copies device memory to host"
                elif (call_name(node) or "").endswith("device_get"):
                    msg = "jax.device_get() synchronizes host and device"
                elif isinstance(node.func, ast.Name) and node.func.id in (
                    "float",
                    "int",
                ):
                    if node.args and not isinstance(node.args[0], ast.Constant):
                        msg = (
                            f"{node.func.id}() on a device value forces a "
                            "blocking transfer"
                        )
                if msg:
                    findings.append(
                        Finding(
                            self.id,
                            module.rel,
                            node.lineno,
                            node.col_offset,
                            f"host sync inside hot path `{fn.name}`: {msg} "
                            "(move off the dispatch loop, or suppress with "
                            "a justification if the sync is the point)",
                        )
                    )
        return findings


@register
class WallClockSeededRNG(Rule):
    id = "GL120"
    title = "wall-clock-seeded RNG"

    def check_module(self, module: Module, project: Project) -> Iterable[Finding]:
        findings = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node) or ""
            is_rng_ctor = name.split(".")[-1] in RNG_CTORS
            seed_kwargs = [
                kw.value
                for kw in node.keywords
                if kw.arg and ("seed" in kw.arg.lower())
            ]
            if not is_rng_ctor and not seed_kwargs:
                continue
            scan = list(node.args) + seed_kwargs if is_rng_ctor else seed_kwargs
            for arg in scan:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Call) and (
                        (call_name(sub) or "") in WALL_CLOCKS
                    ):
                        findings.append(
                            Finding(
                                self.id,
                                module.rel,
                                sub.lineno,
                                sub.col_offset,
                                "RNG seeded from the wall clock — every run "
                                "(and every process of a multi-host job) "
                                "draws a different stream; thread a seed "
                                "from config instead",
                            )
                        )
        return findings


@register
class UnseededModuleRNG(Rule):
    id = "GL121"
    title = "unseeded module-level RNG in library code"

    def check_module(self, module: Module, project: Project) -> Iterable[Finding]:
        findings = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node) or ""
            parts = name.split(".")
            bad = None
            if len(parts) >= 2:
                root = module.resolve_root(parts[0])
                if (
                    root.startswith("numpy")
                    and parts[-2] == "random"
                    and parts[-1] in NP_GLOBAL_RNG
                ):
                    bad = name
                elif root == "random" and parts[-1] in STDLIB_RNG:
                    bad = name
            elif len(parts) == 1:
                resolved = module.resolve_root(parts[0])
                if resolved.startswith("random.") and parts[0] in STDLIB_RNG:
                    bad = resolved
            if bad:
                findings.append(
                    Finding(
                        self.id,
                        module.rel,
                        node.lineno,
                        node.col_offset,
                        f"{bad}() draws from the hidden global generator — "
                        "unseeded (non-replayable) and shared across "
                        "threads; use np.random.RandomState(seed) / "
                        "default_rng(seed) plumbed from config",
                    )
                )
        return findings


@register
class SetIterationOrder(Rule):
    id = "GL122"
    title = "set-iteration ordering feeding construction"

    def _is_set_expr(self, node: ast.AST) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            return node.func.id in ("set", "frozenset")
        if isinstance(node, ast.BinOp):
            return self._is_set_expr(node.left) or self._is_set_expr(node.right)
        return False

    def check_module(self, module: Module, project: Project) -> Iterable[Finding]:
        findings = []
        iters: List[ast.AST] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.For):
                iters.append(node.iter)
            elif isinstance(
                node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)
            ):
                iters.extend(g.iter for g in node.generators)
        for it in iters:
            if self._is_set_expr(it):
                findings.append(
                    Finding(
                        self.id,
                        module.rel,
                        it.lineno,
                        it.col_offset,
                        "iterating a set: the order is arbitrary per process "
                        "(hash randomization), so anything built from it — "
                        "pytree leaves, schedules, file lists — is "
                        "nondeterministic; sort it first",
                    )
                )
        return findings


@register
class DonationAfterUse(Rule):
    id = "GL130"
    title = "donated buffer read after the donating call"

    def _donated_positions(self, call: ast.Call) -> Optional[List[int]]:
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                v = kw.value
                if const_int(v) is not None:
                    return [const_int(v)]
                if isinstance(v, (ast.Tuple, ast.List)):
                    out = [const_int(e) for e in v.elts]
                    if all(x is not None for x in out):
                        return out  # type: ignore[return-value]
                return None  # dynamic (config-driven): can't track statically
        return None

    def check_module(self, module: Module, project: Project) -> Iterable[Finding]:
        findings = []
        scopes = [module.tree] + [
            n
            for n in ast.walk(module.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            body_nodes = (
                scope.body if isinstance(scope, ast.Module) else scope.body
            )
            donators: Dict[str, List[int]] = {}
            # (varname, donated-at-line)
            donated: Dict[str, int] = {}
            events = []
            own_defs = {
                n
                for stmt in body_nodes
                for n in ast.walk(stmt)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            } - {scope}
            skip: Set[ast.AST] = set()
            for inner in own_defs:
                skip.update(ast.walk(inner))
            # Name-Store nodes that are targets of Assign-family statements:
            # their rebind takes effect when the whole statement finishes, so
            # the store event is anchored at the statement's END line — this
            # keeps the canonical `state = fn(\n    state, ...)` multi-line
            # rebind clean (the donate, at the call's end line, is cleared by
            # the store at the same point)
            assign_target_stores: Dict[ast.AST, int] = {}
            for stmt in body_nodes:
                for node in ast.walk(stmt):
                    if node in skip:
                        continue
                    if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                        end = getattr(node, "end_lineno", None) or node.lineno
                        targets = (
                            node.targets
                            if isinstance(node, ast.Assign)
                            else [node.target]
                        )
                        for t in targets:
                            for sub in ast.walk(t):
                                if isinstance(sub, ast.Name):
                                    assign_target_stores[sub] = end
            for stmt in body_nodes:
                for node in ast.walk(stmt):
                    if node in skip:
                        continue  # nested defs are their own scopes
                    if isinstance(node, ast.Assign) and isinstance(
                        node.value, ast.Call
                    ):
                        name = call_name(node.value) or ""
                        if name.split(".")[-1] in JIT_WRAPPERS:
                            pos = self._donated_positions(node.value)
                            if pos:
                                for t in node.targets:
                                    if isinstance(t, ast.Name):
                                        donators[t.id] = pos
                    if isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Name
                    ):
                        pos = donators.get(node.func.id)
                        if pos:
                            # the buffer dies when the CALL completes — its
                            # end line, so arg loads inside a multi-line
                            # call never sort after their own donation
                            end = getattr(node, "end_lineno", None) or node.lineno
                            for p in pos:
                                if p < len(node.args) and isinstance(
                                    node.args[p], ast.Name
                                ):
                                    events.append(
                                        ("donate", end, node.args[p].id)
                                    )
                    if isinstance(node, ast.Name):
                        if node in assign_target_stores:
                            events.append(
                                ("store", assign_target_stores[node], node.id)
                            )
                        else:
                            kind = (
                                "store"
                                if isinstance(node.ctx, (ast.Store, ast.Del))
                                else "load"
                            )
                            events.append((kind, node.lineno, node.id))
            # within one line the evaluation order is: arg loads, then the
            # donating call, then the assignment store (which rebinds the
            # name to the result, making the donated buffer unreachable)
            events.sort(
                key=lambda e: (e[1], {"load": 0, "donate": 1, "store": 2}[e[0]])
            )
            for kind, line, name in events:
                if kind == "donate":
                    donated[name] = line
                elif kind == "store":
                    donated.pop(name, None)
                elif kind == "load" and name in donated and line > donated[name]:
                    findings.append(
                        Finding(
                            self.id,
                            module.rel,
                            line,
                            0,
                            f"`{name}` was donated to a jit-compiled call "
                            f"(donate_argnums, line {donated[name]}) and read "
                            "afterwards — the buffer is dead; rebind the "
                            "result or drop the donation",
                        )
                    )
                    donated.pop(name, None)
        return findings


# ---------------------------------------------------------------------------
# GL140 — float-dtype cast outside the precision policy
# ---------------------------------------------------------------------------

# Modules whose float-cast discipline belongs to ops/precision.py: the
# compiled hot path (layer math, the meta-step, the inner optimizers, the
# serving dispatch). Matched by path fragment so the rule follows the files,
# not a marker someone has to remember.
PRECISION_SCOPED_FRAGMENTS = (
    "howtotrainyourmamlpytorch_tpu/models/",
    "howtotrainyourmamlpytorch_tpu/core/",
    "howtotrainyourmamlpytorch_tpu/ops/",
    "howtotrainyourmamlpytorch_tpu/serving/",
)
PRECISION_HOME_SUFFIX = "howtotrainyourmamlpytorch_tpu/ops/precision.py"
FLOAT_DTYPE_NAMES = {
    "float32", "float64", "float16", "bfloat16", "half", "single", "double",
}


@register
class FloatCastOutsidePolicy(Rule):
    id = "GL140"
    title = "float-dtype cast outside the precision policy"

    def _float_literal(self, module: Module, node: ast.AST):
        """The dtype name when ``node`` is a literal float dtype — a string
        constant ('float32') or a numpy/jnp attribute (jnp.bfloat16) —
        None for anything value-derived (``p.dtype``, a ``stat_dtype``
        parameter), which is exactly the dtype-relative discipline the
        policy threads through and is always clean."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value if node.value in FLOAT_DTYPE_NAMES else None
        name = dotted_name(node)
        if not name or "." not in name:
            return None
        head, _, attr = name.rpartition(".")
        if attr not in FLOAT_DTYPE_NAMES:
            return None
        root = module.resolve_root(head.split(".")[0])
        if root.split(".")[0] in ("numpy", "jax", "jnp", "np", "ml_dtypes"):
            return name
        return None

    def check_module(self, module: Module, project: Project) -> Iterable[Finding]:
        rel = module.rel.replace("\\", "/")
        if rel.endswith(PRECISION_HOME_SUFFIX):
            return ()
        if not any(frag in rel for frag in PRECISION_SCOPED_FRAGMENTS):
            return ()
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "astype"
            ):
                continue
            dtype_arg = node.args[0] if node.args else next(
                (kw.value for kw in node.keywords if kw.arg == "dtype"), None
            )
            if dtype_arg is None:
                continue
            literal = self._float_literal(module, dtype_arg)
            if literal is None:
                continue
            findings.append(
                Finding(
                    self.id,
                    module.rel,
                    node.lineno,
                    node.col_offset,
                    f".astype({literal}) in a hot-path module — float-dtype "
                    "cast boundaries live in ops/precision.py (use the "
                    "policy / as_f32, or a value-derived dtype like "
                    "`p.dtype`); suppress with a justification if this cast "
                    "really is not on the compiled hot path",
                )
            )
        return findings
