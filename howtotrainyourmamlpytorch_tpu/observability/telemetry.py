"""TelemetryHub: one process-level seam joining tracer + registry + jsonl.

The hub owns a :class:`~.trace.SpanTracer` and a
:class:`~.metrics.MetricsRegistry` and periodically snapshots them to
``logs/telemetry.jsonl`` — per epoch and (optionally) per-N steps — so a run
leaves behind a machine-readable record of *where step time went*
(step-phase histograms: data-wait / dispatch / settle / checkpoint / eval),
throughput in episodes/s, and whatever live components register as
providers (recompile-guard snapshot, watchdog beat age, breaker state,
loader stats). ``scripts/obs_report.py`` joins this with ``events.jsonl``
and the xplane device-time breakdown into one run report.

Disabled (``Config.observability.enabled=false``) the hub is fully inert:
``span``/``phase`` return a shared no-op context manager, snapshots return
``{}`` without touching disk, and no file is ever created — the run is
bit-identical to a build without the subsystem (test-asserted).

Snapshot records are JSON lines shaped::

    {"ts": ..., "kind": "epoch"|"step"|"final", "epoch": ..., "steps": N,
     "episodes": M, "episodes_per_s": ..., "interval_episodes_per_s": ...,
     "phases": {phase: {count, window, mean_ms, p50_ms, p95_ms, p99_ms,
                        max_ms, sum_ms}},  # cumulative count/sum, windowed pcts
     "counters": {...}, "gauges": {...}, "providers": {...}}
"""

import os
import time
from typing import Any, Callable, Dict, Optional

from .metrics import DEFAULT_WINDOW, MetricsRegistry
from .trace import NULL_TRACER, SpanTracer

PHASE_PREFIX = "phase."


class _PhaseSpan:
    """Span + histogram observation in one context manager (the per-step
    instrumentation unit: shows up both in the Chrome trace and in the
    telemetry.jsonl percentiles). The histogram reuses the span's own
    duration — one clock pair per phase, trace and percentiles always
    agree."""

    __slots__ = ("_hub", "_name", "_span")

    def __init__(self, hub: "TelemetryHub", name: str, tags: Dict[str, Any]):
        self._hub = hub
        self._name = name
        self._span = hub.tracer.span(name, **tags)

    def __enter__(self):
        self._span.__enter__()
        return self

    def __exit__(self, *exc):
        result = self._span.__exit__(*exc)
        self._hub.registry.observe(
            PHASE_PREFIX + self._name,
            self._span.duration_s,
            window=self._hub.window,
        )
        return result


class _NullPhase:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_PHASE = _NullPhase()


class TelemetryHub:
    def __init__(
        self,
        enabled: bool = True,
        logs_dir: Optional[str] = None,
        window: int = DEFAULT_WINDOW,
        trace_capacity: int = 8192,
        snapshot_every_steps: int = 0,
        export_chrome_trace: bool = True,
        clock: Callable[[], float] = time.monotonic,
        wall_clock: Callable[[], float] = time.time,
    ):
        self.enabled = bool(enabled)
        self.window = int(window)
        self.snapshot_every_steps = int(snapshot_every_steps)
        self.export_chrome_trace = bool(export_chrome_trace)
        self._clock = clock
        self._wall_clock = wall_clock
        self.tracer = (
            SpanTracer(capacity=trace_capacity, clock=clock)
            if self.enabled
            else NULL_TRACER
        )
        self.registry = MetricsRegistry(default_window=self.window)
        self._providers: Dict[str, Callable[[], Any]] = {}
        # per-process session id stamped into every snapshot: a resumed run
        # APPENDS a new session to the same telemetry.jsonl and its
        # cumulative counters restart, so readers (obs_report) must split
        # sessions exactly, not by heuristics on counter resets
        self.session_id = f"{int(wall_clock() * 1e3):x}-{os.getpid()}"
        self._log = None
        self.trace_path: Optional[str] = None
        if self.enabled and logs_dir:
            # storage.EventLog: every append written whole + flushed, handle
            # closed on every exit path — telemetry must survive ugly deaths
            # exactly like events.jsonl does
            from ..experiment.storage import EventLog

            self._log = EventLog(logs_dir, filename="telemetry.jsonl")
            self.trace_path = os.path.join(logs_dir, "trace.json")
            if os.path.exists(self.trace_path):
                # a previous session's trace — possibly the rc=76 wedge
                # post-mortem — must not be clobbered by this session's
                # export at close; archive it under a unique name
                archived = os.path.join(
                    logs_dir,
                    f"trace-{int(os.path.getmtime(self.trace_path))}"
                    f"-{os.getpid()}.json",
                )
                try:
                    os.replace(self.trace_path, archived)
                except OSError:
                    pass  # unarchivable beats uncloseable; export still wins
        self._t_start = clock()
        self._steps = 0
        self._episodes = 0
        self._last_snap_t = self._t_start
        self._last_snap_episodes = 0
        self._last_snap_steps = 0
        self._next_snap_step = self.snapshot_every_steps
        self._closed = False

    @classmethod
    def from_config(cls, obs_cfg, logs_dir: Optional[str] = None) -> "TelemetryHub":
        """Build from a ``Config.observability`` block (duck-typed so the
        package stays importable without the config module)."""
        return cls(
            enabled=getattr(obs_cfg, "enabled", True),
            logs_dir=logs_dir,
            window=getattr(obs_cfg, "histogram_window", DEFAULT_WINDOW),
            trace_capacity=getattr(obs_cfg, "trace_capacity", 8192),
            snapshot_every_steps=getattr(obs_cfg, "snapshot_every_steps", 0),
            export_chrome_trace=getattr(obs_cfg, "export_chrome_trace", True),
        )

    # -- instrumentation hooks ----------------------------------------

    def span(self, name: str, flows=None, **tags):
        """Trace-only span (no phase histogram) — fine-grained serving
        spans. ``flows`` links the span into a request's cross-thread arc
        (observability/context.py flow helpers)."""
        if not self.enabled:
            return _NULL_PHASE
        return self.tracer.span(name, flows=flows, **tags)

    def phase(self, name: str, **tags):
        """Span + ``phase.<name>`` histogram observation — the per-step unit."""
        if not self.enabled:
            return _NULL_PHASE
        return _PhaseSpan(self, name, tags)

    def add_provider(self, name: str, fn: Callable[[], Any]) -> None:
        """Register a zero-arg callable whose return value is embedded in
        every snapshot under ``providers.<name>`` (recompile guard, watchdog
        beat age, breaker state...). Provider errors are contained: a broken
        provider reports its error string, never kills the run."""
        if self.enabled:
            self._providers[name] = fn

    def step_completed(self, episodes: int = 0, steps: int = 1) -> None:
        """One settled dispatch (``episodes`` = meta-batch episodes it
        carried, ``steps`` = meta-steps it ran — K for a multi-step
        dispatch, so ``interval_steps_per_s`` and the MFU it feeds stay in
        meta-steps regardless of dispatch chunking). Drives the per-N-step
        snapshot cadence."""
        if not self.enabled:
            return
        self._steps += steps
        self._episodes += episodes
        # crossing check, not modulo: a K-step jump must not hop over the
        # cadence boundary
        if self.snapshot_every_steps > 0 and self._steps >= self._next_snap_step:
            self._next_snap_step = self._steps + self.snapshot_every_steps
            self.snapshot("step")

    # -- snapshots -----------------------------------------------------

    def _provider_values(self) -> Dict[str, Any]:
        out = {}
        for name, fn in self._providers.items():
            try:
                out[name] = fn()
            except Exception as exc:  # noqa: BLE001 — telemetry must not kill the run
                out[name] = {"provider_error": repr(exc)}
        return out

    def snapshot(self, kind: str, **extra) -> Dict[str, Any]:
        """Build one snapshot record; appended to telemetry.jsonl when the
        hub owns a log. ``extra`` lands at the top level (e.g. the runner's
        per-epoch stats)."""
        if not self.enabled:
            return {}
        now = self._clock()
        interval_s = now - self._last_snap_t
        interval_eps = self._episodes - self._last_snap_episodes
        interval_steps = self._steps - self._last_snap_steps
        self._last_snap_t = now
        self._last_snap_episodes = self._episodes
        self._last_snap_steps = self._steps
        elapsed = now - self._t_start
        record: Dict[str, Any] = {
            "ts": self._wall_clock(),
            "kind": kind,
            "session": self.session_id,
            "elapsed_s": round(elapsed, 3),
            "steps": self._steps,
            "episodes": self._episodes,
            "episodes_per_s": round(self._episodes / elapsed, 3) if elapsed > 0 else None,
            "interval_episodes_per_s": (
                round(interval_eps / interval_s, 3) if interval_s > 0 else None
            ),
            "interval_steps_per_s": (
                round(interval_steps / interval_s, 3) if interval_s > 0 else None
            ),
            "phases": self.registry.summaries(PHASE_PREFIX),
            "counters": self.registry.counters(),
            "gauges": self.registry.gauges(),
            "providers": self._provider_values(),
            "dropped_spans": getattr(self.tracer, "dropped", 0),
        }
        # live MFU: the flops_per_step gauge (set by the runner's compile-
        # ledger observer once the cost model prices the train program)
        # times the interval step rate over the chip-peak gauge. Null with
        # the gauges absent — notably peak on CPU, where the reason rides
        # the mfu_unavailable_reason gauge instead.
        fps = self.registry.gauge("flops_per_step")
        peak = self.registry.gauge("peak_flops_per_sec")
        steps_ps = record["interval_steps_per_s"]
        if steps_ps is None:
            steps_ps = round(self._steps / elapsed, 3) if elapsed > 0 else None
        if fps:
            # a zero-step interval (eval/checkpoint-dominated snapshot) is
            # honestly mfu=0.0, not a fall-back to the lifetime average
            record["mfu"] = (
                round(fps * steps_ps / peak, 5)
                if peak and steps_ps is not None
                else None
            )
        record.update(extra)
        if self._log is not None:
            self._log.append(record)
        return record

    # -- shutdown ------------------------------------------------------

    def close(self) -> None:
        """Final snapshot + Chrome-trace export + log close. Idempotent, and
        safe on every runner exit path (the wedge path's ``os._exit`` skips
        it — telemetry.jsonl is already flushed per append, only the trace
        export is lost, which needs a live main thread anyway)."""
        if not self.enabled or self._closed:
            return
        self._closed = True
        self.snapshot("final")
        if self.trace_path and self.export_chrome_trace:
            try:
                self.tracer.export(self.trace_path)
            except OSError:
                pass  # a full disk must not turn a finished run into a crash
        if self._log is not None:
            self._log.close()


#: Shared inert hub for call sites that want unconditional ``hub.phase(...)``
#: without holding config (bench helpers, bare engines).
NULL_HUB = TelemetryHub(enabled=False)
