"""Fleet campaign scheduler: a config x seed matrix run unattended.

``scripts/sweep.sh`` (now a thin wrapper over ``scripts/fleet_run.py``) used
to own the whole harness policy in bash: which exit codes restart, how long a
silent log means "wedged", how many restarts a run gets. Bash can't import
``exit_codes.py``, so every one of those literals was a GL302-class drift
hazard the linter couldn't see. This module moves the policy into Python,
where it consumes the rc registry directly and is unit-testable with injected
child processes and clocks:

- **rc policy** (``exit_codes.py``, the single source): ``0`` done;
  ``DIVERGED`` (3) is permanent — mark the cell diverged and move on;
  ``RESTARTABLE_RCS`` (75 preemption / 76 wedge) relaunch with exact resume,
  bounded by ``restart_budget`` without burning an attempt; anything else
  burns one of ``max_restarts`` attempts. ``TPU_WAIT_DEADLINE``/
  ``TPU_WAIT_WEDGED`` (64/65) from the *gate* pause the queue until the
  tunnel answers.
- **stall watchdog**: a run whose output log goes silent past
  ``stall_deadline_s`` is killed and relaunched (resume is exact) — the
  harness-side defense for a client wedged so hard its own watchdog never
  fires.
- **budgets**: per-cell wall-clock (``cell_timeout_s``) across attempts, an
  optional fleet-wide ``deadline_epoch`` after which no new cell starts.
- **aggregation**: each finished cell's ``telemetry.jsonl``/``events.jsonl``
  are summarized through ``scripts/obs_report.py``'s own ``build_report``
  (one code path for the per-run and the fleet view), and the whole matrix
  lands in one ``fleet_report.json`` + a ``fleet_events.jsonl`` stream.

Import-light by design (stdlib + the dependency-free rc registry; no jax):
the scheduler must run on a box whose backend is the thing being waited on.
It is loadable both as a package module and by file path
(``scripts/fleet_run.py`` does the latter to skip the heavy package import).
"""

import importlib.util
import json
import os
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_ROOT = os.path.dirname(_PKG_DIR)


def _load_by_path(name: str, path: str):
    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


try:  # package context (tests, in-process embedding)
    from .. import exit_codes
except ImportError:  # file-path load from scripts/fleet_run.py
    exit_codes = _load_by_path(
        "htymp_exit_codes", os.path.join(_PKG_DIR, "exit_codes.py")
    )


# ---------------------------------------------------------------------------
# spec
# ---------------------------------------------------------------------------


@dataclass
class FleetSpec:
    """A config x seed matrix plus the harness policy knobs. YAML form::

        fleet:
          name: accuracy_omniglot_r6
          base_overrides: [dataset=omniglot, inner_optim=gd, ...]
          configs:
            - {name: omniglot.5.1.vgg.gd, overrides: [num_classes_per_set=5]}
          seeds: [0, 1, 2]
          stall_deadline_s: 420
          max_restarts: 8

    ``seed_overrides`` is the per-seed template (``{seed}`` substituted);
    the default pins all three stream seeds, matching the accuracy-matrix
    contract. Every policy default mirrors the retired bash harness."""

    name: str = "fleet"
    configs: List[Dict[str, Any]] = field(default_factory=list)
    seeds: List[int] = field(default_factory=lambda: [0])
    base_overrides: List[str] = field(default_factory=list)
    seed_overrides: List[str] = field(
        default_factory=lambda: ["seed={seed}", "train_seed={seed}", "val_seed={seed}"]
    )
    experiment_root: str = "exps"
    # harness policy (previously hardcoded in sweep.sh)
    stall_deadline_s: float = 420.0
    poll_s: float = 5.0
    max_restarts: int = 8  # hard-failure attempts per cell
    restart_budget: int = 0  # 0 = auto: 3 * max_restarts (the sweep bound)
    cell_timeout_s: float = 0.0  # 0 = unbounded wall clock per cell
    max_parallel: int = 1  # >1 only off the single-client chip
    deadline_epoch: float = 0.0  # 0 = none; wall-clock (epoch s) start cutoff
    # TPU gate pause (64/65 from scripts/wait_for_tpu.py). tpu_gate=false
    # skips the gate entirely (CPU fleets); an explicit JAX_PLATFORMS=cpu
    # environment skips it automatically either way.
    tpu_gate: bool = True
    gate_retry_s: float = 30.0
    gate_give_up_s: float = 3600.0

    def __post_init__(self):
        if not self.configs:
            raise ValueError("fleet spec needs at least one config")
        names = [c.get("name") for c in self.configs]
        if len(set(names)) != len(names) or not all(names):
            raise ValueError(f"fleet config names must be unique and non-empty: {names}")
        for bad in ("/", " "):
            for n in names:
                if bad in n:
                    raise ValueError(f"fleet config name {n!r} contains {bad!r}")
        if self.max_restarts < 0 or self.max_parallel < 1:
            raise ValueError("max_restarts must be >= 0 and max_parallel >= 1")
        if self.restart_budget == 0:
            self.restart_budget = 3 * self.max_restarts

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "FleetSpec":
        data = dict(data.get("fleet", data))
        known = {f for f in cls.__dataclass_fields__}  # noqa: E501
        unknown = set(data) - known
        if unknown:
            raise ValueError(f"unknown fleet spec keys: {sorted(unknown)}")
        configs = []
        for c in data.get("configs", []):
            if isinstance(c, str):
                # "name override override..." shorthand (the sweep.sh job form)
                parts = c.split()
                c = {"name": parts[0], "overrides": parts[1:]}
            configs.append({"name": c["name"], "overrides": list(c.get("overrides", []))})
        data["configs"] = configs
        return cls(**data)

    def cells(self) -> List["FleetCell"]:
        # seed overrides sit BETWEEN base and per-config overrides: the
        # matrix seed is the default, but a job that pins its own seed in
        # its override string (the retired sweep.sh drivers did exactly
        # that) must win — load_config applies overrides last-wins, and
        # silently clobbering an explicit seed would relabel its science
        return [
            FleetCell(
                name=f"{c['name']}.s{seed}",
                config=c["name"],
                seed=int(seed),
                overrides=(
                    list(self.base_overrides)
                    + [o.format(seed=seed) for o in self.seed_overrides]
                    + list(c["overrides"])
                ),
            )
            for c in self.configs
            for seed in self.seeds
        ]


@dataclass
class FleetCell:
    name: str
    config: str
    seed: int
    overrides: List[str]
    status: str = "pending"  # running|done|diverged|failed|skipped
    reason: str = ""
    rcs: List[int] = field(default_factory=list)
    attempts: int = 0  # hard-failure attempts spent
    restarts: int = 0  # free (75/76) restarts spent
    stall_kills: int = 0
    wall_s: float = 0.0
    obs: Optional[Dict[str, Any]] = None

    def as_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "config": self.config,
            "seed": self.seed,
            "overrides": list(self.overrides),
            "status": self.status,
            "reason": self.reason,
            "rcs": list(self.rcs),
            "attempts": self.attempts,
            "restarts": self.restarts,
            "stall_kills": self.stall_kills,
            "wall_s": round(self.wall_s, 1),
            "obs": self.obs,
        }


# ---------------------------------------------------------------------------
# scheduler
# ---------------------------------------------------------------------------


def _default_launcher(cell: FleetCell, attempt: int, exps_root: str):
    """Spawn one training run for ``cell``: ``python -u train_maml_system.py``
    with the cell's overrides, stdout/stderr appended to the cell's .out
    file (the stall watchdog's liveness signal — hence -u)."""
    out_path = os.path.join(exps_root, f"{cell.name}.out")
    out = open(out_path, "ab")
    cmd = [
        sys.executable,
        "-u",
        os.path.join(_REPO_ROOT, "train_maml_system.py"),
        *cell.overrides,
        f"experiment_name={cell.name}",
        f"experiment_root={exps_root}",
    ]
    proc = subprocess.Popen(cmd, cwd=_REPO_ROOT, stdout=out, stderr=subprocess.STDOUT)
    out.close()
    return proc, out_path


def _default_gate() -> int:
    """The TPU tunnel-liveness gate (scripts/wait_for_tpu.py rc contract).
    An explicit CPU run (``JAX_PLATFORMS=cpu``, the same opt-out every entry
    script honors) has no tunnel to gate on — probing for a TPU there would
    block the queue for the full gate deadline with no way to succeed."""
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if platforms.split(",")[0].strip().lower() == "cpu":
        return exit_codes.OK
    return subprocess.run(
        [sys.executable, "-u", os.path.join(_REPO_ROOT, "scripts", "wait_for_tpu.py")],
        cwd=_REPO_ROOT,
    ).returncode


def _default_obs(run_dir: str) -> Optional[Dict[str, Any]]:
    """Per-run observability summary through obs_report's OWN builder — the
    fleet view and the per-run report share one code path."""
    try:
        obs_report = _load_by_path(
            "htymp_obs_report", os.path.join(_REPO_ROOT, "scripts", "obs_report.py")
        )
        report = obs_report.build_report(run_dir)
        return json.loads(obs_report.oneline(report))
    except Exception as exc:  # noqa: BLE001 — a missing report never fails a cell
        return {"error": f"obs_report failed: {exc!r}"}


class FleetScheduler:
    """Drive a :class:`FleetSpec` to completion. Every effectful dependency
    (child launcher, TPU gate, clocks, sleep) is injectable, so the full rc
    policy — bounded restarts, stall kills, gate pauses, budgets — is
    testable in milliseconds with fake children."""

    def __init__(
        self,
        spec: FleetSpec,
        launcher: Optional[Callable] = None,
        gate: Optional[Callable[[], int]] = None,
        obs: Optional[Callable[[str], Optional[Dict[str, Any]]]] = None,
        clock: Callable[[], float] = time.monotonic,
        walltime: Callable[[], float] = time.time,
        sleep: Callable[[float], None] = time.sleep,
        log: Callable[[str], None] = lambda m: print(m, file=sys.stderr, flush=True),
    ):
        self.spec = spec
        self.exps_root = spec.experiment_root
        self._launcher = launcher or (
            lambda cell, attempt: _default_launcher(cell, attempt, self.exps_root)
        )
        if gate is not None:
            self._gate = gate
        elif spec.tpu_gate:
            self._gate = _default_gate
        else:
            self._gate = lambda: exit_codes.OK
        self._obs = obs if obs is not None else _default_obs
        self._clock = clock
        self._walltime = walltime
        self._sleep = sleep
        self._log = log
        self.cells = spec.cells()
        self._events_path = os.path.join(self.exps_root, "fleet_events.jsonl")

    # -- plumbing ----------------------------------------------------------

    def _event(self, event: str, **fields) -> None:
        record = {"ts": self._walltime(), "event": event, **fields}
        try:
            with open(self._events_path, "a") as f:
                f.write(json.dumps(record) + "\n")
        except OSError:
            pass
        self._log(f"fleet: {event} " + " ".join(f"{k}={v}" for k, v in fields.items()))

    def _warm_manifest(self, cell: FleetCell) -> Optional[Dict[str, Any]]:
        """Warm-spawn readiness: a relaunched cell whose run dir carries an
        AOT prewarm manifest (``compile/aot.py``, written next to the
        checkpoints) is expected to hit the persistent compilation cache
        and be stepping in seconds — the scheduler records the expectation
        at launch so a restart that then burns minutes of XLA reads as the
        anomaly it is. Pure JSON read (this module stays jax-free); the
        child process does the authoritative fingerprint verification."""
        path = os.path.join(
            self.exps_root, cell.name, "saved_models", "prewarm_manifest.json"
        )
        try:
            with open(path) as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError, ValueError):
            return None
        fingerprint = manifest.get("fingerprint") or {}
        return {
            "programs": len(manifest.get("programs") or {}),
            "device_kind": fingerprint.get("device_kind"),
            "jaxlib": fingerprint.get("jaxlib"),
            "cache_entries": (manifest.get("cache") or {}).get("entries"),
        }

    def _liveness_age_s(self, out_path: Optional[str]) -> float:
        if not out_path or not os.path.exists(out_path):
            return 0.0
        try:
            return max(0.0, self._walltime() - os.stat(out_path).st_mtime)
        except OSError:
            return 0.0

    # -- policy ------------------------------------------------------------

    def _gate_wait(self) -> None:
        """Pause the queue until the TPU gate clears (64/65 = tunnel not
        answering). Bounded by ``gate_give_up_s``: past it, launch anyway —
        the child's own startup gate is the next line of defense."""
        start = self._clock()
        while True:
            rc = int(self._gate())
            if rc not in (exit_codes.TPU_WAIT_DEADLINE, exit_codes.TPU_WAIT_WEDGED):
                if rc != exit_codes.OK:
                    self._event("gate_nonzero", rc=rc, action="launching anyway")
                return
            waited = self._clock() - start
            if waited >= self.spec.gate_give_up_s:
                self._event(
                    "gate_give_up", rc=rc, waited_s=round(waited, 1),
                    action="launching anyway",
                )
                return
            self._event("gate_paused", rc=rc, retry_in_s=self.spec.gate_retry_s)
            self._sleep(self.spec.gate_retry_s)

    def _finish(self, cell: FleetCell, status: str, reason: str = "") -> None:
        cell.status = status
        cell.reason = reason
        if status in ("done", "diverged", "failed"):
            cell.obs = self._obs(os.path.join(self.exps_root, cell.name))
            try:
                with open(
                    os.path.join(self.exps_root, cell.name, "fleet_cell.json"), "w"
                ) as f:
                    json.dump(cell.as_dict(), f, indent=1)
            except OSError:
                pass
        self._event(
            "cell_" + status, cell=cell.name, rcs=cell.rcs,
            restarts=cell.restarts, attempts=cell.attempts, reason=reason,
        )

    def _classify(self, cell: FleetCell, rc: int) -> Optional[str]:
        """Apply the rc registry to a finished attempt. Returns a terminal
        status, or None to relaunch the cell."""
        cell.rcs.append(rc)
        if rc == exit_codes.OK:
            return "done"
        if rc == exit_codes.DIVERGED:
            # permanent: retrying resumes the same collapsing trajectory
            return "diverged"
        if rc in exit_codes.RESTARTABLE_RCS:
            # preemption/wedge: emergency checkpoint written, resume is
            # exact — a free restart, bounded so a wedge-every-epoch tunnel
            # can't loop forever
            cell.restarts += 1
            if cell.restarts > self.spec.restart_budget:
                return "failed"
            self._event(
                "cell_restart", cell=cell.name, rc=rc,
                kind=exit_codes.describe(rc), restarts=cell.restarts,
            )
            return None
        cell.attempts += 1
        if cell.attempts > self.spec.max_restarts:
            return "failed"
        self._event("cell_retry", cell=cell.name, rc=rc, attempts=cell.attempts)
        return None

    # -- the loop ----------------------------------------------------------

    def run(self) -> Dict[str, Any]:
        os.makedirs(self.exps_root, exist_ok=True)
        t0 = self._clock()
        self._event(
            "fleet_start", spec=self.spec.name, cells=len(self.cells),
            configs=len(self.spec.configs), seeds=list(self.spec.seeds),
        )
        pending: List[FleetCell] = list(self.cells)
        # cell -> (proc, out_path, attempt_started, cell_first_started)
        running: Dict[int, Any] = {}

        def launch(cell: FleetCell) -> None:
            self._gate_wait()
            proc, out_path = self._launcher(cell, cell.attempts)
            if out_path:
                # appending doesn't update mtime on spawn: reset the
                # liveness clock so every (re)launch gets the full window
                try:
                    os.utime(out_path, None)
                except OSError:
                    pass
            cell.status = "running"
            running[id(cell)] = (cell, proc, out_path, self._clock())
            fields = {"cell": cell.name, "attempt": cell.attempts,
                      "restart": cell.restarts}
            warm = self._warm_manifest(cell)
            if warm is not None:
                # expectation on record: this (re)launch should hit warm
                fields["prewarm_manifest"] = warm
            self._event("cell_launch", **fields)

        def kill(proc) -> None:
            try:
                proc.terminate()
            except Exception:
                pass
            deadline = self._clock() + 10.0
            while proc.poll() is None and self._clock() < deadline:
                self._sleep(0.2)
            if proc.poll() is None:
                try:
                    proc.kill()
                except Exception:
                    pass
                proc.wait()

        while pending or running:
            # start cells while slots are free (and the round deadline allows)
            while pending and len(running) < self.spec.max_parallel:
                if (
                    self.spec.deadline_epoch
                    and self._walltime() >= self.spec.deadline_epoch
                ):
                    for cell in pending:
                        self._finish(cell, "skipped", "deadline_epoch passed")
                    pending = []
                    break
                launch(pending.pop(0))
            if not running:
                break
            self._sleep(self.spec.poll_s)
            for key in list(running):
                cell, proc, out_path, started = running[key]
                rc = proc.poll()
                attempt_wall = self._clock() - started
                if rc is None:
                    stalled = (
                        self.spec.stall_deadline_s > 0
                        and self._liveness_age_s(out_path) > self.spec.stall_deadline_s
                    )
                    over_budget = (
                        self.spec.cell_timeout_s > 0
                        and cell.wall_s + attempt_wall > self.spec.cell_timeout_s
                    )
                    if not stalled and not over_budget:
                        continue
                    kill(proc)
                    cell.wall_s += self._clock() - started
                    del running[key]
                    if over_budget:
                        cell.rcs.append(proc.returncode)
                        self._finish(cell, "failed", "cell_timeout_s exhausted")
                        continue
                    cell.stall_kills += 1
                    cell.rcs.append(proc.returncode)
                    cell.attempts += 1
                    self._event(
                        "cell_stalled", cell=cell.name,
                        stall_s=round(self._liveness_age_s(out_path), 1),
                        attempts=cell.attempts,
                    )
                    if cell.attempts > self.spec.max_restarts:
                        self._finish(cell, "failed", "stalled past max_restarts")
                    else:
                        pending.insert(0, cell)  # resume immediately, in order
                    continue
                # attempt finished on its own
                cell.wall_s += self._clock() - started
                del running[key]
                verdict = self._classify(cell, int(rc))
                if verdict is None:
                    pending.insert(0, cell)
                elif verdict == "failed":
                    self._finish(
                        cell, "failed",
                        f"rc={rc} ({exit_codes.describe(int(rc))}) after "
                        f"{cell.attempts} attempts / {cell.restarts} restarts",
                    )
                else:
                    self._finish(cell, verdict)

        report = self.report(elapsed_s=self._clock() - t0)
        try:
            with open(os.path.join(self.exps_root, "fleet_report.json"), "w") as f:
                json.dump(report, f, indent=1)
        except OSError:
            pass
        self._event(
            "fleet_done", ok=report["ok"], done=report["done"],
            diverged=report["diverged"], failed=report["failed"],
            skipped=report["skipped"],
        )
        return report

    def report(self, elapsed_s: float = 0.0) -> Dict[str, Any]:
        by_status: Dict[str, int] = {}
        for cell in self.cells:
            by_status[cell.status] = by_status.get(cell.status, 0) + 1
        return {
            "report": "fleet",
            "spec": self.spec.name,
            "cells": [c.as_dict() for c in self.cells],
            "done": by_status.get("done", 0),
            "diverged": by_status.get("diverged", 0),
            "failed": by_status.get("failed", 0),
            "skipped": by_status.get("skipped", 0),
            # diverged is a model outcome the fleet handled per policy, not
            # a harness failure; failed/skipped cells mean the matrix is
            # incomplete
            "ok": all(c.status in ("done", "diverged") for c in self.cells),
            "restart_rcs": list(exit_codes.RESTARTABLE_RCS),
            "elapsed_s": round(elapsed_s, 1),
        }


def load_spec(path: str) -> FleetSpec:
    """Read a fleet spec YAML (PyYAML when importable, else a minimal
    subset parser is NOT attempted — fleet specs are only read where the
    training stack already runs)."""
    import yaml

    with open(path) as f:
        return FleetSpec.from_dict(yaml.safe_load(f) or {})
