from . import checkpoint, storage  # noqa: F401
from .runner import ExperimentRunner  # noqa: F401
