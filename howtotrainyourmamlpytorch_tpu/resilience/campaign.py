"""Seeded chaos-soak campaign: systematic coverage of the fault matrix.

PR 2 proved each failure class survivable with hand-written drills; this
module turns those drills into a *campaign*: a seeded sampler walks every
registered fault seam (``resilience/faults.py``) across short train / resume
/ serve episodes, and a fixed set of cross-cutting invariants is checked
after every episode — the things that must hold no matter which fault fired:

1. **rc discipline** — the episode exits one of the documented codes
   (0 ok, 3 permanent divergence, 75 preemption, 76 wedged). Anything else
   is an undocumented failure mode.
2. **checkpoint availability** — if any checkpoint file exists,
   ``load_latest_with_fallback`` must produce a loadable state (a corrupt
   ``latest`` must leave a valid fallback, never a bricked run dir).
3. **event-log integrity** — every line of ``logs/events.jsonl`` parses as
   JSON (a torn post-mortem is a post-mortem you can't read).
4. **serving honesty** — a request either succeeds with a well-formed
   payload or fails with a documented error class / HTTP status; shedding,
   breaker rejections and deadline expiries are never dressed up as 200s.
5. **telemetry integrity** — every line of ``logs/telemetry.jsonl`` parses
   as JSON, and every exported Chrome trace (``logs/trace.json``) passes
   the schema + balanced-spans validator — the observability layer must
   stay readable through exactly the deaths it exists to explain.

The campaign is deterministic in ``seed``: the same seed replays the same
episode sequence with the same fault triggers (the injector's own
determinism does the rest). ``scripts/chaos_soak.py`` is the CLI; a fast
fixed-seed smoke runs in tier-1 (``tests/test_chaos_smoke.py``) and the full
soak rides behind ``-m slow``.

Episodes marked ``subprocess`` fork a fresh interpreter because their
verdict *is* the process exit code of an ``os._exit`` path (the rc=76 wedge)
or requires a different visible-device count (degraded-mesh resume, which
shrinks ``dp`` when devices disappear between runs). Everything else runs
in-process for speed and compile-cache reuse.
"""

import dataclasses
import glob
import json
import os
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .. import exit_codes

from ..utils.locks import san_lock

#: Exit codes with documented semantics (docs/OPERATIONS.md rc table) — the
#: rc-discipline invariant checks against the central registry, so a new code
#: added there is automatically accepted (and documented) here.
DOCUMENTED_RCS = exit_codes.DOCUMENTED_RCS

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


# ---------------------------------------------------------------------------
# toy workload (self-contained: no pytest fixtures)
# ---------------------------------------------------------------------------


def make_toy_dataset(root: str, seed: int = 0) -> str:
    """A 20-class on-disk toy Omniglot (4 alphabets x 5 chars x 6 images) —
    the same shape the test suite trains its miniature runs on, small enough
    that an episode is seconds, real enough to exercise the full loader."""
    from PIL import Image

    if os.path.isdir(root) and os.listdir(root):
        return root
    rng = np.random.RandomState(seed)
    for a in range(4):
        for c in range(5):
            d = os.path.join(root, f"alpha{a}", f"char{c}")
            os.makedirs(d, exist_ok=True)
            base = (rng.rand(28, 28) > 0.5).astype(np.uint8) * 255
            for i in range(6):
                noisy = base ^ (rng.rand(28, 28) > 0.95).astype(np.uint8) * 255
                Image.fromarray(noisy, mode="L").convert("1").save(
                    os.path.join(d, f"{i}.png")
                )
    return root


def campaign_config(data_root: str, exp_root: str, name: str, **overrides):
    """Miniature training config (mirrors the test suite's toy runs so the
    in-process XLA compile cache is shared with them)."""
    from ..config import Config, DatasetConfig, ParallelConfig

    base: Dict[str, Any] = dict(
        dataset=DatasetConfig(name="omniglot_toy", path=data_root),
        num_classes_per_set=3,
        num_samples_per_class=2,
        num_target_samples=2,
        batch_size=2,
        parallel=ParallelConfig(dp=2),
        total_epochs=2,
        total_iter_per_epoch=3,
        num_evaluation_tasks=4,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        experiment_root=exp_root,
        experiment_name=name,
        load_into_memory=True,
        num_dataprovider_workers=2,
        train_val_test_split=(0.6, 0.2, 0.2),
        conv_via_patches=True,  # the dp-sharded native-conv GSPMD crash dodge
    )
    base.update(overrides)
    return Config(**base)


def tiny_system(cfg):
    """The shrunken 2-stage/4-filter backbone every campaign episode trains."""
    from ..core import MAMLSystem
    from ..models import build_vgg

    return MAMLSystem(
        cfg,
        model=build_vgg(
            (28, 28, 1),
            cfg.num_classes_per_set,
            num_stages=2,
            cnn_num_filters=4,
            conv_via_patches=True,
        ),
    )


# ---------------------------------------------------------------------------
# episode menu
# ---------------------------------------------------------------------------


@dataclass
class Episode:
    """One sampled chaos episode: a mode, the fault specs to arm, config
    knobs, and the rc set this fault family is *documented* to produce
    (checked against the FAULTED leg; clean resume legs must always exit 0)."""

    kind: str
    mode: str  # train | resume | shrink | grow | serve
    faults: List[str] = field(default_factory=list)
    resilience_overrides: Dict[str, Any] = field(default_factory=dict)
    config_overrides: Dict[str, Any] = field(default_factory=dict)
    expected_rcs: tuple = (0,)
    subprocess: bool = False  # faulted leg needs a fresh interpreter (os._exit)
    resume_after: bool = False  # run a clean resume leg after the faulted one
    resume_devices: int = 8  # visible devices for a subprocess resume leg
    required_events: tuple = ()  # event names that must appear in events.jsonl


def episode_menu(rng: np.random.RandomState) -> List[Episode]:
    """The full seam-coverage menu, trigger indices jittered by ``rng`` so
    consecutive campaigns with different seeds walk different step indices.
    Six dispatches per train episode (2 epochs x 3 iters) bound the jitter."""
    nth = lambda lo, hi: int(rng.randint(lo, hi + 1))  # noqa: E731
    menu = [
        Episode(
            kind="nan-isolated",
            mode="train",
            faults=[f"runner.step=nan-loss:nth={nth(1, 4)}",
                    "checkpoint.write=delay:delay_s=0.01,nth=1"],
            resilience_overrides=dict(max_consecutive_bad_steps=3),
            expected_rcs=(0,),
            required_events=("nan_step_skipped",),
        ),
        Episode(
            kind="nan-persistent",
            mode="train",
            faults=["runner.step=nan-loss:p=1.0"],
            resilience_overrides=dict(max_consecutive_bad_steps=1, max_rollbacks=1),
            expected_rcs=(exit_codes.DIVERGED,),
            required_events=("nan_rollback", "nan_abort"),
        ),
        Episode(
            kind="sigterm-preempt",
            mode="train",
            faults=[f"runner.step=sigterm:nth={nth(2, 4)}"],
            expected_rcs=(exit_codes.PREEMPTED,),
            resume_after=True,
            required_events=("preempted",),
        ),
        Episode(
            kind="loader-transient-io",
            mode="train",
            faults=[f"loader.episode=raise:nth={nth(1, 3)}"],
            resilience_overrides=dict(loader_io_backoff_s=0.0),
            expected_rcs=(0,),
        ),
        Episode(
            kind="corrupt-latest-read",
            mode="resume",
            faults=["checkpoint.read=corrupt-bytes:nth=1"],
            expected_rcs=(0,),
        ),
        Episode(
            kind="wedge-hung-step",
            mode="train",
            faults=[f"runner.step=delay:delay_s=60,nth={nth(2, 5)}"],
            expected_rcs=(exit_codes.WEDGED,),
            subprocess=True,
            resume_after=True,
            required_events=("wedged", "wedge_checkpoint"),
        ),
        Episode(
            kind="device-shrink-resume",
            mode="shrink",
            expected_rcs=(0,),
            subprocess=True,
            resume_devices=1,
            required_events=("degraded_mesh",),
        ),
        Episode(
            # shrink then GROW BACK: full-mesh train, resume on 1 device
            # (degrades), resume again with every device visible — the mesh
            # must grow back (state resharded up, mesh_grown event) and
            # training must continue through the grown epoch
            kind="device-grow-resume",
            mode="grow",
            expected_rcs=(0,),
            subprocess=True,
            resume_devices=1,
            required_events=("degraded_mesh", "mesh_grown"),
        ),
        Episode(
            # SIGTERM landing while an async sharded epoch save is in
            # flight (the checkpoint.write seam fires between shard files,
            # on the writer thread): the manifest commit point means no leg
            # may ever see a loadable-but-torn checkpoint, and the
            # preemption path must still exit 75 with a resumable latest
            kind="sigterm-during-async-save",
            mode="train",
            faults=[f"checkpoint.write=sigterm:nth={nth(2, 3)}"],
            config_overrides=dict(checkpoint_async=True, checkpoint_shards=2),
            expected_rcs=(exit_codes.PREEMPTED,),
            resume_after=True,
            required_events=("preempted",),
        ),
        Episode(kind="serve-dispatch-raise", mode="serve"),
        Episode(kind="serve-dispatch-hang", mode="serve"),
        # kill one replica of a 2-replica fleet mid-load: the router must
        # stop routing to it, no request may be 200'd with a wrong/stale
        # result, the fleet must keep serving, and the death must resolve
        # through the access log (serving/pool.py, serving/router.py)
        Episode(kind="serve-replica-death", mode="serve"),
        # mixed maml++/protonet traffic against ONE prewarmed strict-mode
        # frontend (ISSUE 15, core/strategies.py): per-strategy cache
        # isolation (no cross-strategy cache hit, wrong-strategy predict =
        # honest 404), sealed-guard ZERO outside-prewarm compiles across
        # the whole mixed stream, unknown strategy = 400 over the wire,
        # and every non-200 resolvable to an access line
        Episode(kind="serve-strategy-mix", mode="serve"),
        # guarded online refinement under injected poison (ISSUE 17): a
        # healthy refine commits; a nan-loss refinement rolls back to the
        # last-good snapshot with an HONEST rolled_back:true 200 and
        # bit-identical post-rollback predictions; a consecutive-regression
        # burst quarantines the session (409 + Retry-After on the wire,
        # predict refused too); explicit re-adapt is the only exit; the
        # sealed guard sees ZERO outside-prewarm compiles throughout
        Episode(kind="serve-refine-rollback", mode="serve"),
        # 4 tenants thrashing a weight-pager budget that fits only 2:
        # per-tenant responses stay bit-identical to single-tenant control
        # engines, every eviction is a logged event, the sealed guard sees
        # ZERO outside-prewarm compiles (paging is a transfer, never a
        # compile), tenant A's adaptation id from tenant B is an honest
        # 404, and every non-200 resolves to an access line
        Episode(kind="serve-tenant-thrash", mode="serve"),
        # --- cross-process fleet drills (ISSUE 14): a REAL gateway process
        # (scripts/gateway.py) in front of REAL serve backends (subprocess
        # interpreters running the actual run_server drain path). Marked
        # subprocess so the in-process smoke skips them; tier-1 runs each
        # directly via tests/test_gateway_fleet.py.
        Episode(kind="gateway-kill9-backend", mode="gateway", subprocess=True),
        Episode(kind="gateway-drain-rehydrate", mode="gateway", subprocess=True),
        Episode(kind="gateway-rolling-restart", mode="gateway", subprocess=True),
        # long-lived refined session across process deaths (ISSUE 17): a
        # refined session's lineage (refine count, snapshots, probe) must
        # ride the SIGTERM drain spill -> rehydrate round-trip AND survive a
        # kill -9 of the gateway in front of it — post-recovery predictions
        # bit-identical, the next refine continuing the lineage, never a
        # silently-reset session
        Episode(kind="serve-refine-across-drain", mode="gateway", subprocess=True),
        # self-healing fleet supervisor (ISSUE 18): scripts/fleet_serve.py
        # owning backend lifecycle end to end. fleet-surge: load x4 against
        # a slowed backend -> supervisor scales up into a pre-provisioned
        # slot (healthz-gated) -> SLO recovers -> load stops -> scale-down
        # gracefully drains (rc 0 observed) — zero dropped requests and a
        # refined session's lineage intact across the whole cycle.
        # fleet-crashloop: a die-on-spawn backend walks the bounded backoff
        # ladder into quarantine (never respawned hot, fleet stays
        # routable), and a supervisor kill -9'd mid-spawn restarts, adopts
        # the live fleet from its write-ahead journal, and settles the
        # interrupted spawn without double-spawning or orphaning.
        Episode(kind="fleet-surge", mode="gateway", subprocess=True),
        Episode(kind="fleet-crashloop", mode="gateway", subprocess=True),
    ]
    order = rng.permutation(len(menu))
    return [menu[i] for i in order]


def sample_episodes(
    seed: int, n: int, include_subprocess: bool = True
) -> List[Episode]:
    rng = np.random.RandomState(seed)
    episodes: List[Episode] = []
    while len(episodes) < n:
        for ep in episode_menu(rng):
            if len(episodes) >= n:
                break
            if ep.subprocess and not include_subprocess:
                continue
            episodes.append(ep)
    return episodes


# ---------------------------------------------------------------------------
# invariants
# ---------------------------------------------------------------------------


def _events_in(run_dir: str) -> List[str]:
    path = os.path.join(run_dir, "logs", "events.jsonl")
    if not os.path.exists(path):
        return []
    names = []
    with open(path) as f:
        for line in f:
            if line.strip():
                try:
                    names.append(json.loads(line).get("event", ""))
                except json.JSONDecodeError:
                    pass
    return names


def _check_events_jsonl(run_dir: str) -> Optional[str]:
    path = os.path.join(run_dir, "logs", "events.jsonl")
    if not os.path.exists(path):
        return None  # an episode may die before its first event — fine
    with open(path) as f:
        for i, line in enumerate(f):
            if not line.strip():
                continue
            try:
                json.loads(line)
            except json.JSONDecodeError as exc:
                return f"events.jsonl line {i + 1} unparseable: {exc}"
    return None


def _check_telemetry(run_dir: str) -> List[str]:
    """Invariant 5: telemetry.jsonl is well-formed JSON-lines and any
    exported Chrome trace passes the schema + balanced-spans validator.
    Either file may be absent (observability disabled, or a death before
    the first snapshot / before trace export) — absence is fine, a torn or
    unbalanced artifact is the finding."""
    from ..observability.trace import load_and_validate_trace

    problems: List[str] = []
    tel_path = os.path.join(run_dir, "logs", "telemetry.jsonl")
    if os.path.exists(tel_path):
        with open(tel_path) as f:
            for i, line in enumerate(f):
                if not line.strip():
                    continue
                try:
                    json.loads(line)
                except json.JSONDecodeError as exc:
                    problems.append(
                        f"telemetry.jsonl line {i + 1} unparseable: {exc}"
                    )
    # current export plus any per-session archives (a resumed run renames
    # the previous session's trace — e.g. a wedge post-mortem — aside
    # rather than clobbering it; all of them must stay loadable)
    for trace_path in sorted(
        glob.glob(os.path.join(run_dir, "logs", "trace*.json"))
    ):
        problems.extend(
            f"{os.path.basename(trace_path)}: {p}"
            for p in load_and_validate_trace(trace_path)
        )
    return problems


def _check_checkpoints(run_dir: str, template_state) -> Optional[str]:
    from ..experiment import checkpoint as ckpt

    save_dir = os.path.join(run_dir, "saved_models")
    has_any = os.path.isdir(save_dir) and any(
        name.startswith(ckpt.MODEL_NAME)
        and not name.endswith(".corrupt")
        # stray format-3 shard files without their manifest and write temps
        # (a kill before the commit point) are invisible garbage, not a
        # checkpoint — only a manifest/blob name counts as "one exists"
        and ".shard" not in name
        and ".tmp" not in name
        for name in os.listdir(save_dir)
    )
    if not has_any:
        return None
    try:
        ckpt.load_latest_with_fallback(save_dir, template_state)
    except Exception as exc:  # noqa: BLE001 — any load failure is the finding
        return f"no loadable checkpoint despite files present: {exc!r}"
    return None


# ---------------------------------------------------------------------------
# episode execution
# ---------------------------------------------------------------------------


def _run_train_inprocess(cfg) -> int:
    from ..experiment import ExperimentRunner

    runner = ExperimentRunner(cfg, system=tiny_system(cfg))
    try:
        runner.run_experiment()
        return 0
    except SystemExit as exc:
        return int(exc.code or 0)


def _child_env(n_devices: int) -> Dict[str, str]:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_devices}"
    # share the persistent XLA cache so children skip recompiles
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_test_cache")
    return env


def _run_train_subprocess(cfg_yaml: str, n_devices: int, timeout_s: float = 420.0) -> int:
    """Fork a fresh interpreter for episodes whose verdict is the process rc
    of an ``os._exit`` path, or that need a different visible-device count."""
    code = (
        "import sys;"
        "from howtotrainyourmamlpytorch_tpu.resilience.campaign import child_train_main;"
        "sys.exit(child_train_main(sys.argv[1]))"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code, cfg_yaml],
        cwd=_REPO_ROOT,
        env=_child_env(n_devices),
        capture_output=True,
        text=True,
        timeout=timeout_s,
    )
    return proc.returncode


def child_train_main(cfg_yaml: str) -> int:
    """Subprocess entry: run one campaign training episode from its saved
    config. Importable (not ``__main__``) so the parent can spawn it with a
    one-line ``-c`` after pinning JAX env vars."""
    import jax

    jax.config.update("jax_platforms", "cpu")  # site-hook override guard
    # shared persistent-cache setup, with the conftest test tuning so the
    # campaign's tiny programs cache too (utils/compcache.py)
    from ..utils.compcache import setup_compilation_cache

    setup_compilation_cache(
        os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_test_cache"),
        test_tuning=True,
    )

    from ..config import load_config

    cfg = load_config(cfg_yaml)
    return _run_train_inprocess(cfg)


def _run_serve_episode(ep: Episode) -> List[str]:
    """Serve-mode chaos: drive the frontend/HTTP stack under injected device
    faults and enforce the serving-honesty invariant. Returns violations."""
    import urllib.error
    import urllib.request

    from ..config import Config, ResilienceConfig, ServingConfig
    from ..core import MAMLSystem
    from ..data.synthetic import synthetic_batch
    from ..models import build_vgg
    from ..resilience.faults import FaultInjector
    from ..resilience.retry import DeadlineExceededError
    from ..serving import AdaptationEngine, ServingFrontend, make_http_server
    from .faults import InjectedFault

    violations: List[str] = []
    img = (28, 28, 1)
    cfg = Config(
        num_classes_per_set=5,
        num_samples_per_class=2,
        num_target_samples=3,
        batch_size=2,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        serving=ServingConfig(support_buckets=[16], query_buckets=[16]),
    )
    system = MAMLSystem(
        cfg, model=build_vgg(img, cfg.num_classes_per_set, num_stages=2, cnn_num_filters=4)
    )

    def support(seed):
        epi = synthetic_batch(1, 5, 2, 3, img, seed=seed)
        return epi["x_support"][0], epi["y_support"][0]

    if ep.kind == "serve-dispatch-raise":
        # HTTP end-to-end: injected dispatch failures trip the breaker; the
        # wire must show 500 -> 500 -> fast 503 (+ Retry-After) and a
        # degraded /healthz — and any 200 must carry a real payload. The
        # serving.http delay also exercises the handler seam. The access-log
        # invariant rides the same drill: EVERY non-200 response must carry
        # an X-Request-Id that resolves to a logs/access.jsonl line —
        # failures are exactly the requests an operator greps for, so they
        # bypass sampling by contract (observability/context.py).
        import tempfile

        inj = FaultInjector.from_specs(
            ["serving.dispatch=raise:times=2", "serving.http=delay:delay_s=0.01"],
            include_env=False,
        )
        engine = AdaptationEngine(system, system.init_train_state(), injector=inj)
        res = ResilienceConfig(breaker_failure_threshold=2, breaker_cooldown_s=60.0)
        access_dir = tempfile.mkdtemp(prefix="chaos_access_")
        frontend = ServingFrontend(
            engine, resilience_cfg=res, access_log_dir=access_dir
        )
        server = make_http_server(frontend, "127.0.0.1", 0)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        statuses = []
        non_200_ids = []  # (status, X-Request-Id) of every failure response

        def _note_failure(code, headers):
            rid = headers.get("X-Request-Id") if headers is not None else None
            if rid is None:
                violations.append(f"non-200 ({code}) without X-Request-Id")
            else:
                non_200_ids.append((code, rid))

        try:
            for seed in (1, 2, 3):
                x_s, y_s = support(seed)
                req = urllib.request.Request(
                    base + "/adapt",
                    data=json.dumps(
                        {"x_support": x_s.tolist(), "y_support": y_s.tolist()}
                    ).encode(),
                    headers={"Content-Type": "application/json"},
                )
                try:
                    with urllib.request.urlopen(req, timeout=60) as resp:
                        statuses.append(resp.status)
                        body = json.loads(resp.read())
                        if resp.status == 200 and "adaptation_id" not in body:
                            violations.append(
                                f"200 without adaptation_id: {body!r}"
                            )
                except urllib.error.HTTPError as exc:
                    statuses.append(exc.code)
                    if exc.code not in (
                        400, 404, exit_codes.HTTP_TOO_MANY_REQUESTS, 500,
                        exit_codes.HTTP_UNAVAILABLE, exit_codes.HTTP_DEADLINE,
                    ):
                        violations.append(f"undocumented HTTP status {exc.code}")
                    if exc.code == 503 and "Retry-After" not in exc.headers:
                        violations.append("503 without Retry-After")
                    _note_failure(exc.code, exc.headers)
            if statuses != [500, 500, 503]:
                violations.append(
                    f"breaker wire sequence {statuses} != [500, 500, 503]"
                )
            try:
                with urllib.request.urlopen(base + "/healthz", timeout=60):
                    violations.append("healthz 200 while breaker open")
            except urllib.error.HTTPError as exc:
                if exc.code != 503:
                    violations.append(f"healthz {exc.code} while breaker open")
                _note_failure(exc.code, exc.headers)
            with urllib.request.urlopen(base + "/metrics", timeout=60) as resp:
                json.loads(resp.read())  # must be well-formed
        finally:
            server.shutdown()
            server.server_close()
            frontend.close()
            thread.join(timeout=5)
        # the invariant proper: each failure's request id has an access line
        from ..observability.context import read_access_log

        access_path = os.path.join(access_dir, "access.jsonl")
        logged_ids = set()
        if os.path.exists(access_path):
            records, torn = read_access_log(access_path)
            logged_ids = {r.get("trace_id") for r in records}
            if torn:
                violations.append(f"{torn} torn access.jsonl line(s)")
        for code, rid in non_200_ids:
            if rid not in logged_ids:
                violations.append(
                    f"non-200 ({code}) request {rid} has no access-log line"
                )
        if not non_200_ids:
            violations.append(
                "drill produced no non-200 responses — invariant untested"
            )
    elif ep.kind == "serve-dispatch-hang":
        # A hanging dispatch must surface as DeadlineExceeded (504-class),
        # never as a 200 or an unbounded wait. after=1 keeps the compile
        # warmup dispatch clean so the injected delay measures the hang
        # path, not XLA compile time.
        inj = FaultInjector.from_specs(
            ["serving.dispatch=delay:delay_s=0.4,after=1,times=2"],
            include_env=False,
        )
        engine = AdaptationEngine(system, system.init_train_state(), injector=inj)
        engine.adapt_batch([support(0)])  # warm: compile outside the drill
        res = ResilienceConfig(
            request_deadline_s=0.05, breaker_timeout_threshold=2,
            breaker_cooldown_s=60.0,
        )
        frontend = ServingFrontend(engine, resilience_cfg=res)
        try:
            outcomes = []
            for seed in (4, 5, 6):
                try:
                    out = frontend.adapt(*support(seed))
                    if "adaptation_id" not in out:
                        violations.append(f"success without adaptation_id: {out!r}")
                    outcomes.append("ok")
                except DeadlineExceededError:
                    outcomes.append("deadline")
                except Exception as exc:  # noqa: BLE001
                    if exc.__class__.__name__ == "ServiceUnavailableError":
                        outcomes.append("unavailable")
                    elif isinstance(exc, InjectedFault):
                        outcomes.append("fault")
                    else:
                        violations.append(f"undocumented outcome {exc!r}")
            if "deadline" not in outcomes:
                violations.append(
                    f"hung dispatch never produced a deadline expiry: {outcomes}"
                )
            json.dumps(frontend.metrics())  # observability stays well-formed
        finally:
            frontend.close()
    elif ep.kind == "serve-replica-death":
        # kill one replica of a 2-replica fleet mid-load. Invariants:
        # (1) the router stops routing to the dead replica, (2) no request
        # is 200'd with a wrong/stale result (the displaced session's
        # predict must 404-class, never silently succeed elsewhere; after
        # re-adapt its predictions must be bit-identical to a healthy
        # fleet's), (3) the fleet keeps serving, (4) the death resolves
        # through the access log (a replica_death line + rerouted request
        # lines naming their replica).
        import tempfile

        from ..observability.context import read_access_log
        from ..serving import UnknownAdaptationError

        engine = AdaptationEngine(system, system.init_train_state())
        access_dir = tempfile.mkdtemp(prefix="chaos_access_")
        frontend = ServingFrontend(engine, access_log_dir=access_dir, replicas=2)
        owner = None
        try:
            epi = synthetic_batch(1, 5, 2, 3, img, seed=11)
            x_s, y_s = epi["x_support"][0], epi["y_support"][0]
            x_q = epi["x_target"][0].reshape((-1,) + img)
            info = frontend.adapt(x_s, y_s)
            probs_before = frontend.predict(info["adaptation_id"], x_q)
            owner = frontend.router.route(info["adaptation_id"]).index
            frontend.kill_replica(owner, reason="chaos")
            routed_at_death = frontend.router.stats()["routed"][owner]
            # (2) the displaced session must NOT be silently served a
            # result by a replica that never adapted it
            try:
                frontend.predict(info["adaptation_id"], x_q)
                violations.append(
                    "predict for a dead replica's session succeeded without "
                    "re-adapting — possible stale/wrong 200"
                )
            except UnknownAdaptationError:
                pass
            # (3) the fleet keeps serving: re-adapt lands on the survivor
            # and predictions match the pre-death fleet bit-identically
            info2 = frontend.adapt(x_s, y_s)
            probs_after = frontend.predict(info2["adaptation_id"], x_q)
            if not np.array_equal(
                np.asarray(probs_before), np.asarray(probs_after)
            ):
                violations.append(
                    "post-failover predictions differ from the healthy "
                    "fleet's — wrong result served after replica death"
                )
            # (1) no NEW route went to the dead replica
            stats = frontend.router.stats()
            if stats["routed"][owner] != routed_at_death:
                violations.append(
                    f"router still routed to dead replica r{owner}: {stats}"
                )
            if stats["routable"] != 1 or stats["routed_around"] < 1:
                violations.append(f"router did not route around the death: {stats}")
            health = frontend.healthz()
            if health["status"] != "degraded" or health["routable"] != 1:
                violations.append(f"healthz does not reflect the death: {health}")
            json.dumps(frontend.metrics())  # observability stays well-formed
        finally:
            frontend.close()
        # (4) the death is an access-log-resolvable event
        records, torn = read_access_log(os.path.join(access_dir, "access.jsonl"))
        if torn:
            violations.append(f"{torn} torn access.jsonl line(s)")
        deaths = [r for r in records if r.get("verb") == "replica_death"]
        if owner is None or not deaths or deaths[0].get("replica") != owner:
            violations.append(
                f"replica death not resolvable from the access log: {deaths}"
            )
        served_after = [
            r
            for r in records
            if r.get("outcome") == "ok" and r.get("replica") not in (None, owner)
        ]
        if not served_after:
            violations.append(
                "no post-death access line names a surviving replica"
            )
    elif ep.kind == "serve-strategy-mix":
        # Mixed-strategy traffic (maml++ + the forward-only protonet tier)
        # against ONE strict-mode frontend whose whole strategy grid was
        # prewarmed. Invariants: (1) per-strategy cache isolation — the
        # same support set adapted under each strategy yields DISTINCT
        # adaptation ids, the second adapt of each is a same-strategy
        # cache hit, and a predict naming the wrong strategy for an id is
        # an honest 404, never a cross-strategy result; (2) the sealed
        # recompile guard sees ZERO outside-prewarm compiles across the
        # whole mixed stream; (3) an unknown strategy is a 400 on the
        # wire; (4) every non-200 resolves to an access-log line.
        import dataclasses
        import tempfile
        import urllib.error
        import urllib.request

        from ..observability.context import read_access_log

        mix_cfg = dataclasses.replace(
            cfg,
            strict_recompile_guard=True,
            serving=ServingConfig(
                support_buckets=[16], query_buckets=[16], max_batch_size=2,
                strategies=["maml++", "protonet"],
            ),
        )
        mix_system = MAMLSystem(
            mix_cfg,
            model=build_vgg(img, 5, num_stages=2, cnn_num_filters=4),
        )
        engine = AdaptationEngine(mix_system, mix_system.init_train_state())
        warm = engine.prewarm(max_workers=1)
        if warm["errors"]:
            violations.append(f"strategy-grid prewarm errors: {warm}")
        access_dir = tempfile.mkdtemp(prefix="chaos_access_")
        frontend = ServingFrontend(engine, access_log_dir=access_dir)
        server = make_http_server(frontend, "127.0.0.1", 0)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        non_200_ids = []

        def _post(path, body, timeout=60):
            req = urllib.request.Request(
                base + path,
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, json.loads(resp.read())

        try:
            epi2 = synthetic_batch(1, 5, 2, 3, img, seed=21)
            x_s, y_s = epi2["x_support"][0], epi2["y_support"][0]
            x_q = epi2["x_target"][0].reshape((-1,) + img)
            payload = {"x_support": x_s.tolist(), "y_support": y_s.tolist()}
            ids = {}
            for strategy in ("maml++", "protonet"):
                _, out = _post("/adapt", {**payload, "strategy": strategy})
                if out.get("cached"):
                    violations.append(f"first {strategy} adapt was a cache hit")
                ids[strategy] = out["adaptation_id"]
                _, again = _post("/adapt", {**payload, "strategy": strategy})
                if not again.get("cached"):
                    violations.append(
                        f"repeat {strategy} adapt missed its own cache"
                    )
                _, probs = _post(
                    "/predict",
                    {"adaptation_id": ids[strategy], "x_query": x_q.tolist(),
                     "strategy": strategy},
                )
            # (1) isolation: distinct ids; wrong-strategy predict = 404
            if ids["maml++"] == ids["protonet"]:
                violations.append(
                    "maml++ and protonet produced the SAME adaptation id "
                    "for one support set — cross-strategy cache collision"
                )
            try:
                _post(
                    "/predict",
                    {"adaptation_id": ids["protonet"],
                     "x_query": x_q.tolist(), "strategy": "maml++"},
                )
                violations.append(
                    "predict with the wrong strategy for an id succeeded — "
                    "a prototype table served through a gradient program"
                )
            except urllib.error.HTTPError as exc:
                if exc.code != 404:
                    violations.append(
                        f"wrong-strategy predict returned {exc.code}, not 404"
                    )
                rid = exc.headers.get("X-Request-Id")
                if rid:
                    non_200_ids.append((exc.code, rid))
            # (3) unknown strategy = 400 on the wire
            try:
                _post("/adapt", {**payload, "strategy": "bogus-tier"})
                violations.append("unknown strategy adapt returned 200")
            except urllib.error.HTTPError as exc:
                if exc.code != 400:
                    violations.append(
                        f"unknown strategy returned {exc.code}, not 400"
                    )
                rid = exc.headers.get("X-Request-Id")
                if rid:
                    non_200_ids.append((exc.code, rid))
            # (2) the sealed guard saw zero outside-prewarm compiles
            snap = engine.recompile_guard.snapshot()
            if not snap["prewarmed"] or snap["violations"]:
                violations.append(
                    f"sealed-guard invariant broken under mixed-strategy "
                    f"traffic: {snap}"
                )
            metrics = frontend.metrics()
            json.dumps(metrics)  # observability stays well-formed
            mix = metrics.get("strategies") or {}
            if set(mix) < {"maml++", "protonet"}:
                violations.append(
                    f"/metrics strategies block missing tiers: {sorted(mix)}"
                )
        finally:
            server.shutdown()
            server.server_close()
            frontend.close()
            thread.join(timeout=5)
        # (4) every non-200 resolves to an access line
        records, torn = read_access_log(os.path.join(access_dir, "access.jsonl"))
        if torn:
            violations.append(f"{torn} torn access.jsonl line(s)")
        logged_ids = {r.get("trace_id") for r in records}
        for code, rid in non_200_ids:
            if rid not in logged_ids:
                violations.append(
                    f"non-200 ({code}) request {rid} has no access-log line"
                )
        if not non_200_ids:
            violations.append(
                "drill produced no non-200 responses — invariant untested"
            )
        strategies_logged = {
            r.get("strategy") for r in records if r.get("strategy")
        }
        if strategies_logged < {"maml++", "protonet"}:
            violations.append(
                f"access lines do not carry both strategies: "
                f"{sorted(strategies_logged)}"
            )
    elif ep.kind == "serve-refine-rollback":
        # Guarded online refinement under injected poison. Invariants:
        # (1) a healthy refine commits (refined:true, refine_count 1);
        # (2) a nan-loss refinement is an HONEST rolled_back:true 200 with
        # score null, and post-rollback predictions are bit-identical to
        # the last-good weights' — the poisoned candidate never lands;
        # (3) a consecutive-regression burst quarantines the session: 409 +
        # Retry-After + quarantined:true on the wire, and predict through
        # the quarantined session is refused the same way — never
        # silently-stale; (4) explicit re-adapt is the only exit (served
        # as a miss, never from cache); (5) the sealed recompile guard sees
        # ZERO outside-prewarm compiles across the whole adapt/refine/
        # predict stream; (6) rollback/quarantine/re-adapt are logged
        # events and every non-200 resolves to an access-log line.
        import dataclasses
        import tempfile
        import urllib.error
        import urllib.request

        from ..observability.context import read_access_log

        refine_cfg = dataclasses.replace(
            cfg,
            strict_recompile_guard=True,
            serving=ServingConfig(
                support_buckets=[16], query_buckets=[16], max_batch_size=2,
                refine_enabled=True, refine_quarantine_after=2,
            ),
        )
        refine_system = MAMLSystem(
            refine_cfg,
            model=build_vgg(img, 5, num_stages=2, cnn_num_filters=4),
        )
        engine = AdaptationEngine(refine_system, refine_system.init_train_state())
        warm = engine.prewarm(max_workers=1)
        if warm["errors"]:
            violations.append(f"refine-grid prewarm errors: {warm}")
        access_dir = tempfile.mkdtemp(prefix="chaos_access_")
        frontend = ServingFrontend(engine, access_log_dir=access_dir)
        server = make_http_server(frontend, "127.0.0.1", 0)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        non_200_ids = []

        def _post(path, body, timeout=60):
            req = urllib.request.Request(
                base + path,
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, json.loads(resp.read())

        def _expect_409(path, body, what):
            try:
                _post(path, body)
                violations.append(f"{what} returned 200 while quarantined")
                return
            except urllib.error.HTTPError as exc:
                if exc.code != 409:
                    violations.append(f"{what} returned {exc.code}, not 409")
                if "Retry-After" not in exc.headers:
                    violations.append(f"quarantine 409 without Retry-After ({what})")
                payload_err = _loads_or_empty(exc.read())
                if payload_err.get("quarantined") is not True:
                    violations.append(
                        f"quarantine 409 body lacks quarantined:true: {payload_err}"
                    )
                rid = exc.headers.get("X-Request-Id")
                if rid:
                    non_200_ids.append((exc.code, rid))

        try:
            epi4 = synthetic_batch(1, 5, 2, 3, img, seed=41)
            x_s, y_s = epi4["x_support"][0], epi4["y_support"][0]
            x_q = epi4["x_target"][0].reshape((-1,) + img)
            payload = {"x_support": x_s.tolist(), "y_support": y_s.tolist()}
            refine_body = {**payload, "refine": True}
            _, out = _post("/adapt", payload)
            sid = out["adaptation_id"]
            refine_body["session_id"] = sid
            # (1) healthy refine commits
            _, r1 = _post("/adapt", refine_body)
            if (
                not r1.get("refined")
                or r1.get("rolled_back")
                or r1.get("refine_count") != 1
            ):
                violations.append(f"healthy refine did not commit: {r1}")
            _, good = _post(
                "/predict", {"adaptation_id": sid, "x_query": x_q.tolist()}
            )
            # (2) poisoned refinements roll back honestly
            engine.injector = FaultInjector.from_specs(
                ["serving.refine=nan-loss:times=3"], include_env=False
            )
            _, r2 = _post("/adapt", refine_body)
            if (
                not r2.get("rolled_back")
                or r2.get("score") is not None
                or r2.get("refine_count") != 1
            ):
                violations.append(f"nan-loss refine not rolled back honestly: {r2}")
            _, after = _post(
                "/predict", {"adaptation_id": sid, "x_query": x_q.tolist()}
            )
            if after.get("probs") != good.get("probs"):
                violations.append(
                    "post-rollback predictions differ from last-good — the "
                    "poisoned candidate landed in the session cache"
                )
            # (3) second consecutive regression quarantines: refine AND
            # predict both refused with an honest 409
            _expect_409("/adapt", refine_body, "quarantine-burst refine")
            _expect_409(
                "/predict",
                {"adaptation_id": sid, "x_query": x_q.tolist()},
                "quarantined-session predict",
            )
            # (4) explicit re-adapt is the only exit — served as a miss
            engine.injector = FaultInjector.from_specs([], include_env=False)
            _, out2 = _post("/adapt", payload)
            if out2.get("cached"):
                violations.append(
                    "re-adapt of a quarantined session was served from cache"
                )
            code, _ = _post(
                "/predict", {"adaptation_id": sid, "x_query": x_q.tolist()}
            )
            if code != 200:
                violations.append(f"post-re-adapt predict failed: {code}")
            _, r3 = _post("/adapt", refine_body)
            if r3.get("rolled_back") or r3.get("refine_count") != 1:
                violations.append(
                    f"post-re-adapt refine did not start a fresh lineage: {r3}"
                )
            # (5) the sealed guard saw zero outside-prewarm compiles
            snap = engine.recompile_guard.snapshot()
            if not snap["prewarmed"] or snap["violations"]:
                violations.append(
                    f"sealed-guard invariant broken under refine traffic: {snap}"
                )
            metrics = frontend.metrics()
            json.dumps(metrics)  # observability stays well-formed
            ref = (metrics.get("sessions") or {}).get("refine") or {}
            if not ref.get("rollbacks") or not ref.get("quarantines"):
                violations.append(
                    f"/metrics sessions.refine does not tell the story: {ref}"
                )
        finally:
            server.shutdown()
            server.server_close()
            frontend.close()
            thread.join(timeout=5)
        # (6) rollback/quarantine/re-adapt are logged events...
        events_path = os.path.join(access_dir, "events.jsonl")
        seen_events = set()
        if os.path.exists(events_path):
            with open(events_path) as f:
                for line in f:
                    try:
                        seen_events.add(json.loads(line).get("event"))
                    except ValueError:
                        continue
        for required in (
            "refine_rollback", "session_quarantined", "session_readapted"
        ):
            if required not in seen_events:
                violations.append(f"missing {required} event in events.jsonl")
        # ...and every non-200 resolves to an access line
        records, torn = read_access_log(os.path.join(access_dir, "access.jsonl"))
        if torn:
            violations.append(f"{torn} torn access.jsonl line(s)")
        logged_ids = {r.get("trace_id") for r in records}
        for code, rid in non_200_ids:
            if rid not in logged_ids:
                violations.append(
                    f"non-200 ({code}) request {rid} has no access-log line"
                )
        if not non_200_ids:
            violations.append(
                "drill produced no non-200 responses — invariant untested"
            )
    elif ep.kind == "serve-tenant-thrash":
        # M=4 tenants behind ONE strict-mode frontend, paged under a byte
        # budget sized to fit only M/2 of their masters. Invariants:
        # (1) determinism under thrash — every tenant's probs over the wire
        # are bit-identical to a single-tenant control engine built from
        # that tenant's checkpoint alone, including after its master was
        # evicted and paged back in; (2) the sealed recompile guard sees
        # ZERO outside-prewarm compiles across the whole thrash (a cold
        # tenant costs one host->device transfer, never an XLA compile);
        # (3) evictions happen and are logged (events.jsonl + /metrics);
        # (4) cross-tenant isolation — tenant A's adaptation id predicted
        # as tenant B is an honest 404, unknown tenant is a 400, and every
        # non-200 resolves to an access-log line.
        import dataclasses
        import tempfile
        import urllib.error
        import urllib.request

        from ..experiment import checkpoint as _ckpt
        from ..observability.context import read_access_log
        from ..serving.registry import synthetic_registry

        tenant_ids = [f"t{i}" for i in range(4)]
        thrash_cfg = dataclasses.replace(
            cfg,
            strict_recompile_guard=True,
            serving=ServingConfig(
                support_buckets=[16], query_buckets=[16], max_batch_size=2,
            ),
        )
        thrash_system = MAMLSystem(
            thrash_cfg,
            model=build_vgg(img, 5, num_stages=2, cnn_num_filters=4),
        )
        state = thrash_system.init_train_state()
        reg_root = tempfile.mkdtemp(prefix="chaos_tenants_")
        registry = synthetic_registry(tenant_ids, state, reg_root, seed=7)
        engine = AdaptationEngine(thrash_system, state, registry=registry)
        warm = engine.prewarm(max_workers=1)
        if warm["errors"]:
            violations.append(f"tenant-grid prewarm errors: {warm}")
        # single-tenant CONTROL probs: one engine per tenant, built from
        # that tenant's checkpoint alone (no registry, no pager)
        epi3 = synthetic_batch(1, 5, 2, 3, img, seed=31)
        x_s, y_s = epi3["x_support"][0], epi3["y_support"][0]
        x_q = epi3["x_target"][0].reshape((-1,) + img)
        control_probs = {}
        for tenant in tenant_ids:
            inf, _ = _ckpt.load_for_inference(
                os.path.join(reg_root, tenant, "saved_models"), "latest"
            )
            ctrl = AdaptationEngine(thrash_system, inf)
            control_probs[tenant] = np.asarray(
                ctrl.predict(ctrl.adapt(x_s, y_s), x_q)
            )
        access_dir = tempfile.mkdtemp(prefix="chaos_access_")
        frontend = ServingFrontend(engine, access_log_dir=access_dir)
        server = make_http_server(frontend, "127.0.0.1", 0)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        non_200_ids = []

        def _post(path, body, timeout=60):
            req = urllib.request.Request(
                base + path,
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, json.loads(resp.read())

        try:
            payload = {"x_support": x_s.tolist(), "y_support": y_s.tolist()}
            # size the budget off the first page-in: it must fit M/2
            # masters, so 4 tenants round-robin = guaranteed thrash
            _post("/adapt", {**payload, "tenant": tenant_ids[0]})
            per_tenant = engine.pager.stats()["resident_bytes"]
            if per_tenant <= 0:
                violations.append("pager reports zero resident bytes after a page-in")
            engine.pager.budget_bytes = 2 * per_tenant
            ids = {}
            # two thrash rounds: round 2 re-serves tenants already evicted
            # in round 1, so 'evict then page back in' determinism is
            # exercised for real, not just first-touch paging
            for _ in range(2):
                for tenant in tenant_ids:
                    _, out = _post("/adapt", {**payload, "tenant": tenant})
                    ids[tenant] = out["adaptation_id"]
                    _, probs = _post(
                        "/predict",
                        {"adaptation_id": ids[tenant],
                         "x_query": x_q.tolist(), "tenant": tenant},
                    )
                    if not np.array_equal(
                        np.asarray(probs["probs"], np.float32),
                        control_probs[tenant],
                    ):
                        violations.append(
                            f"tenant {tenant} probs differ from its "
                            "single-tenant control — paging changed results"
                        )
            # (4) isolation: tenant A's id as tenant B = honest 404
            try:
                _post(
                    "/predict",
                    {"adaptation_id": ids[tenant_ids[0]],
                     "x_query": x_q.tolist(), "tenant": tenant_ids[1]},
                )
                violations.append(
                    "tenant B resolved tenant A's adaptation id — "
                    "cross-tenant weight leak"
                )
            except urllib.error.HTTPError as exc:
                if exc.code != 404:
                    violations.append(
                        f"cross-tenant predict returned {exc.code}, not 404"
                    )
                rid = exc.headers.get("X-Request-Id")
                if rid:
                    non_200_ids.append((exc.code, rid))
            # unknown tenant = 400 on the wire
            try:
                _post("/adapt", {**payload, "tenant": "nobody"})
                violations.append("unknown tenant adapt returned 200")
            except urllib.error.HTTPError as exc:
                if exc.code != 400:
                    violations.append(
                        f"unknown tenant returned {exc.code}, not 400"
                    )
                rid = exc.headers.get("X-Request-Id")
                if rid:
                    non_200_ids.append((exc.code, rid))
            # (2) zero outside-prewarm compiles across the whole thrash
            snap = engine.recompile_guard.snapshot()
            if not snap["prewarmed"] or snap["violations"]:
                violations.append(
                    f"sealed-guard invariant broken under tenant thrash: {snap}"
                )
            # (3) the budget thrashed and /metrics says so
            metrics = frontend.metrics()
            json.dumps(metrics)  # observability stays well-formed
            pager_stats = (metrics.get("tenants") or {}).get("pager") or {}
            if not pager_stats.get("evictions"):
                violations.append(
                    f"no evictions under a budget fitting 2 of 4 tenants: "
                    f"{pager_stats}"
                )
            by_tenant = (metrics.get("tenants") or {}).get("by_tenant") or {}
            if set(by_tenant) < set(tenant_ids):
                violations.append(
                    f"/metrics tenants block missing tenants: {sorted(by_tenant)}"
                )
        finally:
            server.shutdown()
            server.server_close()
            frontend.close()
            thread.join(timeout=5)
        # (3) evictions are logged events
        events_path = os.path.join(access_dir, "events.jsonl")
        evict_events = []
        if os.path.exists(events_path):
            with open(events_path) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("event") == "tenant_evicted":
                        evict_events.append(rec)
        if not evict_events:
            violations.append("no tenant_evicted event in events.jsonl")
        # (4) every non-200 resolves to an access line, which carries tenants
        records, torn = read_access_log(os.path.join(access_dir, "access.jsonl"))
        if torn:
            violations.append(f"{torn} torn access.jsonl line(s)")
        logged_ids = {r.get("trace_id") for r in records}
        for code, rid in non_200_ids:
            if rid not in logged_ids:
                violations.append(
                    f"non-200 ({code}) request {rid} has no access-log line"
                )
        if not non_200_ids:
            violations.append(
                "drill produced no non-200 responses — invariant untested"
            )
        tenants_logged = {r.get("tenant") for r in records if r.get("tenant")}
        if tenants_logged < set(tenant_ids):
            violations.append(
                f"access lines do not carry all tenants: {sorted(tenants_logged)}"
            )
    else:
        violations.append(f"unknown serve episode kind {ep.kind!r}")
    return violations


# ---------------------------------------------------------------------------
# cross-process fleet drills (ISSUE 14): real gateway + real serve backends
# ---------------------------------------------------------------------------


def tiny_serving_system(cfg):
    """The shrunken 2-stage/4-filter backbone the serving drills load —
    deliberately NOT reconstructible from config alone, which is why
    :func:`child_serve_main` (not scripts/serve.py) is the drill backend
    entry: it rebuilds the same model the checkpoint was saved from."""
    from ..core import MAMLSystem
    from ..models import build_vgg

    return MAMLSystem(
        cfg,
        model=build_vgg(
            (28, 28, 1), cfg.num_classes_per_set, num_stages=2, cnn_num_filters=4
        ),
    )


def make_serving_run_dir(
    root: str,
    name: str,
    template: Optional[str] = None,
    perturb_seed: Optional[int] = None,
    serving_overrides: Optional[Dict[str, Any]] = None,
) -> str:
    """A toy SERVING run dir a backend subprocess can load: config.yaml +
    an init-state checkpoint + logs/. ``template`` copies another run dir's
    config + checkpoint byte-for-byte (same fingerprint => the fleet's
    backends agree about every session's cache key — exactly the deployed
    shape, where every host serves the same pushed checkpoint).
    ``perturb_seed`` deterministically perturbs the init params before
    saving, so multi-tenant drills get DISTINCT checkpoints (distinct
    fingerprints, distinct predictions) that still share the one tree
    structure the compiled programs key on — the deterministic init would
    otherwise hand every "tenant" the same fingerprint. ``serving_overrides``
    patches the run's ServingConfig (e.g. ``refine_enabled``); with a
    ``template`` the checkpoint is still copied byte-for-byte (same
    fingerprint), only the config is rewritten."""
    import shutil

    run_dir = os.path.join(root, name)
    save_dir = os.path.join(run_dir, "saved_models")
    os.makedirs(save_dir, exist_ok=True)
    os.makedirs(os.path.join(run_dir, "logs"), exist_ok=True)
    if template is not None:
        if serving_overrides:
            from ..config import load_config, save_config

            tcfg = load_config(os.path.join(template, "config.yaml"))
            tcfg = dataclasses.replace(
                tcfg,
                serving=dataclasses.replace(tcfg.serving, **serving_overrides),
                experiment_root=root,
                experiment_name=name,
            )
            save_config(tcfg, os.path.join(run_dir, "config.yaml"))
        else:
            shutil.copy(
                os.path.join(template, "config.yaml"),
                os.path.join(run_dir, "config.yaml"),
            )
        shutil.copy(
            os.path.join(template, "saved_models", "train_model_latest"),
            os.path.join(save_dir, "train_model_latest"),
        )
        return run_dir
    from ..config import AotConfig, Config, ServingConfig, save_config
    from ..experiment import checkpoint as ckpt

    cfg = Config(
        num_classes_per_set=5,
        num_samples_per_class=2,
        num_target_samples=3,
        batch_size=2,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        serving=ServingConfig(
            support_buckets=[16], query_buckets=[16], max_batch_size=2,
            cache_ttl_s=600.0, **(serving_overrides or {}),
        ),
        # AOT on: the respawned replica of a rolling restart loads its
        # executables from the run's store instead of recompiling — the
        # warm-spawn contract the drill gates on via /healthz "warming"
        aot=AotConfig(enabled=True, max_workers=1, serving_background=True),
        experiment_root=root,
        experiment_name=name,
    )
    save_config(cfg, os.path.join(run_dir, "config.yaml"))
    system = tiny_serving_system(cfg)
    state = system.init_train_state()
    if perturb_seed is not None:
        import jax
        import numpy as np

        rng = np.random.default_rng(perturb_seed)

        def _perturb(leaf):
            a = np.asarray(leaf)
            if not np.issubdtype(a.dtype, np.floating):
                return leaf
            return a + (0.01 * rng.standard_normal(a.shape)).astype(a.dtype)

        state = state._replace(params=jax.tree.map(_perturb, state.params))
    ckpt.save_named(save_dir, state, {"epoch": 0}, "latest")
    return run_dir


def child_serve_main(run_dir: str, port_file: str, port: int = 0) -> int:
    """Backend subprocess entry for the fleet drills: load the toy run dir,
    serve it through the REAL ``run_server`` path (SIGTERM => graceful
    drain => spill => rc), and publish the bound port to ``port_file``.
    Importable (not ``__main__``) so the parent spawns it with a one-line
    ``-c`` after pinning the JAX env."""
    import jax

    jax.config.update("jax_platforms", "cpu")  # site-hook override guard
    from ..utils.compcache import setup_compilation_cache

    setup_compilation_cache(
        os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_test_cache"),
        test_tuning=True,
    )
    from ..config import load_config
    from ..serving.engine import AdaptationEngine
    from ..serving.server import ServingFrontend, run_server

    cfg = load_config(os.path.join(run_dir, "config.yaml"))
    engine = AdaptationEngine.from_run_dir(
        run_dir, "latest", cfg=cfg, system=tiny_serving_system(cfg)
    )
    frontend = ServingFrontend(
        engine, access_log_dir=os.path.join(run_dir, "logs")
    )

    def _announce(host, bound_port):
        tmp = f"{port_file}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(str(bound_port))
        os.replace(tmp, port_file)

    return run_server(frontend, "127.0.0.1", port, on_bound=_announce)


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def spawn_serve_backend(run_dir: str, port: int = 0, env_extra=None):
    """Fork one serving backend over ``run_dir``; returns (proc, port_file).
    stdout/stderr land in <run_dir>/serve_stdout.log (pipe-fill-proof)."""
    code = (
        "import sys;"
        "from howtotrainyourmamlpytorch_tpu.resilience.campaign import child_serve_main;"
        "sys.exit(child_serve_main(sys.argv[1], sys.argv[2], int(sys.argv[3])))"
    )
    port_file = os.path.join(run_dir, "serve_port")
    try:
        os.remove(port_file)
    except FileNotFoundError:
        pass
    env = _child_env(1)
    env.update(env_extra or {})
    log_handle = open(os.path.join(run_dir, "serve_stdout.log"), "ab")
    proc = subprocess.Popen(
        [sys.executable, "-c", code, run_dir, port_file, str(port)],
        cwd=_REPO_ROOT,
        env=env,
        stdout=log_handle,
        stderr=subprocess.STDOUT,
    )
    log_handle.close()  # the child holds its own descriptor
    return proc, port_file


def backend_spawn_argv(run_dir: str, port: int) -> List[str]:
    """The respawn command a rolling restart hands scripts/rolling_restart.py
    for one drill backend (same entry :func:`spawn_serve_backend` forks)."""
    code = (
        "import sys;"
        "from howtotrainyourmamlpytorch_tpu.resilience.campaign import child_serve_main;"
        "sys.exit(child_serve_main(sys.argv[1], sys.argv[2], int(sys.argv[3])))"
    )
    return [
        sys.executable, "-c", code, run_dir,
        os.path.join(run_dir, "serve_port"), str(port),
    ]


def _wait_port_file(port_file: str, proc, timeout_s: float = 240.0) -> int:
    end = time.monotonic() + timeout_s
    while time.monotonic() < end:
        if proc is not None and proc.poll() is not None:
            raise RuntimeError(
                f"backend died (rc {proc.returncode}) before binding"
            )
        if os.path.exists(port_file):
            with open(port_file) as f:
                text = f.read().strip()
            if text:
                return int(text)
        time.sleep(0.1)
    raise RuntimeError(f"no port file {port_file} within {timeout_s}s")


def _http_json(url: str, payload=None, timeout_s: float = 60.0):
    """-> (status, body dict, headers). HTTP errors return their status;
    connection failures raise OSError."""
    import urllib.error
    import urllib.request

    data = None if payload is None else json.dumps(payload).encode()
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"}
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return resp.status, _loads_or_empty(resp.read()), dict(resp.headers.items())
    except urllib.error.HTTPError as exc:
        return exc.code, _loads_or_empty(exc.read()), dict(exc.headers.items())
    except urllib.error.URLError as exc:
        raise OSError(str(exc.reason)) from exc


def _loads_or_empty(blob: bytes):
    try:
        out = json.loads(blob)
        return out if isinstance(out, dict) else {}
    except ValueError:
        return {}


def _wait_http_ok(url: str, timeout_s: float, proc=None) -> None:
    end = time.monotonic() + timeout_s
    last = None
    while time.monotonic() < end:
        if proc is not None and proc.poll() is not None:
            raise RuntimeError(f"process died (rc {proc.returncode}) warming up")
        try:
            code, _, _ = _http_json(url, timeout_s=5.0)
            last = code
            if code == 200:
                return
        except OSError as exc:
            last = str(exc)
        time.sleep(0.25)
    raise RuntimeError(f"{url} never answered 200 ({last!r}) in {timeout_s}s")


def spawn_gateway(backend_urls: List[str], log_dir: str, **knobs):
    """Fork scripts/gateway.py over ``backend_urls``; returns (proc, base_url)."""
    os.makedirs(log_dir, exist_ok=True)
    port_file = os.path.join(log_dir, "gateway_port")
    try:
        os.remove(port_file)
    except FileNotFoundError:
        pass
    argv = [
        sys.executable, os.path.join(_REPO_ROOT, "scripts", "gateway.py"),
        "--backends", ",".join(backend_urls),
        "--port", "0", "--port-file", port_file, "--log-dir", log_dir,
        "--health-interval-s", str(knobs.get("health_interval_s", 0.25)),
        "--fail-threshold", str(knobs.get("fail_threshold", 2)),
        "--pass-threshold", str(knobs.get("pass_threshold", 1)),
        "--request-timeout-s", str(knobs.get("request_timeout_s", 120.0)),
    ]
    log_handle = open(os.path.join(log_dir, "gateway_stdout.log"), "ab")
    proc = subprocess.Popen(
        argv, cwd=_REPO_ROOT, stdout=log_handle, stderr=subprocess.STDOUT
    )
    log_handle.close()
    port = _wait_port_file(port_file, proc, timeout_s=30.0)
    return proc, f"http://127.0.0.1:{port}"


def _kill_quiet(proc) -> None:
    if proc is None or proc.poll() is not None:
        return
    try:
        proc.kill()
        proc.wait(timeout=10)
    except (OSError, subprocess.TimeoutExpired):
        pass


def _adapt_payload(seed: int):
    from ..data.synthetic import synthetic_batch

    b = synthetic_batch(1, 5, 2, 3, (28, 28, 1), seed=seed)
    return (
        {"x_support": b["x_support"][0].tolist(),
         "y_support": b["y_support"][0].tolist()},
        b["x_target"][0].reshape((-1, 28, 28, 1)).tolist(),
    )


def _run_gateway_episode(
    ep: Episode, work_dir: Optional[str] = None, template_run: Optional[str] = None
) -> List[str]:
    """One cross-process fleet drill: a REAL gateway subprocess fronting
    REAL serve-backend subprocesses, driven over the wire. Returns
    violations (empty = green). ``template_run`` (a previously built run
    dir) lets the tier-1 tests share one checkpoint across drills."""
    import tempfile

    violations: List[str] = []
    root = tempfile.mkdtemp(prefix=f"chaos_{ep.kind.replace('-', '_')}_",
                            dir=work_dir)
    procs: List[Any] = []
    try:
        if ep.kind == "gateway-kill9-backend":
            violations += _drill_kill9(root, template_run, procs)
        elif ep.kind == "gateway-drain-rehydrate":
            violations += _drill_drain_rehydrate(root, template_run, procs)
        elif ep.kind == "gateway-rolling-restart":
            violations += _drill_rolling_restart(root, template_run, procs)
        elif ep.kind == "serve-refine-across-drain":
            violations += _drill_refine_across_drain(root, template_run, procs)
        elif ep.kind == "fleet-surge":
            violations += _drill_fleet_surge(root, template_run, procs)
        elif ep.kind == "fleet-crashloop":
            violations += _drill_fleet_crashloop(root, template_run, procs)
        else:
            violations.append(f"unknown gateway episode kind {ep.kind!r}")
    except Exception as exc:  # noqa: BLE001 — a drill crash is the finding
        violations.append(f"{ep.kind} drill crashed: {type(exc).__name__}: {exc}")
    finally:
        for proc in procs:
            _kill_quiet(proc)
    return violations


def _spawn_fleet(root: str, template_run: Optional[str], procs: List[Any], n: int):
    """n backends (fixed ports, warm) + one gateway; returns
    (run_dirs, ports, backend_procs, gateway_proc, gateway_url, log_dir)."""
    template = template_run or make_serving_run_dir(root, "template")
    run_dirs, ports, backend_procs = [], [], []
    for i in range(n):
        run_dir = make_serving_run_dir(root, f"b{i}", template=template)
        port = _free_port()
        proc, port_file = spawn_serve_backend(run_dir, port=port)
        procs.append(proc)
        run_dirs.append(run_dir)
        ports.append(port)
        backend_procs.append(proc)
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    for url, proc in zip(urls, backend_procs):
        # past "warming": the AOT prewarm must land before the drill clock
        _wait_http_ok(url + "/healthz", timeout_s=300.0, proc=proc)
    gw_log_dir = os.path.join(root, "gateway", "logs")
    gw_proc, gw_url = spawn_gateway(urls, gw_log_dir)
    procs.append(gw_proc)
    _wait_http_ok(gw_url + "/healthz", timeout_s=30.0, proc=gw_proc)
    return run_dirs, ports, backend_procs, gw_proc, gw_url, gw_log_dir


def _drill_kill9(root, template_run, procs) -> List[str]:
    """kill -9 one backend mid-flight: the gateway routes around it within
    the hysteresis window (availability never reaches zero), the displaced
    session re-adapts — NEVER a stale answer — and the membership flap is
    events-resolvable."""
    violations: List[str] = []
    run_dirs, ports, backends, gw_proc, gw_url, gw_logs = _spawn_fleet(
        root, template_run, procs, n=2
    )
    support, query = _adapt_payload(31)
    code, body, headers = _http_json(gw_url + "/adapt", support)
    if code != 200:
        return [f"warm adapt failed: {code} {body}"]
    aid = body["adaptation_id"]
    owner = headers.get("X-Gateway-Backend")  # "b0" / "b1"
    code, body, _ = _http_json(
        gw_url + "/predict", {"adaptation_id": aid, "x_query": query}
    )
    if code != 200:
        return [f"warm predict failed: {code} {body}"]
    probs_before = body["probs"]
    owner_idx = int(owner[1:])
    os.kill(backends[owner_idx].pid, 9)  # SIGKILL: no drain, no goodbye
    # drive load THROUGH the kill: fresh adapts must keep succeeding (the
    # gateway retries connection failures against the survivor), so
    # availability never reaches zero
    ok = fail = 0
    deadline = time.monotonic() + 20.0
    seed = 100
    while time.monotonic() < deadline:
        s, _ = _adapt_payload(seed)
        seed += 1
        try:
            code, _, _ = _http_json(gw_url + "/adapt", s, timeout_s=30.0)
        except OSError:
            code = None
        if code == 200:
            ok += 1
        else:
            fail += 1
        if ok >= 6:
            break
        time.sleep(0.2)
    if ok < 6:
        violations.append(
            f"availability lost after kill -9: {ok} ok / {fail} failed"
        )
    # the displaced session must NOT be served stale: predict resolves 404
    # (the survivor never adapted it), then a re-adapt + predict must be
    # bit-identical to the pre-kill answer
    code = None
    for _ in range(20):
        try:
            code, body, _ = _http_json(
                gw_url + "/predict", {"adaptation_id": aid, "x_query": query},
                timeout_s=30.0,
            )
        except OSError:
            code = None
        if code in (200, 404):
            break
        time.sleep(0.3)
    if code == 200:
        violations.append(
            "displaced session predict returned 200 without re-adapt — "
            "possible stale/wrong answer after backend death"
        )
    elif code != 404:
        violations.append(f"displaced predict never resolved (last {code})")
    code, body, headers = _http_json(gw_url + "/adapt", support, timeout_s=60.0)
    if code != 200:
        violations.append(f"re-adapt failed: {code}")
    else:
        if headers.get("X-Gateway-Backend") == owner:
            violations.append("re-adapt routed to the killed backend")
        code, body, _ = _http_json(
            gw_url + "/predict", {"adaptation_id": aid, "x_query": query},
            timeout_s=60.0,
        )
        if code != 200 or body.get("probs") != probs_before:
            violations.append(
                "post-failover predictions differ from the healthy fleet's"
            )
    # membership: the dead backend is OUT and the flap is events-resolvable
    code, metrics, _ = _http_json(gw_url + "/metrics", timeout_s=30.0)
    rows = {b["backend"]: b for b in metrics.get("backends", [])}
    if rows.get(owner, {}).get("in") is not False:
        violations.append(f"dead backend {owner} still IN: {rows.get(owner)}")
    events_path = os.path.join(gw_logs, "events.jsonl")
    flaps = []
    if os.path.exists(events_path):
        with open(events_path) as f:
            for line in f:
                if line.strip():
                    rec = json.loads(line)
                    if rec.get("event") == "backend_out":
                        flaps.append(rec.get("backend"))
    if owner not in flaps:
        violations.append(
            f"backend_out event for {owner} missing from gateway events.jsonl"
        )
    return violations


def _drill_drain_rehydrate(root, template_run, procs) -> List[str]:
    """SIGTERM mid-load: zero dropped in-flight requests, clean rc 0, and a
    digest-verified spill -> rehydrate round-trip proven by a post-restart
    predict WITHOUT re-adapt (the session survived the restart)."""
    violations: List[str] = []
    template = template_run or make_serving_run_dir(root, "template")
    run_dir = make_serving_run_dir(root, "b0", template=template)
    port = _free_port()
    # injected 0.5s dispatch delay: requests are genuinely in flight when
    # the SIGTERM lands, so "zero dropped" is actually exercised
    env = {"HTYMP_FAULTS": "serving.dispatch=delay:delay_s=0.5,p=1.0"}
    proc, _ = spawn_serve_backend(run_dir, port=port, env_extra=env)
    procs.append(proc)
    url = f"http://127.0.0.1:{port}"
    _wait_http_ok(url + "/healthz", timeout_s=300.0, proc=proc)
    gw_logs = os.path.join(root, "gateway", "logs")
    gw_proc, gw_url = spawn_gateway([url], gw_logs)
    procs.append(gw_proc)
    _wait_http_ok(gw_url + "/healthz", timeout_s=30.0, proc=gw_proc)
    support, query = _adapt_payload(47)
    code, body, _ = _http_json(gw_url + "/adapt", support, timeout_s=60.0)
    if code != 200:
        return [f"warm adapt failed: {code} {body}"]
    aid = body["adaptation_id"]
    code, body, _ = _http_json(
        gw_url + "/predict", {"adaptation_id": aid, "x_query": query},
        timeout_s=60.0,
    )
    if code != 200:
        return [f"warm predict failed: {code}"]
    probs_before = body["probs"]
    # in-flight load: 3 concurrent predicts (0.5s dispatch each, serialized
    # by the worker) — then SIGTERM lands mid-flight
    results: List[Any] = []
    lock = san_lock("campaign._drill_drain_rehydrate.lock")

    def one_predict():
        try:
            c, b, _ = _http_json(
                gw_url + "/predict", {"adaptation_id": aid, "x_query": query},
                timeout_s=90.0,
            )
        except OSError as exc:
            c, b = None, {"error": str(exc)}
        with lock:
            results.append((c, b))

    threads = [threading.Thread(target=one_predict) for _ in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.3)  # let them reach the backend and queue/dispatch
    proc.send_signal(15)  # SIGTERM: graceful drain
    # a NEW request during the drain must be refused with Retry-After,
    # never silently dropped (single-backend fleet: the gateway has
    # nowhere to retry it)
    try:
        code, _, headers = _http_json(
            gw_url + "/predict", {"adaptation_id": aid, "x_query": query},
            timeout_s=60.0,
        )
        if code == 200:
            pass  # raced ahead of the drain flag — legitimate
        elif code in (
            exit_codes.HTTP_UNAVAILABLE, exit_codes.HTTP_TOO_MANY_REQUESTS
        ):
            if "Retry-After" not in headers:
                violations.append(f"drain-window {code} without Retry-After")
        else:
            violations.append(f"drain-window request got undocumented {code}")
    except OSError:
        violations.append("drain-window request got a dropped connection")
    for t in threads:
        t.join(timeout=120)
    dropped = [r for r in results if r[0] != 200]
    if len(results) != 3 or dropped:
        violations.append(
            f"in-flight requests dropped by drain: {results}"
        )
    try:
        rc = proc.wait(timeout=90)
    except subprocess.TimeoutExpired:
        violations.append("drained backend never exited")
        return violations
    if rc != 0:
        violations.append(f"clean drain exited rc {rc} (want 0)")
    sessions_dir = os.path.join(run_dir, "saved_models", "sessions")
    spilled = (
        [n for n in os.listdir(sessions_dir) if n.startswith("session_")]
        if os.path.isdir(sessions_dir)
        else []
    )
    if not spilled:
        violations.append("drain spilled no sessions")
    # respawn the SAME run dir on the SAME port: the replica must rehydrate
    # and serve the old session without a re-adapt
    proc2, _ = spawn_serve_backend(run_dir, port=port)
    procs.append(proc2)
    _wait_http_ok(url + "/healthz", timeout_s=300.0, proc=proc2)
    # wait for the gateway to readmit it
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline:
        code, m, _ = _http_json(gw_url + "/metrics", timeout_s=10.0)
        if m.get("backends_in") == 1:
            break
        time.sleep(0.3)
    code, body, _ = _http_json(
        gw_url + "/predict", {"adaptation_id": aid, "x_query": query},
        timeout_s=90.0,
    )
    if code != 200:
        violations.append(
            f"post-restart predict for the spilled session failed: {code} "
            "(rehydration lost the session)"
        )
    elif body.get("probs") != probs_before:
        violations.append("rehydrated session served DIFFERENT predictions")
    code, metrics, _ = _http_json(url + "/metrics", timeout_s=30.0)
    sessions = metrics.get("sessions") or {}
    if int(sessions.get("rehydrated", 0)) < 1:
        violations.append(f"backend reports no rehydrated sessions: {sessions}")
    cache = metrics.get("cache") or {}
    if int(cache.get("hits", 0)) < 1:
        violations.append(f"rehydrated predict was not a cache hit: {cache}")
    return violations


def _drill_rolling_restart(root, template_run, procs) -> List[str]:
    """Full rolling restart under load via scripts/rolling_restart.py: both
    backends drained + respawned warm one at a time, the fleet never
    refuses all traffic, and every non-200 the driver saw resolves to a
    gateway access line by request id."""
    violations: List[str] = []
    run_dirs, ports, backends, gw_proc, gw_url, gw_logs = _spawn_fleet(
        root, template_run, procs, n=2
    )
    # background driver: steady adapt/predict mix; record every outcome
    stop = threading.Event()
    outcomes: List[Any] = []
    lock = san_lock("campaign._drill_rolling_restart.lock")

    def drive():
        seed = 500
        aid = None
        while not stop.is_set():
            try:
                if aid is None or seed % 3 == 0:
                    s, q = _adapt_payload(seed % 40)
                    c, b, h = _http_json(gw_url + "/adapt", s, timeout_s=60.0)
                    if c == 200:
                        aid = b.get("adaptation_id")
                else:
                    _, q = _adapt_payload(seed % 40)
                    c, b, h = _http_json(
                        gw_url + "/predict",
                        {"adaptation_id": aid, "x_query": q},
                        timeout_s=60.0,
                    )
                    if c == 404:
                        aid = None  # displaced session: re-adapt next turn
                rid = h.get("X-Request-Id")
            except OSError as exc:
                c, rid = None, None
            with lock:
                outcomes.append((c, rid))
            seed += 1
            stop.wait(0.15)

    driver = threading.Thread(target=drive, daemon=True)
    driver.start()
    time.sleep(1.0)
    # reap each original backend the moment its drain exits: without this
    # they linger as zombies of THIS process and rolling_restart's
    # pid-liveness probe (os.kill(pid, 0)) would never see them die
    for proc in backends:
        threading.Thread(target=proc.wait, daemon=True).start()
    fleet_spec = [
        {
            "url": f"http://127.0.0.1:{port}",
            "pid": proc.pid,
            "respawn": backend_spawn_argv(run_dir, port),
            "cwd": _REPO_ROOT,
            "log": os.path.join(run_dir, "serve_stdout.log"),
        }
        for run_dir, port, proc in zip(run_dirs, ports, backends)
    ]
    fleet_path = os.path.join(root, "fleet.json")
    with open(fleet_path, "w") as f:
        json.dump(fleet_spec, f)
    roll = subprocess.run(
        [
            sys.executable, os.path.join(_REPO_ROOT, "scripts", "rolling_restart.py"),
            "--fleet", fleet_path, "--drain-timeout-s", "90",
            "--warm-timeout-s", "300",
        ],
        cwd=_REPO_ROOT,
        env=_child_env(1),
        capture_output=True,
        text=True,
        timeout=900,
    )
    time.sleep(1.0)
    stop.set()
    driver.join(timeout=120)
    verdict = None
    for line in reversed(roll.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
        except ValueError:
            continue
        if parsed.get("rolling_restart"):
            verdict = parsed
            break
    if roll.returncode != 0 or verdict is None or not verdict.get("ok"):
        violations.append(
            f"rolling restart failed: rc {roll.returncode} verdict {verdict} "
            f"stderr tail: {roll.stderr[-500:]}"
        )
    else:
        # the respawned pids are tracked for cleanup
        for row in verdict["rows"]:
            procs.append(_FakeProc(row.get("new_pid")))
    with lock:
        seen = list(outcomes)
    oks = sum(1 for c, _ in seen if c == 200)
    conn_drops = sum(1 for c, _ in seen if c is None)
    if oks < 5:
        violations.append(f"fleet served only {oks} oks through the roll: {seen}")
    if conn_drops:
        violations.append(
            f"{conn_drops} dropped connections during the roll (gateway must "
            "absorb backend restarts)"
        )
    # every non-200 the driver saw resolves to a gateway access line
    access_path = os.path.join(gw_logs, "access.jsonl")
    logged = set()
    if os.path.exists(access_path):
        with open(access_path) as f:
            for line in f:
                if line.strip():
                    try:
                        logged.add(json.loads(line).get("trace_id"))
                    except ValueError:
                        pass
    for c, rid in seen:
        if c is not None and c != 200:
            if rid is None:
                violations.append(f"non-200 ({c}) without X-Request-Id")
            elif rid not in logged:
                violations.append(
                    f"non-200 ({c}) request {rid} has no gateway access line"
                )
    return violations


def _drill_refine_across_drain(root, template_run, procs) -> List[str]:
    """Long-lived refined session across process deaths: adapt -> refine
    (lineage committed) -> SIGTERM drain (spill carries the lineage) ->
    respawn (rehydrate) -> predict bit-identical WITHOUT re-adapt and the
    next refine CONTINUES the lineage (refine_count 2, never a reset) ->
    kill -9 the gateway and front the same backend with a fresh one ->
    session still bit-identical and still refining (lineage lives with the
    session, not the gateway)."""
    violations: List[str] = []
    template = template_run or make_serving_run_dir(root, "template")
    # same checkpoint bytes as the fleet template (same fingerprint), but
    # the run's OWN config turns the stateful-session path on
    run_dir = make_serving_run_dir(
        root, "b0", template=template,
        serving_overrides={"refine_enabled": True},
    )
    port = _free_port()
    proc, _ = spawn_serve_backend(run_dir, port=port)
    procs.append(proc)
    url = f"http://127.0.0.1:{port}"
    _wait_http_ok(url + "/healthz", timeout_s=300.0, proc=proc)
    gw_logs = os.path.join(root, "gateway", "logs")
    gw_proc, gw_url = spawn_gateway([url], gw_logs)
    procs.append(gw_proc)
    _wait_http_ok(gw_url + "/healthz", timeout_s=30.0, proc=gw_proc)
    support, query = _adapt_payload(53)
    code, body, _ = _http_json(gw_url + "/adapt", support, timeout_s=60.0)
    if code != 200:
        return [f"warm adapt failed: {code} {body}"]
    sid = body["adaptation_id"]
    refine_body = {**support, "refine": True, "session_id": sid}
    code, body, _ = _http_json(gw_url + "/adapt", refine_body, timeout_s=60.0)
    if code != 200 or not body.get("refined") or body.get("rolled_back"):
        return [f"warm refine failed: {code} {body}"]
    if body.get("refine_count") != 1:
        violations.append(f"first refine count != 1: {body}")
    code, body, _ = _http_json(
        gw_url + "/predict", {"adaptation_id": sid, "x_query": query},
        timeout_s=60.0,
    )
    if code != 200:
        return [f"warm predict failed: {code}"]
    probs_refined = body["probs"]
    # SIGTERM: graceful drain must spill the session WITH its lineage
    proc.send_signal(15)
    try:
        rc = proc.wait(timeout=90)
    except subprocess.TimeoutExpired:
        return violations + ["drained backend never exited"]
    if rc != 0:
        violations.append(f"clean drain exited rc {rc} (want 0)")
    # respawn the SAME run dir on the SAME port: rehydration must restore
    # the refined weights AND the lineage
    proc2, _ = spawn_serve_backend(run_dir, port=port)
    procs.append(proc2)
    _wait_http_ok(url + "/healthz", timeout_s=300.0, proc=proc2)
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline:
        code, m, _ = _http_json(gw_url + "/metrics", timeout_s=10.0)
        if m.get("backends_in") == 1:
            break
        time.sleep(0.3)
    code, body, _ = _http_json(
        gw_url + "/predict", {"adaptation_id": sid, "x_query": query},
        timeout_s=90.0,
    )
    if code != 200:
        violations.append(
            f"post-drain predict for the refined session failed: {code} "
            "(rehydration lost the session)"
        )
    elif body.get("probs") != probs_refined:
        violations.append(
            "rehydrated session served predictions differing from its "
            "refined weights — the refinement was lost in the spill"
        )
    code, body, _ = _http_json(gw_url + "/adapt", refine_body, timeout_s=90.0)
    if code != 200 or body.get("rolled_back"):
        violations.append(f"post-drain refine failed: {code} {body}")
    elif body.get("refine_count") != 2:
        violations.append(
            f"post-drain refine did not CONTINUE the lineage "
            f"(refine_count {body.get('refine_count')}, want 2) — the "
            "spill dropped the session's history"
        )
    code, metrics, _ = _http_json(url + "/metrics", timeout_s=30.0)
    sessions = metrics.get("sessions") or {}
    if int(sessions.get("rehydrated", 0)) < 1:
        violations.append(f"backend reports no rehydrated sessions: {sessions}")
    refine_stats = sessions.get("refine") or {}
    if int(refine_stats.get("active_lineages", 0)) < 1:
        violations.append(
            f"no active lineage after rehydrate: {refine_stats}"
        )
    probs_after_refine2 = None
    code, body, _ = _http_json(
        gw_url + "/predict", {"adaptation_id": sid, "x_query": query},
        timeout_s=60.0,
    )
    if code == 200:
        probs_after_refine2 = body["probs"]
    # kill -9 the GATEWAY: the session and its lineage live with the
    # backend, so a fresh gateway over the same backend must serve the
    # session bit-identically and keep refining it
    os.kill(gw_proc.pid, 9)
    gw_proc2, gw_url2 = spawn_gateway([url], os.path.join(root, "gateway2", "logs"))
    procs.append(gw_proc2)
    _wait_http_ok(gw_url2 + "/healthz", timeout_s=30.0, proc=gw_proc2)
    code, body, _ = _http_json(
        gw_url2 + "/predict", {"adaptation_id": sid, "x_query": query},
        timeout_s=90.0,
    )
    if code != 200:
        violations.append(
            f"post-gateway-kill predict failed through the new gateway: {code}"
        )
    elif probs_after_refine2 is not None and body.get("probs") != probs_after_refine2:
        violations.append(
            "predictions changed across the gateway failover — the session "
            "was silently reset or displaced"
        )
    code, body, _ = _http_json(gw_url2 + "/adapt", refine_body, timeout_s=90.0)
    if code != 200 or body.get("refine_count") != 3:
        violations.append(
            f"refine through the new gateway did not continue the lineage: "
            f"{code} {body}"
        )
    return violations


# ---------------------------------------------------------------------------
# fleet supervisor drills (ISSUE 18)
# ---------------------------------------------------------------------------


def _spawn_fleet_supervisor(
    root, name, state_path, gw_url, events_path, procs,
    slots_path=None, **knobs
):
    """Fork scripts/fleet_serve.py; returns (proc, metrics_base_url)."""
    port_file = os.path.join(root, f"{name}_port")
    try:
        os.remove(port_file)
    except FileNotFoundError:
        pass
    argv = [
        sys.executable, os.path.join(_REPO_ROOT, "scripts", "fleet_serve.py"),
        "--state", state_path, "--gateway-url", gw_url,
        "--events", events_path, "--metrics-port", "0",
        "--port-file", port_file,
    ]
    if slots_path:
        argv += ["--slots", slots_path]
    for knob, val in knobs.items():
        argv += ["--" + knob.replace("_", "-"), str(val)]
    log_handle = open(os.path.join(root, f"{name}_stdout.log"), "ab")
    proc = subprocess.Popen(
        argv, cwd=_REPO_ROOT, env=_child_env(1),
        stdout=log_handle, stderr=subprocess.STDOUT,
    )
    log_handle.close()
    procs.append(proc)
    port = _wait_port_file(port_file, proc, timeout_s=60.0)
    return proc, f"http://127.0.0.1:{port}"


def _read_jsonl(path):
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            if line.strip():
                try:
                    out.append(json.loads(line))
                except ValueError:
                    pass
    return out


def _read_fleet_state(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _wait_until(fn, timeout_s, desc, poll_s=0.2):
    """Poll ``fn`` until it returns truthy; raise RuntimeError on timeout."""
    end = time.monotonic() + timeout_s
    while time.monotonic() < end:
        val = fn()
        if val:
            return val
        time.sleep(poll_s)
    raise RuntimeError(f"timeout waiting for {desc}")


def _stop_supervisor(sup_proc, violations, label):
    """SIGTERM stops the control loop ONLY (rc 0, backends untouched)."""
    if sup_proc.poll() is not None:
        violations.append(
            f"{label} supervisor exited early (rc {sup_proc.returncode})"
        )
        return
    sup_proc.send_signal(15)
    try:
        rc = sup_proc.wait(timeout=120)
        if rc != 0:
            violations.append(f"{label} supervisor SIGTERM rc {rc} (want 0)")
    except subprocess.TimeoutExpired:
        violations.append(f"{label} supervisor never exited on SIGTERM")


def _adopt_state_pids(state_path, procs):
    """Track every pid the supervisor journaled so cleanup reaps them."""
    state = _read_fleet_state(state_path) or {}
    for slot in state.get("slots") or []:
        if slot.get("pid"):
            procs.append(_FakeProc(slot["pid"]))


def _drill_fleet_surge(root, template_run, procs) -> List[str]:
    """Traffic-adaptive autoscaling end to end: a slowed backend under
    surging load breaches the queue signal -> the supervisor spawns the
    pre-provisioned second slot (healthz-gated, gateway admits it) -> load
    stops -> consecutive clear polls scale back down via a graceful drain
    (rc 0 observed + reported). Zero dropped connections across the whole
    cycle and a refined session's lineage survives it intact."""
    violations: List[str] = []
    template = template_run or make_serving_run_dir(root, "template")
    run0 = make_serving_run_dir(
        root, "b0", template=template,
        serving_overrides={"refine_enabled": True},
    )
    run1 = make_serving_run_dir(
        root, "b1", template=template,
        serving_overrides={"refine_enabled": True},
    )
    port0, port1 = _free_port(), _free_port()
    url0 = f"http://127.0.0.1:{port0}"
    url1 = f"http://127.0.0.1:{port1}"
    # slot 0: a real backend slowed by an injected 0.4s dispatch delay, so
    # concurrent load genuinely queues (the scale-up trigger). The
    # supervisor-spawned slot 1 inherits the supervisor's clean env — the
    # added capacity is FAST, which is the point of scaling up.
    env = {"HTYMP_FAULTS": "serving.dispatch=delay:delay_s=0.4,p=1.0"}
    proc0, _ = spawn_serve_backend(run0, port=port0, env_extra=env)
    procs.append(proc0)
    _wait_http_ok(url0 + "/healthz", timeout_s=300.0, proc=proc0)
    # reap on exit so a supervisor drain's pid-liveness probe sees death
    threading.Thread(target=proc0.wait, daemon=True).start()
    gw_logs = os.path.join(root, "gateway", "logs")
    # BOTH slot urls are pre-registered: the gateway's backend list is
    # static; the un-spawned slot simply stays OUT until the supervisor
    # fills it
    gw_proc, gw_url = spawn_gateway([url0, url1], gw_logs)
    procs.append(gw_proc)
    _wait_http_ok(gw_url + "/healthz", timeout_s=30.0, proc=gw_proc)
    # a refined session whose lineage must ride out the whole cycle (seed 53:
    # a support set whose refinement COMMITS under the score guard — the
    # same payload the across-drain drill proves to refine_count 3)
    support, query = _adapt_payload(53)
    code, body, _ = _http_json(gw_url + "/adapt", support, timeout_s=60.0)
    if code != 200:
        return [f"warm adapt failed: {code} {body}"]
    sid = body["adaptation_id"]
    refine_body = {**support, "refine": True, "session_id": sid}
    code, body, _ = _http_json(gw_url + "/adapt", refine_body, timeout_s=60.0)
    if code != 200 or body.get("refine_count") != 1:
        return [f"warm refine failed: {code} {body}"]
    code, body, _ = _http_json(
        gw_url + "/predict", {"adaptation_id": sid, "x_query": query},
        timeout_s=60.0,
    )
    if code != 200:
        return [f"warm predict failed: {code}"]
    probs_refined = body["probs"]

    slots = [
        {"url": url0, "port": port0, "pid": proc0.pid,
         "respawn": backend_spawn_argv(run0, port0), "cwd": _REPO_ROOT,
         "log": os.path.join(run0, "serve_stdout.log"), "run_dir": run0},
        {"url": url1, "port": port1,
         "respawn": backend_spawn_argv(run1, port1), "cwd": _REPO_ROOT,
         "log": os.path.join(run1, "serve_stdout.log"), "run_dir": run1},
    ]
    slots_path = os.path.join(root, "slots.json")
    with open(slots_path, "w") as f:
        json.dump(slots, f)
    state_path = os.path.join(root, "fleet_state.json")
    events_path = os.path.join(root, "supervisor_events.jsonl")
    sup_proc, sup_url = _spawn_fleet_supervisor(
        root, "supervisor", state_path, gw_url, events_path, procs,
        slots_path=slots_path,
        min_backends=1, max_backends=2, poll_interval_s=0.3,
        up_polls=2, down_polls=4, cooldown_up_s=1.0, cooldown_down_s=2.0,
        queue_high=2.0, queue_low=1.0, warm_timeout_s=300.0,
        warm_poll_s=0.25, drain_timeout_s=90.0,
    )
    try:
        code, sup_metrics, _ = _http_json(sup_url + "/metrics", timeout_s=10.0)
        if code != 200 or not sup_metrics.get("supervisor"):
            violations.append(f"supervisor /metrics broken: {code} {sup_metrics}")
        # surge: concurrent predict streams against the 0.4s-dispatch
        # backend — the batcher queue climbs past queue_high
        stop = threading.Event()
        outcomes: List[Any] = []
        lock = san_lock("campaign._drill_fleet_surge.lock")

        def drive(seed0):
            seed = seed0
            aid = None
            while not stop.is_set():
                try:
                    if aid is None:
                        s, _ = _adapt_payload(seed % 40)
                        c, b, _h = _http_json(gw_url + "/adapt", s,
                                              timeout_s=60.0)
                        if c == 200:
                            aid = b.get("adaptation_id")
                    else:
                        _, q = _adapt_payload(seed % 40)
                        c, b, _h = _http_json(
                            gw_url + "/predict",
                            {"adaptation_id": aid, "x_query": q},
                            timeout_s=60.0,
                        )
                        if c == 404:
                            aid = None  # displaced by membership change
                except OSError:
                    c = None
                with lock:
                    outcomes.append(c)
                seed += 1

        drivers = [
            threading.Thread(target=drive, args=(1000 * (i + 1),), daemon=True)
            for i in range(6)
        ]
        for t in drivers:
            t.start()
        # scale-up: the supervisor must spawn slot 1 and the gateway must
        # admit it (healthz-gated past "warming")
        try:
            _wait_until(
                lambda: _http_json(gw_url + "/metrics", timeout_s=10.0)[1]
                .get("backends_in") == 2,
                timeout_s=300.0, desc="scale-up to 2 backends",
            )
        except RuntimeError as exc:
            violations.append(f"surge never scaled up: {exc}")
        up_events = [e for e in _read_jsonl(events_path)
                     if e.get("event") == "scale_up"]
        if not up_events:
            violations.append("no scale_up event in supervisor events.jsonl")
        elif up_events[0].get("outcome") != "up" or not up_events[0].get("reason"):
            violations.append(f"malformed scale_up event: {up_events[0]}")
        # SLO recovery: with doubled capacity the fleet keeps answering —
        # collect a post-scale-up window, then stop the surge
        time.sleep(2.0)
        with lock:
            n_at_scaleup = len(outcomes)
        _wait_until(
            lambda: len(outcomes) >= n_at_scaleup + 8,
            timeout_s=120.0, desc="post-scale-up traffic window",
        )
        stop.set()
        for t in drivers:
            t.join(timeout=90)
        with lock:
            seen = list(outcomes)
        oks = sum(1 for c in seen if c == 200)
        drops = sum(1 for c in seen if c is None)
        if drops:
            violations.append(
                f"{drops} dropped connections during the surge cycle "
                f"(of {len(seen)})"
            )
        if oks < 10:
            violations.append(f"only {oks} 200s through the surge: {seen}")
        # scale-down: clear polls -> graceful drain of the added backend,
        # never below min_backends
        try:
            _wait_until(
                lambda: any(e.get("event") == "scale_down"
                            for e in _read_jsonl(events_path)),
                timeout_s=120.0, desc="scale-down drain",
            )
        except RuntimeError as exc:
            violations.append(f"never scaled back down: {exc}")
        else:
            (down,) = [e for e in _read_jsonl(events_path)
                       if e.get("event") == "scale_down"][:1]
            if down.get("slot") != 1:
                violations.append(f"scale-down drained the wrong slot: {down}")
            if down.get("drain_rc") != 0:
                violations.append(
                    f"drain rc not observed clean: {down.get('drain')} "
                    f"rc {down.get('drain_rc')}"
                )
            try:
                _wait_until(
                    lambda: _http_json(gw_url + "/metrics", timeout_s=10.0)[1]
                    .get("backends_in") == 1,
                    timeout_s=60.0, desc="gateway sees the drained slot OUT",
                )
            except RuntimeError as exc:
                violations.append(str(exc))
        state = _read_fleet_state(state_path) or {}
        up_slots = [s for s in state.get("slots", [])
                    if s.get("state") == "up"]
        if [s.get("slot") for s in up_slots] != [0]:
            violations.append(
                f"post-cycle fleet state wrong: {state.get('slots')}"
            )
        # the refined session's lineage is intact: the next refine
        # CONTINUES at refine_count 2 and its pre-surge predictions held
        code, body, _ = _http_json(
            gw_url + "/predict", {"adaptation_id": sid, "x_query": query},
            timeout_s=90.0,
        )
        if code != 200 or body.get("probs") != probs_refined:
            violations.append(
                f"refined session not intact after the cycle: {code}"
            )
        code, body, _ = _http_json(gw_url + "/adapt", refine_body,
                                   timeout_s=90.0)
        if code != 200 or body.get("refine_count") != 2:
            violations.append(
                f"refine lineage broken across the surge cycle: {code} "
                f"{body.get('refine_count')}"
            )
        # supervisor frame: counters + marker for obs_top auto-detect
        code, sup_metrics, _ = _http_json(sup_url + "/metrics", timeout_s=10.0)
        if (
            sup_metrics.get("counters", {}).get("scale_ups", 0) < 1
            or sup_metrics.get("counters", {}).get("scale_downs", 0) < 1
        ):
            violations.append(
                f"supervisor counters missing the cycle: "
                f"{sup_metrics.get('counters')}"
            )
    finally:
        _stop_supervisor(sup_proc, violations, "surge")
        _adopt_state_pids(state_path, procs)
    return violations


def _drill_fleet_crashloop(root, template_run, procs) -> List[str]:
    """Crash-safe control, both halves. (A) A die-on-spawn backend walks
    the bounded exponential-backoff ladder into quarantine — never
    respawned hot — while the fleet stays routable. (B) A supervisor
    kill -9'd mid-spawn (intent + pid journaled, warm gate unfinished)
    restarts, adopts the live fleet from the write-ahead journal, and
    settles the interrupted spawn — same pid, no double-spawn, no orphan."""
    violations: List[str] = []
    template = template_run or make_serving_run_dir(root, "template")
    run0 = make_serving_run_dir(root, "b0", template=template)
    run2 = make_serving_run_dir(root, "b2", template=template)
    port0, port1, port2 = _free_port(), _free_port(), _free_port()
    url0 = f"http://127.0.0.1:{port0}"
    url1 = f"http://127.0.0.1:{port1}"
    url2 = f"http://127.0.0.1:{port2}"
    proc0, _ = spawn_serve_backend(run0, port=port0)
    procs.append(proc0)
    _wait_http_ok(url0 + "/healthz", timeout_s=300.0, proc=proc0)
    threading.Thread(target=proc0.wait, daemon=True).start()
    gw_logs = os.path.join(root, "gateway", "logs")
    gw_proc, gw_url = spawn_gateway([url0, url1, url2], gw_logs)
    procs.append(gw_proc)
    _wait_http_ok(gw_url + "/healthz", timeout_s=30.0, proc=gw_proc)

    # --- leg A: crash-loop containment -------------------------------
    slots_a = [
        {"url": url0, "port": port0, "pid": proc0.pid,
         "respawn": backend_spawn_argv(run0, port0), "cwd": _REPO_ROOT,
         "log": os.path.join(run0, "serve_stdout.log"), "run_dir": run0},
        # slot 1 dies the instant it spawns: the ladder's worst case
        {"url": url1, "port": port1,
         "respawn": [sys.executable, "-c", "import sys; sys.exit(1)"],
         "cwd": _REPO_ROOT,
         "log": os.path.join(root, "crashloop_stdout.log")},
    ]
    slots_a_path = os.path.join(root, "slots_a.json")
    with open(slots_a_path, "w") as f:
        json.dump(slots_a, f)
    state_a = os.path.join(root, "fleet_state_a.json")
    events_a = os.path.join(root, "supervisor_events_a.jsonl")
    sup_a, _sup_a_url = _spawn_fleet_supervisor(
        root, "supervisor_a", state_a, gw_url, events_a, procs,
        slots_path=slots_a_path,
        # min_backends 2 forces spawn attempts into the crash-looping slot
        min_backends=2, max_backends=2, poll_interval_s=0.2,
        crash_max=3, crash_window_s=60.0,
        backoff_base_s=0.2, backoff_max_s=1.0, warm_timeout_s=30.0,
    )
    try:
        try:
            _wait_until(
                lambda: any(e.get("event") == "quarantine"
                            for e in _read_jsonl(events_a)),
                timeout_s=60.0, desc="crash-loop quarantine",
            )
        except RuntimeError as exc:
            violations.append(str(exc))
        events = _read_jsonl(events_a)
        crash_events = [e for e in events if e.get("event") == "spawn_crash"]
        if len(crash_events) != 2:  # crash_max 3 = 2 backoffs + quarantine
            violations.append(
                f"expected 2 spawn_crash events before quarantine, got "
                f"{len(crash_events)}"
            )
        backoffs = [e.get("backoff_s") for e in crash_events]
        if backoffs != sorted(backoffs) or len(set(backoffs)) != len(backoffs):
            violations.append(f"backoff ladder not increasing: {backoffs}")
        # quarantined means NEVER respawned hot: the event log must go
        # quiet for this slot
        before = len(_read_jsonl(events_a))
        time.sleep(2.0)
        after_events = _read_jsonl(events_a)
        new = [e for e in after_events[before:]
               if e.get("slot") == 1 and e.get("event") != "supervisor_stop"]
        if new:
            violations.append(f"quarantined slot kept getting actions: {new}")
        state = _read_fleet_state(state_a) or {}
        slot1 = next((s for s in state.get("slots", [])
                      if s.get("slot") == 1), {})
        if slot1.get("state") != "quarantined":
            violations.append(f"slot 1 not quarantined on disk: {slot1}")
        # the fleet is still routable around the quarantined slot
        s, _ = _adapt_payload(67)
        code, _b, _h = _http_json(gw_url + "/adapt", s, timeout_s=60.0)
        if code != 200:
            violations.append(f"fleet not routable during crash-loop: {code}")
    finally:
        _stop_supervisor(sup_a, violations, "crashloop-A")

    # --- leg B: kill -9 the supervisor mid-spawn ----------------------
    slots_b = [
        {"url": url0, "port": port0, "pid": proc0.pid,
         "respawn": backend_spawn_argv(run0, port0), "cwd": _REPO_ROOT,
         "log": os.path.join(run0, "serve_stdout.log"), "run_dir": run0},
        {"url": url2, "port": port2,
         "respawn": backend_spawn_argv(run2, port2), "cwd": _REPO_ROOT,
         "log": os.path.join(run2, "serve_stdout.log"), "run_dir": run2},
    ]
    slots_b_path = os.path.join(root, "slots_b.json")
    with open(slots_b_path, "w") as f:
        json.dump(slots_b, f)
    state_b = os.path.join(root, "fleet_state_b.json")
    events_b = os.path.join(root, "supervisor_events_b.jsonl")
    sup_b1, _ = _spawn_fleet_supervisor(
        root, "supervisor_b1", state_b, gw_url, events_b, procs,
        slots_path=slots_b_path,
        min_backends=2, max_backends=2, poll_interval_s=0.2,
        warm_timeout_s=300.0, warm_poll_s=0.25,
    )

    def _mid_spawn():
        # the pid is journaled right after Popen, long before the warm
        # gate settles — catching state "spawning" with a pid IS mid-spawn
        state = _read_fleet_state(state_b) or {}
        for slot in state.get("slots", []):
            if slot.get("slot") == 1 and slot.get("state") == "spawning" \
                    and slot.get("pid"):
                return slot["pid"]
        return None

    try:
        spawned_pid = _wait_until(_mid_spawn, timeout_s=120.0,
                                  desc="mid-spawn journal window",
                                  poll_s=0.02)
    except RuntimeError as exc:
        _stop_supervisor(sup_b1, violations, "crashloop-B1")
        _adopt_state_pids(state_b, procs)
        return violations + [str(exc)]
    procs.append(_FakeProc(spawned_pid))
    os.kill(sup_b1.pid, 9)  # the controller dies; the fleet must not care
    sup_b1.wait(timeout=30)
    state = _read_fleet_state(state_b) or {}
    if not (state.get("intent") or {}).get("action") == "spawn":
        violations.append(
            f"journal lost the in-flight spawn intent: {state.get('intent')}"
        )
    try:
        os.kill(spawned_pid, 0)
    except ProcessLookupError:
        violations.append("spawned backend died with its supervisor")
    # restart: the journal (not the slots file) is the source of truth
    sup_b2, _sup_b2_url = _spawn_fleet_supervisor(
        root, "supervisor_b2", state_b, gw_url, events_b, procs,
        min_backends=2, max_backends=2, poll_interval_s=0.2,
        warm_timeout_s=300.0, warm_poll_s=0.25,
    )
    try:
        try:
            _wait_until(
                lambda: (_read_fleet_state(state_b) or {}).get("intent") is None
                and next(
                    (s for s in (_read_fleet_state(state_b) or {}).get(
                        "slots", [])
                     if s.get("slot") == 1), {}
                ).get("state") == "up",
                timeout_s=300.0, desc="adopt-and-settle of the orphaned spawn",
            )
        except RuntimeError as exc:
            violations.append(str(exc))
        state = _read_fleet_state(state_b) or {}
        slot1 = next((s for s in state.get("slots", [])
                      if s.get("slot") == 1), {})
        if slot1.get("pid") != spawned_pid:
            violations.append(
                f"adopt respawned instead of settling: pid {slot1.get('pid')}"
                f" != journaled {spawned_pid} (double-spawn)"
            )
        rollforward = [e for e in _read_jsonl(events_b)
                       if e.get("event") == "adopt_rollforward"]
        if not any(e.get("outcome") == "spawn_settled" for e in rollforward):
            violations.append(
                f"no spawn_settled roll-forward event: {rollforward}"
            )
        try:
            _wait_until(
                lambda: _http_json(gw_url + "/metrics", timeout_s=10.0)[1]
                .get("backends_in") == 2,
                timeout_s=120.0, desc="gateway admits the adopted backend",
            )
        except RuntimeError as exc:
            violations.append(str(exc))
    finally:
        _stop_supervisor(sup_b2, violations, "crashloop-B2")
        _adopt_state_pids(state_b, procs)
    return violations


class _FakeProc:
    """pid-only handle so cleanup can SIGKILL processes we did not spawn
    directly (rolling_restart's respawned backends)."""

    def __init__(self, pid):
        self.pid = pid

    def poll(self):
        if self.pid is None:
            return 0
        try:
            os.kill(self.pid, 0)
        except (ProcessLookupError, PermissionError):
            return 0
        return None

    def kill(self):
        if self.pid is not None:
            try:
                os.kill(self.pid, 9)
            except (ProcessLookupError, PermissionError):
                pass

    def wait(self, timeout=None):
        deadline = time.monotonic() + (timeout or 0)
        while self.poll() is None and time.monotonic() < deadline:
            time.sleep(0.1)
        return 0


# ---------------------------------------------------------------------------
# the campaign
# ---------------------------------------------------------------------------


def run_campaign(
    work_dir: str,
    episodes: int = 8,
    seed: int = 0,
    data_root: Optional[str] = None,
    include_subprocess: bool = True,
    sanitize: bool = False,
    log: Callable[[str], None] = lambda m: print(m, file=sys.stderr, flush=True),
) -> Dict[str, Any]:
    """Run a seeded chaos campaign and return the one-line JSON verdict
    (also what ``scripts/chaos_soak.py`` prints). ``include_subprocess=False``
    drops the fork-a-fresh-interpreter episodes (rc=76 wedge, device-shrink)
    for fast in-process smokes; the CLI keeps them. ``sanitize=True`` arms
    the graftsan lock-discipline sanitizer (``tools/graftsan``) for the
    whole campaign — in-process episodes through the armed runtime,
    subprocess episodes through ``HTYMP_GRAFTSAN=1`` + a shared
    ``HTYMP_GRAFTSAN_LOG`` file under ``work_dir`` — and folds any
    lock-order / held-across-blocking / thread-leak findings into the
    verdict as campaign violations."""
    from ..config import save_config
    from ..experiment import ExperimentRunner

    os.makedirs(work_dir, exist_ok=True)
    graftsan_runtime = None
    graftsan_log = os.path.join(work_dir, "graftsan.jsonl")
    graftsan_prior_env: Dict[str, Optional[str]] = {}
    if sanitize:
        from tools.graftsan import runtime as graftsan_runtime

        # children inherit via _child_env's os.environ copy; the log file
        # is the only channel out of a fork-a-fresh-interpreter episode
        graftsan_prior_env = {
            k: os.environ.get(k)
            for k in ("HTYMP_GRAFTSAN", "HTYMP_GRAFTSAN_LOG")
        }
        os.environ["HTYMP_GRAFTSAN"] = "1"
        os.environ["HTYMP_GRAFTSAN_LOG"] = graftsan_log
        try:
            os.remove(graftsan_log)
        except FileNotFoundError:
            pass
        graftsan_runtime.arm()
        graftsan_runtime.reset()
    data_root = data_root or make_toy_dataset(os.path.join(work_dir, "toy_data"))
    exp_root = os.path.join(work_dir, "exps")
    plan = sample_episodes(seed, episodes, include_subprocess)
    template = None  # built lazily: one init_train_state serves every check
    results: List[Dict[str, Any]] = []
    violations: List[Dict[str, Any]] = []
    t0 = time.time()

    for i, ep in enumerate(plan):
        name = f"ep{i:02d}_{ep.kind}_s{seed}"
        log(f"chaos: episode {i + 1}/{len(plan)} {ep.kind} ({ep.mode})")
        ep_viol: List[str] = []
        rcs: List[int] = []
        run_dir = os.path.join(exp_root, name)

        if ep.mode == "serve":
            ep_viol += _run_serve_episode(ep)
        elif ep.mode == "gateway":
            # cross-process fleet drill: real gateway subprocess + real
            # serve-backend subprocesses, all state under work_dir
            ep_viol += _run_gateway_episode(ep, work_dir=work_dir)
        else:
            if (
                any("sigterm" in f for f in ep.faults)
                and threading.current_thread() is not threading.main_thread()
            ):
                # SIGTERM drills need the runner's main-thread handler; off
                # the main thread the default handler would kill the whole
                # campaign process
                log(f"chaos: skipping {ep.kind} off the main thread")
                results.append({"kind": ep.kind, "skipped": True})
                continue
            base = campaign_config(data_root, exp_root, name, **ep.config_overrides)
            faulted = dataclasses.replace(
                base,
                resilience=dataclasses.replace(
                    base.resilience,
                    faults=list(ep.faults),
                    **ep.resilience_overrides,
                ),
            )
            if ep.subprocess and ep.mode == "train":
                # tightened watchdog so the wedge drill resolves quickly —
                # but the deadline must still clear one COLD-cache XLA
                # compile (~10-20s on a 1-core box), or the drill fires
                # during healthy compile and goes green without ever testing
                # the injected hang. The clean legs keep the production
                # default entirely.
                faulted = dataclasses.replace(
                    faulted,
                    resilience=dataclasses.replace(
                        faulted.resilience,
                        watchdog=dataclasses.replace(
                            faulted.resilience.watchdog,
                            deadline_s=25.0,
                            poll_s=0.5,
                        ),
                    ),
                )

            def _run(cfg, in_subprocess: bool, n_devices: int = 8) -> int:
                if not in_subprocess:
                    return _run_train_inprocess(cfg)
                os.makedirs(run_dir, exist_ok=True)
                cfg_yaml = os.path.join(
                    run_dir, f"chaos_leg{len(rcs)}.yaml"
                )
                save_config(cfg, cfg_yaml)
                return _run_train_subprocess(cfg_yaml, n_devices=n_devices)

            fault_rc: Optional[int] = None
            if ep.mode == "train":
                fault_rc = _run(faulted, ep.subprocess)
                rcs.append(fault_rc)
                if ep.resume_after or fault_rc in exit_codes.RESTARTABLE_RCS:
                    # clean resume leg: the faulted run must have left a
                    # resumable run dir behind
                    rcs.append(_run(base, False))
            elif ep.mode == "resume":
                rcs.append(_run(base, False))  # produce the checkpoints
                fault_rc = _run(faulted, False)  # resume under the fault
                rcs.append(fault_rc)
            elif ep.mode == "shrink":
                # train on the full mesh, then resume with fewer visible
                # devices than ParallelConfig demands — the degraded-mesh
                # path must shrink and keep training, not crash
                rcs.append(_run(base, False))
                fault_rc = _run(
                    dataclasses.replace(base, total_epochs=3),
                    True,
                    n_devices=ep.resume_devices,
                )
                rcs.append(fault_rc)
            elif ep.mode == "grow":
                # shrink leg first (as above), then resume with every
                # device visible again: the grow-back path reshards the
                # state up, logs mesh_grown, and trains the extra epoch
                rcs.append(_run(base, False))
                rcs.append(
                    _run(
                        dataclasses.replace(base, total_epochs=3),
                        True,
                        n_devices=ep.resume_devices,
                    )
                )
                fault_rc = _run(
                    dataclasses.replace(base, total_epochs=4), True, n_devices=8
                )
                rcs.append(fault_rc)
            for rc in rcs:
                if rc not in DOCUMENTED_RCS:
                    ep_viol.append(f"undocumented rc {rc}")
            if fault_rc is not None and fault_rc not in ep.expected_rcs:
                ep_viol.append(
                    f"rc {fault_rc} not in expected {ep.expected_rcs} for {ep.kind}"
                )
            if (
                ep.resume_after or ep.mode in ("resume", "shrink", "grow")
            ) and rcs[-1] != 0:
                ep_viol.append(f"resume leg exited rc {rcs[-1]}")
            err = _check_events_jsonl(run_dir)
            if err:
                ep_viol.append(err)
            ep_viol.extend(_check_telemetry(run_dir))
            seen_events = _events_in(run_dir)
            for required in ep.required_events:
                if required not in seen_events:
                    ep_viol.append(f"missing required event {required!r}")
            if template is None:
                template = tiny_system(
                    campaign_config(data_root, exp_root, "_tmpl")
                ).init_train_state()
            err = _check_checkpoints(run_dir, template)
            if err:
                ep_viol.append(err)

        results.append(
            {"kind": ep.kind, "mode": ep.mode, "rcs": rcs, "violations": ep_viol}
        )
        for v in ep_viol:
            violations.append({"episode": i, "kind": ep.kind, "violation": v})

    sanitizer_summary: Optional[Dict[str, Any]] = None
    if sanitize:
        # restore the caller's env FIRST (a failed parse must not leave the
        # whole test process implicitly armed), then fold findings in
        for key, prior in graftsan_prior_env.items():
            if prior is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = prior
        graftsan_runtime.disarm()
        # the log file is the union: in-process _record writes it too (the
        # env var was set), so it covers subprocess episodes for free
        records: List[Dict[str, Any]] = []
        torn = 0
        try:
            with open(graftsan_log) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        records.append(json.loads(line))
                    except ValueError:
                        torn += 1  # a crash mid-write tears at most one line
        except FileNotFoundError:
            pass
        by_kind: Dict[str, int] = {}
        for rec in records:
            kind = rec.get("kind", "?")
            by_kind[kind] = by_kind.get(kind, 0) + 1
        sanitizer_summary = {
            "armed": True,
            "violations": len(records),
            "by_kind": by_kind,
            "torn_lines": torn,
            "log": graftsan_log,
        }
        for rec in records:
            detail = (
                " -> ".join(
                    s for s in (rec.get("site_a"), rec.get("site_b")) if s
                )
                or rec.get("blocking")
                or rec.get("context")
                or ""
            )
            violations.append(
                {
                    "episode": None,
                    "kind": "graftsan",
                    "violation": f"graftsan {rec.get('kind')}: {detail}".rstrip(
                        ": "
                    ),
                }
            )

    verdict = {
        "campaign": "chaos_soak",
        "seed": seed,
        "episodes": len(results),
        "ok": not violations,
        "violations": violations,
        "sanitizer": sanitizer_summary,
        "invariants": [
            "rc in {0,3,75,76}",
            "latest-or-fallback checkpoint loads",
            "events.jsonl well-formed",
            "serving never 200s a shed/failed payload",
            "telemetry.jsonl well-formed + exported traces balanced",
            "every non-200 HTTP response has an access-log line with its request id",
            "fleet: availability survives backend death; drain drops nothing; "
            "sessions rehydrate digest-verified, never stale",
        ],
        "episode_results": results,
        "elapsed_s": round(time.time() - t0, 1),
    }
    if sanitize:
        verdict["invariants"].append(
            "graftsan: zero lock-order / blocking-under-lock / thread-leak "
            "violations across every episode"
        )
    return verdict
