"""Repeated-batch descent probe: can the full meta-step (second order, MSL,
LSLR, outer Adam) descend on ONE fixed real 20-way batch? f32 vs exact
MXU-default emulation. Argv: [emulate?0/1] [n_way] [steps]"""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
import jax
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
import jax.numpy as jnp
emulate, n_way, steps = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
if emulate:
    from howtotrainyourmamlpytorch_tpu.models import layers as L
    _conv, _lin = L.conv2d, L.linear
    r = lambda a: a.astype(jnp.bfloat16).astype(jnp.float32)
    L.conv2d = lambda p, x, stride=1, padding=0: _conv(dict(p, w=r(p["w"])), r(x), stride=stride, padding=padding)
    L.linear = lambda p, x: r(x) @ r(p["w"]) + p["b"]
from howtotrainyourmamlpytorch_tpu.config import Config, DatasetConfig
from howtotrainyourmamlpytorch_tpu.core import MAMLSystem
from howtotrainyourmamlpytorch_tpu.data import MetaLearningDataLoader
cfg = Config(dataset=DatasetConfig(name="omniglot_dataset", path="datasets/omniglot_dataset"),
             num_classes_per_set=n_way, num_samples_per_class=1, num_target_samples=1,
             batch_size=4, load_into_memory=False, index_cache_dir="/tmp/omniglot_idx",
             unroll_inner_steps=False, remat_inner_steps=False)
loader = MetaLearningDataLoader(cfg, current_iter=0, data_root="/root/reference")
batch = next(iter(loader.train_batches(1, augment_images=True)))
batch = {k: jnp.asarray(v) for k, v in batch.items()}
system = MAMLSystem(cfg)
state = system.init_train_state()
print(f"emulate={emulate} n_way={n_way} backend={jax.default_backend()}", flush=True)
for i in range(steps):
    state, out = system.train_step(state, batch, epoch=0)
    if i % 10 == 0 or i == steps - 1:
        print(f"step {i:3d} loss={float(out.loss):.4f} acc={float(out.accuracy):.4f}", flush=True)
