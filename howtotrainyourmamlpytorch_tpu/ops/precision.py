"""Mixed-precision policy: THE home of float-dtype cast boundaries on the
hot path (ROADMAP item 3 — push MFU from 8% toward the hardware).

The meta-step's inner rollout is a long chain of small convs and tiny
per-tensor updates — exactly the regime where a bf16 MXU path pays off. But
MAML++'s meta-gradient is a small residual of large terms: the *accumulation*
points (BN batch statistics, loss/log-softmax reductions, the MSL-weighted
outer loss, the outer Adam state) must stay f32 or the second-order signal
drowns in rounding (the 20-way collapse family, scripts/grad_precision_probe.py).
The :class:`PrecisionPolicy` encodes that split once:

- **f32** (the default, ``Config.precision.enabled=false`` +
  ``compute_dtype="float32"``): every cast helper is the identity on f32
  inputs — the traced programs are bit-identical to a build without this
  module.
- **legacy_bf16** (``compute_dtype="bfloat16"`` with the precision block
  off): the pre-ISSUE-9 behavior, preserved exactly — params and inputs cast
  to bf16 per forward, BN statistics in the compute dtype, fast-weight math
  in f32 (the inner grads are taken w.r.t. the f32 masters).
- **bf16_inner** (``Config.precision.enabled=true``): the principled policy.
  Params and LSLR lrs stay f32 *master* copies in the ``TrainState``; the
  fast weights (and the differentiable inner-optimizer state) are cast to
  bf16 ONCE at rollout entry, so the whole inner forward/backward/update
  chain runs in bf16 — half the HBM traffic, single-pass MXU — while BN
  statistics and every loss reduction run in ``stat_dtype`` (f32) and the
  meta-gradient accumulates in f32 through the (differentiable) entry cast.

Every float-dtype cast on the hot path lives here or is parameterized from
here (``stat_dtype`` threaded into ``models/layers.py::batch_norm``, the lr
column of ``ops/pallas_update.py``); graftlint rule GL140 pins the hot-path
modules to exactly that — a literal ``.astype(jnp.float32)`` anywhere else in
``models/ core/ ops/ serving/`` is a finding.
"""

from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp


def as_f32(x):
    """The sanctioned f32 upcast for metric/reduction math (accuracy masks,
    loss reductions, the Pallas lr column). Hot-path modules call this
    instead of spelling ``.astype(jnp.float32)`` so GL140 can pin every
    other float cast to this module."""
    return jnp.asarray(x).astype(jnp.float32)


def cast_tree(tree, dtype):
    """Cast every float leaf of a pytree to ``dtype``; integer/bool leaves
    (labels, step counters) pass through untouched. Differentiable: the
    cast's transpose casts cotangents back, so meta-gradients w.r.t. the f32
    masters accumulate in f32."""

    def leaf(a):
        a = jnp.asarray(a)
        if jnp.issubdtype(a.dtype, jnp.inexact):
            return a.astype(dtype)
        return a

    return jax.tree.map(leaf, tree)


class PrecisionPolicy(NamedTuple):
    """Static cast-boundary description threaded through ``MAMLSystem``,
    the model applies, and the serving engine (train and serve share the one
    policy the system was built with)."""

    name: str
    # dtype the model forward (and, under ``cast_inner``, the whole inner
    # loop) runs in
    compute_dtype: Any = jnp.float32
    # dtype BN batch statistics are reduced in; None = the input dtype
    # (the f32 and legacy paths — no extra casts in the traced program)
    stat_dtype: Optional[Any] = None
    # cast fast weights + inner-optimizer state ONCE at rollout entry (the
    # bf16_inner policy); False = the masters' dtype flows through the loop
    cast_inner: bool = False

    # ------------------------------------------------------------------

    @property
    def logits_dtype(self):
        """The dtype :meth:`cast_logits` exits in — and therefore the dtype
        any scan carry holding logits (the MSL per-step-target rollout's
        ``logits0``) must be built in explicitly, so the carry dtype is
        pinned by the policy rather than by promotion accident."""
        return jnp.float32

    def cast_forward_inputs(self, params, x):
        """Entry cast of one model forward: params + input batch to the
        compute dtype. Identity (no ops traced) when compute is f32 — and a
        no-op re-cast when the fast weights already arrive in the compute
        dtype (the bf16_inner rollout)."""
        cdt = self.compute_dtype
        if cdt != jnp.float32:
            params = cast_tree(params, cdt)
            x = x.astype(cdt)
        return params, x

    def cast_logits(self, logits):
        """Exit cast: logits to f32 so the loss/log-softmax reduction always
        runs in full precision, whatever the forward ran in."""
        return as_f32(logits)

    def cast_fast_weights(self, tree):
        """Rollout-entry cast of the fast-weight pytree (and the
        differentiable inner-optimizer state): bf16 under the bf16_inner
        policy, identity otherwise. The f32 master copies in the TrainState
        are never touched — this cast is a node in the meta-gradient graph."""
        if not self.cast_inner:
            return tree
        return cast_tree(tree, self.compute_dtype)

    def describe(self) -> Dict[str, Any]:
        """JSON-able summary for bench lines / serving metrics."""
        return {
            "name": self.name,
            "compute_dtype": jnp.dtype(self.compute_dtype).name,
            "stat_dtype": (
                None if self.stat_dtype is None else jnp.dtype(self.stat_dtype).name
            ),
            "cast_inner": self.cast_inner,
        }


F32 = PrecisionPolicy(name="f32")


def policy_from_config(cfg) -> PrecisionPolicy:
    """Resolve the one policy a system (train or serve) runs under.

    ``Config.precision.enabled`` selects the principled bf16_inner policy;
    with the block off, the legacy ``compute_dtype`` knob keeps its exact
    pre-policy semantics (per-forward operand cast, statistics in the
    compute dtype) so existing configs and the flagship bench recipe are
    bit-identical to before this module existed."""
    pc = getattr(cfg, "precision", None)
    if pc is not None and pc.enabled:
        if pc.compute_dtype == "float32":
            # an explicitly-enabled f32 policy degenerates to the plain path
            return F32
        return PrecisionPolicy(
            name="bf16_inner",
            compute_dtype=jnp.bfloat16,
            stat_dtype=jnp.float32 if pc.stat_dtype == "float32" else None,
            cast_inner=True,
        )
    if cfg.compute_dtype == "bfloat16":
        return PrecisionPolicy(name="legacy_bf16", compute_dtype=jnp.bfloat16)
    return F32
