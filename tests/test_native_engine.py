"""Native C++ episode-assembly engine: build, rot90/normalize parity with
numpy, and bit-exact agreement between the batched native path and the
per-episode numpy path (same RandomState stream)."""

import numpy as np
import pytest
from PIL import Image

from howtotrainyourmamlpytorch_tpu import native
from howtotrainyourmamlpytorch_tpu.config import Config, DatasetConfig
from howtotrainyourmamlpytorch_tpu.data import FewShotDataset, MetaLearningDataLoader


def _engine_or_skip():
    lib = native.load_engine()
    if lib is None:
        pytest.skip("g++ toolchain unavailable; numpy fallback covers behavior")
    return lib


def test_rot90_parity_all_k():
    _engine_or_skip()
    rng = np.random.RandomState(0)
    cache = rng.rand(8, 6, 6, 3).astype(np.float32)
    # one episode, 4 classes, one image each; class ci uses rotation ci
    image_idx = np.arange(4, dtype=np.int64).reshape(1, 4, 1)
    rot_k = np.arange(4, dtype=np.int32).reshape(1, 4)
    out = native.assemble_episodes(cache, image_idx, rot_k, num_threads=2)
    for ci in range(4):
        expected = np.rot90(cache[ci], k=ci, axes=(0, 1))
        np.testing.assert_array_equal(out[0, ci, 0], expected)


def test_normalization_parity():
    _engine_or_skip()
    rng = np.random.RandomState(1)
    cache = rng.rand(6, 5, 5, 3).astype(np.float32)
    mean = np.array([0.4, 0.5, 0.6], np.float32)
    std = np.array([0.2, 0.25, 0.3], np.float32)
    image_idx = np.array([[[0, 3], [5, 1]]], np.int64)  # [1, 2, 2]
    rot_k = np.zeros((1, 2), np.int32)
    out = native.assemble_episodes(cache, image_idx, rot_k, mean=mean, std=std)
    # bit-exact with the numpy fallback's (arr - mean) / std — the native
    # kernel divides rather than multiplying by a reciprocal on purpose
    expected = (cache[image_idx[0]] - mean) / std
    np.testing.assert_array_equal(out[0], expected)


def test_odd_rotation_of_non_square_rejected():
    _engine_or_skip()
    cache = np.zeros((2, 4, 6, 1), np.float32)
    image_idx = np.zeros((1, 1, 1), np.int64)
    with pytest.raises(ValueError):
        native.assemble_episodes(cache, image_idx, np.ones((1, 1), np.int32))
    # even rotations of non-square images are fine
    out = native.assemble_episodes(cache, image_idx, 2 * np.ones((1, 1), np.int32))
    assert out.shape == (1, 1, 1, 4, 6, 1)


def test_threaded_matches_single_thread():
    _engine_or_skip()
    rng = np.random.RandomState(2)
    cache = rng.rand(40, 8, 8, 1).astype(np.float32)
    image_idx = rng.randint(0, 40, size=(4, 5, 3)).astype(np.int64)
    rot_k = rng.randint(0, 4, size=(4, 5)).astype(np.int32)
    a = native.assemble_episodes(cache, image_idx, rot_k, num_threads=1)
    b = native.assemble_episodes(cache, image_idx, rot_k, num_threads=8)
    np.testing.assert_array_equal(a, b)


@pytest.fixture(scope="module")
def omniglot_like(tmp_path_factory):
    root = tmp_path_factory.mktemp("native_ds") / "omniglot_toy"
    rng = np.random.RandomState(0)
    for a in range(4):
        for c in range(4):  # 16 classes: 8 train / 4 val / 4 test
            d = root / f"alphabet{a}" / f"char{c}"
            d.mkdir(parents=True)
            for i in range(6):
                arr = (rng.rand(28, 28) > 0.5).astype(np.uint8) * 255
                Image.fromarray(arr, mode="L").convert("1").save(d / f"{i}.png")
    cfg = Config(
        dataset=DatasetConfig(name="omniglot_toy", path=str(root)),
        num_classes_per_set=4,
        num_samples_per_class=2,
        num_target_samples=1,
        batch_size=3,
        load_into_memory=True,
        train_val_test_split=(0.5, 0.25, 0.25),
    )
    return cfg, FewShotDataset(cfg)


def test_batched_native_path_bit_exact_vs_per_episode(omniglot_like):
    _engine_or_skip()
    cfg, ds = omniglot_like
    assert ds.packed  # packed cache built
    for augment in (False, True):
        seeds = [ds.episode_seed("train", i) for i in range(cfg.batch_size)]
        batch = ds.sample_episode_batch("train", seeds, augment=augment)
        assert batch is not None
        for b, seed in enumerate(seeds):
            ep = ds.sample_episode("train", seed, augment=augment)
            for key in ep:
                np.testing.assert_array_equal(batch[key][b], ep[key], err_msg=key)


def test_loader_uses_native_path_and_is_deterministic(omniglot_like, monkeypatch):
    _engine_or_skip()
    cfg, ds = omniglot_like
    calls = {"batch": 0}
    orig = ds.sample_episode_batch
    monkeypatch.setattr(
        ds, "sample_episode_batch",
        lambda *a, **kw: calls.__setitem__("batch", calls["batch"] + 1) or orig(*a, **kw),
    )
    monkeypatch.setattr(
        ds, "sample_episode",
        lambda *a, **kw: pytest.fail("loader fell back to the per-episode path"),
    )
    loader = MetaLearningDataLoader(cfg, dataset=ds)
    b1 = next(iter(loader.val_batches(1)))
    b2 = next(iter(loader.val_batches(1)))
    assert calls["batch"] == 2  # native batch path actually served both
    assert b1["x_support"].shape == (3, 4, 2, 28, 28, 1)
    assert all(v.flags["C_CONTIGUOUS"] for v in b1.values())
    for key in b1:
        np.testing.assert_array_equal(b1[key], b2[key])
    loader.close()
