"""Self-healing fleet supervisor: traffic-adaptive autoscaling with
crash-safe control and predictive prewarm (ROADMAP item 3 — "the fleet
closes its own loop").

One single-threaded control loop owns backend lifecycle end to end:

- **Reactive.** Each tick polls the gateway's ``/metrics`` (shed/429 rate,
  per-backend membership + flaps) and every IN backend's ``/metrics``
  (batcher queue depth, ``tenants.pager`` evictions / ``page_in_p50_ms``)
  through hysteresis windows — ``up_polls`` consecutive breach ticks to
  scale up, ``down_polls`` consecutive clear ticks to scale down — with
  independent per-direction cooldowns so noise cannot flap the fleet.
  Scale-up spawns into a pre-provisioned port slot and gates on ``/healthz``
  200 past "warming"; a spawn that dies is retried on a bounded exponential
  backoff ladder, and a slot that crashes ``crash_max`` times inside
  ``crash_window_s`` is quarantined with an event and never respawned hot.
  Scale-down gracefully drains the lowest-ranked backend (highest slot
  index), observes + reports the drain rc (0 clean / 77 deadline) and the
  session spill, and never goes below ``min_backends``. A backend that
  disappears without being asked (kill -9) is seen as a dead pid / gateway
  OUT transition and replaced through the same ladder.

- **Crash-safe.** Every intended action is journaled write-ahead to
  ``fleet_state.json`` (atomic tmp+rename via ``fleetctl``): intent → act →
  settle. A supervisor killed mid-spawn or mid-drain restarts, adopts
  still-running backends by pid/port liveness probe, rolls the interrupted
  intent forward (settle the spawn, re-issue the drain) or reaps/adopts the
  orphan a dead supervisor left on a slot's port. The controller is allowed
  to die; the fleet must not care — backends are never killed on supervisor
  exit.

- **Predictive.** Every ``forecast_interval_s`` the (bucket × verb) traffic
  mix is re-read from ``access.jsonl`` over a sliding window; when the
  tuned edges (``buckets.py`` exact DP solver) would cut padding waste past
  ``retune_waste_improvement``, the override strings are parked and
  prewarmed on the NEXT spawned backend (``serving.*_buckets=[...]`` argv
  overrides) — never a live-backend recompile, so sealed strict-mode guards
  stay sealed.

Import-light BY CONTRACT (stdlib only, like the gateway): file-path-loads
its siblings ``fleetctl.py`` and ``buckets.py``; never imports jax, yaml,
or the package. Every collaborator (clock, sleep, HTTP fetch, spawn, drain,
pid probe) is injectable so tests/test_autoscaler.py drives the whole
decision matrix on a fake clock with zero subprocesses.
"""

# graftlint: import-light — file-path-loaded by scripts/fleet_serve.py on supervisor hosts (GL213 gates the closure)
import json
import os
import signal as _signal
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

try:  # graftsan lock factory — needs the repo root on sys.path
    from tools.graftsan.runtime import san_lock
except ImportError:  # gateway-only host: sanitizer off, stdlib primitive

    def san_lock(site=None):
        return threading.Lock()

_HERE = os.path.dirname(os.path.abspath(__file__))


def _load_by_path(name: str, path: str):
    import importlib.util

    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


fleetctl = _load_by_path("htymp_fleetctl_as", os.path.join(_HERE, "fleetctl.py"))
buckets = _load_by_path("htymp_buckets_as", os.path.join(_HERE, "buckets.py"))

RC_OK, RC_USAGE = fleetctl.RC_OK, fleetctl.RC_USAGE


class Policy:
    """The supervisor's knobs — a validated attribute bag (stdlib, no
    dataclasses-with-yaml: the import-light contract). Defaults are pinned
    equal to ``config.AutoscaleConfig`` by test so the two can't drift."""

    DEFAULTS = dict(
        min_backends=1,
        max_backends=4,
        poll_interval_s=2.0,
        up_polls=2,
        down_polls=5,
        cooldown_up_s=10.0,
        cooldown_down_s=60.0,
        queue_high=8.0,
        queue_low=1.0,
        shed_high=0.05,
        evict_high=5,
        page_in_p50_high_ms=0.0,
        warm_timeout_s=300.0,
        warm_poll_s=0.5,
        drain_timeout_s=60.0,
        crash_max=3,
        crash_window_s=60.0,
        backoff_base_s=0.5,
        backoff_max_s=30.0,
        forecast_interval_s=30.0,
        forecast_window_s=300.0,
        forecast_min_requests=20,
        retune_waste_improvement=0.10,
        max_buckets=4,
    )

    def __init__(self, **overrides):
        unknown = set(overrides) - set(self.DEFAULTS)
        if unknown:
            raise ValueError(f"unknown policy knobs: {sorted(unknown)}")
        for key, default in self.DEFAULTS.items():
            setattr(self, key, overrides.get(key, default))
        if self.min_backends < 0:
            raise ValueError("min_backends must be >= 0")
        if self.max_backends < max(1, self.min_backends):
            raise ValueError("max_backends must be >= max(1, min_backends)")
        for knob in ("up_polls", "down_polls", "crash_max"):
            if getattr(self, knob) < 1:
                raise ValueError(f"{knob} must be >= 1")
        for knob in ("poll_interval_s", "warm_timeout_s", "drain_timeout_s",
                     "backoff_base_s", "crash_window_s"):
            if getattr(self, knob) <= 0:
                raise ValueError(f"{knob} must be > 0")


def _default_fetch(url: str, timeout_s: float = 3.0) -> Optional[Dict[str, Any]]:
    """GET ``url`` as JSON; None on any transport/parse failure — the
    supervisor treats an unreachable scrape as 'no signal', never a crash."""
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            out = json.loads(resp.read())
            return out if isinstance(out, dict) else None
    except (urllib.error.URLError, OSError, ValueError):
        return None


def find_pid_by_port(port: int) -> Optional[int]:
    """Locate the pid LISTENing on ``port`` via /proc (Linux): the
    port-liveness half of adopt-on-restart, for the orphan a supervisor
    killed between Popen and journaling the pid left behind. None when the
    scan is unavailable (non-Linux) or nothing is listening."""
    try:
        inodes = set()
        for path in ("/proc/net/tcp", "/proc/net/tcp6"):
            try:
                with open(path) as f:
                    lines = f.read().splitlines()[1:]
            except OSError:
                continue
            for line in lines:
                parts = line.split()
                if len(parts) < 10 or parts[3] != "0A":  # 0A = LISTEN
                    continue
                try:
                    local_port = int(parts[1].rsplit(":", 1)[1], 16)
                except (IndexError, ValueError):
                    continue
                if local_port == int(port):
                    inodes.add(parts[9])
        if not inodes:
            return None
        targets = {f"socket:[{inode}]" for inode in inodes}
        for pid_dir in os.listdir("/proc"):
            if not pid_dir.isdigit():
                continue
            fd_dir = os.path.join("/proc", pid_dir, "fd")
            try:
                fds = os.listdir(fd_dir)
            except OSError:
                continue
            for fd in fds:
                try:
                    link = os.readlink(os.path.join(fd_dir, fd))
                except OSError:
                    continue
                if link in targets:
                    return int(pid_dir)
    except Exception:
        return None
    return None


class Supervisor:
    """The control loop. All state mutations happen under ``self._lock``
    (the /metrics endpoint reads from another thread); blocking waits
    (warm gate, drain) run with the lock released."""

    def __init__(
        self,
        state_path: str,
        policy: Policy,
        gateway_url: Optional[str] = None,
        *,
        events_path: Optional[str] = None,
        access_log: Optional[str] = None,
        current_support: Optional[List[int]] = None,
        current_query: Optional[List[int]] = None,
        clock=time.monotonic,
        wall=time.time,
        sleep=time.sleep,
        fetch=_default_fetch,
        spawn=None,
        drain=None,
        probe=fleetctl.healthz,
        pid_alive=None,
        kill9=None,
        port_pid=find_pid_by_port,
        log=lambda m: print(m, file=sys.stderr, flush=True),
    ):
        self.state_path = state_path
        self.policy = policy
        self.gateway_url = gateway_url.rstrip("/") if gateway_url else None
        self.events_path = events_path
        self.access_log = access_log
        self.current_support = list(current_support or [])
        self.current_query = list(current_query or [])
        self.clock, self.wall, self.sleep = clock, wall, sleep
        self.fetch = fetch
        self.spawn = spawn or self._default_spawn
        self.drain = drain or self._default_drain
        self.probe = probe
        self.pid_alive = pid_alive or self._default_pid_alive
        self.kill9 = kill9 or self._default_kill9
        self.port_pid = port_pid
        self.log = log

        self._lock = san_lock("Supervisor._lock")
        self._events_lock = san_lock("Supervisor._events_lock")
        self.state: Dict[str, Any] = {"slots": [], "intent": None, "target": 0}
        self.counters = {
            "ticks": 0, "scale_ups": 0, "scale_downs": 0, "crashes": 0,
            "quarantines": 0, "replacements": 0, "retunes": 0, "adopted": 0,
        }
        self._stop = threading.Event()
        self._started = self.clock()
        self._up_streak = 0
        self._down_streak = 0
        self._last_up_ts: Optional[float] = None
        self._last_down_ts: Optional[float] = None
        self._last_forecast_ts: Optional[float] = None
        self._last_signals: Dict[str, Any] = {}
        self._last_decision: Optional[Dict[str, Any]] = None
        self._pending_overrides: List[str] = []
        self._prev_gw: Optional[Dict[str, int]] = None
        self._prev_evictions: Optional[int] = None
        self._reaped_rcs: Dict[int, int] = {}

    # -- default collaborators (real processes) ------------------------

    def _default_spawn(self, entry: Dict[str, Any], extra_argv) -> int:
        return fleetctl.spawn_backend(entry, extra_argv).pid

    def _default_drain(self, entry: Dict[str, Any], timeout_s: float) -> dict:
        return fleetctl.drain_backend(entry, timeout_s, log=self.log)

    def _default_pid_alive(self, pid: int) -> bool:
        # reap first: an unreaped child zombie still answers kill(pid, 0)
        try:
            reaped, status = os.waitpid(pid, os.WNOHANG)
            if reaped == pid:
                with self._lock:
                    self._reaped_rcs[pid] = os.waitstatus_to_exitcode(status)
                return False
        except (ChildProcessError, OSError):
            pass
        if pid in self._reaped_rcs:
            return False
        return fleetctl.pid_alive(pid)

    def _default_kill9(self, pid: int) -> None:
        try:
            os.kill(pid, _signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass

    # -- state / journal -----------------------------------------------

    def load_or_init(self, slots: Optional[List[Dict[str, Any]]] = None) -> str:
        """Resume from an existing journal (adopt the live fleet, roll the
        interrupted intent forward) or initialize from a slot template.
        Returns "adopted" or "initialized"."""
        if os.path.exists(self.state_path):
            state = fleetctl.load_fleet_state(self.state_path)
            with self._lock:
                self.state = state
            self.adopt()
            return "adopted"
        if not slots:
            raise ValueError(f"no fleet state at {self.state_path} and no slots")
        norm = []
        for i, slot in enumerate(slots):
            entry = dict(slot)
            entry.setdefault("slot", i)
            entry.setdefault("state", "up" if entry.get("pid") else "down")
            entry.setdefault("pid", None)
            norm.append(entry)
        with self._lock:
            self.state = {
                "version": fleetctl.FLEET_STATE_VERSION,
                "slots": norm,
                "intent": None,
                "target": max(
                    self.policy.min_backends,
                    sum(1 for s in norm if s["state"] == "up"),
                ),
            }
        self._save()
        self._event("supervisor_start", slots=len(norm),
                    target=self.state["target"], mode="initialized")
        return "initialized"

    def _save(self) -> None:
        with self._lock:
            state = dict(self.state)
        fleetctl.save_fleet_state(self.state_path, state)

    def _event(self, name: str, **fields) -> None:
        record = {"ts": self.wall(), "event": name,
                  "component": "supervisor", **fields}
        if name in ("scale_up", "scale_down", "spawn_crash", "quarantine",
                    "backend_died", "retune", "adopt_rollforward"):
            with self._lock:
                self._last_decision = record
        if self.events_path:
            with self._events_lock:
                with open(self.events_path, "a") as f:
                    f.write(json.dumps(record) + "\n")

    def _begin_intent(self, action: str, slot_id: int) -> None:
        with self._lock:
            next_id = int(self.state.get("next_intent_id", 0))
            self.state["next_intent_id"] = next_id + 1
            self.state["intent"] = {
                "id": next_id, "action": action, "slot": slot_id,
                "ts": self.wall(),
            }
        self._save()

    def _settle_intent(self) -> None:
        with self._lock:
            self.state["intent"] = None
        self._save()

    def _slot_by_id(self, slot_id: int) -> Optional[Dict[str, Any]]:
        for slot in self.state["slots"]:
            if slot.get("slot") == slot_id:
                return slot
        return None

    def _running(self) -> int:
        return sum(
            1 for s in self.state["slots"] if s.get("state") in ("up", "spawning")
        )

    # -- signal collection ---------------------------------------------

    def collect_signals(self) -> Dict[str, Any]:
        s: Dict[str, Any] = {
            "gateway": False, "backends_in": None, "requests_delta": 0,
            "shed_delta": 0, "shed_rate": None, "flap_delta": 0,
            "queue_depth": None, "evict_delta": 0, "page_in_p50_ms": None,
            "out_urls": [],
        }
        if self.gateway_url:
            gm = self.fetch(self.gateway_url + "/metrics")
            if gm and gm.get("gateway"):
                s["gateway"] = True
                s["backends_in"] = gm.get("backends_in")
                requests = int(gm.get("requests", 0))
                shed = int(gm.get("admission_shed", 0)) + int(gm.get("no_backend", 0))
                flaps = sum(
                    int(b.get("flaps", 0)) for b in gm.get("backends") or []
                    if isinstance(b, dict)
                )
                if self._prev_gw is not None:
                    s["requests_delta"] = max(0, requests - self._prev_gw["requests"])
                    s["shed_delta"] = max(0, shed - self._prev_gw["shed"])
                    s["flap_delta"] = max(0, flaps - self._prev_gw["flaps"])
                    denom = s["requests_delta"]
                    s["shed_rate"] = (
                        round(s["shed_delta"] / denom, 4) if denom else
                        (1.0 if s["shed_delta"] else 0.0)
                    )
                self._prev_gw = {"requests": requests, "shed": shed, "flaps": flaps}
                s["out_urls"] = [
                    b.get("url") for b in gm.get("backends") or []
                    if isinstance(b, dict) and b.get("state") == "out"
                ]
        queue_max: Optional[float] = None
        evictions = 0
        saw_pager = False
        p50s: List[float] = []
        with self._lock:
            up_slots = [dict(s2) for s2 in self.state["slots"]
                        if s2.get("state") == "up"]
        for slot in up_slots:
            bm = self.fetch(slot["url"].rstrip("/") + "/metrics")
            if not bm:
                continue
            for kind in ("adapt_batcher", "predict_batcher"):
                depth = (bm.get(kind) or {}).get("queue_depth")
                if isinstance(depth, (int, float)):
                    queue_max = max(queue_max or 0, depth)
            pager = (bm.get("tenants") or {}).get("pager") or {}
            if isinstance(pager.get("evictions"), int):
                saw_pager = True
                evictions += pager["evictions"]
            if isinstance(pager.get("page_in_p50_ms"), (int, float)):
                p50s.append(pager["page_in_p50_ms"])
        s["queue_depth"] = queue_max
        if saw_pager:
            if self._prev_evictions is not None:
                s["evict_delta"] = max(0, evictions - self._prev_evictions)
            self._prev_evictions = evictions
        if p50s:
            s["page_in_p50_ms"] = max(p50s)
        with self._lock:
            self._last_signals = s
        return s

    def _breach_reasons(self, s: Dict[str, Any]) -> List[str]:
        p = self.policy
        reasons = []
        if s["queue_depth"] is not None and s["queue_depth"] >= p.queue_high:
            reasons.append(f"queue_depth {s['queue_depth']} >= {p.queue_high}")
        if s["shed_rate"] is not None and s["shed_delta"] > 0 \
                and s["shed_rate"] >= p.shed_high:
            reasons.append(f"shed_rate {s['shed_rate']} >= {p.shed_high}")
        if p.evict_high > 0 and s["evict_delta"] >= p.evict_high:
            reasons.append(f"pager_evictions +{s['evict_delta']} >= {p.evict_high}")
        if p.page_in_p50_high_ms > 0 and s["page_in_p50_ms"] is not None \
                and s["page_in_p50_ms"] >= p.page_in_p50_high_ms:
            reasons.append(
                f"page_in_p50_ms {s['page_in_p50_ms']} >= {p.page_in_p50_high_ms}"
            )
        return reasons

    def _is_clear(self, s: Dict[str, Any]) -> bool:
        return bool(
            s["gateway"]
            and s["queue_depth"] is not None
            and s["queue_depth"] <= self.policy.queue_low
            and s["shed_delta"] == 0
            and s["evict_delta"] == 0
        )

    # -- actions --------------------------------------------------------

    def _spawnable_slot(self) -> Optional[Dict[str, Any]]:
        now = self.wall()
        with self._lock:
            for slot in self.state["slots"]:
                if slot.get("state") != "down":
                    continue
                if not slot.get("respawn"):
                    continue
                if slot.get("next_spawn_ts") and now < slot["next_spawn_ts"]:
                    continue
                return slot
        return None

    def _accepts_overrides(self, slot: Dict[str, Any]) -> bool:
        if "accepts_overrides" in slot:
            return bool(slot["accepts_overrides"])
        return any("serve.py" in str(part) for part in slot.get("respawn") or [])

    def _await_warm(self, slot: Dict[str, Any]) -> str:
        """Block until the spawned backend answers /healthz 200 (past
        "warming"), dies, or times out. -> "up" | "crash" | "warm_timeout"."""
        deadline = self.clock() + self.policy.warm_timeout_s
        while self.clock() < deadline:
            if self._stop.is_set():
                return "interrupted"
            pid = slot.get("pid")
            if pid and not self.pid_alive(pid):
                return "crash"
            code, _ = self.probe(slot["url"])
            if code == 200:
                return "up"
            self.sleep(self.policy.warm_poll_s)
        return "warm_timeout"

    def _record_crash(self, slot: Dict[str, Any], reason: str) -> str:
        """Crash-ladder bookkeeping: prune the window, add this death,
        quarantine at ``crash_max`` or schedule the backed-off retry."""
        p = self.policy
        now = self.wall()
        with self._lock:
            crashes = [t for t in slot.get("crashes", [])
                       if now - t <= p.crash_window_s]
            crashes.append(now)
            slot["crashes"] = crashes
            slot["pid"] = None
            self.counters["crashes"] += 1
            attempts = len(crashes)
            if attempts >= p.crash_max:
                slot["state"] = "quarantined"
                self.counters["quarantines"] += 1
            else:
                slot["state"] = "down"
                backoff = min(p.backoff_max_s,
                              p.backoff_base_s * (2 ** (attempts - 1)))
                slot["next_spawn_ts"] = now + backoff
        if attempts >= p.crash_max:
            self._event("quarantine", slot=slot["slot"], reason=reason,
                        crashes=attempts, window_s=p.crash_window_s)
            self.log(f"autoscaler: slot {slot['slot']} QUARANTINED after "
                     f"{attempts} crashes in {p.crash_window_s}s ({reason})")
            return "quarantined"
        self._event("spawn_crash", slot=slot["slot"], reason=reason,
                    crashes=attempts, backoff_s=round(backoff, 3))
        return "backoff"

    def _spawn_into(self, slot: Dict[str, Any], reason: str,
                    signals: Optional[Dict[str, Any]] = None) -> str:
        """Write-ahead journaled spawn + warm gate. -> "up" | "backoff" |
        "quarantined"."""
        extra = None
        applied_overrides = None
        with self._lock:
            if self._pending_overrides and self._accepts_overrides(slot):
                extra = list(self._pending_overrides)
                applied_overrides = extra
            slot["state"] = "spawning"
        self._begin_intent("spawn", slot["slot"])  # one write-ahead save
        # carries both the intent and the slot's "spawning" state
        t0 = self.clock()
        try:
            pid = int(self.spawn(slot, extra))
        except Exception as exc:
            outcome = self._record_crash(slot, reason=f"spawn raised: {exc}")
            self._settle_intent()
            return outcome
        with self._lock:
            slot["pid"] = pid
        self._save()  # the pid is journaled before the warm wait: a
        # supervisor killed here restarts and adopts this backend by pid
        warm = self._await_warm(slot)
        settle_s = round(self.clock() - t0, 2)
        if warm == "up":
            with self._lock:
                slot["state"] = "up"
                slot["crashes"] = []
                slot.pop("next_spawn_ts", None)
                if applied_overrides:
                    slot["overrides"] = applied_overrides
            if applied_overrides:
                self._apply_retune(applied_overrides)
            self._settle_intent()
            self._event("scale_up", slot=slot["slot"], reason=reason,
                        signals=signals, outcome="up", settle_s=settle_s,
                        pid=pid, overrides=applied_overrides or [])
            self.log(f"autoscaler: slot {slot['slot']} up (pid {pid}, "
                     f"{settle_s}s) [{reason}]")
            return "up"
        if warm == "interrupted":
            # shutting down mid-spawn: leave the intent + pid journaled —
            # the backend lives on and the next supervisor's adopt rolls
            # the spawn forward (never kill a backend on supervisor exit)
            return "interrupted"
        if warm == "warm_timeout":
            self.kill9(pid)
            fleetctl.wait_pid_gone(pid, 10.0)
        outcome = self._record_crash(slot, reason=f"{reason}: {warm}")
        self._settle_intent()
        return outcome

    def _apply_retune(self, overrides: List[str]) -> None:
        """A tuned grid reached a live backend: it is now the fleet's
        reference grid — clear the parked overrides and move the forecast
        baseline so the next divergence is measured against it."""
        with self._lock:
            self._pending_overrides = []
            for item in overrides:
                key, _, value = item.partition("=")
                try:
                    edges = json.loads(value)
                except ValueError:
                    continue
                if key == "serving.support_buckets":
                    self.current_support = list(edges)
                elif key == "serving.query_buckets":
                    self.current_query = list(edges)

    def _drain_slot(self, slot: Dict[str, Any], reason: str,
                    signals: Optional[Dict[str, Any]] = None) -> dict:
        """Write-ahead journaled graceful drain of one backend."""
        self._begin_intent("drain", slot["slot"])
        with self._lock:
            slot["state"] = "draining"
        self._save()
        t0 = self.clock()
        row = self.drain(slot, self.policy.drain_timeout_s)
        spilled = self._count_spilled(slot)
        with self._lock:
            slot["pid"] = None
            slot["state"] = "down"
            slot.pop("next_spawn_ts", None)
        self._settle_intent()
        self._event(
            "scale_down", slot=slot["slot"], reason=reason, signals=signals,
            outcome="down", settle_s=round(self.clock() - t0, 2),
            drain=row.get("drain"), drain_rc=row.get("drain_rc"),
            spilled_sessions=spilled,
        )
        self.log(f"autoscaler: slot {slot['slot']} drained "
                 f"({row.get('drain')}, rc {row.get('drain_rc')}) [{reason}]")
        return row

    def _count_spilled(self, slot: Dict[str, Any]) -> Optional[int]:
        run_dir = slot.get("run_dir")
        if not run_dir:
            return None
        spill_dir = os.path.join(run_dir, "saved_models", "sessions")
        try:
            return len([n for n in os.listdir(spill_dir)
                        if not n.startswith(".")])
        except OSError:
            return 0

    # -- adopt-on-restart ----------------------------------------------

    def adopt(self) -> None:
        """Reconcile the journal against reality after a restart: adopt
        live backends by pid/port probe, roll the interrupted intent
        forward, reap what is actually dead."""
        with self._lock:
            intent = self.state.get("intent")
            slots = list(self.state["slots"])
        intent_slot = intent["slot"] if intent else None
        adopted = found_dead = 0
        for slot in slots:
            if slot.get("slot") == intent_slot:
                continue  # the interrupted action owns this slot (below)
            pid = slot.get("pid")
            if not pid:
                if slot.get("state") in ("up", "spawning", "draining"):
                    with self._lock:
                        slot["state"] = "down"
                continue
            if self.pid_alive(pid):
                code, _ = self.probe(slot["url"])
                if code == 200:
                    with self._lock:
                        slot["state"] = "up"
                    adopted += 1
                    self._event("adopt", slot=slot["slot"], pid=pid)
                else:
                    # alive but not healthy: re-enter the warm gate
                    with self._lock:
                        slot["state"] = "spawning"
                    if self._await_warm(slot) == "up":
                        with self._lock:
                            slot["state"] = "up"
                        adopted += 1
                        self._event("adopt", slot=slot["slot"], pid=pid,
                                    warmed=True)
                    else:
                        self._record_crash(slot, reason="adopt: never warmed")
            else:
                with self._lock:
                    slot["pid"] = None
                    if slot.get("state") != "quarantined":
                        slot["state"] = "down"
                found_dead += 1
                self._event("adopt_found_dead", slot=slot["slot"], pid=pid)
        if intent:
            self._roll_forward(intent)
        with self._lock:
            self.counters["adopted"] += adopted
            self.state["intent"] = None
            running = sum(1 for s in self.state["slots"]
                          if s.get("state") in ("up", "spawning"))
            self.state["target"] = max(
                self.policy.min_backends,
                int(self.state.get("target") or 0) or running,
            )
        self._save()
        self._event("supervisor_start", mode="adopted", adopted=adopted,
                    found_dead=found_dead,
                    rolled_forward=intent["action"] if intent else None,
                    target=self.state["target"])

    def _roll_forward(self, intent: Dict[str, Any]) -> None:
        slot = self._slot_by_id(intent["slot"])
        if slot is None:
            return
        action = intent["action"]
        pid = slot.get("pid")
        if action == "spawn":
            if pid and self.pid_alive(pid):
                # the spawn survived the dead supervisor: finish its warm
                # gate and settle — do NOT double-spawn
                with self._lock:
                    slot["state"] = "spawning"
                if self._await_warm(slot) == "up":
                    with self._lock:
                        slot["state"] = "up"
                        slot["crashes"] = []
                    self._event("adopt_rollforward", slot=slot["slot"],
                                action="spawn", outcome="spawn_settled",
                                pid=pid)
                else:
                    self._record_crash(slot, reason="rollforward: never warmed")
            elif pid:
                self._record_crash(slot, reason="rollforward: spawn died")
                self._event("adopt_rollforward", slot=slot["slot"],
                            action="spawn", outcome="spawn_crashed", pid=pid)
            else:
                # killed between Popen and journaling the pid: probe the
                # slot's port for the orphan
                code, _ = self.probe(slot["url"])
                if code is None:
                    # nothing is listening — the spawn never happened; the
                    # capacity gap re-spawns through the normal ladder
                    with self._lock:
                        slot["state"] = "down"
                    self._event("adopt_rollforward", slot=slot["slot"],
                                action="spawn", outcome="respawn_pending")
                    return
                orphan = self.port_pid(slot.get("port")) if slot.get("port") else None
                if orphan:
                    with self._lock:
                        slot["pid"] = int(orphan)
                        slot["state"] = "spawning"
                    if self._await_warm(slot) == "up":
                        with self._lock:
                            slot["state"] = "up"
                            slot["crashes"] = []
                        self._event("adopt_rollforward", slot=slot["slot"],
                                    action="spawn", outcome="orphan_adopted",
                                    pid=int(orphan))
                    else:
                        self._record_crash(slot, reason="orphan never warmed")
                else:
                    # something answers on the port but its pid is beyond
                    # reach: never spawn on top of it
                    with self._lock:
                        slot["state"] = "quarantined"
                    self._event("adopt_rollforward", slot=slot["slot"],
                                action="spawn", outcome="orphan_unmanaged")
        elif action == "drain":
            if pid and self.pid_alive(pid):
                self._event("adopt_rollforward", slot=slot["slot"],
                            action="drain", outcome="drain_reissued", pid=pid)
                row = self.drain(slot, self.policy.drain_timeout_s)
                with self._lock:
                    slot["pid"] = None
                    slot["state"] = "down"
                self._event("scale_down", slot=slot["slot"],
                            reason="rollforward", outcome="down",
                            drain=row.get("drain"),
                            drain_rc=row.get("drain_rc"),
                            spilled_sessions=self._count_spilled(slot))
            else:
                with self._lock:
                    slot["pid"] = None
                    slot["state"] = "down"
                self._event("adopt_rollforward", slot=slot["slot"],
                            action="drain", outcome="drain_settled")

    # -- predictive loop -----------------------------------------------

    def _forecast_histograms(self) -> Dict[str, Dict[int, int]]:
        """Per-verb true-size histograms over the sliding window of
        access.jsonl (outcome ok only — the buckets.py rule); lines without
        a parseable ts count conservatively."""
        out: Dict[str, Dict[int, int]] = {"adapt": {}, "predict": {}}
        horizon = self.wall() - self.policy.forecast_window_s
        try:
            with open(self.access_log) as f:
                lines = f.read().splitlines()
        except OSError:
            return out
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            verb, size = rec.get("verb"), rec.get("true_size")
            if verb not in out or size is None or rec.get("outcome") != "ok":
                continue
            ts = rec.get("ts")
            if isinstance(ts, (int, float)) and ts < horizon:
                continue
            hist = out[verb]
            hist[int(size)] = hist.get(int(size), 0) + 1
        return out

    def forecast_and_retune(self) -> Optional[Dict[str, Any]]:
        """Re-tune the bucket grid against the windowed traffic mix; when
        the waste cut clears the threshold, park the overrides for the next
        spawn. Returns the tune result when a retune was parked."""
        if not self.access_log or not (self.current_support or self.current_query):
            return None
        traffic = self._forecast_histograms()
        total = sum(sum(h.values()) for h in traffic.values())
        if total < self.policy.forecast_min_requests:
            return None
        result = buckets.tune(
            traffic, self.current_support, self.current_query,
            max_buckets=self.policy.max_buckets,
        )
        before = result.get("padding_waste_frac_before")
        after = result.get("padding_waste_frac_after")
        if before is None or after is None:
            return None
        improvement = round(before - after, 4)
        if improvement < self.policy.retune_waste_improvement:
            return None
        overrides = result.get("overrides") or []
        with self._lock:
            if overrides == self._pending_overrides:
                return None
            self._pending_overrides = list(overrides)
            self.counters["retunes"] += 1
        self._event("retune", overrides=overrides, requests=total,
                    waste_frac_before=before, waste_frac_after=after,
                    improvement=improvement,
                    window_s=self.policy.forecast_window_s)
        self.log(f"autoscaler: retune parked for next spawn "
                 f"(waste {before} -> {after}): {overrides}")
        return result

    # -- the control loop ----------------------------------------------

    def tick(self) -> str:
        """One control iteration. Returns the decision taken (for tests):
        "scale_up" | "scale_down" | "replace" | "spawn_retry" | "idle" |
        a spawn outcome ("backoff" / "quarantined")."""
        p = self.policy
        with self._lock:
            self.counters["ticks"] += 1
        now = self.clock()
        if self.access_log and (
            self._last_forecast_ts is None
            or now - self._last_forecast_ts >= p.forecast_interval_s
        ):
            self._last_forecast_ts = now
            self.forecast_and_retune()
        signals = self.collect_signals()

        # 1. a backend that disappeared without being asked is replaced
        dead = self._find_dead(signals)
        if dead is not None:
            with self._lock:
                pid = dead.get("pid")
                dead["pid"] = None
                dead["state"] = "down"
                self.counters["replacements"] += 1
            if pid and self.pid_alive(pid):
                # gateway-OUT + /healthz unreachable with the process still
                # standing: wedged beyond recovery — clear the slot hard
                self.kill9(pid)
                fleetctl.wait_pid_gone(pid, 10.0)
            self._event("backend_died", slot=dead["slot"], pid=pid,
                        signals=signals,
                        drain_rc=self._reaped_rcs.get(pid))
            self.log(f"autoscaler: slot {dead['slot']} died unasked "
                     f"(pid {pid}) — replacing")
            self._save()
            return "replace"

        # 2. capacity repair: running below target (bootstrap, a replaced
        # death, a crashed spawn past its backoff) — not cooldown-gated
        with self._lock:
            running = self._running()
            target = int(self.state.get("target", 0))
        if running < target:
            slot = self._spawnable_slot()
            if slot is not None:
                self._spawn_into(slot, reason="capacity_repair",
                                 signals=signals)
                return "spawn_retry"
            return "idle"

        # 3. hysteresis + per-direction cooldowns
        reasons = self._breach_reasons(signals)
        with self._lock:
            if reasons:
                self._up_streak += 1
                self._down_streak = 0
            elif self._is_clear(signals):
                self._down_streak += 1
                self._up_streak = 0
            else:
                self._up_streak = 0
                self._down_streak = 0

        if reasons and self._up_streak >= p.up_polls and running < p.max_backends:
            if self._last_up_ts is None or now - self._last_up_ts >= p.cooldown_up_s:
                slot = self._spawnable_slot()
                if slot is not None:
                    with self._lock:
                        self.state["target"] = min(p.max_backends, target + 1)
                        self.counters["scale_ups"] += 1
                    outcome = self._spawn_into(
                        slot, reason="; ".join(reasons), signals=signals
                    )
                    self._last_up_ts = self.clock()
                    self._up_streak = 0
                    return "scale_up" if outcome == "up" else outcome
        if self._down_streak >= p.down_polls and running > p.min_backends:
            if self._last_down_ts is None or now - self._last_down_ts >= p.cooldown_down_s:
                victim = None
                with self._lock:
                    ups = [s for s in self.state["slots"]
                           if s.get("state") == "up"]
                    if ups:
                        victim = max(ups, key=lambda s: s.get("slot", 0))
                if victim is not None:
                    with self._lock:
                        self.state["target"] = max(p.min_backends, target - 1)
                        self.counters["scale_downs"] += 1
                    self._drain_slot(
                        victim,
                        reason=f"clear for {self._down_streak} polls",
                        signals=signals,
                    )
                    self._last_down_ts = self.clock()
                    self._down_streak = 0
                    return "scale_down"
        return "idle"

    def _find_dead(self, signals: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        with self._lock:
            up_slots = [s for s in self.state["slots"] if s.get("state") == "up"]
        for slot in up_slots:
            pid = slot.get("pid")
            if pid and not self.pid_alive(pid):
                return slot
            if slot.get("url") in (signals.get("out_urls") or []):
                # the gateway already hysteresis-proved this backend OUT;
                # if it is also unreachable from here it is gone or wedged
                # (pid may survive as an unreapable zombie of another parent)
                code, _ = self.probe(slot["url"])
                if code is None:
                    return slot
        return None

    def run(self, max_ticks: int = 0) -> None:
        ticks = 0
        while not self._stop.is_set():
            self.tick()
            ticks += 1
            if max_ticks and ticks >= max_ticks:
                return
            self.sleep(self.policy.poll_interval_s)

    def stop(self) -> None:
        self._stop.set()

    # -- observability --------------------------------------------------

    def metrics_snapshot(self) -> Dict[str, Any]:
        p = self.policy
        now = self.clock()
        wall_now = self.wall()

        def _cooldown_left(last_ts, cooldown_s):
            if last_ts is None:
                return 0.0
            return round(max(0.0, cooldown_s - (now - last_ts)), 2)

        with self._lock:
            slots = [
                {
                    "slot": s.get("slot"),
                    "url": s.get("url"),
                    "state": s.get("state"),
                    "pid": s.get("pid"),
                    "crashes_in_window": len([
                        t for t in s.get("crashes", [])
                        if wall_now - t <= p.crash_window_s
                    ]),
                    "next_spawn_in_s": (
                        round(max(0.0, s["next_spawn_ts"] - wall_now), 2)
                        if s.get("next_spawn_ts") else None
                    ),
                }
                for s in self.state["slots"]
            ]
            return {
                "supervisor": True,
                "uptime_s": round(now - self._started, 1),
                "gateway_url": self.gateway_url,
                "target": self.state.get("target"),
                "running": self._running(),
                "min_backends": p.min_backends,
                "max_backends": p.max_backends,
                "streaks": {"up": self._up_streak, "down": self._down_streak},
                "cooldowns": {
                    "up_remaining_s": _cooldown_left(self._last_up_ts,
                                                     p.cooldown_up_s),
                    "down_remaining_s": _cooldown_left(self._last_down_ts,
                                                       p.cooldown_down_s),
                },
                "last_decision": self._last_decision,
                "signals": dict(self._last_signals),
                "pending_overrides": list(self._pending_overrides),
                "counters": dict(self.counters),
                "intent": self.state.get("intent"),
                "slots": slots,
            }


# ---------------------------------------------------------------------------
# the /metrics + /healthz endpoint (fleet_serve.py mounts this)


def run_supervisor_http(supervisor: Supervisor, host: str, port: int):
    """Serve the supervisor's /metrics + /healthz on a daemon thread;
    returns (server, bound_port)."""

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path == "/metrics":
                code, body = 200, supervisor.metrics_snapshot()
            elif self.path == "/healthz":
                code, body = 200, {
                    "status": "ok", "supervisor": True,
                    "running": supervisor.metrics_snapshot()["running"],
                }
            else:
                code, body = 404, {"error": f"unknown path {self.path}"}
            blob = json.dumps(body).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)

        def log_message(self, fmt, *args):  # quiet by default
            pass

    server = ThreadingHTTPServer((host, port), _Handler)
    server.daemon_threads = True
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name="supervisor-http")
    thread.start()
    return server, server.server_address[1]
