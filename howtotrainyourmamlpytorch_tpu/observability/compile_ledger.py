"""Per-program compile ledger: every XLA compile, priced and attributed.

BENCH_r02's 37.9 s of compile+warmup is a single untracked number; ROADMAP
item 2 (AOT compilation, seconds-not-minutes cold start) needs the evidence
base: *which* programs cost what, how often they recompile, and whether the
persistent cache actually absorbs them. The ledger records one JSON line per
program build to ``logs/compile_ledger.jsonl``::

    {"ts": ..., "program": "train/True/False", "lower_s": 0.41,
     "compile_s": 6.2, "total_s": 6.61, "cold": true,
     "persistent_cache": {"dir": ..., "entries_added": 1, "hit": false},
     "flops": 1.2e9, "bytes_accessed": 3.4e7, "session": "..."}

and aggregates in-process (:meth:`CompileLedger.summary` — a TelemetryHub
provider), so ``scripts/obs_report.py`` can render the compile-tax table
per run and ``/metrics`` can show it per serving replica.

Hooked at the seams that already see every compile:

- :meth:`wrap_build` wraps the jitted programs ``MAMLSystem`` /
  ``AdaptationEngine`` build — the first call per argument signature runs
  the explicit AOT split (``.lower()`` timed, ``.compile()`` timed, program
  FLOPs read off the lowered/compiled pair via ``observability/costs.py``)
  and later calls reuse the compiled executable. A *new* signature on the
  same program is exactly an unplanned recompile — it gets its own timed
  entry, which is the whole point. Any AOT failure degrades that signature
  to the plain jitted call and records the error: the ledger must never be
  able to take down a run.
- ``RecompileGuard.wrap()`` (``utils/strictmode.py``) feeds first-call
  timings for guard-wrapped functions through :meth:`record` (total only —
  the guard has no lowered object to split or price).

With no ``logs_dir`` the ledger is collector-only (serving frontends own no
run dir; their summary rides ``/metrics`` and the hub provider instead).
"""

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..utils.compcache import active_cache_dir, cache_entry_count
from .costs import program_cost, program_memory

from ..utils.locks import san_lock


def program_name(key: Any) -> str:
    """Canonical ledger name for a program-cache key: tuples join with
    ``/`` (``("train", True, False)`` -> ``"train/True/False"``)."""
    if isinstance(key, (list, tuple)):
        return "/".join(str(k) for k in key)
    return str(key)


class CompileLedger:
    def __init__(
        self,
        logs_dir: Optional[str] = None,
        session: Optional[str] = None,
        clock: Callable[[], float] = time.perf_counter,
        wall_clock: Callable[[], float] = time.time,
    ):
        self._clock = clock
        self._wall_clock = wall_clock
        self.session = session
        self._lock = san_lock("CompileLedger._lock")
        # program name -> aggregate {builds, lower_s, compile_s, total_s,
        # cache_hits, errors, flops, bytes_accessed}
        self._programs: Dict[str, Dict[str, Any]] = {}
        self._entries = 0
        self._log = None
        if logs_dir is not None:
            from ..experiment.storage import EventLog

            self._log = EventLog(logs_dir, filename="compile_ledger.jsonl")
        #: optional observer called with each entry dict AFTER it is
        #: recorded (the runner uses it to set the flops_per_step gauge the
        #: live MFU computation reads). Observer errors are contained.
        self.on_entry: Optional[Callable[[Dict[str, Any]], None]] = None

    # ------------------------------------------------------------------

    def record(
        self,
        program: Any,
        lower_s: Optional[float] = None,
        compile_s: Optional[float] = None,
        total_s: Optional[float] = None,
        cold: bool = True,
        persistent_cache: Optional[Dict[str, Any]] = None,
        flops: Optional[float] = None,
        bytes_accessed: Optional[float] = None,
        error: Optional[str] = None,
        memory: Optional[Dict[str, Any]] = None,
        **extra: Any,
    ) -> Dict[str, Any]:
        """Append one ledger entry (and fold it into the in-process
        aggregate). Never raises."""
        name = program_name(program)
        if total_s is None and (lower_s is not None or compile_s is not None):
            total_s = (lower_s or 0.0) + (compile_s or 0.0)
        entry: Dict[str, Any] = {
            "ts": self._wall_clock(),
            "program": name,
            "lower_s": round(lower_s, 4) if lower_s is not None else None,
            "compile_s": round(compile_s, 4) if compile_s is not None else None,
            "total_s": round(total_s, 4) if total_s is not None else None,
            "cold": bool(cold),
            "persistent_cache": persistent_cache,
            "flops": flops,
            "bytes_accessed": bytes_accessed,
            # per-program memory breakdown (observability/costs.py::
            # program_memory): argument/output/temp/generated/alias bytes +
            # the derived peak — null-with-reason where the backend hides
            # memory_analysis; absent entirely for entries with no compiled
            # object (guard-seam totals, store hits)
            "memory": memory,
            "error": error,
        }
        if self.session is not None:
            entry["session"] = self.session
        entry.update(extra)
        with self._lock:
            self._entries += 1
            agg = self._programs.setdefault(
                name,
                {
                    "builds": 0,
                    "lower_s": 0.0,
                    "compile_s": 0.0,
                    "total_s": 0.0,
                    "cache_hits": 0,
                    "errors": 0,
                    "flops": None,
                    "bytes_accessed": None,
                    "peak_bytes": None,
                    "donated_bytes": None,
                },
            )
            agg["builds"] += 1
            agg["lower_s"] = round(agg["lower_s"] + (lower_s or 0.0), 4)
            agg["compile_s"] = round(agg["compile_s"] + (compile_s or 0.0), 4)
            agg["total_s"] = round(agg["total_s"] + (total_s or 0.0), 4)
            if persistent_cache and persistent_cache.get("hit"):
                agg["cache_hits"] += 1
            if error is not None:
                agg["errors"] += 1
            if flops is not None:
                agg["flops"] = flops
            if bytes_accessed is not None:
                agg["bytes_accessed"] = bytes_accessed
            if memory is not None:
                if memory.get("peak_bytes") is not None:
                    agg["peak_bytes"] = memory["peak_bytes"]
                if memory.get("alias_bytes") is not None:
                    agg["donated_bytes"] = memory["alias_bytes"]
        if self._log is not None:
            try:
                self._log.append(entry)
            except Exception:
                pass  # a full disk must not turn a compile into a crash
        observer = self.on_entry
        if observer is not None:
            try:
                observer(entry)
            except Exception:
                pass
        return entry

    def summary(self) -> Dict[str, Any]:
        """The compile-tax aggregate (TelemetryHub provider / ``/metrics``
        payload): totals plus the per-program table."""
        with self._lock:
            programs = {k: dict(v) for k, v in self._programs.items()}
            entries = self._entries
        peaks = [
            p["peak_bytes"] for p in programs.values() if p.get("peak_bytes")
        ]
        donated = [
            p["donated_bytes"] for p in programs.values() if p.get("donated_bytes")
        ]
        return {
            "entries": entries,
            "programs": len(programs),
            "total_lower_s": round(sum(p["lower_s"] for p in programs.values()), 3),
            "total_compile_s": round(sum(p["compile_s"] for p in programs.values()), 3),
            "total_s": round(sum(p["total_s"] for p in programs.values()), 3),
            "cache_hits": sum(p["cache_hits"] for p in programs.values()),
            "errors": sum(p["errors"] for p in programs.values()),
            # the headline memory numbers: the biggest program's peak bytes
            # (the one that OOMs first) and its in-place (donated) bytes;
            # None where no backend exposed memory_analysis
            "peak_program_bytes": max(peaks) if peaks else None,
            "donated_bytes": max(donated) if donated else None,
            "by_program": programs,
        }

    def close(self) -> None:
        if self._log is not None:
            self._log.close()

    # ------------------------------------------------------------------
    # the build seam
    # ------------------------------------------------------------------

    def wrap_build(self, program: Any, jitted_fn: Callable) -> "LedgerWrapped":
        """Wrap a freshly-built jitted callable so every compile it pays is
        a timed, priced ledger entry. Call this where the program cache
        inserts a new entry (``MAMLSystem._compiled_train_step`` and
        friends)."""
        return LedgerWrapped(self, program_name(program), jitted_fn)


class LedgerWrapped:
    """A jitted callable whose compiles go through the ledger.

    First call per argument signature: explicit AOT — ``lower`` (timed),
    ``compile`` (timed), program cost off the lowered/compiled pair, one
    ledger entry — then the compiled executable is cached per signature and
    every later call dispatches through it, preserving jit's
    recompile-on-new-shape semantics (a new signature builds, times, and
    records again: that recompile is precisely what the ledger exists to
    see). AOT failure for a signature records the error and pins that
    signature to the plain jitted call."""

    def __init__(self, ledger: CompileLedger, program: str, jitted_fn: Callable):
        self._ledger = ledger
        self.program = program
        self._jitted = jitted_fn
        self._lock = san_lock("LedgerWrapped._lock")
        self._by_sig: Dict[Any, Callable] = {}
        self._clock = ledger._clock

    def lower(self, *args, **kwargs):
        """Delegate so AOT consumers (bench's cost probe) keep working."""
        return self._jitted.lower(*args, **kwargs)

    def _signature(self, args, kwargs) -> Any:
        from ..utils.strictmode import abstract_signature

        try:
            return abstract_signature((args, tuple(sorted(kwargs.items()))))
        except Exception:
            return ("unsigned",)

    def _build(self, sig: Any, args, kwargs, phase: Optional[str] = None) -> Callable:
        extra = {} if phase is None else {"phase": phase}
        clock = self._clock
        cache_dir = active_cache_dir()
        entries_before = cache_entry_count(cache_dir)
        try:
            t0 = clock()
            lowered = self._jitted.lower(*args, **kwargs)
            t1 = clock()
            compiled = lowered.compile()
            t2 = clock()
        except Exception as exc:
            self._ledger.record(
                self.program,
                cold=True,
                error=f"aot build failed: {type(exc).__name__}: {exc}",
                **extra,
            )
            return self._jitted
        entries_after = cache_entry_count(cache_dir)
        cache_info: Optional[Dict[str, Any]] = None
        if entries_before is not None and entries_after is not None:
            added = entries_after - entries_before
            # no new entry on a live cache dir = the compile was served from
            # it (or fell below the cache's size/time thresholds — the raw
            # delta stays in the record so that ambiguity is visible)
            cache_info = {"dir": cache_dir, "entries_added": added, "hit": added == 0}
        cost = program_cost(lowered, compiled)
        self._ledger.record(
            self.program,
            lower_s=t1 - t0,
            compile_s=t2 - t1,
            cold=not (cache_info or {}).get("hit", False),
            persistent_cache=cache_info,
            flops=cost.get("flops"),
            bytes_accessed=cost.get("bytes_accessed"),
            memory=program_memory(compiled),
            **extra,
        )
        return compiled

    def warm(self, *args, store=None, **kwargs) -> Dict[str, Any]:
        """AOT prewarm: produce the executable for this argument signature
        WITHOUT executing it, and cache it exactly where a real call of the
        same signature will look — so the first real dispatch lands on the
        warm fast path. Args may be real arrays or ``jax.ShapeDtypeStruct``
        specs: both carry ``shape``/``dtype``, so they compute the same
        signature, and ``.lower()`` accepts either.

        With an executable ``store`` (``compile/aot.py::ExecutableStore``,
        duck-typed ``load(program, sig)``/``save(program, sig, compiled)``):
        a stored executable is deserialized instead of built — no tracing,
        no XLA, one ledger entry with ``executable_store: {"hit": true}``
        and its load time — and a freshly built one is serialized back so
        the NEXT process can. Build path: lower timed, compile timed, one
        ledger entry with ``phase="prewarm"``."""
        sig = self._signature(args, kwargs)
        with self._lock:
            if sig in self._by_sig:
                return {"program": self.program, "already_warm": True, "signature": sig}
            loaded = stored = False
            fn = None
            if store is not None:
                t0 = self._clock()
                fn = store.load(self.program, sig)
                if fn is not None:
                    loaded = True
                    self._ledger.record(
                        self.program,
                        total_s=self._clock() - t0,
                        cold=False,
                        phase="prewarm",
                        executable_store={"hit": True},
                    )
            if fn is None:
                fn = self._build(sig, args, kwargs, phase="prewarm")
                if store is not None:
                    stored = store.save(self.program, sig, fn)
            try:
                # mark spec-built executables so a live-call aval rejection
                # (see __call__) degrades to one rebuild, never a failure
                fn._htymp_from_spec = True
            except (AttributeError, TypeError):
                pass
            self._by_sig[sig] = fn
        return {
            "program": self.program,
            "already_warm": False,
            "signature": sig,
            "loaded": loaded,
            "stored": stored,
        }

    def __call__(self, *args, **kwargs):
        # steady-state fast path: with exactly one signature built (the
        # overwhelmingly common case — train steps and bucketed serving
        # programs are shape-stable), dispatch straight into its compiled
        # executable. A Compiled validates its own input signature and
        # raises TypeError on mismatch, so a new shape still falls through
        # to the slow path below — no per-call pytree walk, no lock on the
        # hot path. (After an AOT-build failure the cached fn is the plain
        # jitted callable, which handles any signature itself.)
        by_sig = self._by_sig
        if len(by_sig) == 1:
            try:
                return next(iter(by_sig.values()))(*args, **kwargs)
            except TypeError:
                pass  # new signature (or a caller error the rebuild surfaces)
        sig = self._signature(args, kwargs)
        with self._lock:
            fn = self._by_sig.get(sig)
            if fn is None:
                # build under the lock: concurrent first calls of one
                # signature must pay (and record) exactly one compile
                fn = self._build(sig, args, kwargs)
                self._by_sig[sig] = fn
        try:
            return fn(*args, **kwargs)
        except TypeError:
            # a prewarmed executable was built from ShapeDtypeStruct specs
            # (LedgerWrapped.warm); if a live call with the SAME signature
            # is still rejected — an aval detail the signature abstraction
            # can't see, e.g. a weak type — rebuild from the real args
            # rather than failing the dispatch. Recorded with its own
            # phase, so a systematically wrong spec reads as double
            # compiles in the ledger, never as silent breakage.
            if not getattr(fn, "_htymp_from_spec", False):
                raise
            with self._lock:
                rebuilt = self._build(sig, args, kwargs, phase="prewarm_respec")
                self._by_sig[sig] = rebuilt
            return rebuilt(*args, **kwargs)
