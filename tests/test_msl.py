"""MSL weight schedule vs an independent re-implementation of the reference
loop (few_shot_learning_system.py:131-151)."""

import numpy as np

from howtotrainyourmamlpytorch_tpu.ops.msl import final_step_only, per_step_loss_importance


def _reference_loop(epoch, n_steps, msl_epochs):
    loss_weights = np.ones(n_steps) * (1.0 / n_steps)
    decay_rate = 1.0 / n_steps / msl_epochs
    min_non_final = 0.03 / n_steps
    for i in range(len(loss_weights) - 1):
        loss_weights[i] = np.maximum(loss_weights[i] - epoch * decay_rate, min_non_final)
    loss_weights[-1] = np.minimum(
        loss_weights[-1] + epoch * (n_steps - 1) * decay_rate,
        1.0 - (n_steps - 1) * min_non_final,
    )
    return loss_weights


def test_matches_reference_schedule():
    for n_steps, msl_epochs in [(5, 10), (3, 10), (5, 4), (10, 2)]:
        for epoch in range(0, 25):
            ours = np.asarray(per_step_loss_importance(epoch, n_steps, msl_epochs))
            ref = _reference_loop(epoch, n_steps, msl_epochs)
            np.testing.assert_allclose(ours, ref, rtol=1e-6, atol=1e-7, err_msg=f"epoch={epoch}")


def test_weights_sum_to_one_before_saturation():
    for epoch in range(10):
        w = np.asarray(per_step_loss_importance(epoch, 5, 10))
        np.testing.assert_allclose(w.sum(), 1.0, rtol=1e-6)


def test_final_step_only():
    w = np.asarray(final_step_only(5))
    assert w[-1] == 1.0 and w[:-1].sum() == 0.0
