"""Analysis pipeline (analysis.py) + results archiver over synthetic run dirs
that follow the storage artifact contract exactly."""

import os
import tarfile

import numpy as np
import yaml

from howtotrainyourmamlpytorch_tpu import analysis
from howtotrainyourmamlpytorch_tpu.experiment import storage
from howtotrainyourmamlpytorch_tpu.utils import results_archive


def _make_run(root, name, *, seed, net="vgg", inner="sgd", test_acc=0.95, epochs=3,
              betas=False):
    run_dir = os.path.join(root, name)
    _, logs, _ = storage.build_experiment_folder(run_dir)
    cfg = {
        "dataset": {"name": "omniglot_dataset"},
        "num_classes_per_set": 5,
        "num_samples_per_class": 1,
        "net": net,
        "inner_optim": {"kind": inner},
        "seed": seed,
    }
    with open(os.path.join(run_dir, "config.yaml"), "w") as f:
        yaml.safe_dump(cfg, f)
    for epoch in range(epochs):
        storage.save_statistics(
            logs,
            {
                "epoch": epoch,
                "train_accuracy_mean": 0.5 + 0.1 * epoch,
                "train_loss_mean": 1.0 - 0.1 * epoch,
                "val_accuracy_mean": 0.4 + 0.1 * epoch,
                "val_loss_mean": 1.2 - 0.1 * epoch,
            },
        )
        storage.append_hparam_row(run_dir, [0.1 + 0.01 * epoch] * 4, "lrs.csv")
        if betas:
            storage.append_hparam_row(run_dir, [0.5, 0.5] * 4, "betas.csv")
    storage.save_statistics(
        logs, {"test_accuracy_mean": test_acc, "test_loss_mean": 0.2},
        filename="test_summary.csv",
    )
    return run_dir


def test_reconciled_csv_blank_cells_become_none(tmp_path):
    """Regression (advisor r1): header-drift reconciliation back-fills ''
    cells; they must load as None (not strings matplotlib would treat as
    categorical) and plotting must skip them."""
    run_dir = _make_run(tmp_path, "drift", seed=0, epochs=2)
    logs = os.path.join(run_dir, "logs")
    # append a row with a NEW column -> earlier rows get '' back-filled
    storage.save_statistics(
        logs,
        {"epoch": 2, "train_accuracy_mean": 0.9, "train_loss_mean": 0.3,
         "val_accuracy_mean": 0.8, "val_loss_mean": 0.4, "brand_new_metric": 1.0},
    )
    run = analysis.load_run(run_dir)
    assert run.summary[0]["brand_new_metric"] is None
    assert run.summary[2]["brand_new_metric"] == 1.0
    out = analysis.plot_learning_curves(run, str(tmp_path / "curves.png"))
    assert out and os.path.exists(out)


def test_load_run_and_collect(tmp_path):
    root = str(tmp_path)
    _make_run(root, "a.seed0", seed=0)
    _make_run(root, "a.seed1", seed=1, betas=True, inner="adam")
    runs = analysis.collect_runs(root)
    assert len(runs) == 2
    run = runs[0]
    assert run.group_key == ("omniglot_dataset", 5, 1, "vgg", "sgd")
    assert run.test_accuracy == 0.95
    assert run.lrs.shape == (3, 4)
    assert runs[1].betas.shape == (3, 8)


def test_aggregate_mean_std_and_min_seeds(tmp_path):
    root = str(tmp_path)
    _make_run(root, "a.seed0", seed=0, test_acc=0.90)
    _make_run(root, "a.seed1", seed=1, test_acc=0.94)
    _make_run(root, "b.seed0", seed=0, net="resnet-4", test_acc=0.99)
    rows = analysis.aggregate_test_accuracy(analysis.collect_runs(root))
    assert len(rows) == 2
    by_net = {r.net: r for r in rows}
    np.testing.assert_allclose(by_net["vgg"].mean, 92.0)
    np.testing.assert_allclose(by_net["vgg"].std, 2.0)
    assert by_net["vgg"].count == 2
    # the notebook's count==3 filter, generalized
    rows2 = analysis.aggregate_test_accuracy(analysis.collect_runs(root), min_seeds=2)
    assert [r.net for r in rows2] == ["vgg"]
    best = analysis.best_per_config(rows)
    assert len(best) == 1 and best[0].net == "resnet-4"


def test_tables_and_report(tmp_path):
    root, out = str(tmp_path / "exps"), str(tmp_path / "out")
    _make_run(root, "a.seed0", seed=0, betas=True, inner="adam")
    rows = analysis.aggregate_test_accuracy(analysis.collect_runs(root))
    md, tex = analysis.to_markdown(rows), analysis.to_latex(rows)
    assert "| vgg | adam |" in md.replace("  ", " ")
    assert "\\pm" in tex and "95.00" in tex
    # names with underscores must be text-mode escaped for pdflatex
    assert "omniglot\\_dataset" in tex
    result = analysis.write_report(root, out)
    assert result["runs"] == 1 and result["table_rows"] == 1
    assert os.path.exists(os.path.join(out, "test_accuracy.md"))
    assert os.path.exists(os.path.join(out, "test_accuracy.tex"))
    # curves + inner-opt plots rendered
    assert len(result["plots"]) == 2
    for p in result["plots"]:
        assert os.path.getsize(p) > 0


def test_report_sweep_layout_no_plot_collisions(tmp_path):
    # sweep layout exps/{config}/{seed_N}: same basename under different
    # parents must produce distinct plot files
    root, out = str(tmp_path / "exps"), str(tmp_path / "out")
    _make_run(os.path.join(root, "cfg_a"), "seed_0", seed=0)
    _make_run(os.path.join(root, "cfg_b"), "seed_0", seed=0, net="resnet-4")
    result = analysis.write_report(root, out)
    assert result["runs"] == 2
    assert len(result["plots"]) == len(set(result["plots"])) == 4


def test_results_archive_roundtrip(tmp_path):
    run_dir = _make_run(str(tmp_path), "a.seed0", seed=0)
    # a fake checkpoint that must be excluded by default
    with open(os.path.join(run_dir, "saved_models", "train_model_0"), "wb") as f:
        f.write(b"x" * 100)
    archive_dir = str(tmp_path / "archives")
    path = results_archive.pack_run(run_dir, archive_dir)
    with tarfile.open(path) as tar:
        names = tar.getnames()
    assert any("summary_statistics.csv" in n for n in names)
    assert any(n.endswith("config.yaml") for n in names)
    assert not any("saved_models" in n for n in names)
    path2 = results_archive.pack_run(run_dir, archive_dir, include_checkpoints=True,
                                     archive_name="with-ckpt")
    with tarfile.open(path2) as tar:
        assert any("saved_models" in n for n in tar.getnames())
    assert set(results_archive.list_archives(archive_dir)) == {path, path2}
    results_archive.delete_archive(path)
    assert results_archive.list_archives(archive_dir) == [path2]


def test_empty_run_set_is_stamped_not_silent(tmp_path):
    """Regression (VERDICT r5 weak #6): an analysis over zero matching runs
    must say so — '0 runs matched' stamped in the report — instead of
    emitting header-only tables that read as a successful (empty) sweep."""
    root, out = str(tmp_path / "nothing_here"), str(tmp_path / "out")
    os.makedirs(root)
    result = analysis.write_report(root, out)
    assert result["runs"] == 0 and result["table_rows"] == 0
    assert "0 runs matched" in result["warning"]
    md = open(os.path.join(out, "test_accuracy.md")).read()
    assert "0 runs matched" in md
    assert "| Dataset |" not in md  # no header-only table
    tex = open(os.path.join(out, "test_accuracy.tex")).read()
    assert "0 runs matched" in tex and "tabular" not in tex
    # the JSON report is stamped too, not a silently-clean bare []
    import json

    payload = json.load(open(os.path.join(out, "test_accuracy.json")))
    assert "0 runs matched" in payload["warning"] and payload["rows"] == []
    # runs found but none aggregable (no finished test summary / min_seeds):
    # the stamp distinguishes that case too
    run_dir = _make_run(str(tmp_path / "exps2"), "a.seed0", seed=0)
    os.remove(os.path.join(run_dir, "logs", "test_summary.csv"))
    result2 = analysis.write_report(str(tmp_path / "exps2"), str(tmp_path / "out2"))
    assert result2["runs"] == 1 and result2["table_rows"] == 0
    assert "0 aggregate rows" in result2["warning"]


def test_latex_schema_matches_markdown_and_json(tmp_path):
    """ADVICE r5 #2: all three report formats carry the reference-baseline
    columns (ref mean/std + signed delta), so a cell can be compared against
    the published number from any of them."""
    root = str(tmp_path)
    _make_run(root, "a.seed0", seed=0, test_acc=0.9862)  # vgg sgd 5w1s (has ref)
    _make_run(root, "c.seed0", seed=0, inner="rprop", test_acc=0.90)  # no ref
    rows = analysis.aggregate_test_accuracy(analysis.collect_runs(root))
    tex = analysis.to_latex(rows)
    assert "Ref (3 seeds)" in tex and "$\\Delta$ vs ref" in tex
    assert "$99.62 \\pm 0.08$" in tex  # the reference cell
    assert "$-1.00$" in tex  # the signed delta
    # the rprop row renders the no-reference placeholder in both ref columns
    rprop_line = next(line for line in tex.splitlines() if "rprop" in line)
    assert rprop_line.count("--") == 2
    # markdown agrees on the same cells
    md = analysis.to_markdown(rows)
    assert "99.62 ± 0.08" in md and "-1.00" in md


def test_aggregate_rows_carry_reference_baseline(tmp_path):
    """Every aggregated cell the reference also published carries the
    reference's mean/std (BASELINE.md / reference nbs cell 11) and a signed
    delta; cells the reference never ran (any rprop config) carry None."""
    root = str(tmp_path)
    _make_run(root, "a.seed0", seed=0, test_acc=0.9862)  # vgg sgd 5w1s
    _make_run(root, "c.seed0", seed=0, inner="rprop", test_acc=0.90)
    rows = analysis.aggregate_test_accuracy(analysis.collect_runs(root))
    by_opt = {r.inner_optim: r for r in rows}
    vgg = by_opt["sgd"]
    assert (vgg.ref_mean, vgg.ref_std) == (99.62, 0.08)
    np.testing.assert_allclose(vgg.delta_vs_ref, 98.62 - 99.62)
    assert by_opt["rprop"].ref_mean is None
    assert by_opt["rprop"].delta_vs_ref is None
    md = analysis.to_markdown(rows)
    assert "99.62 ± 0.08" in md and "-1.00" in md
    # rprop row renders the no-reference placeholder
    assert "| — | — |" in md
