"""Robust XLA cost-model access: program FLOPs/bytes and MFU arithmetic.

One place owns the fallback chain that ``bench.py`` used to hand-roll (and
that crashed on this jaxlib: accessing ``Lowered.cost_analysis`` can raise
``'NoneType' object has no attribute 'get'`` *inside jax* before any
fallback runs, nulling ``flops_per_step``/``mfu`` in every BENCH line —
BENCH_r02). The contract here is strict: every entry point **degrades to
None with a reason, never raises** — a cost probe must not be able to cost
a benchmark its headline or a run its telemetry.

Pieces:

- :func:`program_cost` — ``{flops, bytes_accessed, source, error}`` from a
  ``jax.stages.Lowered`` / ``Compiled`` (or anything duck-shaped like one),
  walking lowered -> compiled cost analyses and normalizing the half-dozen
  shapes different PJRT plugins return (dict, list-of-dict, None, raising
  property, ``'bytes accessed'`` vs ``'bytes_accessed'`` keys);
- :func:`jit_cost` — the same, from a jitted callable + example args
  (lowering host-side; no device execution);
- :func:`peak_flops_per_sec` — the dense-bf16 per-chip peak table keyed by
  ``device_kind`` substring (moved here from bench.py; the xplane-measured
  peak in ``utils/profiling.py`` wins over this table when a trace exists);
- :func:`mfu` — ``(value, reason)``: FLOPs/step x steps/s over chip peak,
  with the reason string spelled out whenever the value is None (the
  "null-only-with-logged-reason" contract VERDICT asks of bench).
"""

from typing import Any, Callable, Dict, List, Optional, Tuple

#: Dense bf16 peak FLOP/s per chip, keyed by substring of ``device_kind``
#: (lowercased). Order matters: first match wins, so the more specific
#: entries sit above the generic ones.
PEAK_FLOPS_TABLE: List[Tuple[str, float]] = [
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5litepod", 197e12),
    ("v4", 275e12),
]


def peak_flops_per_sec(device_kind: Optional[str]) -> Optional[float]:
    """Table lookup by device-kind substring; None for unknown kinds (CPU,
    new chips not yet tabled) — the caller reports *why* mfu is null."""
    if not device_kind:
        return None
    kind = str(device_kind).lower()
    for sub, peak in PEAK_FLOPS_TABLE:
        if sub in kind:
            return peak
    return None


def _normalize_cost(ca: Any) -> Optional[Dict[str, Optional[float]]]:
    """One cost-analysis return value -> ``{flops, bytes_accessed}`` floats,
    or None when the value carries no usable FLOPs count. Accepts the shapes
    seen across jax versions/plugins: a dict, a list/tuple of per-device
    dicts, a mapping-like without ``.get``, or None."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if ca is None:
        return None
    if not hasattr(ca, "get"):
        try:
            ca = dict(ca)
        except Exception:
            return None
    try:
        flops = ca.get("flops")
        byts = ca.get("bytes accessed")
        if byts is None:
            byts = ca.get("bytes_accessed")
    except Exception:
        return None
    if flops is None:
        return None
    try:
        flops = float(flops)
    except (TypeError, ValueError):
        return None
    if flops <= 0:
        return None
    try:
        byts = float(byts) if byts is not None else None
    except (TypeError, ValueError):
        byts = None
    return {"flops": flops, "bytes_accessed": byts}


def _try_stage(obj: Any, reasons: List[str], label: str):
    """Call ``obj.cost_analysis`` (method or property — both exist in the
    wild) entirely inside a try: on this jaxlib even *accessing* the
    attribute can raise from inside jax."""
    try:
        attr = getattr(obj, "cost_analysis", None)
        if attr is None:
            reasons.append(f"{label}: no cost_analysis attribute")
            return None
        ca = attr() if callable(attr) else attr
    except Exception as exc:
        reasons.append(f"{label}: {type(exc).__name__}: {exc}")
        return None
    cost = _normalize_cost(ca)
    if cost is None:
        reasons.append(f"{label}: no usable flops in {type(ca).__name__}")
    return cost


def program_cost(lowered_or_compiled: Any, compiled: Any = None) -> Dict[str, Any]:
    """Best-effort ``{flops, bytes_accessed, source, error}`` for one XLA
    program. Never raises. ``source`` names the stage that answered
    (``lowered`` / ``compiled`` / ``compiled_from_lowered``); on total
    failure ``flops`` is None and ``error`` joins every stage's reason.

    Pass a pre-built ``compiled`` alongside a lowered to avoid the implicit
    ``lowered.compile()`` fallback paying a second XLA compile (the compile
    ledger does exactly this — it holds both objects already)."""
    reasons: List[str] = []
    stages: List[Tuple[str, Any, bool]] = []
    if lowered_or_compiled is not None:
        is_lowered = hasattr(lowered_or_compiled, "compile")
        stages.append(
            ("lowered" if is_lowered else "compiled", lowered_or_compiled, False)
        )
    if compiled is not None:
        stages.append(("compiled", compiled, False))
    elif stages and stages[0][0] == "lowered":
        stages.append(("compiled_from_lowered", lowered_or_compiled, True))

    for label, obj, needs_compile in stages:
        if needs_compile:
            try:
                obj = obj.compile()
            except Exception as exc:
                reasons.append(f"{label}: compile failed: {type(exc).__name__}: {exc}")
                continue
        cost = _try_stage(obj, reasons, label)
        if cost is not None:
            return {**cost, "source": label, "error": None}
    if not stages:
        reasons.append("no lowered or compiled program given")
    return {
        "flops": None,
        "bytes_accessed": None,
        "source": None,
        "error": "; ".join(reasons),
    }


#: ``memory_analysis()`` fields surfaced per compiled program, in the order
#: the ledger/report tables print them. Attribute names on the XLA
#: ``CompiledMemoryStats`` are ``<field>_size_in_bytes``.
MEMORY_FIELDS = ("argument", "output", "temp", "generated_code", "alias")


def program_memory(compiled: Any) -> Dict[str, Any]:
    """Best-effort per-program memory breakdown off XLA's
    ``Compiled.memory_analysis()``: ``{argument_bytes, output_bytes,
    temp_bytes, generated_code_bytes, alias_bytes, peak_bytes, error}``.
    Never raises — same contract as :func:`program_cost` (the PR 7
    crash-class lesson: merely *accessing* an optional stage attribute can
    raise inside a plugin); a backend without the analysis degrades to all-
    None bytes with the reason in ``error``.

    ``peak_bytes`` is the standard program-peak estimate arguments +
    outputs + temps - aliased (donated inputs overlap outputs, so their
    bytes are not double-counted) — the per-program number to read against
    the device-level HBM watermarks. ``alias_bytes`` is the donation win:
    bytes of input the compiled program updates in place."""
    nulls: Dict[str, Any] = {f"{f}_bytes": None for f in MEMORY_FIELDS}
    nulls["peak_bytes"] = None
    try:
        attr = getattr(compiled, "memory_analysis", None)
        if attr is None:
            return {**nulls, "error": "no memory_analysis attribute"}
        ma = attr() if callable(attr) else attr
    except Exception as exc:
        return {**nulls, "error": f"memory_analysis: {type(exc).__name__}: {exc}"}
    if ma is None:
        return {**nulls, "error": "memory_analysis returned None"}
    out: Dict[str, Any] = {}
    for f in MEMORY_FIELDS:
        try:
            v = getattr(ma, f"{f}_size_in_bytes", None)
            out[f"{f}_bytes"] = int(v) if v is not None else None
        except Exception:
            out[f"{f}_bytes"] = None
    if all(out[f"{f}_bytes"] is None for f in MEMORY_FIELDS):
        return {**nulls, "error": f"no usable byte fields on {type(ma).__name__}"}
    # peak only when ALL THREE components are readable: a partial sum
    # (temps are usually the dominant term) would silently understate the
    # headline OOM number — null-with-reason instead, same contract as the
    # total miss
    trio = {f: out[f"{f}_bytes"] for f in ("argument", "output", "temp")}
    missing = sorted(f for f, v in trio.items() if v is None)
    if missing:
        out["peak_bytes"] = None
        out["error"] = (
            f"partial memory_analysis: missing {'/'.join(missing)} bytes "
            "(peak withheld rather than understated)"
        )
        return out
    peak = sum(trio.values())
    if out["alias_bytes"]:
        peak -= out["alias_bytes"]
    out["peak_bytes"] = peak
    out["error"] = None
    return out


def jit_cost(jitted_fn: Callable, *args, **kwargs) -> Dict[str, Any]:
    """Cost of the program ``jitted_fn(*args, **kwargs)`` would run: lowers
    host-side (one trace, no device execution, no extra XLA compile unless
    the lowered-stage analysis is unavailable) and runs :func:`program_cost`
    over it. Never raises."""
    try:
        lower = getattr(jitted_fn, "lower", None)
        if lower is None:
            return {
                "flops": None,
                "bytes_accessed": None,
                "source": None,
                "error": f"{type(jitted_fn).__name__} has no .lower()",
            }
        lowered = lower(*args, **kwargs)
    except Exception as exc:
        return {
            "flops": None,
            "bytes_accessed": None,
            "source": None,
            "error": f"lowering failed: {type(exc).__name__}: {exc}",
        }
    return program_cost(lowered)


def mfu(
    flops_per_step: Optional[float],
    steps_per_sec: Optional[float],
    device_kind: Optional[str] = None,
    peak: Optional[float] = None,
) -> Tuple[Optional[float], Optional[str]]:
    """Model FLOPs utilization as ``(value, reason)``: exactly one of the
    two is None. ``peak`` (FLOP/s) wins over the ``device_kind`` table
    lookup when both are given — pass the xplane-measured plane peak there
    when a trace exists."""
    if not flops_per_step or flops_per_step <= 0:
        return None, "flops_per_step unknown (cost model unavailable)"
    if not steps_per_sec or steps_per_sec <= 0:
        return None, "steps_per_sec unknown or zero"
    if peak is None:
        peak = peak_flops_per_sec(device_kind)
        if peak is None:
            return None, (
                f"no peak-FLOPs table entry for device_kind {device_kind!r} "
                "(and no measured peak given)"
            )
    return round(float(flops_per_step) * float(steps_per_sec) / float(peak), 5), None
