"""Single source for the JAX persistent-compilation-cache setup.

Every entry point used to copy-paste the same four lines (env check +
``jax.config.update("jax_compilation_cache_dir", ...)`` + test tuning) —
``train_maml_system.py``, ``bench.py``, ``scripts/chaos_soak.py``,
``scripts/stream_replay_probe.py``, ``resilience/campaign.py`` — each with
its own default. One drifting copy means one entry point silently paying
full XLA compiles, invisible until someone diffs startup times. This module
is the one copy, and :func:`active_cache_dir` is how the compile ledger
(``observability/compile_ledger.py``) detects persistent-cache hits: an XLA
compile that adds no entry to a live cache dir was served *from* it.

Deliberately light: ``jax`` is imported inside the functions, so
import-light CLIs can import this module without touching a backend.
"""

import os
from typing import Optional

#: The production default (the historical ``train_maml_system.py`` value):
#: shared across entry points so a bench re-run reuses the training run's
#: compiles and vice versa.
DEFAULT_CACHE_DIR = os.path.join(os.path.expanduser("~"), ".cache", "htymp_tpu_xla")


def resolve_cache_dir(cache_dir: str = "") -> str:
    """Resolution order: explicit argument (``Config.compilation_cache_dir``)
    > ``JAX_COMPILATION_CACHE_DIR`` env var (the standard JAX knob) >
    :data:`DEFAULT_CACHE_DIR`."""
    return cache_dir or os.environ.get("JAX_COMPILATION_CACHE_DIR") or DEFAULT_CACHE_DIR


def setup_compilation_cache(cache_dir: str = "", test_tuning: bool = False) -> str:
    """Point JAX's persistent executable cache at the resolved directory and
    return it. ``test_tuning=True`` additionally drops the min-entry-size /
    min-compile-time thresholds (the conftest values) so the tiny programs
    test suites and chaos drills compile still get cached.

    Must run before the first compile (not before the first jax import);
    safe to call more than once."""
    import jax

    resolved = resolve_cache_dir(cache_dir)
    jax.config.update("jax_compilation_cache_dir", resolved)
    if test_tuning:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
    return resolved


def active_cache_dir() -> Optional[str]:
    """The cache dir jax is *actually* configured with right now (None when
    the persistent cache is off). Never raises — callers use this for
    best-effort hit accounting, not control flow."""
    try:
        import jax

        value = jax.config.jax_compilation_cache_dir
    except Exception:
        value = None
    return value or os.environ.get("JAX_COMPILATION_CACHE_DIR") or None


def cache_entry_count(cache_dir: Optional[str] = None) -> Optional[int]:
    """Number of entries in the persistent cache dir, or None when there is
    no (existing) cache dir. The before/after delta across one XLA compile
    is the hit/miss signal: a compile that wrote nothing new was a hit (or
    fell below the size/time thresholds — the ledger records the raw delta
    alongside the verdict so that ambiguity stays visible)."""
    d = cache_dir or active_cache_dir()
    if not d:
        return None
    try:
        return len(os.listdir(d))
    except OSError:
        return None
