"""graftsan — lock-discipline sanitizer (runtime half).

``runtime.py`` ships the ``san_lock`` / ``san_rlock`` / ``san_condition``
factories every threaded module in ``serving/`` + ``resilience/`` +
``observability/`` constructs its primitives through. With the sanitizer off
(the default) each factory returns the plain stdlib primitive — zero
overhead, bit-identical behavior. Armed (``HTYMP_GRAFTSAN=1`` or
``Config.resilience.sanitizer``), the factories return instrumented wrappers
that maintain a global site-keyed acquisition-order graph, report
lock-order cycles the moment the second edge lands (no actual deadlock
needed), flag blocking calls made while a lock is held, and audit thread
leaks at close seams.

The static half lives in ``tools/graftlint`` (rules GL210–GL213), sharing
the canonical hierarchy in ``order.toml`` via :func:`runtime.load_order`.
"""

from .runtime import (  # noqa: F401
    add_sink,
    arm,
    audit_thread_leaks,
    disarm,
    enabled,
    load_order,
    note_blocking,
    reset,
    san_condition,
    san_lock,
    san_rlock,
    snapshot,
    violations,
)
