"""The MAML++ meta-step as a single jit-compiled XLA program.

This replaces the reference's entire hot path (reference
``few_shot_learning_system.py:178-269,310-364``): the Python loop over tasks
becomes ``jax.vmap``; the inner adaptation loop becomes ``lax.scan`` with
per-step rematerialization (``jax.checkpoint``) so memory is O(1) in inner
steps; the ``higher`` second-order backprop becomes ``jax.grad`` of the scanned
rollout; and the outer Adam + cosine schedule + hyperparameter projection run
in the same compiled program. One program per (n_way, k_shot, steps) shape —
the epoch index is a traced scalar so MSL annealing never recompiles.

Restored knob: the reference accepts ``use_second_order`` but ignores it
(training is always second-order because ``track_higher_grads=True`` —
reference ``few_shot_learning_system.py:178,215-218``; SURVEY.md §2.2). Here
first-order MAML is a real option: ``stop_gradient`` on the inner grads.
"""

import functools
import inspect
import os
import warnings
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax

from ..config import Config, strategy_kind
from ..models import Model, build_model
from ..ops import build_inner_optimizer
from ..ops.losses import cross_entropy
from ..ops.msl import final_step_only, per_step_loss_importance
from ..ops.precision import as_f32, policy_from_config
from ..utils import seeding
from ..utils.trees import tree_count_params
from .train_state import TrainState


class StepOutput(NamedTuple):
    loss: jnp.ndarray
    accuracy: jnp.ndarray
    per_task_losses: jnp.ndarray  # [B]
    per_task_accuracies: jnp.ndarray  # [B] — mean target accuracy per episode
    per_task_target_logits: jnp.ndarray  # [B, n_target, n_way]
    loss_importance_vector: jnp.ndarray  # [num_steps]
    learning_rate: jnp.ndarray


def cosine_epoch_schedule(meta_lr: float, min_lr: float, total_epochs: int, iters_per_epoch: int):
    """CosineAnnealingLR stepped once per *epoch* with the integer epoch index —
    the reference calls ``scheduler.step(epoch=int(epoch))`` every iteration
    (``few_shot_learning_system.py:339-340``), which is the closed form below."""

    def schedule(count):
        epoch = jnp.asarray(count // iters_per_epoch, jnp.float32)
        cos = 0.5 * (1.0 + jnp.cos(jnp.pi * epoch / total_epochs))
        return min_lr + (meta_lr - min_lr) * cos

    return schedule


def _flatten_task(x):
    """[n_way, k, ...] -> [n_way*k, ...] (reference view(-1, c, h, w))."""
    return x.reshape((-1,) + x.shape[2:])


def apply_remat_policy(step, policy: str):
    """Wrap one scanned inner-step body per ``Config.resolved_remat_policy``.

    "none" returns ``step`` untouched (save everything); "full" is the
    legacy all-or-nothing ``jax.checkpoint`` (recompute everything —
    bit-identical to the old ``remat_inner_steps=True`` wrap); the named
    policies map onto ``jax.checkpoint_policies`` so XLA saves exactly that
    class of intermediates (dot/conv outputs under ``dots_saveable``) and
    recomputes the rest. Every choice is mathematically exact — remat moves
    bytes against recompute FLOPs, never the result — which the remat-parity
    tests pin. (jax's ``everything_saveable`` fails exactly that bar on
    jax 0.4.37 — it changes the primal loss under grad for this scanned
    second-order family, with or without CSE prevention — so the config
    rejects it; see ``config.REMAT_POLICIES``.) ``prevent_cse=False``
    throughout: inside ``lax.scan`` CSE prevention is unnecessary and only
    blocks fusion."""
    if policy == "none":
        return step
    if policy == "full":
        return jax.checkpoint(step, prevent_cse=False)
    named = getattr(jax.checkpoint_policies, policy, None)
    if named is None:
        raise ValueError(
            f"remat policy {policy!r} is not a jax.checkpoint_policies "
            "member on this jax — config validation and the mapping here "
            "have drifted"
        )
    return jax.checkpoint(step, prevent_cse=False, policy=named)


class MAMLSystem:
    """Builds and owns the compiled meta-train / meta-eval programs.

    Functional analogue of the reference's ``MAMLFewShotClassifier``; all
    mutable state lives in the ``TrainState`` pytree the caller threads
    through ``train_step`` / ``eval_step``.
    """

    def __init__(self, cfg: Config, model: Optional[Model] = None):
        self.cfg = cfg
        # adaptation strategy (core/strategies.py): which inner rollout the
        # meta-objective differentiates through. "maml++" (default) keeps
        # every code path below EXACTLY as it was — the strategy registry
        # dispatches host-side before tracing, so the default jaxpr (and
        # with it the persistent XLA cache) is bit-identical by
        # construction. Program keys carry the strategy via strategy_kind:
        # bare legacy kinds for the default, "train@anil"-style otherwise.
        self.strategy = getattr(cfg, "strategy", "maml++")
        # conv implementation + pooling convention are baked into the model's
        # apply as explicit build parameters (VERDICT r4 weak #5: these were
        # process globals with last-constructed-system-wins semantics). A
        # caller-supplied ``model`` carries whatever conventions it was built
        # with — pass conv_via_patches=True to the builder when pairing a
        # custom model with parallel.tp_convs. A known mismatch between the
        # model's baked conventions and the config fails here with a clear
        # error rather than a GSPMD partitioner crash (conv) or a silently
        # wrong tie-subgradient convention in a parity-debug run (pool);
        # None on the model means unknown/not-applicable and is not checked.
        if model is not None:
            for attr, want in (
                ("conv_via_patches", cfg.conv_via_patches),
                ("reduce_window_pool", cfg.max_pool_reduce_window),
                ("fuse_conv_bn", cfg.precision.fuse_conv_bn),
            ):
                have = getattr(model, attr, None)
                if have is not None and bool(have) != bool(want):
                    raise ValueError(
                        f"supplied model was built with {attr}={have} but the "
                        f"config requires {want}; rebuild the model with the "
                        f"matching builder argument (see models.build_model)"
                    )
            # conv_via_patches=None means the model never declared its conv
            # implementation (hand-built Model). When the config *requires*
            # the patches form (tp_convs auto-enables it), an undeclared
            # native conv would reach GSPMD's convolution handler and crash
            # at compile time — reject it here instead.
            if cfg.conv_via_patches and getattr(model, "conv_via_patches", None) is None:
                raise ValueError(
                    "config requires conv_via_patches (e.g. parallel.tp_convs) "
                    "but the supplied model does not declare its conv "
                    "implementation (Model.conv_via_patches is None); build it "
                    "via models.build_model/build_vgg/... with "
                    "conv_via_patches=True, or construct the Model with "
                    "conv_via_patches set"
                )
        self.model = model or build_model(
            cfg.net,
            cfg.image_shape,
            cfg.num_classes_per_set,
            conv_via_patches=cfg.conv_via_patches,
            reduce_window_pool=cfg.max_pool_reduce_window,
            fuse_conv_bn=cfg.precision.fuse_conv_bn,
        )
        io = cfg.inner_optim
        kwargs = {"lr": io.lr}
        if io.kind == "adam":
            kwargs.update(beta1=io.beta1, beta2=io.beta2)
        if cfg.use_pallas_inner_update:
            if io.kind not in ("sgd", "gd"):
                raise ValueError(
                    "use_pallas_inner_update only supports the sgd/gd inner "
                    f"optimizer, got inner_optim.kind={io.kind!r}"
                )
            kwargs["fused"] = True
        self.inner_opt = build_inner_optimizer(io.kind, **kwargs)
        # cumulative outer-LR scale (1.0 = the configured schedule); the
        # resilience NaN-rollback ladder shrinks it via scale_meta_lr
        self.meta_lr_scale = 1.0
        self.schedule = cosine_epoch_schedule(
            cfg.meta_learning_rate,
            cfg.min_learning_rate,
            cfg.total_epochs,
            cfg.total_iter_per_epoch,
        )
        self.outer_opt = optax.adam(learning_rate=self.schedule)
        # the ONE precision policy train and serve share (ops/precision.py):
        # every hot-path float cast — forward operands, BN statistics dtype,
        # rollout-entry fast-weight cast, logits exit cast — routes through
        # it. compute_dtype stays as the (derived) legacy alias.
        self.precision = policy_from_config(cfg)
        self.compute_dtype = self.precision.compute_dtype
        # hand-built Models (tests, probes) may predate the stat_dtype kwarg;
        # resolved once here so _apply_forward stays introspection-free —
        # such a model simply keeps its own statistics dtype (it usually has
        # no batch-norm at all)
        try:
            apply_params = inspect.signature(self.model.apply).parameters
            self._model_takes_stat_dtype = "stat_dtype" in apply_params or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in apply_params.values()
            )
        except (TypeError, ValueError):  # builtins/partials without signatures
            self._model_takes_stat_dtype = True
        # process-wide (jax has no per-program toggle for the compiled train
        # step's whole dot/conv population); applied unconditionally so the
        # last-constructed system's config always wins and a 'high'/'highest'
        # from an earlier system in the same process can't silently leak into
        # a later default-precision one. Exception: an explicit
        # JAX_DEFAULT_MATMUL_PRECISION env var wins over the config — it is
        # the documented jax contract and the probe scripts' A/B lever, and
        # the constructor silently clobbering it mislabeled a round-3
        # precision-probe arm (ADVICE r3). Env values may be any valid jax
        # spelling (float32, tensorfloat32, ...), wider than the three this
        # config validates. Last-constructed-wins is itself a footgun for
        # multi-system processes (probes, eval tooling), so any change of an
        # already-set different value is warned loudly.
        env_precision = os.environ.get("JAX_DEFAULT_MATMUL_PRECISION")
        target_precision = env_precision or cfg.matmul_precision
        if env_precision and env_precision != cfg.matmul_precision:
            warnings.warn(
                f"JAX_DEFAULT_MATMUL_PRECISION={env_precision!r} overrides "
                f"Config.matmul_precision={cfg.matmul_precision!r} for this "
                "process (env var wins; unset it to use the config value)",
                stacklevel=2,
            )
        prev = jax.config.jax_default_matmul_precision
        if prev is not None and prev != target_precision:
            warnings.warn(
                f"MAMLSystem(matmul_precision={target_precision!r}) is "
                f"overriding the process-wide jax_default_matmul_precision "
                f"({prev!r}); already-compiled programs keep the old value, "
                f"anything traced from now on uses the new one",
                stacklevel=2,
            )
        jax.config.update("jax_default_matmul_precision", target_precision)
        # (matmul precision is the ONLY process-global this constructor
        # touches — it is jax's own documented contract. The former
        # conv/pool module flags are now per-model build parameters above.)

        # Compiled program cache keyed by the static switches: (second_order,
        # msl_active). msl_active selects the rollout shape — per-step target
        # forwards during the MSL annealing window, a single final-step target
        # forward afterwards (and always for eval), matching the reference's
        # two code paths (few_shot_learning_system.py:239-251) without paying
        # num_steps target forwards when only the last one counts.
        self._train_step_cache = {}
        self._train_multi_cache = {}
        # strict mode: every lowering is noted against the declared train
        # program family BEFORE jit is invoked, so an unplanned variant
        # raises instead of silently paying an XLA compile mid-run
        self.recompile_guard = None
        if cfg.strict_recompile_guard:
            from ..utils.strictmode import RecompileGuard, train_planned_programs

            self.recompile_guard = RecompileGuard(
                planned=train_planned_programs(cfg), name="maml-system"
            )
        # compile ledger (observability/compile_ledger.py): when attached,
        # every program build below is wrapped so its XLA compiles are timed
        # and priced; None (the default) keeps builds exactly as they were
        self.compile_ledger = None
        self._note_program((self._kind("eval"),))
        self._eval_step = self._build_program(
            (self._kind("eval"),), lambda: jax.jit(self._eval_step_impl)
        )
        self._eval_multi = None

    def _kind(self, base: str) -> str:
        """Program-key kind for this system's strategy: the default keeps
        the bare legacy spelling, so default-config ledger rows, manifest
        names, and executable-store files are unchanged."""
        return strategy_kind(base, self.strategy)

    def _note_program(self, key) -> None:
        if self.recompile_guard is not None:
            self.recompile_guard.note(key)

    def _build_program(self, key, build):
        """One program-cache insert: build the jitted fn and, when a compile
        ledger is attached, wrap it so its compiles become ledger entries."""
        fn = build()
        if self.compile_ledger is not None:
            fn = self.compile_ledger.wrap_build(key, fn)
        return fn

    def attach_compile_ledger(self, ledger) -> None:
        """Route every program build through ``ledger`` (and hand it to the
        strict guard for its wrap() seam). The eval program was built
        eagerly at construction — rebuild it through the ledger so its
        compile is priced too (costs one re-trace if eval already ran;
        callers attach before the first dispatch)."""
        self.compile_ledger = ledger
        if self.recompile_guard is not None:
            self.recompile_guard.ledger = ledger
        if ledger is not None:
            self._eval_step = self._build_program(
                (self._kind("eval"),), lambda: jax.jit(self._eval_step_impl)
            )
            self._eval_multi = None

    # ------------------------------------------------------------------
    # state
    # ------------------------------------------------------------------

    @property
    def _per_step_hparams(self) -> bool:
        return bool(self.cfg.lslr_per_step and self.cfg.learnable_inner_opt_params)

    def init_train_state(self, seed: Optional[int] = None) -> TrainState:
        key = seeding.model_init_key(self.cfg.seed if seed is None else seed)
        params, bn_state = self.model.init(key)
        if self.cfg.learnable_inner_opt_params:
            inner_hparams = self.inner_opt.init_hparams(params)
            if self._per_step_hparams:
                # upstream MAML++ LSLR: one value per (tensor, inner step)
                K = self.cfg.number_of_training_steps_per_iter
                inner_hparams = jax.tree.map(
                    lambda a: jnp.tile(a, (K,) + (1,) * jnp.ndim(a)), inner_hparams
                )
        else:
            inner_hparams = {}
        trainables = {"params": params, "hparams": inner_hparams}
        opt_state = self.outer_opt.init(trainables)
        return TrainState(
            params=params,
            bn_state=bn_state,
            inner_hparams=inner_hparams,
            opt_state=opt_state,
            step=jnp.zeros((), jnp.int32),
        )

    def num_params(self, state: TrainState) -> int:
        return tree_count_params({"params": state.params, "hparams": state.inner_hparams})

    def scale_meta_lr(self, factor: float) -> None:
        """Shrink the outer LR schedule in place (resilience rollback
        backoff, experiment/runner.py::_note_bad_step): rebuilds the cosine
        schedule and the optax transform at ``meta_lr_scale * factor`` of the
        configured rates and drops every compiled train/eval program so the
        next step traces against the new schedule. The optimizer *state*
        (Adam moments + count) is structurally unchanged — a restored
        checkpoint keeps working across the swap. Recompiles are paid only
        when a rollback actually happens."""
        self.meta_lr_scale *= float(factor)
        cfg = self.cfg
        self.schedule = cosine_epoch_schedule(
            cfg.meta_learning_rate * self.meta_lr_scale,
            cfg.min_learning_rate * self.meta_lr_scale,
            cfg.total_epochs,
            cfg.total_iter_per_epoch,
        )
        self.outer_opt = optax.adam(learning_rate=self.schedule)
        self.drop_compiled_programs()

    def drop_compiled_programs(self) -> None:
        """Forget every compiled train/eval program so the next dispatch of
        each variant re-traces — the deliberate-invalidation half of the
        program cache, shared by the rollback LR backoff (the schedule
        changed) and the elastic mesh grow-back (the sharding changed:
        programs compiled for the degraded mesh would silently re-place
        inputs onto it). Strict mode re-plans the same family — recompiles
        after a deliberate drop are not violations."""
        self._train_step_cache.clear()
        self._train_multi_cache.clear()
        if self.recompile_guard is not None:
            # a deliberate cache drop re-plans the same family: the variants
            # recompiled against the new programs are not violations
            self.recompile_guard.reset()
        self._note_program((self._kind("eval"),))  # re-jitted below: count it
        self._eval_step = self._build_program(
            (self._kind("eval"),), lambda: jax.jit(self._eval_step_impl)
        )
        self._eval_multi = None

    # ------------------------------------------------------------------
    # inner rollout (per task)
    # ------------------------------------------------------------------

    def _inner_hparams_for_rollout(self, inner_hparams, params):
        if self.cfg.learnable_inner_opt_params:
            return inner_hparams
        # Non-learnable: constant per-tensor scalars from config.
        return self.inner_opt.init_hparams(params)

    def _initial_inner_state(self, params, hparams, opt_state):
        """Seed the inner optimizer state; for inner Adam, warm-start the
        moments from the outer Adam's state (the *intent* of the reference's
        deepcopy at ``few_shot_learning_system.py:219-220``, without the
        one-task lag — decision documented in SURVEY.md §2.2 / config)."""
        init_hp = hparams
        if self._per_step_hparams:
            # per-step (K,...)-shaped hparam leaves: state init (e.g. rprop's
            # step_size = lr) must see one step's values, not the K-vector
            init_hp = jax.tree.map(lambda a: a[0], hparams)
        inner_state = self.inner_opt.init_state(params, init_hp)
        if not (
            self.cfg.warm_start_inner_opt_from_outer
            and self.inner_opt.name == "adam"
            and opt_state is not None
        ):
            return inner_state
        adam_state = None
        for part in jax.tree.leaves(opt_state, is_leaf=lambda x: hasattr(x, "mu")):
            if hasattr(part, "mu"):
                adam_state = part
                break
        if adam_state is None:
            return inner_state
        count = jnp.asarray(adam_state.count, jnp.float32)
        return {
            "step": jax.tree.map(lambda p: count, params),
            "exp_avg": adam_state.mu["params"],
            "exp_avg_sq": adam_state.nu["params"],
        }

    def _apply_forward(self, params, bn_state, x, sample_weight=None):
        """One model forward in the policy's compute dtype, f32 logits out.

        Cast boundaries live in the :class:`PrecisionPolicy`
        (ops/precision.py): operands cast to the compute dtype on entry (a
        no-op when the bf16_inner rollout already carries bf16 fast
        weights), BN statistics reduced in the policy's ``stat_dtype`` when
        set, logits cast to f32 on exit so the loss/log-softmax always
        reduces in full precision.

        ``sample_weight`` ([N], 1 = real / 0 = padding) is forwarded to the
        model so transductive-BN statistics ignore padded samples — only the
        serving engine's shape-bucketed programs pass it; training/eval
        batches are never padded, and None keeps the apply call (and any
        hand-built Model without the kwarg) exactly as before."""
        pol = self.precision
        params, x = pol.cast_forward_inputs(params, x)
        kwargs = {}
        if sample_weight is not None:
            kwargs["sample_weight"] = sample_weight
        if pol.stat_dtype is not None and self._model_takes_stat_dtype:
            kwargs["stat_dtype"] = pol.stat_dtype
        logits, _ = self.model.apply(
            params, bn_state, x, use_batch_stats=True, **kwargs
        )
        return pol.cast_logits(logits)

    def _make_inner_update(
        self, bn_state, x_support, y_support, second_order, support_weight=None
    ):
        """Build ``inner_update(p, opt_state, hp) -> (p', opt_state')`` — one
        support-set gradient step, shared by the meta-objective rollout and
        the serving adapt path."""

        def inner_update(p, opt_s, hp):
            def support_loss_fn(q):
                return cross_entropy(
                    self._apply_forward(q, bn_state, x_support, support_weight),
                    y_support,
                    sample_weight=support_weight,
                )

            grads = jax.grad(support_loss_fn)(p)
            if not second_order:
                grads = jax.tree.map(lax.stop_gradient, grads)
            return self.inner_opt.update(grads, opt_s, p, hp)

        return inner_update

    def _hparam_sequence(self, hparams, num_steps: int):
        """Per-step hparam sequence scanned as xs. Fork semantics (default):
        the same hparams every step (free broadcast). Upstream-LSLR mode
        (lslr_per_step): slice the leading step axis; eval horizons beyond
        the trained one reuse the last step's values."""
        if self._per_step_hparams:
            K = self.cfg.number_of_training_steps_per_iter
            idx = jnp.minimum(jnp.arange(num_steps), K - 1)
            return jax.tree.map(lambda a: a[idx], hparams)
        return jax.tree.map(
            lambda a: jnp.broadcast_to(a, (num_steps,) + jnp.shape(a)), hparams
        )

    def _adapt_loop(
        self,
        params,
        bn_state,
        hparams,
        inner_state,
        x_support,
        y_support,
        second_order: bool,
        num_steps: int,
        support_weight=None,
    ):
        """The inner-loop rollout alone: ``num_steps`` support-set updates ->
        final fast weights. Factored out of the meta-objective so the serving
        engine (serving/engine.py) can run adaptation as a standalone program
        — first-order, no target forward, no meta-gradient graph.

        Under the bf16_inner policy the fast weights and the differentiable
        inner-optimizer state are cast to the compute dtype ONCE here — the
        whole K-step update chain then runs in bf16 while the f32 masters
        (params, LSLR lrs) are untouched and the meta-gradient accumulates
        in f32 through this (differentiable) cast."""
        params = self.precision.cast_fast_weights(params)
        inner_state = self.precision.cast_fast_weights(inner_state)
        inner_update = self._make_inner_update(
            bn_state, x_support, y_support, second_order, support_weight
        )
        hp_seq = self._hparam_sequence(hparams, num_steps)
        unroll = num_steps if self.cfg.unroll_inner_steps else 1

        def step(carry, hp):
            p, opt_s = carry
            return inner_update(p, opt_s, hp), None

        step = apply_remat_policy(step, self.cfg.resolved_remat_policy)
        (p_final, _), _ = lax.scan(
            step, (params, inner_state), hp_seq, unroll=unroll
        )
        return p_final

    def _rollout(
        self,
        params,
        bn_state,
        hparams,
        inner_state,
        x_support,
        y_support,
        x_target,
        y_target,
        loss_weights,
        second_order: bool,
        num_steps: int,
        per_step_target: bool,
    ):
        """Adapt on the support set for ``num_steps``. With ``per_step_target``
        (the MSL annealing window) the target loss is computed after *every*
        inner step and accumulated with ``loss_weights``; otherwise only the
        final adapted parameters see the target set — one target forward total,
        the reference's post-annealing/eval path
        (few_shot_learning_system.py:246-251). Returns
        (task_loss, final_target_logits)."""
        if self.strategy == "anil":
            # head-only inner loop (core/strategies.py): same contract,
            # same MSL/remat/precision composition, a far smaller meta-graph
            from .strategies import anil_rollout

            return anil_rollout(
                self, params, bn_state, hparams, inner_state, x_support,
                y_support, x_target, y_target, loss_weights, second_order,
                num_steps, per_step_target,
            )
        forward = lambda p, x: self._apply_forward(p, bn_state, x)

        if per_step_target:
            # same rollout-entry cast _adapt_loop does: fast weights + inner
            # state in the compute dtype for the whole scanned chain
            params = self.precision.cast_fast_weights(params)
            inner_state = self.precision.cast_fast_weights(inner_state)
            inner_update = self._make_inner_update(
                bn_state, x_support, y_support, second_order
            )
            hp_seq = self._hparam_sequence(hparams, num_steps)
            unroll = num_steps if self.cfg.unroll_inner_steps else 1

            def step(carry, xs):
                weight, hp = xs
                p, opt_s, _ = carry
                p_new, opt_s_new = inner_update(p, opt_s, hp)
                target_logits = forward(p_new, x_target)
                target_loss = cross_entropy(target_logits, y_target)
                return (p_new, opt_s_new, target_logits), weight * target_loss

            step = apply_remat_policy(step, self.cfg.resolved_remat_policy)
            # scan-carry logits built in the policy's logits dtype (f32 —
            # what cast_logits exits in), pinned explicitly so under
            # bf16_inner the carry dtype is a policy decision, not a
            # promotion accident
            logits0 = jnp.zeros(
                (x_target.shape[0], self.cfg.num_classes_per_set),
                dtype=self.precision.logits_dtype,
            )
            (_, _, final_logits), weighted_losses = lax.scan(
                step, (params, inner_state, logits0), (loss_weights, hp_seq), unroll=unroll
            )
            return jnp.sum(weighted_losses), final_logits

        p_final = self._adapt_loop(
            params, bn_state, hparams, inner_state, x_support, y_support,
            second_order, num_steps,
        )
        final_logits = forward(p_final, x_target)
        return cross_entropy(final_logits, y_target), final_logits

    # ------------------------------------------------------------------
    # meta objective over a task batch
    # ------------------------------------------------------------------

    def msl_active(self, epoch: int, training: bool = True) -> bool:
        """Host-side static switch: per-step MSL weighting applies during
        training in the annealing window (reference
        few_shot_learning_system.py:239-240)."""
        return bool(
            training
            and self.cfg.use_multi_step_loss_optimization
            and epoch < self.cfg.multi_step_loss_num_epochs
        )

    def _loss_weights(self, epoch, num_steps, msl_active: bool):
        if msl_active:
            # traced epoch: annealing never recompiles within the window
            return per_step_loss_importance(
                epoch, num_steps, self.cfg.multi_step_loss_num_epochs
            )
        return final_step_only(num_steps)

    def _meta_objective(
        self, trainables, bn_state, opt_state, batch, epoch, second_order, num_steps,
        msl_active
    ):
        params = trainables["params"]
        hparams = self._inner_hparams_for_rollout(trainables["hparams"], params)
        inner_state0 = self._initial_inner_state(params, hparams, opt_state)
        loss_weights = self._loss_weights(epoch, num_steps, msl_active)

        def per_task(x_s, y_s, x_t, y_t):
            return self._rollout(
                params,
                bn_state,
                hparams,
                inner_state0,
                _flatten_task(x_s),
                _flatten_task(y_s),
                _flatten_task(x_t),
                _flatten_task(y_t),
                loss_weights,
                second_order,
                num_steps,
                per_step_target=msl_active,
            )

        task_losses, target_logits = jax.vmap(per_task)(
            batch["x_support"], batch["y_support"], batch["x_target"], batch["y_target"]
        )
        # mean over tasks (reference get_across_task_loss_metrics,
        # few_shot_learning_system.py:170-176)
        loss = jnp.mean(task_losses)
        y_t_flat = batch["y_target"].reshape(batch["y_target"].shape[0], -1)
        # per-episode target accuracy [B]: the unit the published tables'
        # error bars are computed over (reference aggregates per-episode
        # accuracies; VERDICT r2 weak #2 — batch-mean std understates spread)
        per_task_acc = jnp.mean(
            as_f32(jnp.argmax(target_logits, axis=-1) == y_t_flat),
            axis=-1,
        )
        acc = jnp.mean(per_task_acc)
        aux = {
            "accuracy": acc,
            "per_task_losses": task_losses,
            "per_task_accuracies": per_task_acc,
            "target_logits": target_logits,
            "loss_weights": loss_weights,
        }
        return loss, aux

    # ------------------------------------------------------------------
    # compiled steps
    # ------------------------------------------------------------------

    def _train_step_impl(self, state: TrainState, batch, *, second_order: bool, msl_active: bool):
        cfg = self.cfg
        epoch = state.step // cfg.total_iter_per_epoch
        trainables = {"params": state.params, "hparams": state.inner_hparams}
        grad_fn = jax.value_and_grad(self._meta_objective, has_aux=True)
        (loss, aux), grads = grad_fn(
            trainables,
            state.bn_state,
            state.opt_state,
            batch,
            epoch,
            second_order,
            cfg.number_of_training_steps_per_iter,
            msl_active,
        )
        if cfg.is_imagenet:
            # element-wise clamp of classifier grads only (reference
            # few_shot_learning_system.py:317-320)
            grads = {
                "params": jax.tree.map(lambda g: jnp.clip(g, -10.0, 10.0), grads["params"]),
                "hparams": grads["hparams"],
            }
        updates, new_opt_state = self.outer_opt.update(grads, state.opt_state, trainables)
        new_trainables = optax.apply_updates(trainables, updates)
        new_hparams = new_trainables["hparams"]
        if cfg.learnable_inner_opt_params:
            new_hparams = self.inner_opt.project_hparams(new_hparams)
        new_state = TrainState(
            params=new_trainables["params"],
            bn_state=state.bn_state,
            inner_hparams=new_hparams,
            opt_state=new_opt_state,
            step=state.step + 1,
        )
        out = StepOutput(
            loss=loss,
            accuracy=aux["accuracy"],
            per_task_losses=aux["per_task_losses"],
            per_task_accuracies=aux["per_task_accuracies"],
            per_task_target_logits=aux["target_logits"],
            loss_importance_vector=aux["loss_weights"],
            learning_rate=self.schedule(state.step),
        )
        return new_state, out

    def _eval_step_impl(self, state: TrainState, batch):
        cfg = self.cfg
        epoch = state.step // cfg.total_iter_per_epoch
        trainables = {"params": state.params, "hparams": state.inner_hparams}
        loss, aux = self._meta_objective(
            trainables,
            state.bn_state,
            state.opt_state,
            batch,
            epoch,
            False,
            cfg.number_of_evaluation_steps_per_iter,
            False,  # eval is always final-step-only (reference :239-251)
        )
        return StepOutput(
            loss=loss,
            accuracy=aux["accuracy"],
            per_task_losses=aux["per_task_losses"],
            per_task_accuracies=aux["per_task_accuracies"],
            per_task_target_logits=aux["target_logits"],
            loss_importance_vector=aux["loss_weights"],
            learning_rate=self.schedule(state.step),
        )

    # ------------------------------------------------------------------
    # public API (mirrors reference run_train_iter / run_validation_iter)
    # ------------------------------------------------------------------

    def use_second_order(self, epoch: int) -> bool:
        """Reference intent (few_shot_learning_system.py:288-289): second order
        iff ``second_order`` and ``epoch > first_order_to_second_order_epoch``.
        The ``fomaml`` strategy IS this switch pinned False for the whole
        run — its train program coincides with maml++'s
        ``second_order=false`` variant by construction."""
        if self.strategy == "fomaml":
            return False
        return bool(
            self.cfg.second_order and epoch > self.cfg.first_order_to_second_order_epoch
        )

    def _donate_argnums(self) -> Tuple[int, ...]:
        """Donated args of the compiled train step/chunk: the TrainState
        (arg 0, behind the corruption-verdict gate — config.py
        ``donate_train_state``) and the episode batch buffers (arg 1 —
        throwaway by construction: the loader transfers a fresh batch every
        step and nothing reads one after its dispatch)."""
        donate = []
        if self.cfg.donate_train_state:
            donate.append(0)
        if self.cfg.donate_batch:
            donate.append(1)
        return tuple(donate)

    def _compiled_train_step(self, second_order: bool, msl_active: bool):
        key = (second_order, msl_active)
        if key not in self._train_step_cache:
            self._note_program((self._kind("train"),) + key)
            donate = self._donate_argnums()
            self._train_step_cache[key] = self._build_program(
                (self._kind("train"),) + key,
                lambda: jax.jit(
                    functools.partial(
                        self._train_step_impl, second_order=second_order, msl_active=msl_active
                    ),
                    donate_argnums=donate,
                ),
            )
        return self._train_step_cache[key]

    def train_step(
        self, state: TrainState, batch, epoch: Optional[int] = None
    ) -> Tuple[TrainState, StepOutput]:
        """One outer update. ``epoch`` (host int) selects the compiled program
        variant; pass it in the training loop to avoid a host-device sync —
        when omitted it is read from ``state.step`` (blocking)."""
        if epoch is None:
            epoch = int(state.step) // self.cfg.total_iter_per_epoch
        step_fn = self._compiled_train_step(
            self.use_second_order(epoch), self.msl_active(epoch)
        )
        return step_fn(state, batch)

    def eval_step(self, state: TrainState, batch) -> StepOutput:
        return self._eval_step(state, batch)

    # ------------------------------------------------------------------
    # serving entry points (adapt-once / predict-many; serving/engine.py)
    # ------------------------------------------------------------------

    def adapt_fast_weights(
        self,
        state: TrainState,
        x_support,
        y_support,
        num_steps: Optional[int] = None,
        support_weight=None,
        strategy: Optional[str] = None,
    ):
        """Inner-loop adaptation only: support set [S, H, W, C] / [S] ->
        adapted parameter pytree. First-order (no meta-gradient graph is ever
        built — nothing differentiates through this), no target forward; the
        same rollout ``eval_step`` runs per task, so
        ``predict_logits(adapt_fast_weights(...), ...)`` reproduces the
        eval-step target logits. ``support_weight`` masks padded samples out
        of the loss and the transductive-BN statistics (shape bucketing).
        Deliberately not jitted here — the serving engine jits per shape
        bucket and task-batch size.

        ``strategy`` picks the serving-side rollout PER CALL (the engine
        serves an accuracy/latency menu from one checkpoint): None = this
        system's own strategy; ``"maml++"``/``"fomaml"`` are the full
        rollout (serving adaptation is already first-order, so they
        coincide here); ``"anil"`` runs the head-only loop. ``"protonet"``
        has no fast-weight rollout — use :meth:`protonet_adapt`."""
        cfg = self.cfg
        strategy = self.strategy if strategy is None else strategy
        if strategy == "protonet":
            raise ValueError(
                "protonet adaptation is a prototype reduction, not a "
                "fast-weight rollout; use protonet_adapt/protonet_predict"
            )
        if num_steps is None:
            num_steps = cfg.number_of_evaluation_steps_per_iter
        hparams = self._inner_hparams_for_rollout(state.inner_hparams, state.params)
        inner_state = self._initial_inner_state(
            state.params, hparams, state.opt_state
        )
        if strategy == "anil":
            from .strategies import anil_adapt_loop

            return anil_adapt_loop(
                self,
                state.params,
                state.bn_state,
                hparams,
                inner_state,
                x_support,
                y_support,
                second_order=False,
                num_steps=num_steps,
                support_weight=support_weight,
            )
        return self._adapt_loop(
            state.params,
            state.bn_state,
            hparams,
            inner_state,
            x_support,
            y_support,
            second_order=False,
            num_steps=num_steps,
            support_weight=support_weight,
        )

    def refine_fast_weights(
        self,
        state: TrainState,
        fast_weights,
        x_support,
        y_support,
        num_steps: Optional[int] = None,
        support_weight=None,
        strategy: Optional[str] = None,
    ):
        """Update-in-place refinement: the K-step rollout of
        :meth:`adapt_fast_weights`, but started FROM a session's previously
        adapted ``fast_weights`` instead of the masters — the serving-side
        continual-adaptation primitive (ISSUE 17). Inner-optimizer hparams
        and state are still derived from the masters (``state.params``): a
        refinement is a fresh K-step episode over new support data, not a
        continuation of the original optimizer trajectory, so every
        refinement is governed by the same LSLR schedule the checkpoint was
        trained with."""
        cfg = self.cfg
        strategy = self.strategy if strategy is None else strategy
        if strategy == "protonet":
            raise ValueError(
                "protonet has no fast-weight rollout to refine — recompute "
                "prototypes from the new support set via protonet_adapt"
            )
        if num_steps is None:
            num_steps = cfg.number_of_evaluation_steps_per_iter
        hparams = self._inner_hparams_for_rollout(state.inner_hparams, state.params)
        inner_state = self._initial_inner_state(
            state.params, hparams, state.opt_state
        )
        if strategy == "anil":
            from .strategies import anil_adapt_loop

            return anil_adapt_loop(
                self,
                fast_weights,
                state.bn_state,
                hparams,
                inner_state,
                x_support,
                y_support,
                second_order=False,
                num_steps=num_steps,
                support_weight=support_weight,
            )
        return self._adapt_loop(
            fast_weights,
            state.bn_state,
            hparams,
            inner_state,
            x_support,
            y_support,
            second_order=False,
            num_steps=num_steps,
            support_weight=support_weight,
        )

    def protonet_adapt(self, state: TrainState, x_support, y_support,
                       support_weight=None):
        """ProtoNet ``adapt`` (core/strategies.py): one embedding forward +
        masked class-prototype reduction -> ``{"prototypes": [n_way, D]}``
        — the forward-only serving tier's session state. Zero gradients."""
        from .strategies import protonet_prototypes

        return protonet_prototypes(
            self, state.params, state.bn_state, x_support, y_support,
            support_weight,
        )

    def protonet_predict_logits(self, state_params, bn_state, prototypes,
                                x_query, sample_weight=None):
        """ProtoNet ``predict``: distance logits of a query batch against a
        prototype table (master params embed the queries)."""
        from .strategies import protonet_logits

        return protonet_logits(
            self, state_params, bn_state, prototypes, x_query, sample_weight
        )

    def predict_logits(self, fast_weights, bn_state, x, sample_weight=None):
        """Forward a query batch [Q, H, W, C] through adapted fast weights ->
        f32 logits [Q, num_classes]. Transductive BN over the query batch
        (the reference's eval convention); ``sample_weight`` masks padded
        queries out of the statistics."""
        return self._apply_forward(fast_weights, bn_state, x, sample_weight)

    # ------------------------------------------------------------------
    # multi-step dispatch
    # ------------------------------------------------------------------

    def _train_multi_impl(self, state: TrainState, batches, *, second_order: bool, msl_active: bool):
        def body(carry, batch):
            new_state, out = self._train_step_impl(
                carry, batch, second_order=second_order, msl_active=msl_active
            )
            # light per-step outputs only — the training loop consumes just
            # these three; hauling K x [B, ...] per-task logits through the
            # scan carry would cost HBM and D2H for nothing
            return new_state, (out.loss, out.accuracy, out.learning_rate)
        return jax.lax.scan(body, state, batches)

    def _compiled_train_multi(self, second_order: bool, msl_active: bool):
        key = (second_order, msl_active)
        if key not in self._train_multi_cache:
            self._note_program((self._kind("train_multi"),) + key)
            donate = self._donate_argnums()
            self._train_multi_cache[key] = self._build_program(
                (self._kind("train_multi"),) + key,
                lambda: jax.jit(
                    functools.partial(
                        self._train_multi_impl, second_order=second_order, msl_active=msl_active
                    ),
                    donate_argnums=donate,
                ),
            )
        return self._train_multi_cache[key]

    def train_step_multi(
        self, state: TrainState, batches, epoch: int
    ) -> Tuple[TrainState, Tuple]:
        """K outer updates in ONE dispatch: ``lax.scan`` of the train step
        over ``batches`` whose leaves carry a leading ``[K]`` axis (from
        ``MetaLearningDataLoader.train_batch_chunks``). Identical math to K
        ``train_step`` calls — the scan body IS ``_train_step_impl`` — but
        one host->device dispatch and one transfer per K steps, which is
        what matters when the chip sits behind a network tunnel whose
        per-call RPC latency rivals the ~30 ms device step itself (the
        measured 10-16 ms/step wall-vs-device gap, docs/DESIGN.md §6).

        Returns ``(new_state, (losses[K], accuracies[K], learning_rates[K]))``.
        The chunk must not span an epoch where the (second_order, msl_active)
        program variant flips — the runner dispatches within one epoch, and
        MSL's *within*-variant annealing stays exact because loss weights are
        computed from the traced ``state.step`` each scan iteration.
        """
        step_fn = self._compiled_train_multi(
            self.use_second_order(epoch), self.msl_active(epoch)
        )
        return step_fn(state, batches)

    def _eval_multi_impl(self, state: TrainState, batches):
        def body(carry, batch):
            out = self._eval_step_impl(state, batch)
            return carry, (out.per_task_losses, out.per_task_accuracies)
        _, ys = jax.lax.scan(body, (), batches)
        return ys

    def _compiled_eval_multi(self):
        if self._eval_multi is None:
            self._note_program((self._kind("eval_multi"),))
            self._eval_multi = self._build_program(
                (self._kind("eval_multi"),), lambda: jax.jit(self._eval_multi_impl)
            )
        return self._eval_multi

    def eval_step_multi(self, state: TrainState, batches):
        """Every eval batch in ONE dispatch: ``lax.scan`` of the eval step
        over ``batches`` with a leading ``[N]`` axis. Same per-batch math as
        N ``eval_step`` calls; amortizes the per-dispatch overhead across
        the whole fixed evaluation set (75 dispatches/epoch at the flagship
        config's 600 tasks / batch 8). Returns
        ``(per_task_losses [N, B], per_task_accuracies [N, B])``."""
        return self._compiled_eval_multi()(state, batches)

    # ------------------------------------------------------------------
    # AOT prewarm (compile/aot.py; ROADMAP item 2)
    # ------------------------------------------------------------------

    def prewarm(
        self,
        state: TrainState,
        batch_sharding=None,
        chunk_sharding=None,
        max_workers: Optional[int] = None,
        compile_timeout_s: Optional[float] = None,
        on_program=None,
        store=None,
    ) -> Dict[str, Any]:
        """AOT-compile the ENTIRE planned train program family — the exact
        ``train_planned_programs`` set the strict guard enforces — before
        the first step, every compile timed through the ledger with
        ``phase="prewarm"``, nothing executed. Shardings: pass the runner's
        batch/chunk shardings so the warmed programs bake the placements
        the real dispatches use. Returns the prewarm summary (programs,
        seconds, persistent-cache hits, per-program table)."""
        from ..compile.aot import prewarm_train

        aot_cfg = getattr(self.cfg, "aot", None)
        return prewarm_train(
            self,
            state,
            batch_sharding=batch_sharding,
            chunk_sharding=chunk_sharding,
            max_workers=max_workers
            if max_workers is not None
            else getattr(aot_cfg, "max_workers", 4),
            compile_timeout_s=compile_timeout_s
            if compile_timeout_s is not None
            else getattr(aot_cfg, "compile_timeout_s", 3600.0),
            on_program=on_program,
            store=store,
        )
