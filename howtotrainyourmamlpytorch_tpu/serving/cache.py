"""Content-addressed LRU cache of adapted parameter trees.

The serving workload is adapt-once / predict-many: a client uploads a support
set, the engine runs the inner loop, then answers query requests against the
adapted weights. Repeat clients (same support set, same checkpoint) are the
common case — the cache keys adapted weights by
``(checkpoint fingerprint, support-set digest)`` so they skip the inner loop
entirely. Bounded by a byte budget (LRU eviction) and a TTL; hit / miss /
eviction / expiration counters feed the ``/metrics`` endpoint.
"""

import hashlib
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from ..utils.locks import san_lock

# (checkpoint fingerprint, adaptation strategy, support-set digest)
CacheKey = Tuple[str, str, str]


def support_digest(
    x_support, y_support, num_steps: int, strategy: str = "maml++",
    tenant: Optional[str] = None,
) -> str:
    """Content hash of one adapt request: support tensors + shapes + dtypes +
    the inner-step horizon (the same support set adapted for a different
    number of steps is a different cache entry) + the adaptation strategy —
    a ProtoNet prototype table and a MAML fast-weight tree for the same
    support set are different sessions, so their adaptation ids (and with
    them every cache key, session-spill file, and gateway affinity hash)
    never collide. ``tenant`` folds in the same way (serving/tenancy.py):
    the same support set adapted against two tenants' masters is two
    sessions, and the gateway's body-hash affinity separates them for free.
    The default strategy and the default/absent tenant contribute nothing
    to the hash, so every pre-tenancy adaptation id is unchanged."""
    h = hashlib.sha256()
    for arr in (x_support, y_support):
        a = np.ascontiguousarray(arr)
        h.update(str(a.shape).encode())
        h.update(str(a.dtype).encode())
        h.update(a.tobytes())
    h.update(str(int(num_steps)).encode())
    if strategy != "maml++":
        h.update(f"strategy:{strategy}".encode())
    if tenant:
        h.update(f"tenant:{tenant}".encode())
    return h.hexdigest()


def tree_bytes(tree: Any) -> int:
    return sum(leaf.nbytes for leaf in jax.tree.leaves(tree))


class SessionLineage:
    """Versioned session state for guarded update-in-place refinement
    (ISSUE 17): a cache entry that has been refined is no longer a one-shot
    memo but a VERSION, and this record carries its history — committed
    refinement count, held-out score trail, a bounded ring of previously
    committed fast-weight versions (the rollback targets), the
    consecutive-regression streak, and the quarantine flag. The frontend
    (``serving/server.py::ServingFrontend.refine``) owns the guard POLICY;
    this is the bookkeeping that rides ``SessionStore`` spill/rehydrate so
    lineage survives drains and rolling restarts. Not thread-safe by
    itself — the frontend serializes mutations under its lineage lock."""

    #: held-out score history bound: enough for a trend, never unbounded
    MAX_SCORES = 32

    def __init__(self, snapshot_ring: int = 2):
        self.snapshot_ring = max(1, int(snapshot_ring))
        self.refine_count = 0
        self.rollbacks = 0
        self.consecutive_regressions = 0
        self.quarantined = False
        self.scores: list = []  # committed held-out scores, oldest first
        self.snapshots: list = []  # previous committed trees, oldest first
        # persistent held-out probe (x, y): carved from the FIRST refine's
        # support set, so every later refinement is scored against the same
        # yardstick — scores stay comparable across the session's life
        self.probe = None

    @property
    def last_good_score(self):
        return self.scores[-1] if self.scores else None

    def set_baseline(self, score: float) -> None:
        """Seed the score trail with the PRE-refinement weights' held-out
        score (first refine only): the first guard comparison needs a
        last-good to regress against."""
        if not self.scores:
            self.scores.append(float(score))

    def commit(self, previous_tree: Any, score: float) -> None:
        """A refinement passed the guard: the previously committed weights
        join the (bounded) snapshot ring, the score joins the trail, and
        the regression streak resets."""
        self.snapshots.append(previous_tree)
        while len(self.snapshots) > self.snapshot_ring:
            self.snapshots.pop(0)
        self.scores.append(float(score))
        while len(self.scores) > self.MAX_SCORES:
            self.scores.pop(0)
        self.refine_count += 1
        self.consecutive_regressions = 0

    def reject(self) -> int:
        """A refinement failed the guard (non-finite or regressed past
        tolerance): the candidate is discarded, the session stays at its
        last-good version. Returns the new consecutive-regression streak —
        the frontend quarantines at ``serving.refine_quarantine_after``."""
        self.rollbacks += 1
        self.consecutive_regressions += 1
        return self.consecutive_regressions

    def snapshot_bytes(self) -> int:
        """Bytes held by the rollback ring — the honest extra footprint a
        refined session carries beyond its live cache entry."""
        return sum(tree_bytes(t) for t in self.snapshots)

    def summary(self) -> Dict[str, Any]:
        return {
            "refine_count": self.refine_count,
            "rollbacks": self.rollbacks,
            "consecutive_regressions": self.consecutive_regressions,
            "quarantined": self.quarantined,
            "snapshots": len(self.snapshots),
            "snapshot_bytes": self.snapshot_bytes(),
            "last_good_score": self.last_good_score,
        }


class AdaptedWeightCache:
    """Thread-safe LRU of adapted parameter pytrees.

    ``max_bytes`` bounds the sum of leaf sizes (an entry that alone exceeds
    the budget is rejected — counted as an eviction); ``ttl_s`` expires
    entries lazily on access and on insert. ``clock`` is injectable so tests
    exercise TTL without sleeping."""

    def __init__(
        self,
        max_bytes: int,
        ttl_s: float,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.max_bytes = int(max_bytes)
        self.ttl_s = float(ttl_s)
        self._clock = clock
        self._lock = san_lock("AdaptedWeightCache._lock")
        # key -> (tree, nbytes, inserted_at); OrderedDict order = LRU order
        self._entries: "OrderedDict[CacheKey, Tuple[Any, int, float]]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.expirations = 0

    def _expire_locked(self, now: float) -> None:
        if self.ttl_s <= 0:
            return
        dead = [
            key
            for key, (_, _, t) in self._entries.items()
            if now - t > self.ttl_s
        ]
        for key in dead:
            _, nbytes, _ = self._entries.pop(key)
            self._bytes -= nbytes
            self.expirations += 1

    def get(self, key: CacheKey, ctx=None) -> Optional[Any]:
        """``ctx`` (observability/context.py RequestContext) gets the per-
        request hit verdict stamped on it — the access log's ``cache_hit``
        field, attributed at the seam that knows, not re-derived upstream."""
        now = self._clock()
        with self._lock:
            self._expire_locked(now)
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                if ctx is not None:
                    ctx.cache_hit = False
                return None
            self._entries.move_to_end(key)
            self.hits += 1
        if ctx is not None:
            ctx.cache_hit = True
        return entry[0]

    def put(self, key: CacheKey, tree: Any, age_s: float = 0.0) -> None:
        """``age_s`` back-dates the entry (rehydration after a drain: a
        session restored with 1s of TTL budget left must expire in 1s, not
        get a fresh full TTL)."""
        nbytes = tree_bytes(tree)
        now = self._clock()
        with self._lock:
            self._expire_locked(now)
            if key in self._entries:
                _, old_bytes, _ = self._entries.pop(key)
                self._bytes -= old_bytes
            if nbytes > self.max_bytes:
                # one entry over the whole budget: caching it would evict
                # everything and still bust the bound — refuse
                self.evictions += 1
                return
            self._entries[key] = (tree, nbytes, now - float(age_s))
            self._bytes += nbytes
            while self._bytes > self.max_bytes:
                _, (_, evicted_bytes, _) = self._entries.popitem(last=False)
                self._bytes -= evicted_bytes
                self.evictions += 1

    def bytes_for_fingerprint(self, fingerprint: str) -> int:
        """Live adapted-session bytes keyed under one checkpoint fingerprint
        — the honest denominator for a per-tenant resident-bytes quota
        (serving/tenancy.py::TenantQuotas): summed from the actual entries,
        not estimated from counters."""
        now = self._clock()
        with self._lock:
            self._expire_locked(now)
            return sum(
                nbytes
                for key, (_, nbytes, _) in self._entries.items()
                if key[0] == fingerprint
            )

    def snapshot_entries(self):
        """``[(key, tree, age_s)]`` of every live (unexpired) entry, LRU
        order, under the lock — the graceful-drain spill's source
        (serving/sessions.py). ``age_s`` lets the spill preserve each
        entry's ORIGINAL TTL budget across a restart."""
        now = self._clock()
        with self._lock:
            self._expire_locked(now)
            return [
                (key, tree, now - inserted)
                for key, (tree, _, inserted) in self._entries.items()
            ]

    def __contains__(self, key: CacheKey) -> bool:
        with self._lock:
            return key in self._entries

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            total = self.hits + self.misses
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": (self.hits / total) if total else 0.0,
                "evictions": self.evictions,
                "expirations": self.expirations,
            }
