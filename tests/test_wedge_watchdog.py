"""Wedge watchdog + elastic degraded-mesh resume (ISSUE 3 acceptance).

The failure class PR 2 could not touch: a device call that hangs instead of
raising (the BENCH_r03-r05 wedged-tunnel signature), and a device count that
shrinks between runs. The drills here mirror the PR 2 SIGTERM drill shape:
inject the failure, assert the bounded response (rc=76 + thread stacks in
events.jsonl / a shrunken mesh + degraded_mesh event), then prove the
subsequent resume matches an uninterrupted control run.

The rc=76 path ends in ``os._exit`` and the device-shrink path needs a
different visible-device count, so those legs run in subprocesses (via the
chaos campaign's child entry); everything else is in-process with fake
clocks and injected exit functions.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

from howtotrainyourmamlpytorch_tpu.config import (
    Config,
    ParallelConfig,
    ResilienceConfig,
    WatchdogConfig,
    save_config,
)
from howtotrainyourmamlpytorch_tpu.experiment import ExperimentRunner
from howtotrainyourmamlpytorch_tpu.experiment.storage import EventLog
from howtotrainyourmamlpytorch_tpu.parallel import degraded_mesh_plan
from howtotrainyourmamlpytorch_tpu.resilience import HeartbeatWatchdog
from howtotrainyourmamlpytorch_tpu.resilience.campaign import (
    _child_env,
    campaign_config,
    tiny_system,
)

from tests.test_runner import runner_config, small_system, toy_dataset  # noqa: F401

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# HeartbeatWatchdog state machine (fake clock, injected exit)
# ---------------------------------------------------------------------------


def _wd(deadline=5.0, **kw):
    t = {"now": 0.0}
    exits, infos = [], []
    wd = HeartbeatWatchdog(
        deadline,
        on_wedge=infos.append,
        clock=lambda: t["now"],
        exit_fn=exits.append,
        poll_s=3600,  # the real thread never polls during the test
        **kw,
    )
    return wd, t, exits, infos


def test_watchdog_fires_once_past_deadline_with_stacks():
    wd, t, exits, infos = _wd(5.0)
    t["now"] = 100.0
    assert not wd.check()  # not armed: a stale clock can't fire it
    wd.arm("stage-a")
    t["now"] = 104.0
    assert not wd.check()  # within deadline
    wd.beat("stage-b")  # progress resets the clock
    t["now"] = 108.0
    assert not wd.check()
    t["now"] = 110.1  # 6.1s since the beat
    assert wd.check()
    assert exits == [76]
    (info,) = infos
    assert info["stage"] == "stage-b"
    assert info["stall_s"] > 5.0
    # every live thread's stack is in the post-mortem, incl. this one
    assert info["threads"] and any(
        "test_watchdog_fires_once" in "".join(stack)
        for stack in info["threads"].values()
    )
    # single-shot: a second expiry does not fire (or exit) again
    t["now"] = 200.0
    assert not wd.check()
    assert exits == [76]


def test_watchdog_disarm_and_idle_hold():
    wd, t, exits, _ = _wd(5.0)
    wd.arm()
    wd.disarm()
    t["now"] = 100.0
    assert not wd.check() and not exits  # disarmed: never fires
    # poll mode: pending_fn falsy holds the clock reset — idle is not wedged
    pend = {"pending": False}
    prog = {"n": 0}
    wd2, t2, exits2, _ = _wd(
        5.0, pending_fn=lambda: pend["pending"], progress_fn=lambda: prog["n"]
    )
    wd2.arm()
    t2["now"] = 100.0
    assert not wd2.check()
    # work appears; progress advances each poll: still healthy
    pend["pending"] = True
    for now in (103.0, 106.0, 109.0):
        prog["n"] += 1
        t2["now"] = now
        assert not wd2.check()
    # progress stalls with work pending: fires after the deadline
    t2["now"] = 112.0
    assert not wd2.check()  # 109 -> 112: only 3s stalled
    t2["now"] = 114.2
    assert wd2.check()
    assert exits2 == [76]


def test_watchdog_exit_code_and_on_wedge_exception_still_exits():
    def boom(info):
        raise RuntimeError("post-mortem bug")

    t = {"now": 0.0}
    exits = []
    wd = HeartbeatWatchdog(
        1.0, on_wedge=boom, clock=lambda: t["now"], exit_fn=exits.append,
        poll_s=3600, exit_code=77,
    )
    wd.arm()
    t["now"] = 2.5
    assert wd.check()
    assert exits == [77]  # a broken on_wedge must not turn rc into a zombie


# ---------------------------------------------------------------------------
# EventLog: flushed appends, closed handles, never-dropped late events
# ---------------------------------------------------------------------------


def test_event_log_flushes_and_survives_close(tmp_path):
    log = EventLog(str(tmp_path))
    log.append({"event": "a"})
    # flushed immediately: another reader sees it before close
    with open(log.path) as f:
        assert json.loads(f.readline())["event"] == "a"
    log.close()
    log.close()  # idempotent
    log.append({"event": "late"})  # after close: still lands, not dropped
    with open(log.path) as f:
        events = [json.loads(line)["event"] for line in f]
    assert events == ["a", "late"]


# ---------------------------------------------------------------------------
# degraded mesh plan arithmetic
# ---------------------------------------------------------------------------


def test_degraded_mesh_plan_shrinks_dp_keeps_mp_or_falls_back():
    # feasible: no plan
    assert degraded_mesh_plan(ParallelConfig(dp=4, mp=2), 8, 8) is None
    assert degraded_mesh_plan(ParallelConfig(dp=-1, mp=2), 8, 8) is None
    # dp shrinks to the largest batch divisor that fits
    assert degraded_mesh_plan(ParallelConfig(dp=4), 2, 4) == (2, 1)
    assert degraded_mesh_plan(ParallelConfig(dp=8), 3, 8) == (2, 1)
    # mp kept if it still fits; dp drops around it
    assert degraded_mesh_plan(ParallelConfig(dp=4, mp=2), 4, 4) == (2, 2)
    # mp larger than the device count collapses to 1; dp never grows past
    # what the config asked for, even with devices freed by the collapse
    assert degraded_mesh_plan(ParallelConfig(dp=1, mp=8), 2, 4) == (1, 1)
    assert degraded_mesh_plan(ParallelConfig(dp=4, mp=8), 2, 4) == (2, 1)
    # nothing divides: single-device fallback
    assert degraded_mesh_plan(ParallelConfig(dp=4), 2, 3) == (1, 1)
    assert degraded_mesh_plan(ParallelConfig(dp=2), 1, 2) == (1, 1)


def test_runner_degrades_infeasible_mesh_in_process(toy_dataset, tmp_path):
    """A config demanding more devices than visible (dp=16 on the 8-device
    test platform) shrinks to the largest feasible dp instead of crashing,
    logs the degraded_mesh event, and trains to completion."""
    cfg = runner_config(
        toy_dataset, tmp_path, experiment_name="toy_degraded16",
        parallel=ParallelConfig(dp=16), total_epochs=1,
    )
    runner = ExperimentRunner(cfg, system=small_system(cfg))
    assert runner.degraded_mesh == {
        "requested": [16, 1], "granted": [2, 1], "visible_devices": 8,
    }
    assert runner.mesh is not None and runner.mesh.shape["dp"] == 2
    result = runner.run_experiment()
    assert "test_accuracy_mean" in result
    with open(os.path.join(runner.run_dir, "logs", "events.jsonl")) as f:
        events = [json.loads(line) for line in f if line.strip()]
    degraded = [e for e in events if e.get("event") == "degraded_mesh"]
    assert degraded and degraded[0]["granted"] == [2, 1]


# ---------------------------------------------------------------------------
# serving-side watchdog: a hung flush worker is restart-only
# ---------------------------------------------------------------------------


def test_serving_watchdog_detects_hung_flush_worker():
    """A flush worker parked in a hung device dispatch with work pending:
    the breaker fail-fasts clients but cannot un-hang the thread — the
    serving watchdog must fire the wedge exit (injected here) after
    serve_deadline_s of zero flush progress."""
    import time

    from howtotrainyourmamlpytorch_tpu.core import MAMLSystem
    from howtotrainyourmamlpytorch_tpu.config import ServingConfig
    from howtotrainyourmamlpytorch_tpu.data.synthetic import synthetic_batch
    from howtotrainyourmamlpytorch_tpu.models import build_vgg
    from howtotrainyourmamlpytorch_tpu.resilience import FaultInjector
    from howtotrainyourmamlpytorch_tpu.resilience.retry import DeadlineExceededError
    from howtotrainyourmamlpytorch_tpu.serving import AdaptationEngine, ServingFrontend

    img = (28, 28, 1)
    cfg = Config(
        num_classes_per_set=5,
        num_samples_per_class=2,
        num_target_samples=3,
        batch_size=2,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        serving=ServingConfig(support_buckets=[16], query_buckets=[16]),
    )
    system = MAMLSystem(
        cfg, model=build_vgg(img, cfg.num_classes_per_set, num_stages=2, cnn_num_filters=4)
    )
    # dispatch 1 (warmup/compile) clean; dispatch 2 hangs for 3s
    inj = FaultInjector.from_specs(
        ["serving.dispatch=delay:delay_s=3.0,after=1,times=1"], include_env=False
    )
    engine = AdaptationEngine(system, system.init_train_state(), injector=inj)

    def support(seed):
        ep = synthetic_batch(1, 5, 2, 3, img, seed=seed)
        return ep["x_support"][0], ep["y_support"][0]

    exits = []
    res = ResilienceConfig(
        request_deadline_s=0.2,
        watchdog=WatchdogConfig(serve_deadline_s=0.6, poll_s=0.05),
    )
    engine.adapt_batch([support(0)])  # warm: compile outside the drill (and
    # outside the 0.2s request deadline a compile would blow through)
    frontend = ServingFrontend(engine, resilience_cfg=res, wedge_exit=exits.append)
    try:
        with pytest.raises(DeadlineExceededError):
            frontend.adapt(*support(1))  # worker now parked in the 3s hang
        deadline = time.monotonic() + 5.0
        while not exits and time.monotonic() < deadline:
            time.sleep(0.05)
        assert exits == [76]
        assert frontend.counters.get("wedged") == 1
    finally:
        frontend.close()


# ---------------------------------------------------------------------------
# the wedge drill: hung step -> stacks -> rc=76 -> exact resume
# ---------------------------------------------------------------------------


def _run_child(cfg, tmp_path, name, n_devices=8, timeout=300):
    cfg_yaml = str(tmp_path / f"{name}.yaml")
    save_config(cfg, cfg_yaml)
    code = (
        "import sys;"
        "from howtotrainyourmamlpytorch_tpu.resilience.campaign "
        "import child_train_main;"
        "sys.exit(child_train_main(sys.argv[1]))"
    )
    return subprocess.run(
        [sys.executable, "-c", code, cfg_yaml],
        cwd=REPO,
        env=_child_env(n_devices),
        capture_output=True,
        text=True,
        timeout=timeout,
    )


def test_wedge_drill_rc76_stack_dump_exact_resume(toy_dataset, tmp_path):
    """ISSUE 3 acceptance: an injected hung step (delay far past the
    watchdog deadline) exits rc=76 with all-thread stacks in events.jsonl
    and an emergency checkpoint; the subsequent resume matches an
    uninterrupted control run exactly."""
    # control: uninterrupted 2-epoch run, in-process
    ctl_cfg = campaign_config(toy_dataset, str(tmp_path), "wedge_ctl")
    ctl = ExperimentRunner(ctl_cfg, system=tiny_system(ctl_cfg))
    ctl.run_experiment()

    # wedged: dispatch 4 (epoch 1, iter 0) sleeps 120s; a 25s zero-progress
    # deadline fires long before the sleep ends but still clears one
    # cold-cache XLA compile, so the drill pins the injected hang — never a
    # healthy compile
    wedge_cfg = campaign_config(
        toy_dataset, str(tmp_path), "wedge_run",
        resilience=ResilienceConfig(
            faults=["runner.step=delay:delay_s=120,nth=4"],
            watchdog=WatchdogConfig(deadline_s=25.0, poll_s=0.5),
        ),
    )
    proc = _run_child(wedge_cfg, tmp_path, "wedge_run")
    assert proc.returncode == 76, (proc.stdout, proc.stderr)
    assert "WEDGED" in proc.stdout

    run_dir = os.path.join(str(tmp_path), "wedge_run")
    with open(os.path.join(run_dir, "logs", "events.jsonl")) as f:
        events = [json.loads(line) for line in f if line.strip()]
    wedged = [e for e in events if e.get("event") == "wedged"]
    assert wedged, [e.get("event") for e in events]
    # the hung thread's stack pins the exact frame that never returned —
    # here the injected delay's sleep inside the fault injector
    stacks = wedged[0]["threads"]
    assert stacks and any("fire" in "".join(s) for s in stacks.values())
    assert wedged[0]["stall_s"] >= 25.0
    assert any(e.get("event") == "wedge_checkpoint" for e in events)

    # resume (clean config, default watchdog): epoch 0's checkpoint anchors
    # the replay of the wedged epoch over the deterministic stream
    resume_cfg = campaign_config(toy_dataset, str(tmp_path), "wedge_run")
    resumed = ExperimentRunner(resume_cfg, system=tiny_system(resume_cfg))
    assert resumed.start_epoch == 1  # epoch 0 completed before the wedge
    resumed.run_experiment()

    for a, b in zip(
        jax.tree.leaves(ctl.state.params), jax.tree.leaves(resumed.state.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        )


def test_dp4_checkpoint_resumes_and_trains_on_one_device(toy_dataset, tmp_path):
    """ISSUE 3 acceptance: a checkpoint written under dp=4 resumes on 1
    visible device (degraded_mesh event, single-device fallback), evaluates
    within tolerance of the dp=4 eval of the same state, and keeps
    training."""
    base = dict(batch_size=4, parallel=ParallelConfig(dp=4), total_epochs=1)
    cfg = campaign_config(toy_dataset, str(tmp_path), "shrink_run", **base)
    runner = ExperimentRunner(cfg, system=tiny_system(cfg))
    assert runner.mesh is not None and runner.mesh.shape["dp"] == 4
    runner.run_experiment()

    # reference eval: a fresh dp=4 runner restores the checkpoint and
    # evaluates val on the full mesh
    ref = ExperimentRunner(cfg, system=tiny_system(cfg))
    try:
        assert ref.start_epoch == 1
        ref_val = ref._eval_split("val")
    finally:
        ref.loader.close()

    # child on ONE visible device: must resume the same checkpoint through
    # the degraded path, report matching eval, and train an extra epoch
    child_cfg = campaign_config(
        toy_dataset, str(tmp_path), "shrink_run", **{**base, "total_epochs": 2}
    )
    cfg_yaml = str(tmp_path / "shrink_resume.yaml")
    save_config(child_cfg, cfg_yaml)
    code = (
        "import sys, json;"
        "from howtotrainyourmamlpytorch_tpu.resilience.campaign import "
        "child_train_main, campaign_config, tiny_system;"
        "from howtotrainyourmamlpytorch_tpu.config import load_config;"
        "from howtotrainyourmamlpytorch_tpu.experiment import ExperimentRunner;"
        "cfg = load_config(sys.argv[1]);"
        "r = ExperimentRunner(cfg, system=tiny_system(cfg));"
        "assert r.start_epoch == 1, r.start_epoch;"
        "assert r.degraded_mesh is not None, 'expected a degraded mesh';"
        "val = r._eval_split('val');"
        "r.run_experiment();"
        "print('CHILD_JSON ' + json.dumps({'val': val, "
        "'degraded': r.degraded_mesh}))"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code, cfg_yaml],
        cwd=REPO,
        env=_child_env(1),
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    payload = next(
        json.loads(line.split(" ", 1)[1])
        for line in proc.stdout.splitlines()
        if line.startswith("CHILD_JSON ")
    )
    assert payload["degraded"] == {
        "requested": [4, 1], "granted": [1, 1], "visible_devices": 1,
    }
    # same restored state, same fixed eval stream: parity within numeric
    # tolerance (single-device vs dp=4 differ only in reduction layout)
    assert payload["val"]["val_num_episodes"] == ref_val["val_num_episodes"]
    np.testing.assert_allclose(
        payload["val"]["val_accuracy_mean"],
        ref_val["val_accuracy_mean"],
        atol=1e-6,
    )
    np.testing.assert_allclose(
        payload["val"]["val_loss_mean"], ref_val["val_loss_mean"], rtol=1e-5
    )
    # the degraded event landed in the shared run dir, and the extra epoch
    # actually trained (a second epoch row exists)
    run_dir = os.path.join(str(tmp_path), "shrink_run")
    with open(os.path.join(run_dir, "logs", "events.jsonl")) as f:
        events = [json.loads(line) for line in f if line.strip()]
    assert any(e.get("event") == "degraded_mesh" for e in events)
    import csv

    with open(os.path.join(run_dir, "logs", "summary_statistics.csv")) as f:
        rows = list(csv.DictReader(f))
    assert {int(float(r["epoch"])) for r in rows} == {0, 1}
