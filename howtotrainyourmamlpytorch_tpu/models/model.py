"""The Model container: a pair of pure functions over pytrees.

``init(key) -> (params, state)`` and
``apply(params, state, x, *, use_batch_stats, update_running) -> (logits, state')``.

``params`` are the meta-learned weights (the inner loop produces fast-weight
variants of this same pytree); ``state`` holds batch-norm running statistics,
which the reference tracks but never consults for normalization (transductive
BN everywhere — reference ``few_shot_learning_system.py:388``).
"""

from typing import Any, Callable, NamedTuple, Optional, Tuple


class Model(NamedTuple):
    init: Callable[..., Tuple[Any, Any]]
    apply: Callable[..., Tuple[Any, Any]]
    name: str = "model"
    # Build conventions baked into ``apply`` (explicit per-model parameters,
    # not process globals — VERDICT r4 weak #5). ``None`` = unknown or not
    # applicable (hand-built Model, or a backbone without max-pooling);
    # MAMLSystem checks a caller-supplied model's values against the config
    # so a mismatch fails with a clear Python error instead of a GSPMD crash
    # or a silently wrong pooling convention.
    conv_via_patches: Optional[bool] = None
    reduce_window_pool: Optional[bool] = None
    # fused conv->BN GEMM epilogue (Config.precision.fuse_conv_bn); None =
    # unknown/not applicable (hand-built Model, or a backbone without the
    # fused layer implemented)
    fuse_conv_bn: Optional[bool] = None
