"""Low-overhead thread-safe span tracer with Chrome-trace export.

The training hot loop dispatches thousands of steps per epoch; the serving
path flushes micro-batches from worker threads. Both need *where does the
time go* answered without perturbing what they measure, so the tracer is
deliberately minimal: a ``span(name)`` context manager costs two clock reads
and two lock acquisitions, completed spans land in a bounded ring buffer
(old spans evicted, never an unbounded list growing for 150 epochs), and
nesting depth is tracked per thread so exported traces render as a proper
flame graph. The clock is injectable so tests walk time deterministically.

Export is the Chrome trace-event JSON format (``{"traceEvents": [...]}``,
complete ``"ph": "X"`` events with microsecond ``ts``/``dur``), which both
``chrome://tracing`` and Perfetto (https://ui.perfetto.dev) open directly.
Complete events are balanced by construction — a span only reaches the ring
when its ``__exit__`` ran — and :func:`validate_chrome_trace` re-checks that
plus the schema, which the chaos campaign runs over every exported trace.
"""

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

try:
    from ..utils.locks import san_lock
except ImportError:  # file-path-loaded (trace_merge toy fleets run this
    # module standalone): take the repo-root import, else a plain primitive
    try:
        from tools.graftsan.runtime import san_lock
    except ImportError:

        def san_lock(site=None):
            return threading.Lock()


class _NullSpan:
    """Shared no-op context manager: the disabled tracer's ``span()`` must
    cost one attribute lookup and nothing else."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class NullTracer:
    """Inert tracer: every hook is a no-op. Instrumented code holds one of
    these when observability is disabled, so call sites never branch."""

    enabled = False

    def span(self, name: str, flows: Optional[Sequence] = None, **tags):
        return _NULL_SPAN

    def records(self) -> List[dict]:
        return []

    def durations_s(self, name: str) -> List[float]:
        return []

    def open_spans(self) -> int:
        return 0

    def to_chrome_trace(self) -> Dict[str, Any]:
        return {"traceEvents": []}

    def export(self, path: str) -> None:
        pass


NULL_TRACER = NullTracer()


class _Span:
    """Live span handed out by :meth:`SpanTracer.span`; records itself into
    the tracer's ring on exit. ``duration_s`` is set on exit so wrappers
    (the hub's phase histograms) reuse the span's own clock pair instead of
    reading the clock again — one measurement, two consumers. ``flows`` is
    a sequence of ``(flow_id, role)`` pairs (role in ``s``/``t``/``f``)
    exported as Chrome *flow events* anchored to this span, linking a
    request's spans across threads (observability/context.py builds them)."""

    __slots__ = ("_tracer", "name", "tags", "flows", "_t0", "_depth", "duration_s")

    def __init__(
        self,
        tracer: "SpanTracer",
        name: str,
        tags: Dict[str, Any],
        flows: Optional[Sequence] = None,
    ):
        self._tracer = tracer
        self.name = name
        self.tags = tags
        self.flows = tuple(flows) if flows else None

    def __enter__(self):
        self._depth = self._tracer._enter()
        self._t0 = self._tracer._clock()
        return self

    def __exit__(self, *exc):
        t1 = self._tracer._clock()
        self.duration_s = t1 - self._t0
        self._tracer._exit_record(
            self.name, self._t0, t1, self._depth, self.tags, self.flows
        )
        return False


class SpanTracer:
    """Bounded-ring span recorder.

    ``capacity`` bounds the completed-span ring; evictions are counted in
    ``dropped`` so a truncated export is visible as truncated rather than
    passing for the whole run. ``clock`` must be monotonic; tests inject a
    fake. Completed spans are ``(name, t0, t1, thread_name, depth, tags,
    flows)`` tuples relative to the tracer's epoch (construction time).
    ``epoch_unix`` anchors that epoch to the wall clock so
    ``scripts/trace_merge.py`` can align traces from different processes
    on one timeline.
    """

    enabled = True

    def __init__(
        self,
        capacity: int = 8192,
        clock: Callable[[], float] = time.monotonic,
        wall_clock: Callable[[], float] = time.time,
    ):
        if capacity < 1:
            raise ValueError(f"tracer capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._clock = clock
        self._epoch = clock()
        self.epoch_unix = wall_clock()
        self._lock = san_lock("SpanTracer._lock")
        self._ring: deque = deque(maxlen=self.capacity)
        self._local = threading.local()
        self.dropped = 0
        self._open = 0

    # -- span lifecycle (called from _Span) ----------------------------

    def _enter(self) -> int:
        depth = getattr(self._local, "depth", 0)
        self._local.depth = depth + 1
        with self._lock:
            self._open += 1
        return depth

    def _exit_record(self, name, t0, t1, depth, tags, flows=None) -> None:
        self._local.depth = depth
        rec = (name, t0 - self._epoch, t1 - self._epoch,
               threading.current_thread().name, depth, tags, flows)
        with self._lock:
            self._open -= 1
            if len(self._ring) == self.capacity:
                self.dropped += 1
            self._ring.append(rec)

    # -- public API ----------------------------------------------------

    def span(self, name: str, flows: Optional[Sequence] = None, **tags) -> _Span:
        """``with tracer.span("dispatch", epoch=3): ...``; ``flows`` links
        this span into cross-thread request arcs (see :class:`_Span`)."""
        return _Span(self, name, tags, flows=flows)

    def open_spans(self) -> int:
        """Spans entered but not yet exited, across all threads — zero when
        every ``with`` block has unwound (the balance invariant)."""
        with self._lock:
            return self._open

    def records(self) -> List[dict]:
        """Snapshot of the ring as dicts (seconds relative to tracer epoch)."""
        with self._lock:
            ring = list(self._ring)
        return [
            {"name": n, "t0_s": t0, "t1_s": t1, "dur_s": t1 - t0,
             "thread": thread, "depth": depth, "tags": tags, "flows": flows}
            for n, t0, t1, thread, depth, tags, flows in ring
        ]

    def durations_s(self, name: str) -> List[float]:
        with self._lock:
            ring = list(self._ring)
        return [t1 - t0 for n, t0, t1, *_ in ring if n == name]

    # -- export --------------------------------------------------------

    def to_chrome_trace(self) -> Dict[str, Any]:
        """Chrome trace-event JSON object. Only completed (balanced) spans
        are exported; in-flight spans and ring evictions are surfaced as
        metadata so a partial trace reads as partial. Spans recorded with
        ``flows`` additionally emit flow events (``ph: s/t/f``, one shared
        name+cat per the format's flow-binding rule, id = the trace id)
        anchored at the span's start, so a request renders as one linked
        arc across threads in Perfetto."""
        with self._lock:
            ring = list(self._ring)
            open_spans = self._open
            dropped = self.dropped
        pid = os.getpid()
        events: List[Dict[str, Any]] = []
        tids: Dict[str, int] = {}
        for name, t0, t1, thread, depth, tags, flows in ring:
            tid = tids.setdefault(thread, len(tids))
            ts = round(t0 * 1e6, 3)
            event = {
                "name": name,
                "cat": "host",
                "ph": "X",
                "ts": ts,
                "dur": round((t1 - t0) * 1e6, 3),
                "pid": pid,
                "tid": tid,
            }
            if tags:
                # viewer 'args' values must be JSON scalars; stringify the rest
                event["args"] = {
                    k: (v if isinstance(v, (int, float, bool, str, type(None))) else str(v))
                    for k, v in tags.items()
                }
            events.append(event)
            for flow_id, role in flows or ():
                flow = {
                    "name": "request",
                    "cat": "request",
                    "ph": role,
                    "id": flow_id,
                    "ts": ts,
                    "pid": pid,
                    "tid": tid,
                }
                if role in ("t", "f"):
                    # bind to the ENCLOSING slice (this span), not the next
                    flow["bp"] = "e"
                events.append(flow)
        for thread, tid in tids.items():
            events.append(
                {"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                 "args": {"name": thread}}
            )
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "open_spans": open_spans,
                "dropped_spans": dropped,
                "pid": pid,
                "epoch_unix": self.epoch_unix,
            },
        }

    def export(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)


# ---------------------------------------------------------------------------
# trace validation (the chaos-campaign invariant)
# ---------------------------------------------------------------------------

_REQUIRED_X_KEYS = ("name", "ph", "ts", "dur", "pid", "tid")


def validate_chrome_trace(trace: Dict[str, Any]) -> List[str]:
    """Schema + balance check of an exported trace; returns violations
    (empty = valid). Accepts the object form (``{"traceEvents": [...]}``).
    Balance means: every duration event is complete (``"X"`` with a
    non-negative ``dur``), any ``"B"``/``"E"`` pairs match per (pid, tid),
    and the exporter left no span open. Flow events (``s``/``t``/``f``)
    must carry an ``id`` and pair up: a finish (or step) whose flow never
    started is a violation. A start with no finish is NOT — that is what a
    request that never reached a device dispatch (cache hit, shed,
    breaker rejection) legitimately looks like."""
    problems: List[str] = []
    if not isinstance(trace, dict) or not isinstance(trace.get("traceEvents"), list):
        return ["trace is not an object with a traceEvents list"]
    begin_depth: Dict[Tuple[Any, Any], int] = {}
    flow_started: set = set()
    flow_continued: Dict[Any, str] = {}
    for i, ev in enumerate(trace["traceEvents"]):
        if not isinstance(ev, dict):
            problems.append(f"event {i} is not an object")
            continue
        ph = ev.get("ph")
        if ph == "M":
            continue
        if ph in ("s", "t", "f"):
            if "id" not in ev:
                problems.append(f"event {i}: flow event ({ph}) without an id")
                continue
            if ph == "s":
                flow_started.add(ev["id"])
            else:
                flow_continued.setdefault(ev["id"], ph)
            continue
        if ph == "i":
            # instant events (trace_merge's access-log / fleet-event marks)
            if not isinstance(ev.get("ts"), (int, float)):
                problems.append(f"event {i} (instant) has bad ts {ev.get('ts')!r}")
            continue
        if ph == "X":
            missing = [k for k in _REQUIRED_X_KEYS if k not in ev]
            if missing:
                problems.append(f"event {i} missing keys {missing}")
                continue
            if not isinstance(ev["ts"], (int, float)) or ev["ts"] < 0:
                problems.append(f"event {i} has bad ts {ev['ts']!r}")
            if not isinstance(ev["dur"], (int, float)) or ev["dur"] < 0:
                problems.append(f"event {i} has negative/bad dur {ev['dur']!r}")
        elif ph == "B":
            key = (ev.get("pid"), ev.get("tid"))
            begin_depth[key] = begin_depth.get(key, 0) + 1
        elif ph == "E":
            key = (ev.get("pid"), ev.get("tid"))
            depth = begin_depth.get(key, 0) - 1
            if depth < 0:
                problems.append(f"event {i}: 'E' without matching 'B' on {key}")
            begin_depth[key] = depth
        else:
            problems.append(f"event {i} has unsupported ph {ph!r}")
    for key, depth in begin_depth.items():
        if depth > 0:
            problems.append(f"{depth} unclosed 'B' span(s) on {key}")
    # order-independent pairing: the ring orders events by span COMPLETION,
    # so a request's "f" (dispatch span, exits first) legitimately precedes
    # its "s" (the enclosing HTTP span) in the event list
    for flow_id, ph in flow_continued.items():
        if flow_id not in flow_started:
            problems.append(f"flow {flow_id!r} has '{ph}' but no start ('s')")
    open_spans = (trace.get("otherData") or {}).get("open_spans", 0)
    if open_spans:
        problems.append(f"exporter reported {open_spans} span(s) still open")
    return problems


def load_and_validate_trace(path: str) -> List[str]:
    """Parse + validate an exported trace file; unparseable JSON is itself
    the violation."""
    try:
        with open(path) as f:
            trace = json.load(f)
    except (OSError, json.JSONDecodeError) as exc:
        return [f"trace unreadable: {exc}"]
    return validate_chrome_trace(trace)
