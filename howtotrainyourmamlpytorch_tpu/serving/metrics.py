"""Serving latency metrics: thin adapters over the shared MetricsRegistry.

Same request-path surface as before (``LatencyStats`` per-phase p50/p95/p99,
``EventCounters`` for the resilience counts) and the exact same ``/metrics``
payload schema, but the storage now lives in one
:class:`~..observability.metrics.MetricsRegistry` — the same registry the
TelemetryHub snapshots — instead of a private island. The registry also
fixes the old lock shape: ``summary()`` used to compute numpy percentiles
*inside* the recording lock, so every recorder thread (HTTP handlers,
batcher workers) blocked behind a ``/metrics`` scrape; the registry copies
each phase window under the lock and runs the percentile math after
releasing it.
"""

import time
from typing import Any, Dict, Optional

from ..observability.metrics import MetricsRegistry

#: registry namespaces the adapters write under — one registry can host both
#: (plus the hub's ``phase.*`` histograms) without key collisions
LATENCY_PREFIX = "serving.latency."
EVENTS_PREFIX = "serving.events."


class LatencyStats:
    """Per-phase latency percentiles ("adapt", "adapt_cached", "predict",
    "queue"): a bounded window of wall-clock seconds per phase, exact
    percentiles over the recent window (cold-start compiles forgotten at
    window pace). ``summary()`` is the ``/metrics`` payload — schema
    unchanged from the pre-registry implementation."""

    def __init__(self, window: int = 2048, registry: Optional[MetricsRegistry] = None):
        self.window = int(window)
        self.registry = registry if registry is not None else MetricsRegistry()

    def record(self, phase: str, seconds: float) -> None:
        self.registry.observe(LATENCY_PREFIX + phase, seconds, window=self.window)

    def time(self, phase: str):
        """Context manager: ``with stats.time("adapt"): ...``"""
        return _Timer(self, phase)

    def summary(self) -> Dict[str, Dict[str, Any]]:
        out = self.registry.summaries(LATENCY_PREFIX)
        for stats in out.values():
            # the registry adds a cumulative sum; /metrics keeps its
            # historical per-phase key set exactly
            stats.pop("sum_ms", None)
        return out


class EventCounters:
    """Thread-safe named counters for the resilience surface (shed requests,
    deadline misses, breaker rejections, dispatch failures) — the numbers the
    OPERATIONS.md degraded-modes runbook reads off ``/metrics``."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry if registry is not None else MetricsRegistry()

    def inc(self, name: str, n: int = 1) -> None:
        self.registry.inc(EVENTS_PREFIX + name, n)

    def get(self, name: str) -> int:
        return self.registry.counter(EVENTS_PREFIX + name)

    def snapshot(self) -> Dict[str, int]:
        return self.registry.counters(EVENTS_PREFIX)


class _Timer:
    def __init__(self, stats: LatencyStats, phase: str):
        self._stats = stats
        self._phase = phase

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self._stats.record(self._phase, time.monotonic() - self._t0)
        return False
