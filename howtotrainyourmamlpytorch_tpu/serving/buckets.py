"""Traffic-driven shape-bucket auto-tuning (ROADMAP 4d, ISSUE 12).

The serving engine pads every request up to a compiled (support-size /
query-count) bucket; the default edges are a guess, and PR 11's
padding-waste accounting (``access.jsonl`` true sizes, the
``/metrics.padding`` tallies) records what the guess costs: per-sample
FLOPs scale ~linearly in the flattened sample count, so
``padded - true`` samples are wasted device work. This module closes the
loop: consume recorded traffic, solve for the bucket edges minimizing
padded samples under a max-program-count budget, and emit the config
overrides (``serving.support_buckets=[...]`` /
``serving.query_buckets=[...]``) that the engine's bucket tables, the
strict-mode planned sets (``utils/strictmode.py::serving_planned_programs``),
and the AOT prewarm grid (``compile/aot.py::prewarm_serving``) all already
derive from — tuned edges flow everywhere by construction.

The solver is exact: for observed sizes ``s_1 < ... < s_n`` with counts
``c_i``, an optimal edge set is a subset of the observed sizes (lowering an
edge below its group's max strands requests; raising it above only adds
padding), so minimizing total padded samples over at most K edges is a
contiguous-partition DP — group ``i..j`` costs ``s_j * sum(c_i..c_j)`` —
in O(n^2 K). Optimality is test-pinned against brute force.

Deliberately stdlib-only (no jax, no package imports): ``scripts/
bucket_tune.py`` file-path-loads this module so tuning a recorded trace
never pays a jax import.
"""

import json
from typing import Any, Dict, Iterable, List, Optional, Tuple

#: observed size -> request count
SizeHistogram = Dict[int, int]


# ---------------------------------------------------------------------------
# cost model
# ---------------------------------------------------------------------------


def bucket_for(size: int, edges: List[int]) -> int:
    """Smallest edge >= size; an oversize request keeps its exact shape
    (compiles on demand) — the engine's ``_bucket_for`` rule, duplicated
    here in stdlib form and cross-checked by test against the engine."""
    for e in edges:
        if e >= size:
            return e
    return size


def padded_samples(hist: SizeHistogram, edges: List[int]) -> int:
    """Total device samples the traffic pays under ``edges`` (true samples
    plus padding). Proportional to padded FLOPs at fixed image shape."""
    edges = sorted(edges)
    return sum(count * bucket_for(size, edges) for size, count in hist.items())


def true_samples(hist: SizeHistogram) -> int:
    return sum(size * count for size, count in hist.items())


def waste_frac(hist: SizeHistogram, edges: List[int]) -> Optional[float]:
    """1 - true/padded over this traffic — the same definition as the
    serving ``padding_waste_frac`` gauge. None on empty traffic."""
    padded = padded_samples(hist, edges)
    if not padded:
        return None
    return round(1.0 - true_samples(hist) / padded, 4)


# ---------------------------------------------------------------------------
# the exact solver
# ---------------------------------------------------------------------------


def optimal_edges(hist: SizeHistogram, max_buckets: int) -> List[int]:
    """Bucket edges minimizing :func:`padded_samples` over ``hist`` using
    at most ``max_buckets`` edges. The top edge is always the largest
    observed size (everything must be covered). Exact DP, O(n^2 K) in the
    number of distinct sizes."""
    if max_buckets < 1:
        raise ValueError(f"max_buckets must be >= 1, got {max_buckets}")
    sizes = sorted(s for s in hist if hist[s] > 0)
    if not sizes:
        return []
    n = len(sizes)
    k_max = min(max_buckets, n)
    counts = [hist[s] for s in sizes]
    prefix = [0] * (n + 1)
    for i, c in enumerate(counts):
        prefix[i + 1] = prefix[i] + c
    inf = float("inf")
    # dp[k][j] = min padded samples covering sizes[:j] with exactly k edges
    dp = [[inf] * (n + 1) for _ in range(k_max + 1)]
    choice = [[0] * (n + 1) for _ in range(k_max + 1)]
    dp[0][0] = 0.0
    for k in range(1, k_max + 1):
        for j in range(1, n + 1):
            best, best_i = inf, 0
            for i in range(k - 1, j):
                if dp[k - 1][i] == inf:
                    continue
                cost = dp[k - 1][i] + sizes[j - 1] * (prefix[j] - prefix[i])
                if cost < best:
                    best, best_i = cost, i
            dp[k][j] = best
            choice[k][j] = best_i
    k_best = min(range(1, k_max + 1), key=lambda k: dp[k][n])
    edges: List[int] = []
    j = n
    for k in range(k_best, 0, -1):
        edges.append(sizes[j - 1])
        j = choice[k][j]
    return sorted(edges)


# ---------------------------------------------------------------------------
# traffic sources
# ---------------------------------------------------------------------------


def traffic_from_access_log(path: str) -> Dict[str, SizeHistogram]:
    """Per-verb true-size histograms off ``logs/access.jsonl`` (the precise
    source: every line carries the pre-padding sample count). Only ``ok``
    lines count — sheds and router rejections never dispatched, so their
    sizes are not padded FLOPs (the same rule the padding gauge applies).
    Torn lines are skipped, matching every other access-log reader."""
    out: Dict[str, SizeHistogram] = {"adapt": {}, "predict": {}}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            verb, size = rec.get("verb"), rec.get("true_size")
            if verb not in out or size is None or rec.get("outcome") != "ok":
                continue
            hist = out[verb]
            hist[int(size)] = hist.get(int(size), 0) + 1
    return out


def traffic_from_metrics(metrics: Dict[str, Any]) -> Dict[str, SizeHistogram]:
    """Approximate per-verb histograms off a ``/metrics`` snapshot's
    ``padding.by_bucket`` tallies (``{verb: {bucket: {count,
    true_samples}}}``): each bucket's traffic is placed at its mean true
    size, plus ONE sentinel request at the largest occupied bucket's edge —
    the upper bound of the recorded sizes. The sentinel pins the tuned top
    edge at (or above) that bound, so traffic the tallies DID see can never
    be stranded below it just because its bucket mean sat lower (sizes
    within a bucket are only known up to the edge). Bucket-granular — good
    enough to tune against, but the access log is the precise source."""
    padding = metrics.get("padding", metrics) or {}
    by_bucket = padding.get("by_bucket") or {}
    out: Dict[str, SizeHistogram] = {"adapt": {}, "predict": {}}
    for verb, buckets in by_bucket.items():
        if verb not in out:
            continue
        top_edge = 0
        for bucket_id, row in (buckets or {}).items():
            count = int(row.get("count") or 0)
            true = int(row.get("true_samples") or 0)
            if count <= 0 or true <= 0:
                continue
            try:
                top_edge = max(top_edge, int(bucket_id))
            except (TypeError, ValueError):
                pass
            mean = max(1, round(true / count))
            out[verb][mean] = out[verb].get(mean, 0) + count
        if out[verb] and top_edge > max(out[verb]):
            out[verb][top_edge] = out[verb].get(top_edge, 0) + 1
    return out


def merge_histograms(histograms: Iterable[SizeHistogram]) -> SizeHistogram:
    out: SizeHistogram = {}
    for hist in histograms:
        for size, count in hist.items():
            out[size] = out.get(size, 0) + count
    return out


# ---------------------------------------------------------------------------
# program-count budget
# ---------------------------------------------------------------------------


def batch_bucket_count(max_batch: int) -> int:
    """How many task-batch buckets the engine's power-of-two rounding
    produces for ``max_batch`` (``serving/engine.py::_batch_bucket``:
    powers of two below ``max_batch``, plus ``max_batch`` itself).
    Duplicated here in stdlib form; cross-checked by test against
    ``utils/strictmode.py::batch_buckets`` so the rules can't drift."""
    count, b = 0, 1
    while b < max_batch:
        count += 1
        b *= 2
    return count + 1


def shape_buckets_for_program_budget(max_programs: int, max_batch: int) -> int:
    """Per-verb shape-bucket budget from a TOTAL compiled-program budget:
    the planned serving grid is (adapt + predict) x shape bucket x
    task-batch bucket, so each shape bucket costs ``2 *
    batch_bucket_count`` programs."""
    per_bucket = 2 * batch_bucket_count(max_batch)
    return max(1, max_programs // per_bucket)


# ---------------------------------------------------------------------------
# the tuner
# ---------------------------------------------------------------------------

_VERB_TO_KEY = {"adapt": "support_buckets", "predict": "query_buckets"}


def tune(
    traffic: Dict[str, SizeHistogram],
    current_support: List[int],
    current_query: List[int],
    max_buckets: Optional[int] = None,
    max_programs: Optional[int] = None,
    max_batch: int = 8,
    keep_max_edge: bool = False,
) -> Dict[str, Any]:
    """Solve both verbs and emit the override payload.

    ``max_buckets`` caps edges per verb (default: the current edge count,
    so tuning is waste-for-waste comparable); ``max_programs`` instead caps
    the TOTAL planned serving grid and derives the per-verb cap. With
    ``keep_max_edge`` the current top edge is appended when it exceeds the
    tuned top, preserving coverage for sizes the recorded traffic never
    showed (it costs one budget slot). A verb with no recorded traffic
    keeps its current edges and emits no override."""
    if max_programs is not None:
        max_buckets = shape_buckets_for_program_budget(max_programs, max_batch)
    current = {"adapt": sorted(current_support), "predict": sorted(current_query)}
    verbs: Dict[str, Any] = {}
    overrides: List[str] = []
    edges_out: Dict[str, List[int]] = {}
    for verb, key in _VERB_TO_KEY.items():
        hist = traffic.get(verb) or {}
        cur = current[verb]
        if not hist:
            verbs[verb] = {
                "requests": 0,
                "edges": cur,
                "tuned": False,
                "reason": "no recorded traffic",
            }
            edges_out[key] = cur
            continue
        budget = max_buckets if max_buckets is not None else max(1, len(cur))
        edges = optimal_edges(hist, budget)
        if keep_max_edge and cur and cur[-1] > edges[-1]:
            # the appended coverage edge costs one budget slot (the
            # documented contract): re-solve one edge short so the append
            # never silently exceeds — or silently skips — the budget. At
            # budget 1 coverage wins: the single edge is the current top.
            if len(edges) >= budget:
                edges = (
                    optimal_edges(hist, budget - 1) if budget > 1 else []
                )
            if not edges or cur[-1] > edges[-1]:
                edges.append(cur[-1])
        verbs[verb] = {
            "requests": sum(hist.values()),
            "true_samples": true_samples(hist),
            "edges": edges,
            "tuned": True,
            "padded_before": padded_samples(hist, cur),
            "padded_after": padded_samples(hist, edges),
            "waste_frac_before": waste_frac(hist, cur),
            "waste_frac_after": waste_frac(hist, edges),
        }
        edges_out[key] = edges
        overrides.append(f"serving.{key}={json.dumps(edges)}")
    tuned = [v for v in verbs.values() if v.get("tuned")]
    total_before = sum(v["padded_before"] for v in tuned)
    total_after = sum(v["padded_after"] for v in tuned)
    total_true = sum(v["true_samples"] for v in tuned)
    return {
        "verbs": verbs,
        "edges": edges_out,
        "overrides": overrides,
        "config": {"serving": dict(edges_out)},
        "padded_before": total_before,
        "padded_after": total_after,
        "padding_waste_frac_before": (
            round(1.0 - total_true / total_before, 4) if total_before else None
        ),
        "padding_waste_frac_after": (
            round(1.0 - total_true / total_after, 4) if total_after else None
        ),
    }
