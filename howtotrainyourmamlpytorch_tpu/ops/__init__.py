from . import inner_optim, losses, msl, precision  # noqa: F401
from .inner_optim import InnerOptimizer, build_inner_optimizer  # noqa: F401
from .precision import PrecisionPolicy, policy_from_config  # noqa: F401
