from . import inner_optim, losses, msl  # noqa: F401
from .inner_optim import InnerOptimizer, build_inner_optimizer  # noqa: F401
