#!/usr/bin/env python
"""Block until the tunneled TPU backend answers, probing with short-lived
child processes. The axon tunnel wedges for minutes at a time (server-side;
a hung client never returns from backend init and holds nothing releasable),
so the sweep harness calls this BEFORE each training attempt instead of
burning watchdog restarts against a dead backend.

Each probe is a separate python child (backend init happens once per
process) killed on timeout. Exits 0 when a probe sees the TPU, 1 when the
deadline passes.
"""
import subprocess
import sys
import time

PROBE = "import jax; d = jax.devices(); print('TPU_OK', len(d), d[0].device_kind)"


def main(deadline_s: float = 3600.0, probe_timeout_s: float = 90.0) -> int:
    start = time.time()
    attempt = 0
    while time.time() - start < deadline_s:
        attempt += 1
        try:
            out = subprocess.run(
                [sys.executable, "-c", PROBE],
                timeout=probe_timeout_s,
                capture_output=True,
                text=True,
            )
            if "TPU_OK" in out.stdout:
                print(f"wait_for_tpu: backend up after {time.time()-start:.0f}s "
                      f"({attempt} probes): {out.stdout.strip().splitlines()[-1]}",
                      flush=True)
                return 0
        except subprocess.TimeoutExpired:
            pass
        print(f"wait_for_tpu: probe {attempt} failed ({time.time()-start:.0f}s elapsed)",
              flush=True)
        time.sleep(30)
    print("wait_for_tpu: deadline exceeded", flush=True)
    return 1


if __name__ == "__main__":
    sys.exit(main(*(float(a) for a in sys.argv[1:])))
