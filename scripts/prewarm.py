#!/usr/bin/env python
"""AOT prewarm service: compile the planned program set ahead of time.

Builds the exact program families strict mode plans — the train variants
``(single|multi, second_order, msl)`` plus eval, and/or the serving
(bucket x batch-bucket) grid — lowers and compiles them through the compile
ledger (every compile timed, ``phase="prewarm"``), persists the XLA
artifacts in the persistent compilation cache (``utils/compcache.py``), and
writes the executable-store manifest next to the checkpoints so a restarted
run, a fleet relaunch, or a freshly spawned serving replica can verify it
will hit warm before accepting work. Prints exactly ONE JSON line (the
``bench.py`` contract); progress goes to stderr.

Usage::

    # warm a run dir's train programs (fresh fleets run this before work):
    JAX_PLATFORMS=cpu python scripts/prewarm.py exps/<run>

    # warm the serving grid too (replica spawn):
    python scripts/prewarm.py exps/<run> --serving

    # no run dir: prewarm a config built from overrides alone
    python scripts/prewarm.py --no-train --serving num_classes_per_set=5

Exit codes: 0 = prewarmed (manifest written), 2 = usage error. Per-program
compile failures are contained and counted in the JSON line — a partially
warm cache still beats a cold one.
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "run_dir", nargs="?", default=None,
        help="experiment directory (config.yaml + saved_models); omit to "
        "prewarm a config built purely from overrides",
    )
    parser.add_argument("--serving", action="store_true",
                        help="also prewarm the serving (bucket x batch-bucket) grid")
    parser.add_argument("--no-train", action="store_true",
                        help="skip the train program family")
    parser.add_argument("--max-workers", type=int, default=None,
                        help="compile-pool width (default: config aot.max_workers)")
    parser.add_argument("overrides", nargs="*", default=[],
                        help="config overrides, key=value dotted paths")
    args = parser.parse_args(argv)
    if args.run_dir and "=" in args.run_dir:
        # overrides-only invocation: argparse hands the first key=value to
        # the optional run_dir positional — put it back
        args.overrides.insert(0, args.run_dir)
        args.run_dir = None
    if args.no_train and not args.serving:
        print("prewarm: nothing to do (--no-train without --serving)", file=sys.stderr)
        return 2

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        # a site hook may override platform selection after capturing the
        # env; re-assert the user's choice (the serve.py pattern)
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from howtotrainyourmamlpytorch_tpu.compile import aot
    from howtotrainyourmamlpytorch_tpu.config import load_config
    from howtotrainyourmamlpytorch_tpu.core import MAMLSystem
    from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt
    from howtotrainyourmamlpytorch_tpu.parallel import (
        batch_sharding,
        chunk_sharding,
        make_mesh,
        shard_train_state,
    )

    yaml_path = None
    if args.run_dir:
        yaml_path = os.path.join(args.run_dir, "config.yaml")
        if not os.path.exists(yaml_path):
            print(f"prewarm: no config.yaml under {args.run_dir}", file=sys.stderr)
            return 2
    cfg = load_config(yaml_path, args.overrides)
    cache_dir = aot.ensure_persistent_cache(cfg)
    _log(f"prewarm: persistent cache at {cache_dir}")

    t0 = time.perf_counter()
    system = MAMLSystem(cfg)
    state = system.init_train_state()

    # mesh parity: a run that trains on a dp x mp mesh compiles programs
    # with those shardings baked in — prewarm must match or it warms the
    # wrong executables. Mirrors the runner's mesh construction; on any
    # infeasibility (batch not divisible, single device) fall back to the
    # meshless programs with a logged note.
    mesh = None
    b_sharding = c_sharding = None
    mesh_shape = [1, 1]
    if cfg.parallel.shard_meta_batch and len(jax.devices()) > 1:
        try:
            mesh = make_mesh(cfg.parallel)
            global_batch = cfg.batch_size * cfg.samples_per_iter
            if global_batch % mesh.shape["dp"] != 0:
                raise ValueError(
                    f"meta-batch {global_batch} not divisible by dp={mesh.shape['dp']}"
                )
            state = shard_train_state(state, mesh, tp_convs=cfg.parallel.tp_convs)
            b_sharding, c_sharding = batch_sharding(mesh), chunk_sharding(mesh)
            mesh_shape = [int(mesh.shape["dp"]), int(mesh.shape.get("mp", 1))]
        except Exception as exc:  # noqa: BLE001 — degrade to meshless programs
            _log(f"prewarm: meshless fallback ({type(exc).__name__}: {exc})")
            mesh = None
            b_sharding = c_sharding = None

    save_dir = os.path.join(args.run_dir, "saved_models") if args.run_dir else None
    store = None
    if save_dir:
        expected_warm, reason = aot.verify_manifest(
            ckpt.load_prewarm_manifest(save_dir), mesh_shape
        )
        _log(
            "prewarm: manifest promises a warm start"
            if expected_warm
            else f"prewarm: cold start expected ({reason})"
        )
        if cfg.aot.executable_store:
            # stored executables deserialize (no tracing, no XLA); loads
            # gated on the manifest verdict so a changed environment
            # compiles cold instead of loading stale artifacts
            store = aot.ExecutableStore(
                os.path.join(save_dir, "executables"), allow_load=expected_warm
            )

    train_summary = serving_summary = None
    if not args.no_train:
        _log("prewarm: compiling the train program family...")
        train_summary = system.prewarm(
            state,
            batch_sharding=b_sharding,
            chunk_sharding=c_sharding,
            max_workers=args.max_workers,
            on_program=lambda name: _log(f"prewarm:   {name}"),
            store=store,
        )
    if args.serving:
        from howtotrainyourmamlpytorch_tpu.serving import AdaptationEngine

        _log("prewarm: compiling the serving grid...")
        engine = AdaptationEngine(system, state)
        serving_summary = engine.prewarm(
            max_workers=args.max_workers,
            on_program=lambda name: _log(f"prewarm:   {name}"),
            store=store,
        )

    manifest_path = None
    if save_dir and cfg.aot.executable_store:
        manifest_path = ckpt.save_prewarm_manifest(
            save_dir,
            aot.build_manifest(
                train_summary=train_summary,
                serving_summary=serving_summary,
                mesh_shape=mesh_shape,
                store=store,
            ),
        )

    def slim(summary):
        if summary is None:
            return None
        return {k: v for k, v in summary.items() if k != "by_program"}

    report = {
        "report": "prewarm",
        "platform": jax.default_backend(),
        "run_dir": args.run_dir,
        "seconds": round(time.perf_counter() - t0, 3),
        "train": slim(train_summary),
        "serving": slim(serving_summary),
        "cache_dir": cache_dir,
        "manifest": manifest_path,
    }
    print(json.dumps(report), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
