"""Micro-batching scheduler for concurrent adapt / predict requests.

Concurrent requests whose tensors pad to the same shape bucket are stacked
along the task axis — the axis ``MAMLSystem`` already vmaps over — and
dispatched to the device as ONE compiled call. A group flushes when it
reaches ``max_batch`` requests or when its oldest request has waited
``deadline_ms`` (a few ms: long enough to coalesce a concurrent burst, short
enough to be invisible next to an inner-loop rollout). One worker thread owns
all flushes, so device dispatch is serialized — no jit-cache races, no
interleaved transfers.

``continuous=True`` adds the Orca lesson (iteration-level scheduling; Yu et
al., OSDI'22) at this batcher's granularity: requests arriving while a flush
is in flight are admitted into the NEXT flush the moment the worker frees,
instead of waiting out their own deadline window. Under load the worker runs
back-to-back flushes whose sizes grow toward ``max_batch``; at light load
nothing changes — an idle worker still holds a lone request for
``deadline_ms`` hoping to coalesce a burst, so the deadline semantics
stragglers rely on are preserved.
"""

import threading
import time
from collections import OrderedDict
from concurrent.futures import Future, InvalidStateError
from typing import Any, Callable, Dict, Hashable, List, Tuple

from ..observability.context import flow_step
from ..observability.trace import NULL_TRACER

from ..utils.locks import san_condition, san_lock


class QueueFullError(RuntimeError):
    """submit() refused: the batcher already holds ``max_queue_depth``
    undispatched requests. Load-shedding at the queue (rather than letting it
    grow unboundedly and time every request out) keeps tail latency bounded
    under overload; the HTTP layer maps this to 503 + ``Retry-After``."""


class MicroBatcher:
    """Groups submitted payloads by bucket key and flushes each group through
    ``flush_fn(bucket_key, payloads) -> results`` (one result per payload, in
    order). ``submit`` returns a ``Future``; a ``flush_fn`` exception fails
    every future of its group. ``max_queue_depth`` (None = unbounded, the
    pre-resilience behavior) sheds submits beyond that many queued requests
    with :class:`QueueFullError`, counted in ``stats()['shed']``."""

    def __init__(
        self,
        flush_fn: Callable[[Hashable, List[Any]], List[Any]],
        max_batch: int,
        deadline_ms: float,
        name: str = "batcher",
        max_queue_depth: int = None,
        tracer=None,
        pass_contexts: bool = False,
        continuous: bool = False,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self._flush_fn = flush_fn
        # flush spans on the worker thread (observability/trace.py); the
        # shared NULL_TRACER default keeps the un-instrumented path free
        self._tracer = tracer if tracer is not None else NULL_TRACER
        # pass_contexts=True widens the flush contract to
        # ``flush_fn(bucket_key, payloads, ctxs)`` so the engine can finish
        # each request's flow at its dispatch span (the frontend opts in;
        # the 2-arg default keeps every existing flush_fn working)
        self._pass_contexts = bool(pass_contexts)
        self.max_batch = int(max_batch)
        self.deadline_s = float(deadline_ms) / 1000.0
        self.max_queue_depth = None if max_queue_depth is None else int(max_queue_depth)
        self.continuous = bool(continuous)
        self.name = name
        self._lock = san_lock("MicroBatcher._lock")
        self._wake = san_condition("MicroBatcher._wake", self._lock)
        # bucket key -> list of (payload, future, enqueue_time, ctx);
        # insertion-ordered so the group with the oldest head is flushed
        # first on deadline. ctx (observability/context.py RequestContext,
        # or None) rides the queue so the flush can stamp each request's
        # queue wait + flush batch and link its trace flow.
        self._groups: "OrderedDict[Hashable, List[Tuple[Any, Future, float, Any]]]" = OrderedDict()
        self._closed = False
        self.requests = 0
        self.shed = 0  # submits refused at max_queue_depth
        self.flushes_full = 0
        self.flushes_deadline = 0
        # continuous-mode flushes: requests admitted while the previous
        # flush was in flight, dispatched the moment the worker freed —
        # under load these dominate and the deadline never paces a flush
        self.flushes_continuous = 0
        # set after every completed flush, cleared when the worker finds the
        # queue empty: only requests that queued DURING a flush skip their
        # deadline window (a straggler arriving at an idle worker does not)
        self._just_flushed = False
        # flushes whose flush_fn RETURNED (result or exception) — the
        # worker-progress signal server._dispatch uses to tell a backlogged
        # worker from a wedged one when a queued request's deadline expires
        self.flushes_done = 0
        # requests currently INSIDE flush_fn (no longer queued, not yet
        # resolved): queue_depth + in_flight is the wedge watchdog's
        # "work pending" signal — a worker parked forever in a hung device
        # dispatch has queue_depth 0 but in_flight > 0
        self.in_flight = 0
        self.batched_requests = 0  # requests that shared a flush with others
        self._worker = threading.Thread(
            target=self._run, name=f"{name}-flush", daemon=True
        )
        self._worker.start()

    # ------------------------------------------------------------------

    def submit(self, bucket_key: Hashable, payload: Any, ctx=None) -> Future:
        fut: Future = Future()
        with self._wake:
            if self._closed:
                raise RuntimeError(f"{self.name} is closed")
            if (
                self.max_queue_depth is not None
                and sum(len(g) for g in self._groups.values()) >= self.max_queue_depth
            ):
                # shed under the same lock the depth is read under — no race
                # between the check and the enqueue
                self.shed += 1
                raise QueueFullError(
                    f"{self.name} queue full ({self.max_queue_depth} requests "
                    "undispatched) — shedding"
                )
            self._groups.setdefault(bucket_key, []).append(
                (payload, fut, time.monotonic(), ctx)
            )
            self.requests += 1
            self._wake.notify()
        return fut

    def queue_depth(self) -> int:
        with self._lock:
            return sum(len(g) for g in self._groups.values())

    def flushes_completed(self) -> int:
        with self._lock:
            return self.flushes_done

    def pending(self) -> int:
        """Requests queued or mid-flush — nonzero means the worker has work
        it is accountable for making progress on."""
        with self._lock:
            return sum(len(g) for g in self._groups.values()) + self.in_flight

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            flushes = (
                self.flushes_full + self.flushes_deadline + self.flushes_continuous
            )
            return {
                "requests": self.requests,
                "shed": self.shed,
                "flushes": flushes,
                "flushes_full": self.flushes_full,
                "flushes_deadline": self.flushes_deadline,
                "flushes_continuous": self.flushes_continuous,
                "flushes_done": self.flushes_done,
                "batched_requests": self.batched_requests,
                "mean_batch": (self.requests / flushes) if flushes else 0.0,
                "queue_depth": sum(len(g) for g in self._groups.values()),
                "in_flight": self.in_flight,
            }

    def close(self, join_timeout_s: float = None) -> None:
        """Flush everything still queued, then stop the worker.
        ``join_timeout_s`` bounds the wait (the drain-deadline-exceeded
        path: a worker parked in a hung dispatch must not also hang the
        exiting process — it is a daemon thread and dies with it)."""
        with self._wake:
            if self._closed:
                return
            self._closed = True
            self._wake.notify()
        self._worker.join(timeout=join_timeout_s)

    # ------------------------------------------------------------------

    def _take_locked(self, key: Hashable) -> List[Tuple[Any, Future, float, Any]]:
        """Pop at most ``max_batch`` items off a group's head; the remainder
        stays queued with its own enqueue times (its head ages toward the
        deadline like any other group)."""
        group = self._groups[key]
        if len(group) <= self.max_batch:
            return self._groups.pop(key)
        taken, rest = group[: self.max_batch], group[self.max_batch :]
        self._groups[key] = rest
        return taken

    def _pop_ready_locked(self, now: float):
        """The next batch due for flush: any group at max_batch, else — in
        continuous mode, right after a flush — the oldest-head group (its
        requests queued while the worker was busy; making them wait out a
        deadline on top would be pure idle time), else one whose head has
        passed the deadline; None when nothing is due."""
        for key, group in self._groups.items():
            if len(group) >= self.max_batch:
                self.flushes_full += 1
                return key, self._take_locked(key)
        if self.continuous and self._just_flushed and self._groups:
            key = min(self._groups, key=lambda k: self._groups[k][0][2])
            self.flushes_continuous += 1
            return key, self._take_locked(key)
        for key, group in list(self._groups.items()):
            if now - group[0][2] >= self.deadline_s:
                self.flushes_deadline += 1
                return key, self._take_locked(key)
        return None

    def _run(self) -> None:
        while True:
            with self._wake:
                while True:
                    now = time.monotonic()
                    if self._closed and not self._groups:
                        return
                    if self._closed:
                        # drain: every remaining group is due immediately
                        key = next(iter(self._groups))
                        self.flushes_deadline += 1
                        ready = (key, self._take_locked(key))
                        break
                    ready = self._pop_ready_locked(now)
                    if ready is not None:
                        break
                    if self._groups:
                        next_due = (
                            min(g[0][2] for g in self._groups.values())
                            + self.deadline_s
                        )
                        self._wake.wait(timeout=max(next_due - now, 0.0))
                    else:
                        # queue drained: the next arrival meets an idle
                        # worker and gets the full coalescing window
                        self._just_flushed = False
                        self._wake.wait()
                if len(ready[1]) > 1:
                    self.batched_requests += len(ready[1])
            key, group = ready
            # a future cancelled while queued (request-deadline shed,
            # serving/server.py::_dispatch) must not consume device work —
            # and completing it would raise InvalidStateError and kill this
            # worker thread
            group = [(p, fut, t, c) for p, fut, t, c in group if not fut.cancelled()]
            if not group:
                # dropping an all-cancelled group is still worker liveness:
                # without counting it, a deadline tight enough to cancel
                # every queued request reads as zero progress and the wedge
                # watchdog rc=76s a demonstrably live worker
                with self._lock:
                    self.flushes_done += 1
                    # arm continuous pickup ONLY for requests that queued
                    # while this flush was in flight; a later straggler at
                    # the then-idle worker keeps its coalescing deadline
                    self._just_flushed = bool(self._groups)
                continue
            payloads = [p for p, _, _, _ in group]
            # stamp each request's journey through this flush BEFORE the
            # dispatch: queue wait (enqueue -> worker pickup) and how many
            # flush-mates it shares the device call with — the numbers a
            # continuous-batching p99 investigation needs per request
            pickup = time.monotonic()
            ctxs = []
            for _, _, t_enq, c in group:
                if c is not None:
                    c.queue_wait_s = pickup - t_enq
                    c.flush_batch = len(group)
                ctxs.append(c)
            flows = flow_step(ctxs)
            with self._lock:
                self.in_flight = len(group)
            try:
                with self._tracer.span(
                    f"serve.flush.{self.name}", flows=flows,
                    batch=len(group), bucket=key,
                ):
                    if self._pass_contexts:
                        results = self._flush_fn(key, payloads, ctxs)
                    else:
                        results = self._flush_fn(key, payloads)
                if len(results) != len(group):
                    raise RuntimeError(
                        f"{self.name} flush_fn returned {len(results)} results "
                        f"for {len(group)} payloads"
                    )
            except BaseException as exc:  # noqa: BLE001 — fail the futures, keep serving
                with self._lock:
                    self.flushes_done += 1  # an exception is still progress
                    self.in_flight = 0
                    self._just_flushed = bool(self._groups)
                for _, fut, _, _ in group:
                    self._complete(fut, exc=exc)
                continue
            with self._lock:
                self.flushes_done += 1
                self.in_flight = 0
                self._just_flushed = bool(self._groups)
            for (_, fut, _, _), res in zip(group, results):
                self._complete(fut, result=res)

    @staticmethod
    def _complete(fut: Future, result=None, exc=None) -> None:
        """Set a future's outcome, tolerating a cancel that raced the flush
        (the caller already gave up on it; the worker must survive)."""
        try:
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(result)
        except InvalidStateError:
            pass
