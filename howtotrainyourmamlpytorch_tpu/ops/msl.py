"""Multi-step-loss (MSL) importance annealing.

Exact re-derivation of the reference schedule
(``few_shot_learning_system.py:131-151``): weights start uniform ``1/N`` over
the ``N`` inner steps; each epoch every non-final weight decays by
``1/(N * multi_step_loss_num_epochs)`` down to a floor of ``0.03/N`` while the
final-step weight grows symmetrically up to ``1 - (N-1) * 0.03/N``. All
non-final weights are equal at every epoch, so the loop in the reference
collapses to the closed form below. Implemented on traced scalars so one
compiled meta-step program serves every epoch.
"""

import jax.numpy as jnp


def per_step_loss_importance(epoch, num_steps: int, multi_step_loss_num_epochs: int):
    """Weight vector [num_steps] as a function of the (traced) epoch index."""
    epoch = jnp.asarray(epoch, jnp.float32)
    n = float(num_steps)
    decay_rate = 1.0 / n / multi_step_loss_num_epochs
    min_non_final = 0.03 / n
    non_final = jnp.maximum(1.0 / n - epoch * decay_rate, min_non_final)
    final = jnp.minimum(
        1.0 / n + epoch * (n - 1.0) * decay_rate,
        1.0 - (n - 1.0) * min_non_final,
    )
    weights = jnp.full((num_steps,), 1.0, jnp.float32) * non_final
    return weights.at[-1].set(final)


def final_step_only(num_steps: int):
    """The no-MSL weighting: only the last inner step's target loss counts
    (reference ``few_shot_learning_system.py:246-251``)."""
    return jnp.zeros((num_steps,), jnp.float32).at[-1].set(1.0)
