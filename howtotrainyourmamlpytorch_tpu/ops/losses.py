"""Loss and metric primitives."""

import jax
import jax.numpy as jnp


def cross_entropy(logits, labels):
    """Mean softmax cross-entropy with integer labels (= F.cross_entropy,
    reference ``few_shot_learning_system.py:223-224``)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(nll)


def accuracy(logits, labels):
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))
