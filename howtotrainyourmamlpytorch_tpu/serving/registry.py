"""Tenant model registry: many checkpoints behind one serving fleet.

A frontend used to serve exactly one checkpoint fingerprint; the registry
maps **tenant ids** to checkpoint run directories so thousands of fine-tuned
variants can sit behind the same compiled engine. Master weights load
**lazily into host RAM** (a registry naming 1000 tenants costs nothing until
traffic arrives for one) and are keyed on the existing sha256 checkpoint
fingerprints — the same content address the adapted-weight cache, session
store, and gateway affinity already use, so tenant isolation falls out of
content addressing rather than a parallel namespace.

The registry is pure host-side bookkeeping: paging masters onto a device
under a byte budget is ``serving/tenancy.py::WeightPager``'s job, and the
compiled programs never key on a tenant at all (the program set is
shape-keyed — ``docs/OPERATIONS.md`` "Multi-tenant serving").

Registry sources, in precedence order:

1. an explicit ``serving.tenant_registry`` YAML path;
2. ``<run_dir>/tenants.yaml`` next to the served run's ``config.yaml``.

YAML format (``checkpoint`` optional, ``best`` with a ``latest`` fallback,
matching ``AdaptationEngine.from_run_dir``)::

    tenants:
      acme:
        run_dir: exps/acme_finetune
        checkpoint: best
"""

import os
import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import yaml

from ..core import TrainState
from ..experiment import checkpoint as ckpt

from ..utils.locks import san_lock


def _tree_shapes(tree: Any) -> List[Tuple[str, Tuple[int, ...]]]:
    """Sorted (path, shape) pairs — the structural identity two checkpoints
    must share to flow through one shape-keyed compiled program. Optimizer
    state is excluded: serving never touches it, and registry loads always
    come back with ``opt_state=None`` while a directly-constructed fleet
    master may still carry one."""
    if hasattr(tree, "_replace"):
        tree = tree._replace(opt_state=None)
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    return sorted(
        (jax.tree_util.keystr(path), tuple(np.shape(leaf)))
        for path, leaf in flat
    )


class TenantRegistry:
    """Lazy host-RAM store of tenant master states, fingerprint-keyed.

    ``entries`` maps tenant id -> ``{"run_dir": ..., "checkpoint": ...}``.
    ``host_state(tenant)`` loads the checkpoint on first use (host numpy
    arrays — no device memory until the pager asks) and validates its tree
    structure against ``template`` when one is set: a tenant whose backbone
    differs from the fleet master cannot share the compiled programs, and
    failing at load beats recompiling on the serving hot path."""

    def __init__(self, entries: Dict[str, Dict[str, Any]], base_dir: str = ""):
        self.base_dir = base_dir
        self._entries: Dict[str, Dict[str, Any]] = {}
        for tenant, spec in entries.items():
            if not isinstance(spec, dict) or "run_dir" not in spec:
                raise ValueError(
                    f"tenant {tenant!r}: registry entry must be a mapping "
                    f"with a run_dir, got {spec!r}"
                )
            self._entries[str(tenant)] = {
                "run_dir": str(spec["run_dir"]),
                "checkpoint": str(spec.get("checkpoint", "best")),
            }
        if not self._entries:
            raise ValueError("tenant registry names no tenants")
        self._lock = san_lock("TenantRegistry._lock")
        # tenant -> (host TrainState, fingerprint); populated lazily
        self._masters: Dict[str, Tuple[TrainState, str]] = {}
        self.template: Optional[Any] = None
        self.loads = 0

    # -- construction ----------------------------------------------------

    @classmethod
    def from_yaml(cls, path: str) -> "TenantRegistry":
        with open(path) as f:
            doc = yaml.safe_load(f)
        if not isinstance(doc, dict) or not isinstance(doc.get("tenants"), dict):
            raise ValueError(
                f"tenant registry {path}: expected a top-level 'tenants' mapping"
            )
        # relative run_dirs resolve against the registry file's directory,
        # so a registry travels with the run tree it names
        return cls(doc["tenants"], base_dir=os.path.dirname(os.path.abspath(path)))

    @classmethod
    def discover(
        cls, serving_cfg, run_dir: Optional[str] = None
    ) -> Optional["TenantRegistry"]:
        """The two registry sources, explicit path winning. None = the
        single-tenant mode every pre-tenancy deployment runs in."""
        explicit = getattr(serving_cfg, "tenant_registry", None)
        if explicit:
            return cls.from_yaml(explicit)
        if run_dir:
            auto = os.path.join(run_dir, "tenants.yaml")
            if os.path.exists(auto):
                return cls.from_yaml(auto)
        return None

    # -- lookup ----------------------------------------------------------

    def tenants(self) -> Tuple[str, ...]:
        return tuple(sorted(self._entries))

    def __contains__(self, tenant: str) -> bool:
        return tenant in self._entries

    def _resolve_run_dir(self, tenant: str) -> str:
        run_dir = self._entries[tenant]["run_dir"]
        if not os.path.isabs(run_dir) and self.base_dir:
            run_dir = os.path.join(self.base_dir, run_dir)
        return run_dir

    def host_state(self, tenant: str) -> Tuple[TrainState, str]:
        """(host-RAM master TrainState, checkpoint fingerprint) for one
        tenant, loaded on first use and cached (masters are immutable)."""
        if tenant not in self._entries:
            raise KeyError(f"unknown tenant {tenant!r}")
        with self._lock:
            cached = self._masters.get(tenant)
            if cached is not None:
                return cached
            save_dir = os.path.join(self._resolve_run_dir(tenant), "saved_models")
            idx = self._entries[tenant]["checkpoint"]
            if idx == "best" and not ckpt.checkpoint_exists(save_dir, "best"):
                idx = "latest"
            inf, _ = ckpt.load_for_inference(save_dir, idx)
            state = TrainState(
                params=inf.params,
                bn_state=inf.bn_state,
                inner_hparams=inf.inner_hparams,
                opt_state=None,
                step=jnp.asarray(inf.step, jnp.int32),
            )
            if self.template is not None and _tree_shapes(state) != _tree_shapes(
                self.template
            ):
                raise ValueError(
                    f"tenant {tenant!r}: checkpoint structure differs from the "
                    "fleet master — it cannot share the shape-keyed compiled "
                    "programs (serve it from its own fleet)"
                )
            self._masters[tenant] = (state, inf.fingerprint)
            self.loads += 1
            return self._masters[tenant]

    def fingerprint(self, tenant: str) -> str:
        return self.host_state(tenant)[1]

    def hosted_fingerprints(self) -> Dict[str, str]:
        """tenant -> fingerprint for masters ALREADY in host RAM (no loads
        triggered) — the drain spill's reverse map: only a loaded tenant
        can have adapted sessions in any cache."""
        with self._lock:
            return {t: fp for t, (_, fp) in self._masters.items()}

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "tenants": len(self._entries),
                "hosted": len(self._masters),
                "loads": self.loads,
            }


def synthetic_registry(
    tenant_ids, state, root: str, seed: int = 0
) -> TenantRegistry:
    """N deterministically-perturbed copies of ``state`` saved as real
    checkpoints under ``root`` (one run dir per tenant), behind a
    TenantRegistry — the in-process multi-tenant backend loadgen and
    bench_serving share (same idea as the chaos campaign's perturbed toy
    run dirs, without needing an existing run dir)."""
    entries: Dict[str, Dict[str, Any]] = {}
    for i, tenant in enumerate(tenant_ids):
        rng = np.random.default_rng((int(seed) << 8) + i + 1)

        def _perturb(leaf):
            a = np.asarray(leaf)
            if not np.issubdtype(a.dtype, np.floating):
                return leaf
            return a + (0.01 * rng.standard_normal(a.shape)).astype(a.dtype)

        run_dir = os.path.join(root, str(tenant))
        save_dir = os.path.join(run_dir, "saved_models")
        os.makedirs(save_dir, exist_ok=True)
        ckpt.save_named(
            save_dir,
            state._replace(params=jax.tree.map(_perturb, state.params)),
            {"epoch": 0},
            "latest",
        )
        entries[str(tenant)] = {"run_dir": run_dir, "checkpoint": "latest"}
    return TenantRegistry(entries)
