"""Results archiver — the TPU build's stand-in for the reference's
results-uploader.

The reference ships a PyDrive Google-Drive uploader only as compiled bytecode
(``utils/__pycache__/gdrive_utils.cpython-36.pyc``: ``get_drive``,
``get_folder_id``, ``delete_file``) — an aux tool for shipping experiment
artifacts off the training machine. TPU pods have no interactive OAuth flow
and this environment has no egress, so the equivalent here is local-first:
pack a run's artifacts (logs, configs, learned-hparam CSVs — NOT the large
checkpoints unless asked) into a single tar.gz that any transport (gsutil,
scp, a results bucket) can ship, plus list/delete management of the archive
dir mirroring the uploader's folder management.
"""

import os
import tarfile
import time
from typing import List, Optional

#: artifact names worth shipping (checkpoints excluded by default — they are
#: the bulk of a run dir and rarely wanted off-device)
_DEFAULT_INCLUDE = ("logs", "config.yaml", "lrs.csv", "betas.csv", "visual_outputs")


def pack_run(
    run_dir: str,
    archive_dir: str,
    include_checkpoints: bool = False,
    archive_name: Optional[str] = None,
) -> str:
    """Tar a run directory's artifacts into ``archive_dir``; returns the path."""
    run_dir = run_dir.rstrip("/")
    if not os.path.isdir(run_dir):
        raise FileNotFoundError(run_dir)
    os.makedirs(archive_dir, exist_ok=True)
    stem = archive_name or os.path.basename(run_dir)
    base = os.path.join(archive_dir, f"{stem}.{time.strftime('%Y%m%d-%H%M%S')}")
    out_path, n = base + ".tar.gz", 0
    while os.path.exists(out_path):  # same stem in the same second
        n += 1
        out_path = f"{base}.{n}.tar.gz"
    include = _DEFAULT_INCLUDE + (("saved_models",) if include_checkpoints else ())
    with tarfile.open(out_path, "w:gz") as tar:
        for name in include:
            path = os.path.join(run_dir, name)
            if os.path.exists(path):
                tar.add(path, arcname=os.path.join(os.path.basename(run_dir), name))
    return out_path


def list_archives(archive_dir: str) -> List[str]:
    if not os.path.isdir(archive_dir):
        return []
    return sorted(
        os.path.join(archive_dir, f) for f in os.listdir(archive_dir) if f.endswith(".tar.gz")
    )


def delete_archive(path: str) -> None:
    """Remove one archive (the uploader's ``delete_file`` management op)."""
    if not path.endswith(".tar.gz"):
        raise ValueError(f"refusing to delete non-archive path {path!r}")
    os.remove(path)
