"""Full-train-state checkpointing.

Fixes the reference's resume gap (SURVEY.md §5.4): its ``save_model`` writes
only ``state_dict()`` — outer Adam moments and scheduler position are lost on
resume (reference ``few_shot_learning_system.py:409-432``). Here the checkpoint
is the complete ``TrainState`` pytree (params + BN state + learned inner-opt
hyperparams + outer optimizer state + step counter) plus runner bookkeeping
(epoch, data cursor, best-val tracking), serialized with flax msgpack.

File naming mirrors the reference ("{name}_{idx}" with idx = epoch or
'latest'); ``max_models_to_save`` rotation matches ``config.yaml:12``.
"""

import hashlib
import os
import re
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import numpy as np
from flax import serialization

from ..core.train_state import TrainState

MODEL_NAME = "train_model"


class InferenceState(NamedTuple):
    """The checkpoint subset a serving process needs: meta-parameters, BN
    state, learned inner-opt hyperparams, and the step counter — WITHOUT the
    outer optimizer moments (for the flagship config the optimizer state is
    ~2/3 of the checkpoint, and a server never takes an outer step).
    ``fingerprint`` is a content hash of the checkpoint file, the cache-key
    component that invalidates adapted-weight cache entries across model
    pushes (serving/cache.py)."""

    params: Any
    bn_state: Any
    inner_hparams: Any
    step: Any
    fingerprint: str


def _path(save_dir: str, idx) -> str:
    return os.path.join(save_dir, f"{MODEL_NAME}_{idx}")


def _serialize(state: TrainState, bookkeeping: Dict[str, Any]) -> bytes:
    payload = {
        "network": serialization.to_bytes(jax.tree.map(np.asarray, state)),
        "bookkeeping": bookkeeping,
    }
    return serialization.msgpack_serialize(payload)


def _write_atomic(target: str, blob: bytes) -> None:
    tmp = target + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, target)  # atomic: preemption-safe (SURVEY.md §5.3)


def save_named(save_dir: str, state: TrainState, bookkeeping: Dict[str, Any], idx) -> str:
    """Write a single checkpoint file under any idx (e.g. 'best')."""
    path = _path(save_dir, idx)
    _write_atomic(path, _serialize(state, bookkeeping))
    return path


def save_checkpoint(
    save_dir: str,
    state: TrainState,
    bookkeeping: Dict[str, Any],
    epoch: int,
    max_models_to_save: int = 5,
    val_acc_by_epoch: Optional[Dict[int, float]] = None,
) -> str:
    """Write ``train_model_{epoch}`` + ``train_model_latest`` and rotate.

    Rotation keeps ``max_models_to_save`` per-epoch files: the most recent
    ones by default, or — when ``val_acc_by_epoch`` is given — the top ones by
    validation accuracy (upstream MAML++ kept its best-5 val models for test
    ensembling; SURVEY.md §2.9 item 4)."""
    blob = _serialize(state, bookkeeping)
    path = _path(save_dir, epoch)
    for target in (path, _path(save_dir, "latest")):
        _write_atomic(target, blob)
    _rotate(save_dir, max_models_to_save, val_acc_by_epoch)
    return path


def _rotate(save_dir: str, keep: int, val_acc_by_epoch: Optional[Dict[int, float]] = None) -> None:
    if keep <= 0:
        return
    epochs = available_epochs(save_dir)
    if val_acc_by_epoch is not None:
        # drop lowest-val-acc first; epochs missing a recorded val acc (e.g.
        # from an older run) rank lowest, ties broken oldest-first
        epochs = sorted(epochs, key=lambda e: (val_acc_by_epoch.get(e, -1.0), e))
    for epoch in epochs[:-keep]:
        os.remove(_path(save_dir, epoch))


def load_checkpoint(
    save_dir: str, idx, template_state: TrainState
) -> Tuple[TrainState, Dict[str, Any]]:
    """``idx`` is an epoch number or 'latest' (reference load_model API,
    ``few_shot_learning_system.py:419-432``). ``template_state`` supplies the
    pytree structure (an ``init_train_state()`` result)."""
    with open(_path(save_dir, idx), "rb") as f:
        payload = serialization.msgpack_restore(f.read())
    template = jax.tree.map(np.asarray, template_state)
    state = serialization.from_bytes(template, payload["network"])
    return TrainState(*state), payload["bookkeeping"]


def load_for_inference(save_dir: str, idx) -> Tuple[InferenceState, Dict[str, Any]]:
    """Restore params / BN state / inner hyperparams / step for serving,
    dropping the outer optimizer state (serving never takes an outer step;
    note this also means an inner-Adam config with
    ``warm_start_inner_opt_from_outer`` adapts from cold inner moments when
    loaded this way — the warm start is a training-time coupling to the
    outer Adam that a standalone server deliberately does not carry).

    Unlike :func:`load_checkpoint` this needs no template state: the flax
    msgpack payload stores the TrainState by field name with plain
    dict-of-ndarray subtrees, which restore structurally as-is."""
    with open(_path(save_dir, idx), "rb") as f:
        blob = f.read()
    payload = serialization.msgpack_restore(blob)
    # "network" is itself msgpack bytes (see _serialize): decode the inner
    # layer to the field-name-keyed TrainState dict
    net = serialization.msgpack_restore(payload["network"])
    state = InferenceState(
        params=net["params"],
        bn_state=net["bn_state"],
        inner_hparams=net["inner_hparams"],
        step=np.asarray(net["step"]),
        fingerprint=hashlib.sha256(blob).hexdigest(),
    )
    return state, payload["bookkeeping"]


def latest_checkpoint_exists(save_dir: str) -> bool:
    return checkpoint_exists(save_dir, "latest")


def checkpoint_exists(save_dir: str, idx) -> bool:
    return os.path.exists(_path(save_dir, idx))


def available_epochs(save_dir: str):
    pattern = re.compile(rf"^{MODEL_NAME}_(\d+)$")
    if not os.path.isdir(save_dir):
        return []
    return sorted(
        int(m.group(1)) for name in os.listdir(save_dir) if (m := pattern.match(name))
    )
