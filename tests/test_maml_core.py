"""Meta-step program tests (SURVEY.md §4 'Gradient' tier): second-order
meta-gradient vs finite differences, first-order/second-order divergence (the
knob the reference silently broke), MSL weighting, cosine schedule parity with
torch, warm-start semantics, and a learning smoke test."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from howtotrainyourmamlpytorch_tpu.config import Config, InnerOptimConfig
from howtotrainyourmamlpytorch_tpu.core import MAMLSystem, cosine_epoch_schedule
from howtotrainyourmamlpytorch_tpu.data.synthetic import (
    learnable_synthetic_batch,
    synthetic_batch,
)
from howtotrainyourmamlpytorch_tpu.models import Model, build_vgg

TINY_SHAPE = (8, 8, 1)


def tiny_linear_model(num_classes=3, dim=None):
    """Minimal pure-linear model for gradient math tests."""
    d = dim or int(np.prod(TINY_SHAPE))

    def init(key):
        k1, k2 = jax.random.split(key)
        params = {
            "w": 0.1 * jax.random.normal(k1, (d, num_classes)),
            "b": jnp.zeros((num_classes,)),
        }
        return params, {}

    def apply(params, state, x, *, use_batch_stats=True, update_running=False):
        flat = x.reshape((x.shape[0], -1))
        return flat @ params["w"] + params["b"], state

    return Model(init=init, apply=apply, name="tiny")


def tiny_config(**overrides) -> Config:
    base = dict(
        num_classes_per_set=3,
        num_samples_per_class=2,
        num_target_samples=2,
        batch_size=2,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        total_iter_per_epoch=4,
        total_epochs=5,
        multi_step_loss_num_epochs=3,
        seed=0,
    )
    base.update(overrides)
    return Config(**base)


def tiny_batch(seed=0, n_way=3, k=2, t=2):
    return synthetic_batch(2, n_way, k, t, TINY_SHAPE, seed=seed)


def _as_jnp(batch):
    return {k: jnp.asarray(v) for k, v in batch.items()}


# ---------------------------------------------------------------------------


def test_cosine_schedule_matches_torch():
    meta_lr, min_lr, total_epochs, iters = 1e-3, 1e-5, 150, 500
    sched = cosine_epoch_schedule(meta_lr, min_lr, total_epochs, iters)
    p = torch.nn.Parameter(torch.zeros(1))
    opt = torch.optim.Adam([p], lr=meta_lr)
    scheduler = torch.optim.lr_scheduler.CosineAnnealingLR(opt, T_max=total_epochs, eta_min=min_lr)
    for epoch in [0, 1, 5, 75, 149]:
        scheduler.step(epoch=epoch)
        torch_lr = opt.param_groups[0]["lr"]
        ours = float(sched(epoch * iters + 3))  # any iter within the epoch
        np.testing.assert_allclose(ours, torch_lr, rtol=1e-4)  # f32 cosine


def test_second_order_meta_gradient_vs_finite_differences():
    cfg = tiny_config(use_multi_step_loss_optimization=False, learnable_inner_opt_params=False)
    system = MAMLSystem(cfg, model=tiny_linear_model())
    state = system.init_train_state()
    batch = _as_jnp(tiny_batch())

    def objective(params):
        loss, _ = system._meta_objective(
            {"params": params, "hparams": {}},
            state.bn_state,
            None,
            batch,
            jnp.asarray(0),
            True,
            cfg.number_of_training_steps_per_iter,
            False,  # msl_active
        )
        return loss

    g = jax.grad(objective)(state.params)
    eps = 1e-3
    rng = np.random.RandomState(0)
    for name in ["w", "b"]:
        arr = np.asarray(state.params[name])
        for _ in range(3):
            idx = tuple(rng.randint(0, s) for s in arr.shape)
            # NB: jnp.asarray can alias numpy memory on CPU — copy per probe.
            plus = arr.copy()
            plus[idx] += eps
            minus = arr.copy()
            minus[idx] -= eps
            p_plus = dict(state.params, **{name: jnp.asarray(plus)})
            p_minus = dict(state.params, **{name: jnp.asarray(minus)})
            fd = (float(objective(p_plus)) - float(objective(p_minus))) / (2 * eps)
            np.testing.assert_allclose(
                float(np.asarray(g[name])[idx]), fd, rtol=2e-2, atol=1e-4
            )


def test_first_vs_second_order_differ():
    """The reference broke first-order (SURVEY.md §2.2); here it must be a real
    switch: the two gradients should differ."""
    cfg = tiny_config(use_multi_step_loss_optimization=False, learnable_inner_opt_params=False)
    system = MAMLSystem(cfg, model=tiny_linear_model())
    state = system.init_train_state()
    batch = _as_jnp(tiny_batch())

    def objective(params, second_order):
        loss, _ = system._meta_objective(
            {"params": params, "hparams": {}},
            state.bn_state,
            None,
            batch,
            jnp.asarray(0),
            second_order,
            cfg.number_of_training_steps_per_iter,
            False,  # msl_active
        )
        return loss

    g2 = jax.grad(lambda p: objective(p, True))(state.params)
    g1 = jax.grad(lambda p: objective(p, False))(state.params)
    diff = float(
        jnp.linalg.norm(g2["w"] - g1["w"]) / (jnp.linalg.norm(g2["w"]) + 1e-12)
    )
    assert diff > 1e-3, f"first- and second-order gradients identical (diff={diff})"


def test_msl_weighting_matches_manual_rollout():
    """Meta-loss must equal sum_i w_i * CE(target after step i), mean over tasks."""
    cfg = tiny_config(learnable_inner_opt_params=False)
    system = MAMLSystem(cfg, model=tiny_linear_model())
    state = system.init_train_state()
    batch = _as_jnp(tiny_batch())
    epoch = 1
    loss, aux = system._meta_objective(
        {"params": state.params, "hparams": {}},
        state.bn_state,
        None,
        batch,
        jnp.asarray(epoch),
        True,
        cfg.number_of_training_steps_per_iter,
        True,  # msl_active: epoch 1 < multi_step_loss_num_epochs
    )

    # manual per-task rollout with plain SGD
    from howtotrainyourmamlpytorch_tpu.ops.losses import cross_entropy
    from howtotrainyourmamlpytorch_tpu.ops.msl import per_step_loss_importance

    w_vec = np.asarray(
        per_step_loss_importance(epoch, cfg.number_of_training_steps_per_iter, cfg.multi_step_loss_num_epochs)
    )
    model = system.model
    total = []
    for b in range(2):
        p = state.params
        xs = batch["x_support"][b].reshape((-1,) + TINY_SHAPE)
        ys = batch["y_support"][b].reshape(-1)
        xt = batch["x_target"][b].reshape((-1,) + TINY_SHAPE)
        yt = batch["y_target"][b].reshape(-1)
        task_loss = 0.0
        for i in range(cfg.number_of_training_steps_per_iter):
            grads = jax.grad(lambda q: cross_entropy(model.apply(q, {}, xs)[0], ys))(p)
            p = jax.tree.map(lambda a, g: a - cfg.inner_optim.lr * g, p, grads)
            task_loss += w_vec[i] * float(cross_entropy(model.apply(p, {}, xt)[0], yt))
        total.append(task_loss)
    np.testing.assert_allclose(float(loss), np.mean(total), rtol=1e-5)


def test_warm_start_seeds_inner_adam_from_outer_state():
    cfg = tiny_config(inner_optim=InnerOptimConfig(kind="adam", lr=0.1, beta1=0.5, beta2=0.5))
    system = MAMLSystem(cfg, model=tiny_linear_model())
    state = system.init_train_state()
    # Run one train step so the outer Adam accumulates moments.
    state2, _ = system.train_step(state, _as_jnp(tiny_batch()))
    hp = system._inner_hparams_for_rollout(state2.inner_hparams, state2.params)
    inner0 = system._initial_inner_state(state2.params, hp, state2.opt_state)
    assert float(jnp.abs(inner0["exp_avg"]["w"]).sum()) > 0  # warm-started
    assert float(inner0["step"]["w"]) == 1.0
    cfg_cold = dataclasses.replace(cfg, warm_start_inner_opt_from_outer=False)
    system_cold = MAMLSystem(cfg_cold, model=tiny_linear_model())
    inner0_cold = system_cold._initial_inner_state(state2.params, hp, state2.opt_state)
    assert float(jnp.abs(inner0_cold["exp_avg"]["w"]).sum()) == 0.0


def test_train_step_learns_synthetic_tasks():
    # long cosine horizon + larger meta-lr so 40 steps of signal are visible
    cfg = tiny_config(total_epochs=100, total_iter_per_epoch=50, meta_learning_rate=0.01)
    system = MAMLSystem(cfg, model=tiny_linear_model())
    state = system.init_train_state()
    losses = []
    for i in range(40):
        batch = _as_jnp(learnable_synthetic_batch(2, 3, 2, 2, TINY_SHAPE, seed=i % 4))
        state, out = system.train_step(state, batch)
        losses.append(float(out.loss))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.9, losses
    eval_out = system.eval_step(state, _as_jnp(learnable_synthetic_batch(2, 3, 2, 2, TINY_SHAPE, seed=2)))
    assert float(eval_out.accuracy) > 0.5


@pytest.mark.parametrize("kind", ["adam", "rprop"])
def test_train_step_learns_with_each_inner_optimizer(kind):
    """Every inner-optimizer ablation axis trains end-to-end (reference
    config.yaml:68-85 gd/rprop/adam nodes), incl. learnable per-tensor lrs.
    Inner Adam at the reference's aggressive lr=0.1/beta=0.5 is high-variance
    (its published Adam ablations carry std up to ±11.6 accuracy points), so
    the assertion is adaptation above chance + finite, moving hyperparams —
    not monotone loss. The outer->inner Adam moment warm-start (the reference
    quirk, SURVEY §2.2) measurably *hurts* on this tiny task — chance-level
    with it, 0.67 accuracy without — so it's disabled here; its mechanics are
    pinned separately in test_warm_start_seeds_inner_adam_from_outer_state."""
    cfg = tiny_config(
        total_epochs=100, total_iter_per_epoch=50, meta_learning_rate=0.003,
        warm_start_inner_opt_from_outer=False,
        inner_optim=InnerOptimConfig(kind=kind, lr=0.03, beta1=0.5, beta2=0.5),
    )
    system = MAMLSystem(cfg, model=tiny_linear_model())
    state = system.init_train_state()
    losses = []
    for i in range(40):
        batch = _as_jnp(learnable_synthetic_batch(2, 3, 2, 2, TINY_SHAPE, seed=i % 4))
        state, out = system.train_step(state, batch, epoch=0)
        losses.append(float(out.loss))
    assert np.all(np.isfinite(losses)), (kind, losses)
    ev = system.eval_step(state, _as_jnp(learnable_synthetic_batch(2, 3, 2, 2, TINY_SHAPE, seed=1)))
    assert float(ev.accuracy) > 0.45, (kind, float(ev.accuracy))  # chance = 1/3
    # learnable lr hparams moved and respect the projection floor
    lr = float(np.asarray(state.inner_hparams["lr"]["w"]))
    assert lr >= 1e-4 - 1e-8
    assert lr != 0.03


def test_learned_lrs_change_and_stay_projected():
    cfg = tiny_config()
    system = MAMLSystem(cfg, model=tiny_linear_model())
    state = system.init_train_state()
    lr0 = np.asarray(state.inner_hparams["lr"]["w"])
    for i in range(5):
        state, _ = system.train_step(state, _as_jnp(learnable_synthetic_batch(2, 3, 2, 2, TINY_SHAPE, seed=i)))
    lr1 = np.asarray(state.inner_hparams["lr"]["w"])
    assert lr1 != lr0
    assert lr1 >= 1e-4 - 1e-8


def test_per_step_lslr_restores_upstream_semantics():
    """lslr_per_step=True: one learnable lr per (tensor, step) — upstream
    MAML++ LSLR, which the reference fork regressed to per-tensor
    (SURVEY §2.2). Checks shape, equivalence-at-init with the fork mode,
    per-step divergence under training, and eval-horizon clamping."""
    cfg = tiny_config(lslr_per_step=True, meta_learning_rate=0.01,
                      number_of_evaluation_steps_per_iter=4)  # > train steps
    system = MAMLSystem(cfg, model=tiny_linear_model())
    state = system.init_train_state()
    K = cfg.number_of_training_steps_per_iter
    assert np.asarray(state.inner_hparams["lr"]["w"]).shape == (K,)

    # at init, per-step mode computes exactly what the fork mode computes
    cfg_fork = tiny_config(meta_learning_rate=0.01)
    system_fork = MAMLSystem(cfg_fork, model=tiny_linear_model())
    state_fork = system_fork.init_train_state()
    batch = _as_jnp(learnable_synthetic_batch(2, 3, 2, 2, TINY_SHAPE, seed=0))
    state, out_ps = system.train_step(state, batch, epoch=0)  # state donated
    _, out_fork = system_fork.train_step(state_fork, batch, epoch=0)
    np.testing.assert_allclose(float(out_ps.loss), float(out_fork.loss), rtol=1e-5)

    # training moves the per-step lrs apart (they get distinct gradients)
    for i in range(8):
        state, _ = system.train_step(
            state, _as_jnp(learnable_synthetic_batch(2, 3, 2, 2, TINY_SHAPE, seed=i)), epoch=0
        )
    lr = np.asarray(state.inner_hparams["lr"]["w"])
    assert lr.shape == (K,)
    assert np.ptp(lr) > 0, lr  # steps diverged from each other
    assert (lr >= 1e-4 - 1e-8).all()  # projection applies elementwise
    # eval with a longer horizon than trained clamps to the last step's lr
    ev = system.eval_step(state, batch)
    assert np.isfinite(float(ev.loss))


def test_per_step_lslr_with_rprop_inner_opt():
    """Regression (advisor r1): rprop's init_state derives step_size from the
    lr hparam; with lslr_per_step the lr leaves are (K,)-shaped and init must
    see one step's values, not the K-vector (broadcast crash otherwise)."""
    from howtotrainyourmamlpytorch_tpu.config import InnerOptimConfig

    cfg = tiny_config(
        lslr_per_step=True, inner_optim=InnerOptimConfig(kind="rprop", lr=0.1)
    )
    system = MAMLSystem(cfg, model=tiny_linear_model())
    state = system.init_train_state()
    batch = _as_jnp(tiny_batch())
    state, out = system.train_step(state, batch, epoch=0)
    assert np.isfinite(float(out.loss))
    K = cfg.number_of_training_steps_per_iter
    assert np.asarray(state.inner_hparams["lr"]["w"]).shape == (K,)


def test_vgg_meta_step_runs():
    """End-to-end meta-step through a real conv+BN backbone (small variant)."""
    cfg = tiny_config(num_classes_per_set=3)
    model = build_vgg((8, 8, 1), 3, num_stages=2, cnn_num_filters=8)
    system = MAMLSystem(cfg, model=model)
    state = system.init_train_state()
    state, out = system.train_step(state, _as_jnp(tiny_batch()))
    assert np.isfinite(float(out.loss))
    assert 0.0 <= float(out.accuracy) <= 1.0
    assert int(state.step) == 1


def test_unrolled_scan_matches_rolled():
    """``unroll_inner_steps`` is a pure scheduling knob: the unrolled and
    rolled inner-step scans must produce identical losses, params, and learned
    hyperparameters (both MSL and final-step-only paths)."""
    for msl in (True, False):
        outs = {}
        for unroll in (True, False):
            cfg = tiny_config(
                unroll_inner_steps=unroll, use_multi_step_loss_optimization=msl
            )
            system = MAMLSystem(cfg, model=tiny_linear_model())
            state = system.init_train_state()
            batch = _as_jnp(tiny_batch())
            state, out = system.train_step(state, batch, epoch=0)
            outs[unroll] = (state, out)
        s_u, o_u = outs[True]
        s_r, o_r = outs[False]
        np.testing.assert_allclose(o_u.loss, o_r.loss, rtol=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7),
            (s_u.params, s_u.inner_hparams),
            (s_r.params, s_r.inner_hparams),
        )


def test_bfloat16_compute_train_step_runs_and_learns():
    """Mixed-precision path (bf16 compute, fp32 master params): the flagship
    bench recipe. Loss must stay finite and decrease on learnable synthetic
    tasks; params remain float32."""
    cfg = tiny_config(compute_dtype="bfloat16")
    system = MAMLSystem(cfg, model=tiny_linear_model())
    state = system.init_train_state()
    losses = []
    for i in range(20):
        batch = _as_jnp(learnable_synthetic_batch(2, 3, 2, 2, TINY_SHAPE, seed=i))
        state, out = system.train_step(state, batch, epoch=0)
        losses.append(float(out.loss))
    assert np.isfinite(losses).all()
    assert np.mean(losses[-5:]) < np.mean(losses[:5])
    assert all(leaf.dtype == jnp.float32 for leaf in jax.tree.leaves(state.params))


def test_deep_inner_loop_rolled_remat_is_tractable():
    """SURVEY §5.7 long-context analogue: the memory wall in the reference is
    the B x K unrolled second-order torch graph (it ships K=5). Here the
    rolled ``lax.scan`` + per-step ``jax.checkpoint`` rollout keeps live
    memory O(1) in inner depth, so a 10x deeper inner loop must simply work:
    50 second-order inner steps compile, run, stay finite, and still deliver
    meta-gradient signal to the learnable inner lrs."""
    K = 50
    cfg = tiny_config(
        number_of_training_steps_per_iter=K,
        number_of_evaluation_steps_per_iter=K,
        unroll_inner_steps=False,
        remat_inner_steps=True,
        # small inner lr: 50 SGD steps at the default 0.1 can overshoot on
        # the tiny synthetic task and would test divergence, not depth
        inner_optim=InnerOptimConfig(kind="sgd", lr=0.01),
    )
    system = MAMLSystem(cfg, model=tiny_linear_model())
    state = system.init_train_state()
    lrs_before = jax.tree.map(np.asarray, state.inner_hparams)
    batch = _as_jnp(tiny_batch())
    state, out = system.train_step(state, batch, epoch=0)
    assert np.isfinite(float(out.loss))
    assert out.loss_importance_vector.shape == (K,)
    # the learnable per-tensor lrs moved: the second-order meta-gradient
    # reached through all 50 scanned steps
    moved = jax.tree.map(
        lambda a, b: float(np.max(np.abs(np.asarray(a) - b))),
        state.inner_hparams,
        lrs_before,
    )
    assert max(jax.tree.leaves(moved)) > 0
