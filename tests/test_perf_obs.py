"""Performance-observability layer (ISSUE 7): cost-model fallback chain,
compile ledger cold/warm semantics, HBM watermark schema, loadgen schedule
determinism + SLO report schema, and the off-switch zero-file contract
extended to the new providers."""

import dataclasses
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from PIL import Image

from howtotrainyourmamlpytorch_tpu.config import (
    Config,
    DatasetConfig,
    ObservabilityConfig,
    ParallelConfig,
    ServingConfig,
)
from howtotrainyourmamlpytorch_tpu.core import MAMLSystem
from howtotrainyourmamlpytorch_tpu.experiment import ExperimentRunner
from howtotrainyourmamlpytorch_tpu.models import build_vgg
from howtotrainyourmamlpytorch_tpu.observability import costs
from howtotrainyourmamlpytorch_tpu.observability.compile_ledger import (
    CompileLedger,
)
from howtotrainyourmamlpytorch_tpu.observability.memory import MemoryWatermarks
from howtotrainyourmamlpytorch_tpu.observability import slo

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# ---------------------------------------------------------------------------
# cost model (observability/costs.py)
# ---------------------------------------------------------------------------


def test_jit_cost_toy_program_nonnull_flops_on_cpu():
    """The acceptance path bench.py rides: the HLO cost model prices a jit
    on the CPU backend — non-null flops, no exception."""

    @jax.jit
    def f(x):
        return (x @ x).sum()

    x = jnp.ones((32, 32), jnp.float32)
    cost = costs.jit_cost(f, x)
    assert cost["error"] is None
    assert cost["flops"] and cost["flops"] > 0
    assert cost["source"] in ("lowered", "compiled", "compiled_from_lowered")


def test_program_cost_degrades_to_null_with_reason_never_raises():
    """The BENCH_r02 crash class: cost_analysis that raises from inside jax
    (even on attribute access), returns None, or returns junk must yield
    flops=None with the reasons joined — never an exception."""

    class RaisingProperty:
        @property
        def cost_analysis(self):
            # the observed in-the-wild crash escapes as a non-Attribute
            # error from inside jax's own property machinery
            raise RuntimeError("'NoneType' object has no attribute 'get'")

        def compile(self):
            raise RuntimeError("backend gone")

    cost = costs.program_cost(RaisingProperty())
    assert cost["flops"] is None
    assert "NoneType" in cost["error"] and "backend gone" in cost["error"]

    class ReturnsNone:
        def cost_analysis(self):
            return None

        def compile(self):
            return self

    assert costs.program_cost(ReturnsNone())["flops"] is None

    cost = costs.program_cost(None)
    assert cost["flops"] is None and "no lowered" in cost["error"]

    # no .lower() on the callable: jit_cost degrades the same way
    assert costs.jit_cost(lambda x: x, 1)["flops"] is None


def test_program_cost_normalizes_plugin_return_shapes():
    """List-wrapped per-device dicts and the 'bytes accessed' (with space)
    key both normalize; a compiled-only object works without .compile()."""

    class ListCompiled:  # no .compile attr => treated as compiled
        def cost_analysis(self):
            return [{"flops": 5.0, "bytes accessed": 7.0}]

    cost = costs.program_cost(ListCompiled())
    assert cost["flops"] == 5.0 and cost["bytes_accessed"] == 7.0
    assert cost["source"] == "compiled"


def test_mfu_table_lookup_and_reasons():
    value, reason = costs.mfu(1e12, 2.0, "TPU v5e")
    assert reason is None
    assert value == pytest.approx(2e12 / 197e12, abs=5e-6)  # rounded to 5 dp
    # explicit measured peak wins over the table
    value, _ = costs.mfu(1e12, 2.0, "TPU v5e", peak=4e12)
    assert value == pytest.approx(0.5)
    value, reason = costs.mfu(1e12, 2.0, "cpu")
    assert value is None and "no peak-FLOPs table entry" in reason
    value, reason = costs.mfu(None, 2.0, "TPU v4")
    assert value is None and "flops_per_step" in reason
    value, reason = costs.mfu(1e12, 0.0, "TPU v4")
    assert value is None and "steps_per_sec" in reason


# ---------------------------------------------------------------------------
# compile ledger (observability/compile_ledger.py)
# ---------------------------------------------------------------------------


def test_compile_ledger_cold_warm_and_new_shape(tmp_path):
    ledger = CompileLedger(logs_dir=str(tmp_path))
    calls = {"n": 0}

    def f(x):
        calls["n"] += 1
        return x * 2 + 1

    wrapped = ledger.wrap_build(("toy", 1), jax.jit(f))
    a = jnp.ones((4,), jnp.float32)
    out1 = wrapped(a)
    out2 = wrapped(a + 1)  # same signature: no new entry, compiled reused
    np.testing.assert_allclose(np.asarray(out2), np.full((4,), 5.0))
    assert ledger.summary()["entries"] == 1
    wrapped(jnp.ones((8,), jnp.float32))  # new shape = a recompile = an entry
    summary = ledger.summary()
    assert summary["entries"] == 2
    assert summary["by_program"]["toy/1"]["builds"] == 2
    ledger.close()

    entries = [
        json.loads(line)
        for line in open(os.path.join(tmp_path, "compile_ledger.jsonl"))
    ]
    assert len(entries) == 2
    for e in entries:
        assert e["program"] == "toy/1"
        assert e["lower_s"] >= 0 and e["compile_s"] >= 0
        assert e["total_s"] == pytest.approx(e["lower_s"] + e["compile_s"], abs=1e-3)
        assert isinstance(e["cold"], bool)
        assert "persistent_cache" in e and "flops" in e
        # the conftest cache dir is live, so hit accounting must be present
        assert e["persistent_cache"] is None or "hit" in e["persistent_cache"]
    # the AOT split priced the program off the lowered/compiled pair
    assert any(e["flops"] for e in entries)
    assert np.asarray(out1).tolist() == [3.0] * 4


def test_compile_ledger_broken_jit_falls_back_and_records_error(tmp_path):
    ledger = CompileLedger(logs_dir=str(tmp_path))

    class NoLower:
        def __call__(self, x):
            return x + 1

    wrapped = ledger.wrap_build("broken", NoLower())
    assert wrapped(1) == 2
    # same signature: pinned to the plain callable, no second error entry
    assert wrapped(1) == 2
    summary = ledger.summary()
    assert summary["errors"] == 1
    assert summary["by_program"]["broken"]["errors"] == 1
    ledger.close()


def test_compile_ledger_observer_and_recompile_guard_seam():
    from howtotrainyourmamlpytorch_tpu.utils.strictmode import RecompileGuard

    ledger = CompileLedger()  # collector-only (the serving-frontend shape)
    seen = []
    ledger.on_entry = seen.append
    guard = RecompileGuard(budget=4, name="probe")
    guard.ledger = ledger
    wrapped = guard.wrap(jax.jit(lambda x: x * x))
    wrapped(jnp.ones((3,)))
    wrapped(jnp.ones((3,)))  # warm: no new signature, no entry
    wrapped(jnp.ones((5,)))
    summary = ledger.summary()
    assert summary["entries"] == 2
    prog = summary["by_program"]["probe/<lambda>"]
    assert prog["builds"] == 2 and prog["total_s"] > 0
    assert len(seen) == 2 and all(e["total_s"] > 0 for e in seen)
    # a broken observer must never break recording
    ledger.on_entry = lambda e: 1 / 0
    wrapped(jnp.ones((7,)))
    assert ledger.summary()["entries"] == 3


# ---------------------------------------------------------------------------
# HBM watermarks (observability/memory.py)
# ---------------------------------------------------------------------------


def test_memory_snapshot_schema_on_this_backend():
    """Whatever this backend supports, every row is explicit about it:
    available rows carry the watermark fields, unavailable rows a reason."""
    snap = MemoryWatermarks().snapshot()
    assert set(snap) == {
        "devices",
        "available_devices",
        "peak_bytes_in_use_max",
        "headroom_frac_min",
    }
    assert len(snap["devices"]) >= 1
    for row in snap["devices"]:
        assert "device" in row and "kind" in row
        if row["available"]:
            assert {"bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                    "headroom_frac"} <= set(row)
        else:
            assert row["reason"]


def test_memory_headroom_warning_latches_per_device():
    rows = [
        {"device": 0, "kind": "fake", "available": True, "bytes_in_use": 98,
         "peak_bytes_in_use": 99, "bytes_limit": 100, "headroom_frac": 0.02},
        {"device": 1, "kind": "fake", "available": True, "bytes_in_use": 10,
         "peak_bytes_in_use": 20, "bytes_limit": 100, "headroom_frac": 0.9},
    ]
    mw = MemoryWatermarks(warn_headroom_frac=0.05, stats_fn=lambda: rows)

    class Log:
        def __init__(self):
            self.records = []

        def append(self, r):
            self.records.append(r)

    log = Log()
    fired = mw.maybe_warn(log)
    assert len(fired) == 1 and fired[0]["device"] == 0
    assert fired[0]["event"] == "hbm_headroom_low"
    assert log.records == fired
    # latched: the same device hovering below threshold fires once
    assert mw.maybe_warn(log) == []
    snap = mw.snapshot()
    assert snap["peak_bytes_in_use_max"] == 99
    assert snap["headroom_frac_min"] == 0.02


# ---------------------------------------------------------------------------
# loadgen schedule + SLO report (observability/slo.py, scripts/loadgen.py)
# ---------------------------------------------------------------------------


def test_schedule_same_seed_bit_identical_different_seed_not():
    kw = dict(duration_s=30.0, stairs_rps=[2.0, 4.0, 8.0], adapt_frac=0.3)
    a = slo.generate_schedule(7, **kw)
    b = slo.generate_schedule(7, **kw)
    assert a == b  # frozen dataclasses: full-field bit-identity
    assert slo.schedule_digest(a) == slo.schedule_digest(b)
    c = slo.generate_schedule(8, **kw)
    assert a != c
    # stairs partition the duration; times are monotonic within the run
    times = [r.t for r in a]
    assert times == sorted(times)
    per_stair = 30.0 / 3
    for r in a:
        assert r.stair * per_stair <= r.t < (r.stair + 1) * per_stair
        assert r.kind in ("adapt", "predict")
        assert r.n_query in (5, 15, 40)


def test_loadgen_cli_print_schedule_bit_identical():
    cmd = [
        sys.executable, os.path.join(REPO_ROOT, "scripts", "loadgen.py"),
        "--seed", "0", "--duration-s", "5", "--print-schedule",
    ]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    runs = [
        subprocess.run(cmd, capture_output=True, text=True, timeout=120, env=env)
        for _ in range(2)
    ]
    for proc in runs:
        assert proc.returncode == 0, proc.stderr
        assert len(proc.stdout.strip().splitlines()) == 1  # exactly one line
    assert runs[0].stdout == runs[1].stdout
    payload = json.loads(runs[0].stdout)
    assert payload["digest"]["n"] == len(payload["schedule"])


def test_loadgen_profile_transform_deterministic_and_off_by_default():
    """ISSUE 18: --profile is a DETERMINISTIC stairs transform (no RNG) —
    diurnal mirrors the staircase into a trough->peak->trough day curve,
    surge:K appends a K-fold spike of the peak + recovery; absent leaves
    the stairs (and therefore the schedule bytes) untouched; junk is a
    usage error before any backend spins up."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "t_loadgen", os.path.join(REPO_ROOT, "scripts", "loadgen.py")
    )
    lg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lg)

    assert lg._apply_profile([4.0, 8.0, 16.0], None) == [4.0, 8.0, 16.0]
    assert lg._apply_profile([4.0, 8.0, 16.0], "diurnal") == [
        4.0, 8.0, 16.0, 8.0, 4.0]
    assert lg._apply_profile([4.0], "diurnal") == [4.0]
    assert lg._apply_profile([4.0, 8.0, 16.0], "surge:3") == [
        4.0, 8.0, 16.0, 48.0, 4.0]
    for junk in ("weird", "surge:", "surge:0", "surge:-2", "SURGE:3"):
        with pytest.raises(SystemExit):
            lg._apply_profile([4.0], junk)

    # end to end over the CLI: same seed + same profile = bit-identical
    # stdout; the profile visibly reshapes the schedule vs the plain stairs
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}

    def run(extra):
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO_ROOT, "scripts", "loadgen.py"),
                "--seed", "0", "--duration-s", "5", "--print-schedule",
                *extra,
            ],
            capture_output=True, text=True, timeout=120, env=env,
        )
        assert proc.returncode == 0, proc.stderr
        return proc.stdout

    surge_a, surge_b = run(["--profile", "surge:3"]), run(["--profile", "surge:3"])
    assert surge_a == surge_b
    plain = json.loads(run([]))
    surged = json.loads(surge_a)
    assert surged["digest"] != plain["digest"]
    # default stairs [4,8,16] -> surge:3 adds two stages (spike + recovery)
    assert max(r["stair"] for r in surged["schedule"]) == 4
    assert max(r["stair"] for r in plain["schedule"]) <= 2


def test_slo_report_schema_and_sustained_headline():
    stairs = [2.0, 4.0]
    schedule = slo.generate_schedule(3, 10.0, stairs)
    rows = []
    for r in schedule:
        # stair 0 healthy, stair 1 drowning: half shed, slow p99
        if r.stair == 0:
            rows.append({"stair": 0, "kind": r.kind, "outcome": "ok",
                         "latency_ms": 10.0})
        else:
            outcome = "shed" if len(rows) % 2 else "ok"
            rows.append({"stair": 1, "kind": r.kind, "outcome": outcome,
                         "latency_ms": 900.0})
    run = {"rows": rows, "wall_s": 10.0,
           "breaker_trips": 1, "breaker": {"opens": 1}}
    report = slo.slo_report(
        schedule, run, stairs_rps=stairs, duration_s=10.0, seed=3,
        slo_p99_ms=500.0, max_shed_rate=0.05, metric_suffix="_5w1s",
    )
    assert report["metric"] == "serving_slo_sustained_rps_5w1s"
    assert report["unit"] == "req/s within SLO"
    assert report["vs_baseline"] is None
    assert report["value"] == 2.0  # only the healthy stair met the SLO
    assert report["breaker_trips"] == 1
    assert len(report["stairs"]) == 2
    s0, s1 = report["stairs"]
    assert s0["slo_met"] and not s1["slo_met"]
    assert s0["p99_ms"] == 10.0 and s0["shed_rate"] == 0.0
    assert s1["shed"] > 0 and s1["shed_rate"] > 0.05
    assert report["requests"] == len(schedule)
    assert report["ok"] + report["shed"] + report["deadline"] + report["error"] == len(schedule)
    json.dumps(report)  # one-line contract: everything serializes


def test_run_load_against_tiny_frontend():
    """The in-process e2e: a real ServingFrontend under a short open-loop
    schedule — outcomes for every scheduled request, warmup excluded,
    breaker delta reported."""
    from howtotrainyourmamlpytorch_tpu.data.synthetic import synthetic_batch
    from howtotrainyourmamlpytorch_tpu.serving import AdaptationEngine
    from howtotrainyourmamlpytorch_tpu.serving.server import ServingFrontend

    img = (28, 28, 1)
    cfg = Config(
        num_classes_per_set=5,
        num_samples_per_class=1,
        num_target_samples=2,
        serving=ServingConfig(support_buckets=[5], query_buckets=[5, 10]),
    )
    system = MAMLSystem(
        cfg, model=build_vgg(img, 5, num_stages=2, cnn_num_filters=4)
    )
    frontend = ServingFrontend(AdaptationEngine(system, system.init_train_state()))

    def make_support(seed):
        b = synthetic_batch(1, 5, 1, 2, img, seed & 0x7FFFFFFF)
        return b["x_support"][0], b["y_support"][0]

    def make_query(seed, n_query):
        b = synthetic_batch(1, 5, 1, 2, img, seed & 0x7FFFFFFF)
        return b["x_target"][0].reshape((-1,) + img)[:n_query]

    schedule = slo.generate_schedule(
        0, 1.5, [4.0], adapt_frac=0.3, query_sizes=(5, 10), query_weights=(0.8, 0.2)
    )
    try:
        run = slo.run_load(frontend, schedule, make_support, make_query)
    finally:
        frontend.close()
    assert len(run["rows"]) == len(schedule)
    assert all(r["outcome"] in ("ok", "shed", "deadline", "error") for r in run["rows"])
    assert sum(1 for r in run["rows"] if r["outcome"] == "ok") >= 1
    assert run["breaker_trips"] == 0
    report = slo.slo_report(
        schedule, run, stairs_rps=[4.0], duration_s=1.5, seed=0,
        slo_p99_ms=30_000.0, max_shed_rate=1.0,
    )
    assert report["requests"] == len(schedule)
    # the serving programs' compiles landed in the frontend's ledger, and
    # warmup compiled EVERY query bucket the schedule hits (a cold bucket
    # compile inside a measured stair would poison that stair's p99)
    compiled = frontend.engine.compile_counts()
    assert compiled["compile_ledger"]["entries"] >= 2
    warmed = {
        name.split("/")[1]
        for name in compiled["compile_ledger"]["by_program"]
        if name.startswith("serve_predict/")
    }
    for n_query in {r.n_query for r in schedule}:
        bucket = min(b for b in (5, 10) if b >= n_query)
        assert str(bucket) in warmed, (n_query, warmed)


def test_run_load_empty_schedule_raises():
    with pytest.raises(ValueError, match="schedule is empty"):
        slo.run_load(None, [], lambda s: None, lambda s, n: None)


def test_run_load_latency_counts_queue_wait_from_scheduled_arrival():
    """The coordinated-omission guard: with one worker and a slow backend,
    the second request's latency must include the time it spent queued
    behind the first — measured from its scheduled arrival, not worker
    pickup."""
    import time

    class SlowFrontend:
        class _Breaker:
            def snapshot(self):
                return {"opens": 0}

        breaker = _Breaker()

        def adapt(self, x, y):
            return {"adaptation_id": "warm"}

        def predict(self, aid, xq):
            time.sleep(0.25)
            return None

    schedule = [
        slo.Request(t=0.0, kind="predict", episode_seed=0, n_query=5, stair=0),
        slo.Request(t=0.01, kind="predict", episode_seed=1, n_query=5, stair=0),
    ]
    run = slo.run_load(
        SlowFrontend(), schedule, lambda s: (None, None), lambda s, n: None,
        warm_adaptations=1, max_workers=1,
    )
    lat = sorted(r["latency_ms"] for r in run["rows"])
    assert lat[0] >= 200  # the slow predict itself
    assert lat[1] >= 400  # ~250ms queued behind request 1 + its own 250ms


def test_live_mfu_zero_step_interval_reports_zero_not_lifetime(tmp_path):
    """A snapshot over an interval with zero settled steps must say mfu=0.0
    (no training ran), never fall back to the healthy lifetime average."""
    from howtotrainyourmamlpytorch_tpu.observability.telemetry import TelemetryHub

    t = {"now": 0.0}
    hub = TelemetryHub(
        enabled=True, logs_dir=str(tmp_path), clock=lambda: t["now"],
        export_chrome_trace=False,
    )
    hub.registry.set_gauge("flops_per_step", 1e9)
    hub.registry.set_gauge("peak_flops_per_sec", 1e12)
    for _ in range(10):
        hub.step_completed(episodes=1)
    t["now"] = 1.0
    busy = hub.snapshot("step")
    assert busy["mfu"] == pytest.approx(10 * 1e9 / 1e12)
    t["now"] = 2.0  # a whole interval of eval/checkpoint: zero steps
    idle = hub.snapshot("epoch")
    assert idle["interval_steps_per_s"] == 0.0
    assert idle["mfu"] == 0.0
    hub.close()


def test_live_mfu_counts_meta_steps_under_multi_dispatch(tmp_path):
    """With train_steps_per_dispatch=K the runner settles ONE dispatch per
    K meta-steps; interval_steps_per_s (and the MFU it feeds, against the
    per-meta-step flops gauge) must count meta-steps, not dispatches —
    the review-found factor-of-K MFU understatement."""
    from howtotrainyourmamlpytorch_tpu.observability.telemetry import TelemetryHub

    t = {"now": 0.0}
    hub = TelemetryHub(
        enabled=True, logs_dir=str(tmp_path), clock=lambda: t["now"],
        export_chrome_trace=False,
    )
    hub.registry.set_gauge("flops_per_step", 1e9)  # per META-step (÷K)
    hub.registry.set_gauge("peak_flops_per_sec", 1e12)
    for _ in range(5):  # 5 dispatches x K=4 = 20 meta-steps over 1s
        hub.step_completed(episodes=8, steps=4)
    t["now"] = 1.0
    rec = hub.snapshot("epoch")
    assert rec["steps"] == 20
    assert rec["interval_steps_per_s"] == pytest.approx(20.0)
    assert rec["mfu"] == pytest.approx(20 * 1e9 / 1e12)
    hub.close()
    # the K-jump cadence: a K-step jump over a multiple of
    # snapshot_every_steps still fires the step snapshot (crossing check,
    # not modulo — K=2 never lands exactly on a multiple of 3)
    cadence = TelemetryHub(
        enabled=True, snapshot_every_steps=3, export_chrome_trace=False,
    )
    fired = []
    cadence.snapshot = lambda kind, **kw: fired.append(kind)  # count only
    for _ in range(5):  # _steps: 2, 4, 6, 8, 10 — crossings at 4 and 8
        cadence.step_completed(episodes=1, steps=2)
    assert fired == ["step", "step"]


def test_run_load_unresolved_request_costs_grace_not_report():
    """A request the backend never answers (hung flush, deadlocked
    frontend — what a load test exists to surface) must cost at most
    result_grace_s and an `unresolved` count, never the report itself."""
    import threading

    hang = threading.Event()

    class WedgedFrontend:
        class _Breaker:
            def snapshot(self):
                return {"opens": 0}

        breaker = _Breaker()
        predicts = 0

        def adapt(self, x, y):
            return {"adaptation_id": "warm"}

        def predict(self, aid, xq):
            self.predicts += 1
            if self.predicts == 1:
                return None  # the warmup predict passes; measured traffic wedges
            hang.wait(timeout=30.0)
            return None

    schedule = [
        slo.Request(t=0.0, kind="predict", episode_seed=0, n_query=5, stair=0),
    ]
    try:
        run = slo.run_load(
            WedgedFrontend(), schedule, lambda s: (None, None),
            lambda s, n: None, warm_adaptations=1, max_workers=1,
            result_grace_s=0.5,
        )
    finally:
        hang.set()  # release the worker thread either way
    assert run["unresolved"] == 1 and run["unresolved_by_stair"] == {0: 1}
    report = slo.slo_report(
        schedule, run, stairs_rps=[1.0], duration_s=1.0, seed=0,
        slo_p99_ms=1000.0, max_shed_rate=0.5,
    )
    assert report["unresolved"] == 1 and report["requests"] == 1
    assert report["stairs"][0]["unresolved"] == 1
    assert not report["stairs"][0]["slo_met"] and report["value"] is None


def test_warmup_delegates_to_engine_prewarm():
    """Pre-clock warmup must compile the full planned (bucket x
    batch-bucket) grid — a cold serve_predict/(bucket, b>1) compile inside
    a measured stair would poison that stair's p99. The grid logic lives in
    ``AdaptationEngine.prewarm()`` (compile/aot.py) now; loadgen's warmup
    DELEGATES instead of duplicating it."""

    class _Engine:
        def __init__(self):
            self.prewarm_calls = 0

        def prewarm(self, **kwargs):
            self.prewarm_calls += 1
            return {"programs": 8, "seconds": 0.5, "cache_hits": 3, "errors": 0}

    class _Frontend:
        engine = None

    frontend = _Frontend()
    frontend.engine = _Engine()
    schedule = [
        slo.Request(t=0.0, kind="predict", episode_seed=0, n_query=5, stair=0),
    ]
    logged = []
    slo._warm_batch_buckets(
        frontend, schedule, lambda s: (None, None), lambda s, n: n, logged.append
    )
    assert frontend.engine.prewarm_calls == 1
    assert any("prewarmed 8 serving programs" in m for m in logged)
    # a frontend without an engine (test double) degrades to a logged skip
    logged = []
    slo._warm_batch_buckets(
        object(), schedule, lambda s: (None, None), lambda s, n: n, logged.append
    )
    assert any("skipped" in m for m in logged)
    # ... as does an engine-shaped double without a prewarm method
    logged = []
    frontend.engine = object()
    slo._warm_batch_buckets(
        frontend, schedule, lambda s: (None, None), lambda s, n: n, logged.append
    )
    assert any("skipped" in m for m in logged)
    # a prewarm failure is contained: logged, never raised into the test
    class _Broken:
        def prewarm(self, **kwargs):
            raise RuntimeError("device on fire")

    logged = []
    frontend.engine = _Broken()
    slo._warm_batch_buckets(
        frontend, schedule, lambda s: (None, None), lambda s, n: n, logged.append
    )
    assert any("warmup failed" in m for m in logged)


# ---------------------------------------------------------------------------
# runner e2e: ledger file, MFU gauges, obs_report sections, off-switch
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def toy_dataset(tmp_path_factory):
    root = tmp_path_factory.mktemp("data") / "omniglot_toy"
    rng = np.random.RandomState(0)
    for a in range(4):
        for c in range(5):
            d = root / f"alpha{a}" / f"char{c}"
            d.mkdir(parents=True)
            base = (rng.rand(28, 28) > 0.5).astype(np.uint8) * 255
            for i in range(6):
                noisy = base ^ (rng.rand(28, 28) > 0.95).astype(np.uint8) * 255
                Image.fromarray(noisy, mode="L").convert("1").save(d / f"{i}.png")
    return str(root)


def _toy_config(toy_dataset, tmp_path, name, **overrides):
    base = dict(
        dataset=DatasetConfig(name="omniglot_toy", path=toy_dataset),
        num_classes_per_set=3,
        num_samples_per_class=2,
        num_target_samples=2,
        batch_size=2,
        parallel=ParallelConfig(dp=2),
        total_epochs=1,
        total_iter_per_epoch=3,
        num_evaluation_tasks=4,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        experiment_root=str(tmp_path),
        experiment_name=name,
        load_into_memory=True,
        num_dataprovider_workers=2,
        train_val_test_split=(0.6, 0.2, 0.2),
        conv_via_patches=True,  # the dp-sharded native-conv GSPMD crash dodge
    )
    base.update(overrides)
    return Config(**base)


def _toy_system(cfg):
    return MAMLSystem(
        cfg,
        model=build_vgg(
            (28, 28, 1), cfg.num_classes_per_set, num_stages=2,
            cnn_num_filters=4, conv_via_patches=True,
        ),
    )


def test_runner_compile_ledger_and_mfu_fields_e2e(toy_dataset, tmp_path):
    cfg = _toy_config(toy_dataset, tmp_path, "perf_obs_on")
    runner = ExperimentRunner(cfg, system=_toy_system(cfg))
    runner.run_experiment()
    logs = os.path.join(runner.run_dir, "logs")

    # compile_ledger.jsonl: the train + eval programs, priced and timed
    entries = [
        json.loads(line)
        for line in open(os.path.join(logs, "compile_ledger.jsonl"))
    ]
    programs = {e["program"] for e in entries}
    assert any(p.startswith("train/") for p in programs), programs
    assert "eval" in programs
    for e in entries:
        assert e["total_s"] is not None and e["total_s"] >= 0
        assert "session" in e
    train_entry = next(e for e in entries if e["program"].startswith("train/"))
    assert train_entry["flops"] and train_entry["flops"] > 0

    # telemetry: the cost gauges + the live-mfu contract (null on CPU with
    # the reason gauge set, never a crash)
    records = [
        json.loads(line) for line in open(os.path.join(logs, "telemetry.jsonl"))
    ]
    last = records[-1]
    assert last["gauges"]["flops_per_step"] == train_entry["flops"]
    assert "mfu_unavailable_reason" in last["gauges"]
    assert "mfu" in last and last["mfu"] is None
    assert any(r.get("interval_steps_per_s") is not None for r in records)
    assert "memory" in last["providers"] and "compile_ledger" in last["providers"]
    assert last["providers"]["compile_ledger"]["entries"] == len(entries)

    # obs_report: compile-tax section + the new oneline fields
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts", "obs_report.py"),
         runner.run_dir, "--json"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stdout)
    tax = report["compile_tax"]
    assert tax["entries"] == len(entries)
    assert tax["total_s"] == pytest.approx(
        sum(e["total_s"] for e in entries), abs=0.05
    )
    assert set(tax["by_program"]) == programs
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts", "obs_report.py"),
         runner.run_dir, "--oneline"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    line = json.loads(proc.stdout)
    assert line["compile_tax_s"] == tax["total_s"]
    # mfu is null on CPU => dropped from the oneline rather than lying
    assert "mfu" not in line

    # human render carries the compile-tax table
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts", "obs_report.py"),
         runner.run_dir],
        capture_output=True, text=True, timeout=120,
    )
    assert "compile tax" in proc.stdout


def test_off_switch_zero_file_extends_to_perf_providers(toy_dataset, tmp_path):
    """PR 5's inertness contract extended: with observability disabled the
    new providers leave no compile_ledger.jsonl (and no telemetry/trace),
    and the system's program builds stay plain jit objects."""
    cfg = _toy_config(
        toy_dataset, tmp_path, "perf_obs_off",
        observability=ObservabilityConfig(enabled=False),
    )
    system = _toy_system(cfg)
    runner = ExperimentRunner(cfg, system=system)
    assert runner._compile_ledger is None and runner._memory is None
    assert system.compile_ledger is None
    result = runner.run_experiment()
    assert "test_accuracy_mean" in result
    logs = os.path.join(runner.run_dir, "logs")
    for name in ("compile_ledger.jsonl", "telemetry.jsonl", "trace.json"):
        assert not os.path.exists(os.path.join(logs, name)), name
    # the program cache holds plain jitted callables, not ledger wrappers
    fn = system._compiled_train_step(True, True)
    assert type(fn).__name__ != "LedgerWrapped"
