"""Dataset registry constants (reference ``data.py:123-132``)."""

from dataclasses import dataclass
from typing import Tuple


@dataclass(frozen=True)
class DatasetSpec:
    # which path components name the class: "<grandparent>/<parent>" of each
    # image file (reference data.py:124,128,370-380)
    indexes_of_folders_indicating_class: Tuple[int, int]
    train_val_test_split: Tuple[float, float, float]
    image_height: int
    image_width: int
    image_channels: int
    # per-episode per-class rotation augmentation (omniglot-only in reference:
    # data.py:90-93 vs 96-104)
    rotation_augmentation: bool
    # normalization applied after load (imagenet: /255 at load + ImageNet
    # mean/std in the transform, data.py:396-399,96-104)
    normalize_mean: Tuple[float, ...] = ()
    normalize_std: Tuple[float, ...] = ()

    @property
    def image_shape(self):
        return (self.image_height, self.image_width, self.image_channels)


def is_pkl_variant(dataset_name: str) -> bool:
    """Single predicate for the pkl-packed dataset naming (reference
    utils/dataset_tools.py:37 keys on the name containing 'pkl'; we key on the
    suffix so a name merely containing 'pkl' isn't misclassified — shared by
    spec lookup and integrity check so they can never disagree)."""
    return dataset_name.endswith("pkl")


def get_dataset_spec(dataset_name: str) -> DatasetSpec:
    if is_pkl_variant(dataset_name):
        # the pkl-packed mini-imagenet variant is integrity-checkable
        # (check_dataset_integrity counts its 3 pickles, matching reference
        # utils/dataset_tools.py:37-40) but — exactly as in the reference
        # snapshot, whose data.py only walks image folders — not loadable.
        # Fail here, at dataset construction, with a clear remedy.
        raise ValueError(
            f"dataset {dataset_name!r}: the pkl-packed variant cannot be "
            "loaded (no pickle episode reader, matching the reference's data "
            "pipeline); unpack it to the image-folder layout instead"
        )
    if "omniglot" in dataset_name:
        return DatasetSpec(
            indexes_of_folders_indicating_class=(-3, -2),
            train_val_test_split=(0.70918052988, 0.03080714725, 0.2606284658),
            image_height=28,
            image_width=28,
            image_channels=1,
            rotation_augmentation=True,
        )
    if "imagenet" in dataset_name:
        return DatasetSpec(
            indexes_of_folders_indicating_class=(-3, -2),
            train_val_test_split=(0.64, 0.16, 0.20),
            image_height=84,
            image_width=84,
            image_channels=3,
            rotation_augmentation=False,
            normalize_mean=(0.485, 0.456, 0.406),
            normalize_std=(0.229, 0.224, 0.225),
        )
    raise ValueError(f"unknown dataset {dataset_name!r}")
