"""bench.py emission guarantees (driver contract: exactly ONE JSON line on
stdout, whatever the tunnel does).

The real failure mode these pin: the axon tunnel wedges mid-run — a device
call that never returns and is not interruptible from Python — which in
round 3/4 trapped an already-measured headline inside a hung process and
cost the round its bench artifact. The _Watchdog must salvage the partial
report from a secondary thread (stage deadline) or a SIGTERM from the
queue's outer ``timeout``. Tested in subprocesses: the salvage path ends in
``os._exit``, which must not take the test runner with it.
"""

import json
import os
import signal
import subprocess
import sys
import time

# children must import bench.py from the repo root regardless of pytest's cwd
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = {**os.environ, "BENCH_WATCHDOG_POLL_S": "0.2"}


def _only_json_line(text):
    # the driver contract is exactly ONE JSON line on stdout — a dropped
    # single-shot guard (double emission) must fail here, not be tolerated
    lines = text.strip().splitlines()
    assert len(lines) == 1, f"expected exactly one stdout line, got {lines!r}"
    return json.loads(lines[0])


def test_stage_deadline_emits_partial_and_exits_zero():
    code = subprocess.run(
        [
            sys.executable,
            "-c",
            """
import sys; sys.argv = ['bench']
import bench, time
r = {"metric": bench.METRIC, "value": 1.23, "unit": "u"}
wd = bench._Watchdog(r, enabled=True)
wd.enter("stuck-stage", 0.1)
time.sleep(60)  # simulated wedge: watchdog must os._exit(0) with the JSON
""",
        ],
        capture_output=True,
        text=True,
        timeout=60,
        cwd=REPO,
        env=ENV,
    )
    assert code.returncode == 0
    d = _only_json_line(code.stdout)
    assert d["wedged_at"] == "stuck-stage"
    assert d["value"] == 1.23


def test_stage_deadline_before_headline_fails_structured():
    code = subprocess.run(
        [
            sys.executable,
            "-c",
            """
import sys; sys.argv = ['bench']
import bench, time
r = {"metric": bench.METRIC, "value": None, "unit": "u"}
wd = bench._Watchdog(r, enabled=True)
wd.enter("compile+warmup", 0.1)
time.sleep(60)
""",
        ],
        capture_output=True,
        text=True,
        timeout=60,
        cwd=REPO,
        env=ENV,
    )
    assert code.returncode == 2
    d = _only_json_line(code.stdout)
    assert d["value"] is None
    assert "compile+warmup" in d["error"]


def test_sigterm_salvages_measured_headline():
    p = subprocess.Popen(
        [
            sys.executable,
            "-c",
            """
import sys; sys.argv = ['bench']
import bench, time, signal
r = {"metric": bench.METRIC, "value": 9.87, "unit": "u"}
wd = bench._Watchdog(r, enabled=True)
signal.signal(signal.SIGTERM, wd.on_sigterm)
wd.enter("some-stage", 9999)
print("READY", file=sys.stderr, flush=True)
time.sleep(60)
""",
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        cwd=REPO,
        env=ENV,
    )
    # wait for the handler to be installed before terming; raw os.read on
    # the fd keeps the deadline real (select + buffered readline would
    # strand READY inside the TextIOWrapper buffer and stall to the
    # deadline; a bare readline() would block past it entirely)
    import select

    fd = p.stderr.fileno()
    seen = ""
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and "READY" not in seen:
        ready, _, _ = select.select([fd], [], [], 1.0)
        if not ready:
            continue
        chunk = os.read(fd, 4096).decode(errors="replace")
        if chunk == "":  # EOF: child died early
            break
        seen += chunk
    p.send_signal(signal.SIGTERM)
    out, _ = p.communicate(timeout=30)
    assert p.returncode == 0
    d = _only_json_line(out)
    assert d["value"] == 9.87
    assert "sigterm" in d["wedged_at"]


def test_wait_for_backend_stops_after_consecutive_wedged_probes(monkeypatch):
    """ISSUE 3 satellite: K consecutive hung probes (the dead-tunnel
    signature — r05 burned ~30 min re-probing one 15 times) end the probe
    loop immediately with the distinct 'wedged' status; a probe that
    *answers* (even badly) resets the streak."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import wait_for_tpu

    # a probe that hangs forever, killed by the per-probe timeout
    monkeypatch.setenv("WAIT_FOR_TPU_PROBE", "import time; time.sleep(60)")
    logs = []
    t0 = time.monotonic()
    status = wait_for_tpu.wait_for_backend(
        deadline_s=600.0, probe_timeout_s=0.3, log=logs.append,
        max_consecutive_wedged=3, sleep=lambda s: None,
    )
    assert status == "wedged"
    assert time.monotonic() - t0 < 60  # 3 bounded probes, not the deadline
    assert any("3/3 consecutive" in m for m in logs)

    # an answering-but-failing probe is NOT the hang signature: the loop
    # keeps probing until the deadline and reports 'deadline' instead
    monkeypatch.setenv("WAIT_FOR_TPU_PROBE", "import sys; sys.exit(9)")
    status = wait_for_tpu.wait_for_backend(
        deadline_s=1.0, probe_timeout_s=5.0, log=lambda m: None,
        max_consecutive_wedged=3, sleep=lambda s: None,
    )
    assert status == "deadline"

    # rc mapping: the CLI gives each give-up mode a distinct nonzero code
    assert wait_for_tpu.RC_UP == 0
    assert wait_for_tpu.RC_DEADLINE == 64 and wait_for_tpu.RC_WEDGED == 65


def test_bench_emits_partial_json_immediately_on_wedged_tunnel():
    """bench.py gives up on a wedged tunnel after K hung probes and emits
    its one structured JSON line at once — no in-process backend contact,
    no 15x90s re-probe marathon."""
    t0 = time.monotonic()
    code = subprocess.run(
        [sys.executable, "bench.py"],
        capture_output=True,
        text=True,
        timeout=120,
        cwd=REPO,
        env={
            **os.environ,
            # probes hang; tiny per-probe timeout; 2-strike wedge cutoff
            "WAIT_FOR_TPU_PROBE": "import time; time.sleep(60)",
            "BENCH_STARTUP_TIMEOUT_S": "0.3",
            "BENCH_PROBE_INTERVAL_S": "0.05",
            "BENCH_MAX_WEDGED_PROBES": "2",
            "BENCH_STARTUP_DEADLINE_S": "600",
            "JAX_PLATFORMS": "cpu",
        },
    )
    assert code.returncode == 2
    assert time.monotonic() - t0 < 60
    d = _only_json_line(code.stdout)
    assert d["value"] is None
    assert "wedged" in d["error"]
    assert "2 consecutive" in d["error"]


def test_disabled_watchdog_never_fires():
    code = subprocess.run(
        [
            sys.executable,
            "-c",
            """
import sys; sys.argv = ['bench']
import bench, time
r = {"metric": bench.METRIC, "value": 1.0, "unit": "u"}
wd = bench._Watchdog(r, enabled=False)   # CPU mode: no tunnel to wedge
wd.enter("slow-cpu-stage", 0.1)
time.sleep(2)
wd.update(extra=1)
wd.emit_final()
""",
        ],
        capture_output=True,
        text=True,
        timeout=60,
        cwd=REPO,
        env=ENV,
    )
    assert code.returncode == 0
    d = _only_json_line(code.stdout)
    assert d["value"] == 1.0 and d["extra"] == 1 and "wedged_at" not in d
