"""Model-core tests: shapes for every backbone/dataset combo, BN semantics,
and torch-parity of the layer primitives (conv / BN / pooling math checked
against torch.nn.functional as an independent oracle)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from howtotrainyourmamlpytorch_tpu.models import build_model, layers
from howtotrainyourmamlpytorch_tpu.models.registry import MODEL_NAMES

OMNIGLOT = (28, 28, 1)
IMAGENET = (84, 84, 3)


# Full backbone family on omniglot; one net per family on imagenet shapes
# (the imagenet variants differ only in input dims — keep the 1-core CI fast).
_COMBOS = [(net, OMNIGLOT) for net in MODEL_NAMES] + [
    ("vgg", IMAGENET),
    ("resnet-4", IMAGENET),
    ("densenet-8", IMAGENET),
]


@pytest.mark.parametrize("net,image_shape", _COMBOS)
def test_forward_shapes(net, image_shape):
    n_way = 5
    model = build_model(net, image_shape, n_way)
    params, state = model.init(jax.random.PRNGKey(0))
    x = jnp.ones((2,) + image_shape)
    logits, new_state = model.apply(params, state, x)
    assert logits.shape == (2, n_way)
    assert jnp.all(jnp.isfinite(logits))
    assert jax.tree.structure(new_state) == jax.tree.structure(state)


def test_vgg_feature_width_matches_reference():
    """Reference VGG flatten width: 64 feats on omniglot (28->14->7->3->1),
    64*5*5 on imagenet (84->42->21->10->5) — models.py:46-48 dummy-inference."""
    m_o = build_model("vgg", OMNIGLOT, 5)
    p_o, _ = m_o.init(jax.random.PRNGKey(0))
    assert p_o["fc"]["w"].shape == (64, 5)
    m_i = build_model("vgg", IMAGENET, 5)
    p_i, _ = m_i.init(jax.random.PRNGKey(0))
    assert p_i["fc"]["w"].shape == (64 * 5 * 5, 5)


def test_densenet_feature_progression():
    """Stem-less DenseNet-BC feature count (reference models.py:180-199):
    omniglot densenet-8: 1 ->(block)17 ->(trans)8 ->24 ->12 ->28 ->14 ->30."""
    m = build_model("densenet-8", OMNIGLOT, 5)
    p, _ = m.init(jax.random.PRNGKey(0))
    assert p["classifier"]["w"].shape[0] == 30
    assert p["norm5"]["scale"].shape == (30,)


def test_conv_matches_torch():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 9, 9, 3).astype(np.float32)
    w = rng.randn(3, 3, 3, 8).astype(np.float32)
    b = rng.randn(8).astype(np.float32)
    ours = layers.conv2d({"w": jnp.array(w), "b": jnp.array(b)}, jnp.array(x), stride=2, padding=1)
    theirs = F.conv2d(
        torch.tensor(x).permute(0, 3, 1, 2),
        torch.tensor(w).permute(3, 2, 0, 1),
        torch.tensor(b),
        stride=2,
        padding=1,
    ).permute(0, 2, 3, 1)
    np.testing.assert_allclose(np.asarray(ours), theirs.numpy(), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize(
    "kh,cin,cout,stride,pad,bias",
    [
        (3, 3, 8, 1, 1, True),   # vgg stage (max_pooling path)
        (3, 8, 8, 2, 1, False),  # vgg/resnet strided stage
        (1, 8, 4, 1, 0, False),  # densenet bottleneck / transition
        (1, 8, 4, 2, 0, False),  # resnet downsample shortcut
        (3, 4, 6, 1, 0, True),   # unpadded case
    ],
)
def test_conv_patches_matches_native(kh, cin, cout, stride, pad, bias, monkeypatch):
    """The patches-GEMM conv (the parallel.tp_convs enabler — see
    layers.CONV_VIA_PATCHES) is the same math as the native conv for every
    (kernel, stride, padding) the model zoo uses: forward, kernel grad, and
    input grad all match to f32 accumulation tolerance."""
    # pin the process-global conv selector: a conv_via_patches=True
    # MAMLSystem built by an earlier test would otherwise make conv2d
    # dispatch to the patches path and turn this into patches-vs-patches
    monkeypatch.setattr(layers, "CONV_VIA_PATCHES", False)
    p = layers.init_conv(jax.random.PRNGKey(0), kh, kh, cin, cout, bias=bias)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 9, 9, cin))

    a = layers.conv2d(p, x, stride=stride, padding=pad)
    b = layers.conv2d_patches(p, x, stride=stride, padding=pad)
    assert a.shape == b.shape
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)

    ga = jax.grad(lambda w: layers.conv2d({**p, "w": w}, x, stride=stride, padding=pad).sum())(p["w"])
    gb = jax.grad(lambda w: layers.conv2d_patches({**p, "w": w}, x, stride=stride, padding=pad).sum())(p["w"])
    np.testing.assert_allclose(np.asarray(ga), np.asarray(gb), rtol=1e-5, atol=1e-5)

    gxa = jax.grad(lambda x: layers.conv2d(p, x, stride=stride, padding=pad).sum())(x)
    gxb = jax.grad(lambda x: layers.conv2d_patches(p, x, stride=stride, padding=pad).sum())(x)
    np.testing.assert_allclose(np.asarray(gxa), np.asarray(gxb), rtol=1e-5, atol=1e-5)


def test_batch_norm_matches_torch_train_mode():
    rng = np.random.RandomState(1)
    x = rng.randn(4, 5, 5, 7).astype(np.float32)
    scale = rng.rand(7).astype(np.float32) + 0.5
    bias = rng.randn(7).astype(np.float32)
    params = {"scale": jnp.array(scale), "bias": jnp.array(bias)}
    _, state = layers.init_batch_norm(7)
    ours, new_state = layers.batch_norm(params, state, jnp.array(x), update_running=True)
    xt = torch.tensor(x).permute(0, 3, 1, 2)
    bn = torch.nn.BatchNorm2d(7)
    bn.weight.data = torch.tensor(scale)
    bn.bias.data = torch.tensor(bias)
    bn.train()
    theirs = bn(xt).permute(0, 2, 3, 1).detach().numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(new_state["mean"]), bn.running_mean.numpy(), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(new_state["var"]), bn.running_var.numpy(), rtol=1e-4, atol=1e-5
    )


def test_max_pool_matches_torch_floor_mode():
    rng = np.random.RandomState(2)
    x = rng.randn(1, 7, 7, 2).astype(np.float32)  # odd size -> floor matters
    ours = layers.max_pool(jnp.array(x))
    theirs = (
        F.max_pool2d(torch.tensor(x).permute(0, 3, 1, 2), 2, 2).permute(0, 2, 3, 1).numpy()
    )
    assert ours.shape == theirs.shape == (1, 3, 3, 2)
    np.testing.assert_allclose(np.asarray(ours), theirs, rtol=1e-5, atol=1e-6)


def test_transductive_bn_is_default():
    """Normalization must use batch stats even with stale running stats
    (reference evaluates in train mode — few_shot_learning_system.py:388)."""
    params = {"scale": jnp.ones((3,)), "bias": jnp.zeros((3,))}
    state = {"mean": jnp.full((3,), 100.0), "var": jnp.full((3,), 0.01), "count": jnp.zeros(())}
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 4, 4, 3))
    out, _ = layers.batch_norm(params, state, x)
    assert abs(float(jnp.mean(out))) < 1e-4  # normalized by batch stats, not running


def test_init_distributions():
    """torch-default conv init: U(-1/sqrt(fan_in), 1/sqrt(fan_in))."""
    w = layers.kaiming_uniform_conv(jax.random.PRNGKey(0), (3, 3, 64, 64))
    bound = 1.0 / np.sqrt(3 * 3 * 64)
    assert float(jnp.max(jnp.abs(w))) <= bound + 1e-6
    w2 = layers.kaiming_normal_conv(jax.random.PRNGKey(1), (3, 3, 64, 128), mode="fan_out")
    expected_std = np.sqrt(2.0 / (128 * 9))
    assert abs(float(jnp.std(w2)) - expected_std) / expected_std < 0.05


def test_pool_reshape_path_matches_reduce_window_and_grads():
    """The non-overlapping (window==stride) pools use slice+reshape+max/mean
    instead of lax.reduce_window (its select_and_scatter backward measured
    ~27% of bench-step device time on a real v5e). Pin forward equality and,
    for continuous (tie-free) inputs, gradient equality against the
    reduce_window formulation on odd + even sizes. (On exactly-tied maxima
    the subgradient conventions differ by design: even split vs
    first-argmax — see max_pool docstring.)"""
    import jax.numpy as jnp
    from jax import lax

    def rw_max(x):
        return lax.reduce_window(
            x, -jnp.inf, lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
        )

    def rw_avg(x):
        return lax.reduce_window(
            x, 0.0, lax.add, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
        ) / 4.0

    rng = np.random.RandomState(7)
    for hw in (7, 8, 28):
        x = jnp.asarray(rng.randn(2, hw, hw, 3).astype(np.float32))
        np.testing.assert_allclose(layers.max_pool(x), rw_max(x), rtol=0, atol=0)
        np.testing.assert_allclose(
            layers.avg_pool(x), rw_avg(x), rtol=1e-6, atol=1e-6
        )
        g_fast = jax.grad(lambda x: jnp.sum(layers.max_pool(x) ** 2))(x)
        g_ref = jax.grad(lambda x: jnp.sum(rw_max(x) ** 2))(x)
        np.testing.assert_allclose(g_fast, g_ref, rtol=1e-6, atol=1e-6)
        ga_fast = jax.grad(lambda x: jnp.sum(layers.avg_pool(x) ** 2))(x)
        ga_ref = jax.grad(lambda x: jnp.sum(rw_avg(x) ** 2))(x)
        np.testing.assert_allclose(ga_fast, ga_ref, rtol=1e-6, atol=1e-6)


def test_avg_pool_matches_torch_floor_mode():
    rng = np.random.RandomState(3)
    x = rng.randn(1, 7, 7, 2).astype(np.float32)
    ours = layers.avg_pool(jnp.array(x))
    theirs = (
        F.avg_pool2d(torch.tensor(x).permute(0, 3, 1, 2), 2, 2).permute(0, 2, 3, 1).numpy()
    )
    assert ours.shape == theirs.shape == (1, 3, 3, 2)
    np.testing.assert_allclose(np.asarray(ours), theirs, rtol=1e-5, atol=1e-6)


def test_max_pool_tie_subgradient_convention():
    """On exactly-tied window maxima the reshape path splits the gradient
    evenly among ties (documented deliberate difference from torch's/
    select_and_scatter's first-argmax convention — see max_pool docstring)."""
    x = jnp.zeros((1, 2, 2, 1), np.float32).at[0, 0, 0, 0].set(1.0).at[0, 1, 1, 0].set(1.0)
    g = jax.grad(lambda a: jnp.sum(layers.max_pool(a)))(x)
    np.testing.assert_allclose(np.asarray(g).squeeze(), [[0.5, 0.0], [0.0, 0.5]])
    x_all_tied = jnp.ones((1, 2, 2, 1), np.float32)
    g2 = jax.grad(lambda a: jnp.sum(layers.max_pool(a)))(x_all_tied)
    np.testing.assert_allclose(np.asarray(g2), 0.25 * np.ones((1, 2, 2, 1)))


def test_max_pool_reduce_window_escape_hatch():
    """Config.max_pool_reduce_window forces the reduce_window path, whose
    select_and_scatter backward uses torch's first-argmax tie subgradient —
    the escape hatch for ruling the pooling convention in/out under bf16
    quantization (ADVICE r3; max_pool docstring)."""
    from howtotrainyourmamlpytorch_tpu.config import Config

    x_all_tied = jnp.ones((1, 2, 2, 1), np.float32)
    prev = layers.FORCE_REDUCE_WINDOW_POOL
    try:
        layers.FORCE_REDUCE_WINDOW_POOL = True
        g = jax.grad(lambda a: jnp.sum(layers.max_pool(a)))(x_all_tied)
        expected = np.zeros((1, 2, 2, 1), np.float32)
        expected[0, 0, 0, 0] = 1.0  # all gradient to the first argmax
        np.testing.assert_allclose(np.asarray(g), expected)
        # tie-free forward unchanged
        rng = np.random.RandomState(0)
        xc = jnp.asarray(rng.randn(1, 8, 8, 2).astype(np.float32))
        forced = layers.max_pool(xc)
        layers.FORCE_REDUCE_WINDOW_POOL = False
        np.testing.assert_allclose(forced, layers.max_pool(xc), rtol=0, atol=0)
    finally:
        layers.FORCE_REDUCE_WINDOW_POOL = prev

    # config knob threads through to the module flag at system construction
    from howtotrainyourmamlpytorch_tpu.core import MAMLSystem

    try:
        layers.FORCE_REDUCE_WINDOW_POOL = False  # an already-configured process
        with pytest.warns(UserWarning, match="tie-subgradient"):
            # flipping a configured value mid-process must warn (the flag is
            # not in any compile-cache key — convention-change guard)
            MAMLSystem(Config(max_pool_reduce_window=True))
        assert layers.FORCE_REDUCE_WINDOW_POOL is True
    finally:
        layers.FORCE_REDUCE_WINDOW_POOL = prev
