"""graftsan runtime — lockdep-style lock-order sanitizer, stdlib-only.

Every threaded class in the serving / resilience / observability layers
builds its primitives through the factories here::

    self._lock = san_lock("MicroBatcher._lock")
    self._wake = san_condition("MicroBatcher._wake", self._lock)

Off (the default): the factories return plain ``threading.Lock`` /
``RLock`` / ``Condition`` objects — no wrapper, no indirection, bit-identical
to the hand-rolled constructions they replaced. Armed (``HTYMP_GRAFTSAN=1``
in the environment, or :func:`arm` called from ``Config.resilience.sanitizer``
wiring), the factories return ``SanLock`` / ``SanRLock`` wrappers that feed a
single process-wide analysis:

- **Acquisition-order graph.** Locks are keyed by *site* (owner class +
  attribute name, e.g. ``"WeightPager._lock"``), not by instance: two
  replicas' batcher locks are the same site. Acquiring B while holding A
  lands the edge A→B (first-acquisition stack recorded). The moment an edge
  closes a cycle — some path B→…→A already exists — a ``lock_order_cycle``
  violation is reported with both stacks. No actual deadlock has to occur:
  the two halves of an ABBA can run minutes apart, on threads that never
  contend, and the cycle is still caught deterministically.

- **Declared-hierarchy check.** ``order.toml`` ships the canonical
  acquisition order (registry → pager → cache → batcher → breaker). An edge
  that *inverts* the declared order is a ``lock_order_inversion`` violation
  even before a full cycle exists — the dynamic twin of graftlint's GL210.

- **Held-across-blocking.** While armed, ``concurrent.futures.Future.result``
  and ``queue.Queue.get`` are wrapped to check the calling thread's held-lock
  stack; serving seams call :func:`note_blocking` (engine dispatch, HTTP
  I/O). A blocking wait with any SanLock held is a ``held_across_blocking``
  violation — the shape that turns one hung device call into a
  whole-process wedge (rc=76), because every other thread piles up behind
  the held lock.

- **Thread-leak audit.** :func:`audit_thread_leaks` (called from
  ``ServingFrontend.close`` and the chaos campaign) reports non-daemon
  threads alive beyond the arm-time baseline as ``thread_leak`` violations.

Violations land in an in-process list (:func:`violations`), are pushed to
registered sinks (the serving frontend and the runner forward them into
``events.jsonl`` as ``graftsan_violation`` events), and — when
``HTYMP_GRAFTSAN_LOG`` names a file — are appended there as JSON lines so
subprocess chaos episodes report back to the campaign.
``scripts/graftsan_report.py`` turns either stream into a one-JSON-line
verdict.

The sanitizer's own bookkeeping uses one plain ``threading.Lock`` held only
for dict/list updates — never while acquiring a user lock or calling user
code — so it cannot itself deadlock or invert an order.
"""

# graftlint: import-light — stdlib-only runtime (GL213 gates the closure)
import json
import os
import threading
import time
import traceback

__all__ = [
    "SanLock",
    "SanRLock",
    "add_sink",
    "arm",
    "audit_thread_leaks",
    "disarm",
    "enabled",
    "load_order",
    "note_blocking",
    "reset",
    "san_condition",
    "san_lock",
    "san_rlock",
    "snapshot",
    "violations",
]

_ENV_FLAG = "HTYMP_GRAFTSAN"
_ENV_LOG = "HTYMP_GRAFTSAN_LOG"
#: stack frames kept per recorded acquisition (enough to name both sides of
#: an inversion without dumping whole request stacks into events.jsonl)
_STACK_DEPTH = 12


class _State:
    """All sanitizer state, guarded by one plain meta-lock (held only for
    bookkeeping — never across user code, lock acquisition, or sinks)."""

    def __init__(self):
        self.meta = threading.Lock()
        self.armed = False
        # site -> {successor site -> edge record}
        self.graph = {}
        # (a, b) pairs already reported as cycles/inversions (dedup)
        self.reported = set()
        self.violations = []
        self.sinks = []
        self.baseline_threads = set()
        self.order_rank = {}  # tier name -> rank
        self.site_rank = {}  # memo: site -> rank or None
        self.tier_classes = {}  # class-name fragment -> tier
        self.blocking_patched = False
        self.tls = threading.local()

    def held(self):
        stack = getattr(self.tls, "held", None)
        if stack is None:
            stack = self.tls.held = []
        return stack


_state = _State()


def enabled() -> bool:
    """True when the factories should hand out instrumented locks."""
    return _state.armed or os.environ.get(_ENV_FLAG) == "1"


def arm(order_path: str = None) -> None:
    """Arm the sanitizer explicitly (the ``Config.resilience.sanitizer``
    path; the env var arms implicitly). Loads the declared hierarchy,
    snapshots the thread baseline for leak audits, and patches the stdlib
    blocking seams. Idempotent."""
    with _state.meta:
        first = not _state.armed
        _state.armed = True
        if first:
            _state.baseline_threads = {t.ident for t in threading.enumerate()}
    if first:
        _load_declared_order(order_path)
        _patch_blocking_seams()


def disarm() -> None:
    with _state.meta:
        _state.armed = False


def reset() -> None:
    """Clear the graph, violations, and baselines (tests; campaign start).
    Keeps the armed flag and any registered sinks."""
    with _state.meta:
        _state.graph = {}
        _state.reported = set()
        _state.violations = []
        _state.site_rank = {}
        _state.baseline_threads = {t.ident for t in threading.enumerate()}


def add_sink(fn) -> None:
    """Register ``fn(violation_dict)``; buffered violations are replayed so
    a sink attached after arming (the frontend's events.jsonl) misses
    nothing."""
    with _state.meta:
        _state.sinks.append(fn)
        backlog = list(_state.violations)
    for record in backlog:
        try:
            fn(record)
        except Exception:
            pass


def violations():
    with _state.meta:
        return list(_state.violations)


def snapshot():
    """Counts by kind + edge count — the /metrics-shaped summary."""
    with _state.meta:
        by_kind = {}
        for v in _state.violations:
            by_kind[v["kind"]] = by_kind.get(v["kind"], 0) + 1
        edges = sum(len(s) for s in _state.graph.values())
        return {
            "armed": enabled(),
            "violations": len(_state.violations),
            "by_kind": by_kind,
            "sites": len(_state.graph),
            "edges": edges,
        }


# ---------------------------------------------------------------------------
# order.toml — the canonical hierarchy (shared with graftlint GL210)
# ---------------------------------------------------------------------------


def load_order(path: str):
    """Parse ``order.toml`` (this file predates a stdlib ``tomllib`` on the
    shipped Python; the parser covers exactly the subset the file uses:
    ``[section]`` headers, ``key = "str"`` and ``key = ["a", "b"]``).

    Returns ``{"order": [tier, ...], "tiers": {tier: {"classes": [...],
    "attrs": [...]}}}`` or None when the file is missing/unreadable."""
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return None
    section = None
    out = {"order": [], "tiers": {}}
    for raw in text.splitlines():
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        if line.startswith("[") and line.endswith("]"):
            section = line[1:-1].strip()
            if section.startswith("tiers."):
                out["tiers"].setdefault(section[6:], {})
            continue
        if "=" not in line:
            continue
        key, _, value = line.partition("=")
        key, value = key.strip(), value.strip()
        if value.startswith("["):
            items = [
                v.strip().strip("\"'")
                for v in value.strip("[]").split(",")
                if v.strip().strip("\"'")
            ]
        else:
            items = value.strip("\"'")
        if section == "hierarchy" and key == "order":
            out["order"] = list(items)
        elif section and section.startswith("tiers."):
            out["tiers"][section[6:]][key] = items
    if not out["order"]:
        return None
    return out


def default_order_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "order.toml")


def _load_declared_order(path: str = None) -> None:
    spec = load_order(path or default_order_path())
    if spec is None:
        return
    rank = {tier: i for i, tier in enumerate(spec["order"])}
    classes = {}
    for tier, info in spec["tiers"].items():
        for cls in info.get("classes", []):
            classes[cls] = tier
    with _state.meta:
        _state.order_rank = rank
        _state.tier_classes = classes
        _state.site_rank = {}


def _rank_of_locked(site: str):
    """Declared rank of a site ("Class.attr"), or None when its class is not
    in any tier. Caller holds the meta lock."""
    if site in _state.site_rank:
        return _state.site_rank[site]
    cls = site.split(".", 1)[0]
    tier = _state.tier_classes.get(cls)
    rank = _state.order_rank.get(tier) if tier else None
    _state.site_rank[site] = rank
    return rank


# ---------------------------------------------------------------------------
# violation recording
# ---------------------------------------------------------------------------


def _short_stack(skip: int = 2):
    frames = traceback.extract_stack()[:-skip]
    return [
        f"{os.path.basename(f.filename)}:{f.lineno}:{f.name}"
        for f in frames[-_STACK_DEPTH:]
    ]


def _record(kind: str, **fields) -> None:
    record = {
        "event": "graftsan_violation",
        "kind": kind,
        "thread": threading.current_thread().name,
        "time": time.time(),
    }
    record.update(fields)
    with _state.meta:
        _state.violations.append(record)
        sinks = list(_state.sinks)
    log_path = os.environ.get(_ENV_LOG)
    if log_path:
        try:
            with open(log_path, "a") as f:
                f.write(json.dumps(record) + "\n")
                f.flush()
        except OSError:
            pass
    for fn in sinks:
        try:
            fn(record)
        except Exception:
            pass


# ---------------------------------------------------------------------------
# the acquisition-order analysis
# ---------------------------------------------------------------------------


def _path_between(graph, src, dst):
    """Edge path src -> ... -> dst in the site graph, or None (iterative DFS
    — the graph is tiny but recursion limits are not ours to burn)."""
    stack = [(src, [])]
    seen = set()
    while stack:
        node, path = stack.pop()
        if node in seen:
            continue
        seen.add(node)
        for succ in graph.get(node, {}):
            edge_path = path + [(node, succ)]
            if succ == dst:
                return edge_path
            if succ not in seen:
                stack.append((succ, edge_path))
    return None


def _note_acquire(lock) -> None:
    held = _state.held()
    stack = None
    new_edges = []
    with _state.meta:
        for held_lock in held:
            a, b = held_lock.site, lock.site
            if a == b:
                # two instances of the same site nested in one thread: an
                # ABBA with itself the instant another thread nests them the
                # other way round — report unless explicitly address-ordered
                if held_lock is not lock and (a, b) not in _state.reported:
                    _state.reported.add((a, b))
                    new_edges.append(("same_site", a, b, None))
                continue
            succ = _state.graph.setdefault(a, {})
            if b not in succ:
                if stack is None:
                    stack = _short_stack(skip=4)
                succ[b] = {"stack": stack, "thread": threading.current_thread().name}
                new_edges.append(("edge", a, b, succ[b]))
    held.append(lock)
    if not new_edges:
        return
    # cycle / declared-order checks OUTSIDE the per-edge insert but re-taking
    # the meta lock per query: the graph only grows, so a cycle present at
    # insert time is still present here
    for tag, a, b, edge in new_edges:
        if tag == "same_site":
            _record(
                "lock_order_same_site",
                site_a=a,
                site_b=b,
                detail="two instances of the same lock site nested in one "
                "thread — order them by id() or restructure",
                stack_b=_short_stack(skip=3),
            )
            continue
        with _state.meta:
            back_path = _path_between(_state.graph, b, a)
            rank_a, rank_b = _rank_of_locked(a), _rank_of_locked(b)
            back_stacks = None
            if back_path:
                back_stacks = [
                    {
                        "edge": f"{x}->{y}",
                        "stack": _state.graph.get(x, {}).get(y, {}).get("stack"),
                    }
                    for x, y in back_path
                ]
                cycle_key = frozenset([(a, b)] + back_path)
                if cycle_key in _state.reported:
                    back_stacks = False  # already reported
                else:
                    _state.reported.add(cycle_key)
            inversion = (
                rank_a is not None
                and rank_b is not None
                and rank_b < rank_a
                and (a, b, "inv") not in _state.reported
            )
            if inversion:
                _state.reported.add((a, b, "inv"))
        if back_stacks:
            _record(
                "lock_order_cycle",
                site_a=a,
                site_b=b,
                detail=f"acquiring {b} while holding {a} closes a cycle: "
                f"{' ; '.join(e['edge'] for e in back_stacks)} already "
                "recorded — this is an ABBA deadlock waiting for contention",
                stack_b=edge["stack"],
                reverse_edges=back_stacks,
            )
        if inversion:
            _record(
                "lock_order_inversion",
                site_a=a,
                site_b=b,
                detail=f"declared hierarchy orders {b} (rank {rank_b}) before "
                f"{a} (rank {rank_a}) — acquiring it while holding {a} "
                "inverts tools/graftsan/order.toml",
                stack_b=edge["stack"],
            )


def _note_release(lock) -> None:
    held = _state.held()
    for i in range(len(held) - 1, -1, -1):
        if held[i] is lock:
            del held[i]
            return


def held_sites():
    """Sites held by the calling thread (outermost first)."""
    return [lk.site for lk in _state.held()]


class SanLock:
    """Drop-in ``threading.Lock`` recording site-keyed acquisition order."""

    _recursive = False

    def __init__(self, site: str):
        self.site = site
        self._inner = self._make_inner()
        self._depth = 0  # recursion depth (SanRLock); guarded by ownership

    def _make_inner(self):
        return threading.Lock()

    def acquire(self, blocking=True, timeout=-1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            if self._recursive and self._depth:
                self._depth += 1
            else:
                self._depth = 1
                _note_acquire(self)
        return ok

    def release(self):
        # note BEFORE the actual release: until release returns, the lock is
        # still ours, and noting first keeps the held stack consistent if
        # release raises on an unheld lock
        if self._depth == 1:
            _note_release(self)
        self._depth = max(0, self._depth - 1)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<{type(self).__name__} site={self.site!r}>"


class SanRLock(SanLock):
    """Drop-in ``threading.RLock``; exposes the ``_release_save`` protocol so
    ``threading.Condition`` wait/notify keeps the held-stack accurate."""

    _recursive = True

    def _make_inner(self):
        return threading.RLock()

    # Condition-integration protocol: a wait() fully releases the lock
    # (however deep the recursion) and re-acquires it on wake — the held
    # stack must mirror that or every post-wait acquisition looks nested.
    def _release_save(self):
        _note_release(self)
        depth, self._depth = self._depth, 0
        state = self._inner._release_save()
        return (state, depth)

    def _acquire_restore(self, saved):
        state, depth = saved
        self._inner._acquire_restore(state)
        self._depth = depth
        _note_acquire(self)

    def _is_owned(self):
        return self._inner._is_owned()


# ---------------------------------------------------------------------------
# factories
# ---------------------------------------------------------------------------


def san_lock(site: str = None):
    """``threading.Lock`` when off; ``SanLock(site)`` when armed."""
    if not enabled():
        return threading.Lock()
    return SanLock(site or _caller_site())


def san_rlock(site: str = None):
    if not enabled():
        return threading.RLock()
    return SanRLock(site or _caller_site())


def san_condition(site: str = None, lock=None):
    """``threading.Condition``; when armed and no lock is shared, the
    condition's internal lock is a ``SanRLock`` so waits/notifies feed the
    same analysis. A shared lock (the batcher's ``Condition(self._lock)``
    pattern) carries its own site — tracking rides the lock itself."""
    if not enabled():
        return threading.Condition(lock)
    return threading.Condition(lock if lock is not None else SanRLock(site or _caller_site()))


def _caller_site() -> str:
    for frame in reversed(traceback.extract_stack()[:-2]):
        return f"{os.path.basename(frame.filename)}:{frame.lineno}"
    return "<unknown>"


# ---------------------------------------------------------------------------
# blocking-call + thread-leak audits
# ---------------------------------------------------------------------------


def note_blocking(what: str, timeout=None) -> None:
    """Serving seams (engine dispatch, HTTP proxy I/O) call this before a
    potentially-blocking operation; cheap no-op when the sanitizer is off or
    no lock is held by this thread."""
    if not enabled():
        return
    held = held_sites()
    if held:
        _record(
            "held_across_blocking",
            blocking=what,
            held=held,
            timeout=timeout,
            stack_b=_short_stack(skip=3),
        )


def _patch_blocking_seams() -> None:
    """Wrap ``Future.result`` and ``Queue.get`` so a wait entered with a
    SanLock held is reported. Patched once, on first arm; the wrappers are
    pure pass-throughs for threads holding nothing."""
    with _state.meta:
        if _state.blocking_patched:
            return
        _state.blocking_patched = True
    import queue as _queue
    from concurrent.futures import Future as _Future

    orig_result = _Future.result

    def result(self, timeout=None):
        note_blocking("Future.result", timeout=timeout)
        return orig_result(self, timeout)

    _Future.result = result

    orig_get = _queue.Queue.get

    def get(self, block=True, timeout=None):
        if block:
            note_blocking("Queue.get", timeout=timeout)
        return orig_get(self, block, timeout)

    _queue.Queue.get = get


def audit_thread_leaks(context: str, baseline=None) -> list:
    """Non-daemon threads alive beyond the arm-time (or given) baseline —
    the threads a close() was supposed to join. Returns the leaked names
    (empty when clean) and records a ``thread_leak`` violation when armed."""
    base = baseline if baseline is not None else _state.baseline_threads
    leaked = [
        t.name
        for t in threading.enumerate()
        if t.is_alive()
        and not t.daemon
        and t is not threading.main_thread()
        and t.ident not in base
    ]
    if leaked and enabled():
        _record("thread_leak", context=context, threads=sorted(leaked))
    return sorted(leaked)
