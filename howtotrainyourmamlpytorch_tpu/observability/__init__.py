"""Unified observability: span tracing, metrics registry, run telemetry.

The first subsystem that spans both stacks: the training runner and the
serving frontend instrument their hot paths through the same three pieces —

- :mod:`trace` — ``SpanTracer``: low-overhead thread-safe span recorder
  (bounded ring, injectable clock, per-thread nesting) with Chrome
  trace-event / Perfetto JSON export and a balance validator the chaos
  campaign runs over every exported trace;
- :mod:`metrics` — ``MetricsRegistry``: counters, gauges, and windowed
  histograms with exact percentiles (window copied under the lock, numpy
  math outside it). ``serving/metrics.py``'s ``LatencyStats`` /
  ``EventCounters`` are thin adapters over it, ``/metrics`` schema
  unchanged;
- :mod:`telemetry` — ``TelemetryHub``: snapshots the registry to
  ``logs/telemetry.jsonl`` per epoch / per-N steps (episodes/s throughput,
  step-phase histograms, provider snapshots: recompile guard, watchdog beat
  age, breaker state).

Knobs: ``Config.observability`` (``config.py::ObservabilityConfig``) —
fully inert and bit-identical when disabled. Report CLI:
``scripts/obs_report.py``; howto: ``docs/OPERATIONS.md`` "Reading a run".
"""

from .metrics import MetricsRegistry  # noqa: F401
from .telemetry import NULL_HUB, TelemetryHub  # noqa: F401
from .trace import (  # noqa: F401
    NULL_TRACER,
    NullTracer,
    SpanTracer,
    load_and_validate_trace,
    validate_chrome_trace,
)
