"""The serving front-end: in-process API + a thin stdlib HTTP JSON layer.

``ServingFrontend`` wires the pieces together — engine (compiled adapt /
predict), adapted-weight cache, micro-batchers, latency metrics — behind the
request API a client sees:

- ``adapt(x_support, y_support) -> {adaptation_id, cached, ...}``: run (or
  skip, on cache hit) the inner loop; the returned id names the cached
  adapted weights.
- ``predict(adaptation_id, x_query) -> probs``: forward queries through the
  cached adapted weights.
- ``adapt_predict(...)``: both in one call, for one-shot clients.
- ``metrics() / healthz()``: the observability surface.

The HTTP layer (``ThreadingHTTPServer`` + JSON bodies) is deliberately
stdlib-only — no framework dependency — and thin: every handler parses JSON,
calls the frontend, serializes the result. Concurrency comes from the
threaded server (one thread per in-flight request) feeding the batchers,
whose single worker serializes device dispatch.
"""

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from ..config import Config, ServingConfig
from ..core import MAMLSystem
from .batcher import MicroBatcher
from .cache import AdaptedWeightCache, support_digest
from .engine import AdaptationEngine
from .metrics import LatencyStats


class UnknownAdaptationError(KeyError):
    """predict() named an adaptation id that is not (or no longer) cached."""


class ServingFrontend:
    def __init__(self, engine: AdaptationEngine, serving_cfg: Optional[ServingConfig] = None):
        self.engine = engine
        self.serving = serving_cfg or engine.serving
        self.cache = AdaptedWeightCache(
            max_bytes=self.serving.cache_max_bytes, ttl_s=self.serving.cache_ttl_s
        )
        self.latency = LatencyStats(self.serving.latency_window)
        self._adapt_batcher = MicroBatcher(
            lambda bucket, payloads: self.engine.adapt_batch(payloads),
            max_batch=self.serving.max_batch_size,
            deadline_ms=self.serving.batch_deadline_ms,
            name="adapt",
        )
        self._predict_batcher = MicroBatcher(
            lambda bucket, payloads: self.engine.predict_batch(payloads),
            max_batch=self.serving.max_batch_size,
            deadline_ms=self.serving.batch_deadline_ms,
            name="predict",
        )
        self._started = time.monotonic()
        self._closed = False

    # ------------------------------------------------------------------

    def _cache_key(self, digest: str) -> Tuple[str, str]:
        return (self.engine.fingerprint, digest)

    def adapt(self, x_support, y_support) -> Dict[str, Any]:
        t0 = time.monotonic()
        x, y = self.engine._flatten_support(x_support, y_support)
        digest = support_digest(x, y, self.engine.num_steps)
        key = self._cache_key(digest)
        cached = self.cache.get(key) is not None
        if not cached:
            bucket = self.engine.support_bucket(x.shape[0])
            fast_weights = self._adapt_batcher.submit(bucket, (x, y)).result()
            self.cache.put(key, fast_weights)
        elapsed = time.monotonic() - t0
        self.latency.record("adapt_cached" if cached else "adapt", elapsed)
        return {
            "adaptation_id": digest,
            "cached": cached,
            "support_size": int(x.shape[0]),
            "latency_ms": round(elapsed * 1e3, 3),
        }

    def predict(self, adaptation_id: str, x_query) -> np.ndarray:
        t0 = time.monotonic()
        fast_weights = self.cache.get(self._cache_key(adaptation_id))
        if fast_weights is None:
            raise UnknownAdaptationError(
                f"unknown or expired adaptation_id {adaptation_id!r}; "
                "re-send the support set via /adapt"
            )
        x = np.asarray(x_query, np.float32)
        bucket = self.engine.query_bucket(x.shape[0])
        probs = self._predict_batcher.submit(bucket, (fast_weights, x)).result()
        self.latency.record("predict", time.monotonic() - t0)
        return probs

    def adapt_predict(self, x_support, y_support, x_query) -> Dict[str, Any]:
        info = self.adapt(x_support, y_support)
        probs = self.predict(info["adaptation_id"], x_query)
        return {**info, "probs": probs}

    # ------------------------------------------------------------------

    def healthz(self) -> Dict[str, Any]:
        return {
            "status": "ok",
            "platform": jax.default_backend(),
            "checkpoint_fingerprint": self.engine.fingerprint,
            "model": self.engine.system.model.name,
            "num_classes": self.engine.num_classes,
            "adapt_steps": self.engine.num_steps,
            "uptime_s": round(time.monotonic() - self._started, 1),
        }

    def metrics(self) -> Dict[str, Any]:
        return {
            "latency": self.latency.summary(),
            "cache": self.cache.stats(),
            "adapt_batcher": self._adapt_batcher.stats(),
            "predict_batcher": self._predict_batcher.stats(),
            "compiled": self.engine.compile_counts(),
            "uptime_s": round(time.monotonic() - self._started, 1),
        }

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._adapt_batcher.close()
        self._predict_batcher.close()


def frontend_from_run_dir(
    run_dir: str, checkpoint_idx="best", cfg: Optional[Config] = None
) -> ServingFrontend:
    engine = AdaptationEngine.from_run_dir(run_dir, checkpoint_idx, cfg=cfg)
    return ServingFrontend(engine)


# ---------------------------------------------------------------------------
# HTTP layer
# ---------------------------------------------------------------------------


class _Handler(BaseHTTPRequestHandler):
    # the frontend is attached to the server instance by make_http_server
    protocol_version = "HTTP/1.1"

    def _send_json(self, code: int, payload: Dict[str, Any]) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_json(self) -> Dict[str, Any]:
        length = int(self.headers.get("Content-Length", 0))
        if length <= 0:
            return {}
        return json.loads(self.rfile.read(length))

    def log_message(self, fmt, *args):  # quiet by default; metrics cover it
        pass

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        frontend: ServingFrontend = self.server.frontend  # type: ignore[attr-defined]
        try:
            if self.path == "/healthz":
                self._send_json(200, frontend.healthz())
            elif self.path == "/metrics":
                self._send_json(200, frontend.metrics())
            else:
                self._send_json(404, {"error": f"unknown path {self.path}"})
        except Exception as exc:  # noqa: BLE001 — keep the server alive
            self._send_json(500, {"error": f"internal error: {exc!r}"})

    def do_POST(self):  # noqa: N802
        frontend: ServingFrontend = self.server.frontend  # type: ignore[attr-defined]
        try:
            req = self._read_json()
            if self.path == "/adapt":
                out = frontend.adapt(req["x_support"], req["y_support"])
                self._send_json(200, out)
            elif self.path == "/predict":
                probs = frontend.predict(req["adaptation_id"], req["x_query"])
                self._send_json(200, {"probs": probs.tolist()})
            elif self.path == "/adapt_predict":
                out = frontend.adapt_predict(
                    req["x_support"], req["y_support"], req["x_query"]
                )
                out["probs"] = out["probs"].tolist()
                self._send_json(200, out)
            else:
                self._send_json(404, {"error": f"unknown path {self.path}"})
        except UnknownAdaptationError as exc:
            self._send_json(404, {"error": str(exc)})
        except (KeyError, ValueError, TypeError) as exc:
            self._send_json(400, {"error": f"bad request: {exc!r}"})
        except Exception as exc:  # noqa: BLE001 — keep the server alive
            self._send_json(500, {"error": f"internal error: {exc!r}"})


def make_http_server(
    frontend: ServingFrontend, host: str = "127.0.0.1", port: int = 0
) -> ThreadingHTTPServer:
    """Bind (port 0 = ephemeral, for tests) but do not serve; the caller owns
    ``serve_forever`` / ``shutdown``."""
    server = ThreadingHTTPServer((host, port), _Handler)
    server.frontend = frontend  # type: ignore[attr-defined]
    server.daemon_threads = True
    return server


def serve_forever(frontend: ServingFrontend, host: str, port: int) -> None:
    server = make_http_server(frontend, host, port)
    addr = server.server_address
    print(
        f"serving on http://{addr[0]}:{addr[1]} "
        f"(checkpoint {frontend.engine.fingerprint[:12]}, "
        f"platform {jax.default_backend()})",
        flush=True,
    )
    try:
        server.serve_forever()
    finally:
        server.server_close()
        frontend.close()
