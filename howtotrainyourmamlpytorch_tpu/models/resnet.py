"""Stem-less ResNet for few-shot learning (reference ``models.py:60-150``).

The reference's ResNet drops the ImageNet stem entirely: ``inplanes`` starts at
the *input channel count* (``models.py:83``) and the network is 4 stages of
torchvision ``BasicBlock`` at widths 32/64/128/256, each with stride 2
(``models.py:84-93``), then global average pool -> Linear. resnet-4/8/12 map to
``layers=[1,1,1,1] / [2,2,2,2] / [3,3,3,3]`` (reference
``few_shot_learning_system.py:63-68``).

Init parity: kaiming-normal fan_out for convs, unit/zero BN
(``models.py:98-103``), optional zero-init of each block's second BN scale
(``models.py:109-114``); the final Linear keeps the torch default init.
"""

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from . import layers
from .model import Model

_STAGE_WIDTHS = (32, 64, 128, 256)


def _init_basic_block(key, cin, planes, stride):
    k1, k2, k3 = jax.random.split(key, 3)
    bn1_p, bn1_s = layers.init_batch_norm(planes)
    bn2_p, bn2_s = layers.init_batch_norm(planes)
    params = {
        "conv1": layers.init_conv(k1, 3, 3, cin, planes, bias=False, init="kaiming_normal_fan_out"),
        "bn1": bn1_p,
        "conv2": layers.init_conv(k2, 3, 3, planes, planes, bias=False, init="kaiming_normal_fan_out"),
        "bn2": bn2_p,
    }
    state = {"bn1": bn1_s, "bn2": bn2_s}
    if stride != 1 or cin != planes:
        dbn_p, dbn_s = layers.init_batch_norm(planes)
        params["downsample"] = {
            "conv": layers.init_conv(k3, 1, 1, cin, planes, bias=False, init="kaiming_normal_fan_out"),
            "bn": dbn_p,
        }
        state["downsample"] = {"bn": dbn_s}
    return params, state


def _apply_basic_block(
    params, state, x, stride, use_batch_stats, update_running, via_patches=False,
    sample_weight=None, stat_dtype=None,
):
    identity = x
    out = layers.conv2d(params["conv1"], x, stride=stride, padding=1, via_patches=via_patches)
    out, bn1_s = layers.batch_norm(
        params["bn1"], state["bn1"], out, use_batch_stats, update_running,
        sample_weight=sample_weight, stat_dtype=stat_dtype,
    )
    out = layers.relu(out)
    out = layers.conv2d(params["conv2"], out, stride=1, padding=1, via_patches=via_patches)
    out, bn2_s = layers.batch_norm(
        params["bn2"], state["bn2"], out, use_batch_stats, update_running,
        sample_weight=sample_weight, stat_dtype=stat_dtype,
    )
    new_state = {"bn1": bn1_s, "bn2": bn2_s}
    if "downsample" in params:
        identity = layers.conv2d(
            params["downsample"]["conv"], x, stride=stride, padding=0,
            via_patches=via_patches,
        )
        identity, dbn_s = layers.batch_norm(
            params["downsample"]["bn"], state["downsample"]["bn"], identity,
            use_batch_stats, update_running, sample_weight=sample_weight,
            stat_dtype=stat_dtype,
        )
        new_state["downsample"] = {"bn": dbn_s}
    return layers.relu(out + identity), new_state


def build_resnet(
    image_shape: Tuple[int, int, int],
    num_classes: int,
    blocks_per_stage: Sequence[int] = (1, 1, 1, 1),
    zero_init_residual: bool = False,
    conv_via_patches: bool = False,
) -> Model:
    """``conv_via_patches`` bakes the conv implementation into this model's
    apply (explicit parameter, not a process global — see layers.conv2d).
    No pooling knob: the only pools here are global-average."""
    h, w, c = image_shape

    def init(key):
        params, state = {}, {}
        cin = c
        n_blocks = sum(blocks_per_stage)
        keys = jax.random.split(key, n_blocks + 1)
        ki = 0
        for si, (planes, n) in enumerate(zip(_STAGE_WIDTHS, blocks_per_stage)):
            stage_p, stage_s = {}, {}
            for bi in range(n):
                stride = 2 if bi == 0 else 1
                bp, bs = _init_basic_block(keys[ki], cin, planes, stride)
                ki += 1
                if zero_init_residual:
                    bp["bn2"]["scale"] = jnp.zeros_like(bp["bn2"]["scale"])
                stage_p[f"block_{bi}"] = bp
                stage_s[f"block_{bi}"] = bs
                cin = planes
            params[f"layer{si + 1}"] = stage_p
            state[f"layer{si + 1}"] = stage_s
        params["fc"] = layers.init_linear(keys[-1], _STAGE_WIDTHS[-1], num_classes)
        return params, state

    def apply(params, state, x, *, use_batch_stats=True, update_running=False,
              sample_weight=None, stat_dtype=None):
        new_state = {}
        for si, n in enumerate(blocks_per_stage):
            lname = f"layer{si + 1}"
            stage_s = {}
            for bi in range(n):
                bname = f"block_{bi}"
                stride = 2 if bi == 0 else 1
                x, bs = _apply_basic_block(
                    params[lname][bname], state[lname][bname], x, stride,
                    use_batch_stats, update_running, conv_via_patches,
                    sample_weight, stat_dtype,
                )
                stage_s[bname] = bs
            new_state[lname] = stage_s
        x = layers.global_avg_pool(x)
        return layers.linear(params["fc"], x), new_state

    # reduce_window_pool=None: no max-pooling in this backbone (global
    # average pools only), so the convention does not apply
    return Model(
        init=init, apply=apply, name="resnet", conv_via_patches=conv_via_patches
    )
