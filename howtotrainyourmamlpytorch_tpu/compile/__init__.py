"""Ahead-of-time compilation subsystem (ROADMAP item 2: kill the compile
tax). ``compile.aot`` prewarms the strict-mode planned program sets before
the first step / first request and persists the evidence two ways: the JAX
persistent compilation cache (the XLA artifact) and an executable-store
manifest next to the checkpoints (the warm-start contract a fresh process
verifies before accepting work)."""

from .aot import (  # noqa: F401
    ExecutableStore,
    build_manifest,
    ensure_persistent_cache,
    environment_fingerprint,
    prewarm_serving,
    prewarm_train,
    verify_manifest,
)
