from .mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    batch_sharding,
    global_batch_from_local,
    host_shard_bounds,
    initialize_distributed,
    make_mesh,
    replicate,
    replicated,
    shard_batch,
    shard_train_state,
    train_state_shardings,
)
