"""Multi-tenant adaptation platform (``serving/registry.py`` +
``serving/tenancy.py``): registry manifest round-trip and lazy host loads,
LRU/watermark paging arithmetic, default-tenant digest stability, quota
429s with honest Retry-After, session spill/rehydrate carrying tenants,
and the tier-1 platform drill — 4 tenants behind one fleet under a budget
fitting 2, cold tenants served via page-in with ZERO outside-prewarm
compiles, bit-identical to single-tenant controls, quota breaches shed
without degrading anyone else."""

import os

import numpy as np
import pytest

import jax

from howtotrainyourmamlpytorch_tpu.config import Config, ServingConfig
from howtotrainyourmamlpytorch_tpu.core import MAMLSystem
from howtotrainyourmamlpytorch_tpu.data.synthetic import synthetic_batch
from howtotrainyourmamlpytorch_tpu.models import build_vgg
from howtotrainyourmamlpytorch_tpu.serving import AdaptationEngine, ServingFrontend
from howtotrainyourmamlpytorch_tpu.serving.cache import support_digest, tree_bytes
from howtotrainyourmamlpytorch_tpu.serving.errors import (
    ServiceUnavailableError,
    UnknownAdaptationError,
)
from howtotrainyourmamlpytorch_tpu.serving.registry import (
    TenantRegistry,
    synthetic_registry,
)
from howtotrainyourmamlpytorch_tpu.serving.sessions import SessionStore
from howtotrainyourmamlpytorch_tpu.serving.tenancy import (
    QuotaExceededError,
    TenantQuotas,
    WeightPager,
    normalize_tenant,
    validate_request_tenant,
)

_IMG = (14, 14, 1)


def _config(**kw):
    serving = kw.pop("serving", None)
    base = dict(
        num_classes_per_set=5,
        num_samples_per_class=2,
        num_target_samples=3,
        batch_size=2,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        total_iter_per_epoch=4,
    )
    base.update(kw)
    if serving is not None:
        base["serving"] = serving
    return Config(**base)


def _system(cfg):
    return MAMLSystem(
        cfg,
        model=build_vgg(
            _IMG, cfg.num_classes_per_set, num_stages=2, cnn_num_filters=4
        ),
    )


def _episode(seed=0):
    b = synthetic_batch(1, 5, 2, 3, _IMG, seed=seed)
    return (
        b["x_support"][0],
        b["y_support"][0],
        b["x_target"][0].reshape((-1,) + _IMG),
    )


# ---------------------------------------------------------------------------
# registry: manifest round-trip + lazy host loads
# ---------------------------------------------------------------------------


def test_registry_yaml_round_trip_and_discovery_precedence(tmp_path):
    reg_path = tmp_path / "tenants.yaml"
    reg_path.write_text(
        "tenants:\n"
        "  acme: {run_dir: acme_runs, checkpoint: latest}\n"
        "  bravo: {run_dir: /abs/bravo}\n"
    )
    reg = TenantRegistry.from_yaml(str(reg_path))
    assert reg.tenants() == ("acme", "bravo")
    assert "acme" in reg and "nobody" not in reg
    # relative run_dirs resolve against the registry file's directory
    assert reg._resolve_run_dir("acme") == str(tmp_path / "acme_runs")
    assert reg._resolve_run_dir("bravo") == "/abs/bravo"
    # checkpoint defaults to "best"
    assert reg._entries["bravo"]["checkpoint"] == "best"

    class _Cfg:
        tenant_registry = str(reg_path)

    # explicit path wins over <run_dir>/tenants.yaml; no source => None
    assert TenantRegistry.discover(_Cfg(), run_dir="/nonexistent") is not None
    _Cfg.tenant_registry = ""
    assert TenantRegistry.discover(_Cfg(), run_dir=str(tmp_path)) is not None
    assert TenantRegistry.discover(_Cfg(), run_dir="/nonexistent") is None

    with pytest.raises(ValueError):
        TenantRegistry({"acme": "not-a-mapping"})
    with pytest.raises(ValueError):
        TenantRegistry({})


def test_registry_loads_masters_lazily_and_once(tmp_path):
    cfg = _config()
    system = _system(cfg)
    state = system.init_train_state()
    reg = synthetic_registry(["a", "b"], state, str(tmp_path))
    # naming tenants costs nothing until traffic arrives for one
    assert reg.stats() == {"tenants": 2, "hosted": 0, "loads": 0}
    assert reg.hosted_fingerprints() == {}
    st_a, fp_a = reg.host_state("a")
    assert reg.stats()["loads"] == 1 and reg.stats()["hosted"] == 1
    # cached: a second ask is NOT a second disk load
    st_a2, fp_a2 = reg.host_state("a")
    assert reg.stats()["loads"] == 1 and fp_a2 == fp_a
    assert reg.fingerprint("b") != fp_a  # distinct perturbed checkpoints
    assert reg.hosted_fingerprints() == {"a": fp_a, "b": reg.fingerprint("b")}
    with pytest.raises(KeyError):
        reg.host_state("nobody")


def test_registry_rejects_structurally_foreign_checkpoints(tmp_path):
    cfg = _config()
    system = _system(cfg)
    reg = synthetic_registry(["a"], system.init_train_state(), str(tmp_path))
    # a wider model cannot share the fleet's shape-keyed programs
    wide = MAMLSystem(
        cfg, model=build_vgg(_IMG, 5, num_stages=2, cnn_num_filters=8)
    )
    reg.template = wide.init_train_state()
    with pytest.raises(ValueError, match="structure differs"):
        reg.host_state("a")


# ---------------------------------------------------------------------------
# normalization + pager arithmetic (fake registry, fake byte budget)
# ---------------------------------------------------------------------------


def test_tenant_normalization_and_validation():
    assert normalize_tenant(None) is None
    assert normalize_tenant("") is None
    assert normalize_tenant("default") is None
    assert normalize_tenant(" acme ") == "acme"
    with pytest.raises(ValueError):
        normalize_tenant(7)
    assert validate_request_tenant("default", None) is None
    with pytest.raises(ValueError, match="no tenant registry"):
        validate_request_tenant("acme", None)

    class _Reg:
        def __contains__(self, t):
            return t == "acme"

        def tenants(self):
            return ("acme",)

    assert validate_request_tenant("acme", _Reg()) == "acme"
    with pytest.raises(ValueError, match="unknown tenant"):
        validate_request_tenant("bravo", _Reg())


class _FakeRegistry:
    """Registry double: each tenant's 'master' is one float32 vector of
    ``leaf_n`` elements (4*leaf_n bytes once device-resident)."""

    def __init__(self, tenants, leaf_n=256):
        self._tenants = list(tenants)
        self.leaf_n = leaf_n

    def host_state(self, tenant):
        i = self._tenants.index(tenant)
        return (
            {"w": np.full((self.leaf_n,), float(i), np.float32)},
            f"fp-{tenant}",
        )


def test_pager_lru_eviction_arithmetic_under_byte_budget():
    per = 256 * 4
    pager = WeightPager(
        _FakeRegistry(["a", "b", "c"]), template={"w": np.zeros(1)},
        budget_bytes=2 * per,
    )
    pager.resident("a")
    pager.resident("b")
    assert pager.stats()["resident_bytes"] == 2 * per
    assert pager.stats()["resident_tenants"] == ["a", "b"]
    # touching "a" refreshes its recency: paging "c" in evicts "b", not "a"
    pager.resident("a")
    pager.resident("c")
    st = pager.stats()
    assert st["resident_tenants"] == ["a", "c"]
    assert st["evictions"] == 1 and st["page_ins"] == 3
    assert st["resident_bytes"] == 2 * per
    assert st["page_in_p50_ms"] is not None
    # a re-request of the evicted tenant is a page-in, never an error
    np.testing.assert_array_equal(
        np.asarray(pager.resident("b")["w"])[:1], [1.0]
    )
    # drained events tell the whole story with honest byte counts
    events = pager.drain_events()
    assert [e["event"] for e in events].count("tenant_evicted") == 2
    assert all(e["bytes"] == per for e in events)
    assert pager.drain_events() == []  # drained means drained
    # the default tenant is the pinned template: no paging, no accounting
    assert pager.resident(None) is pager.template
    assert pager.stats()["page_ins"] == 4


def test_pager_watermark_pressure_evicts_lru():
    class _Watermarks:
        headroom = 1.0

        def snapshot(self):
            return {"headroom_frac_min": self.headroom}

    wm = _Watermarks()
    pager = WeightPager(
        _FakeRegistry(["a", "b"]), template=None,
        min_headroom_frac=0.1, watermarks=wm,
    )
    pager.resident("a")
    pager.resident("b")
    assert pager.check_watermark() is None  # plenty of headroom
    wm.headroom = 0.05
    assert pager.check_watermark() == "a"  # LRU goes first
    assert pager.stats()["resident_tenants"] == ["b"]
    drained = pager.drain_events()
    assert drained[-1]["reason"] == "hbm_watermark"
    # no provider / knob off => free no-op
    assert WeightPager(_FakeRegistry(["a"]), None).check_watermark() is None


# ---------------------------------------------------------------------------
# digest stability + quotas
# ---------------------------------------------------------------------------


def test_default_tenant_digest_stability_pin():
    x, y, _ = _episode(3)
    base = support_digest(x, y, 2)
    # absent, None, and explicitly-default tenants are all byte-identical
    # to the pre-tenancy digest — adaptation ids never churn on upgrade
    assert support_digest(x, y, 2, tenant=None) == base
    assert support_digest(x, y, 2, "maml++", None) == base
    assert support_digest(x, y, 2, tenant="acme") != base
    assert support_digest(x, y, 2, tenant="acme") != support_digest(
        x, y, 2, tenant="bravo"
    )


def test_quotas_rate_inflight_and_resident_bytes():
    now = [0.0]
    q = TenantQuotas(
        max_inflight=2, rate_rps=1.0, max_resident_bytes=100,
        clock=lambda: now[0],
    )
    assert q.enabled
    q.acquire("a")  # burst token
    with pytest.raises(QuotaExceededError) as exc:
        q.acquire("a")
    assert exc.value.reason == "rate"
    assert 0 < exc.value.retry_after_s <= 1.0  # honest token-refill time
    # tenants do not share buckets: "b" is unaffected by "a"'s breach
    q.acquire("b")
    now[0] += 2.0  # refill
    q.acquire("a")
    now[0] += 2.0
    with pytest.raises(QuotaExceededError) as exc:
        q.acquire("a")  # 2 inflight already held
    assert exc.value.reason == "inflight"
    q.release("a")
    now[0] += 2.0
    q.acquire("a")  # freed slot admits again
    q.check_resident_bytes("a", 100)  # at the limit is fine
    with pytest.raises(QuotaExceededError):
        q.check_resident_bytes("a", 101)
    st = q.stats()
    assert st["inflight"]["a"] == 2
    assert st["rejections"] == {
        "a.rate": 1, "a.inflight": 1, "a.resident_bytes": 1
    }
    assert not TenantQuotas().enabled  # all-zero = off


# ---------------------------------------------------------------------------
# sessions: spill/rehydrate carries the tenant
# ---------------------------------------------------------------------------


def test_session_spill_rehydrate_round_trips_tenant(tmp_path):
    store = SessionStore(str(tmp_path))
    tree = {"w": np.arange(4, dtype=np.float32)}
    store.spill("d" * 64, tree, "fp-acme", 1.0, 600.0, tenant="acme")
    store.spill("e" * 64, tree, "fleet-fp", 1.0, 600.0)
    # without the tenant map, the tenant entry stays foreign (never served
    # under the wrong master); the default entry rehydrates
    entries, stats = store.load_all("fleet-fp", tree)
    assert stats == {"loaded": 1, "stale": 0, "corrupt": 0, "foreign": 1}
    assert [(e[0], e[4]) for e in entries] == [("e" * 64, None)]
    # with the map, the tenant entry rehydrates carrying its tenant
    entries, stats = store.load_all(
        "fleet-fp", tree, tenant_fingerprints={"acme": "fp-acme"}
    )
    assert stats["loaded"] == 1
    digest, loaded, lived_s, strategy, tenant = entries[0]
    assert (digest, strategy, tenant) == ("d" * 64, "maml++", "acme")
    np.testing.assert_array_equal(np.asarray(loaded["w"]), tree["w"])


def test_pre_tenancy_session_files_read_as_default_tenant(tmp_path):
    # a file spilled WITHOUT the tenant field (pre-tenancy writer) must
    # read back as the default tenant and rehydrate against the fleet
    # master — the upgrade story for spilled sessions
    store = SessionStore(str(tmp_path))
    tree = {"w": np.ones(2, np.float32)}
    store.spill("a" * 64, tree, "fleet-fp", 0.0, 600.0, tenant=None)
    entries, stats = store.load_all(
        "fleet-fp", tree, tenant_fingerprints={"acme": "fp-acme"}
    )
    assert stats["loaded"] == 1
    assert entries[0][4] is None
    # a tenant whose fingerprint moved (re-finetuned checkpoint) stays
    # foreign rather than serving stale weights
    store.spill("b" * 64, tree, "fp-old", 0.0, 600.0, tenant="acme")
    _, stats = store.load_all(
        "fleet-fp", tree, tenant_fingerprints={"acme": "fp-new"}
    )
    assert stats["foreign"] == 1


# ---------------------------------------------------------------------------
# the tier-1 platform drill + quota isolation over the frontend
# ---------------------------------------------------------------------------


def test_platform_drill_tenant_thrash_all_invariants():
    """The acceptance drill: 4 tenants (distinct toy checkpoints) behind
    one fleet, budget fits 2 — cold tenants complete via page-in with zero
    outside-prewarm compiles (sealed guard), responses bit-identical per
    tenant to single-tenant controls, evictions/page-ins visible in
    /metrics.tenants and events.jsonl, every non-200 access-resolvable."""
    from howtotrainyourmamlpytorch_tpu.resilience.campaign import (
        Episode,
        _run_serve_episode,
    )

    violations = _run_serve_episode(
        Episode(kind="serve-tenant-thrash", mode="serve")
    )
    assert violations == []


@pytest.fixture(scope="module")
def tenant_fleet(tmp_path_factory):
    cfg = _config(
        serving=ServingConfig(
            support_buckets=[10], query_buckets=[15], max_batch_size=2
        )
    )
    system = _system(cfg)
    state = system.init_train_state()
    registry = synthetic_registry(
        ["acme", "bravo"], state,
        str(tmp_path_factory.mktemp("tenant_fleet")),
    )
    frontend = ServingFrontend(
        AdaptationEngine(system, state, registry=registry)
    )
    yield frontend
    frontend.close()


def test_quota_breach_sheds_429_without_degrading_others(tenant_fleet):
    x, y, _ = _episode(11)
    now = [0.0]
    saved = tenant_fleet.quotas
    # fake-clock quotas so the rate breach is deterministic (and so the
    # 1 rps limit can't leak into later tests sharing the fleet)
    tenant_fleet.quotas = TenantQuotas(rate_rps=1.0, clock=lambda: now[0])
    try:
        out = tenant_fleet.adapt(x, y, tenant="acme")
        assert out["tenant"] == "acme"
        # burst=1 token at 1 rps: the immediate second request is an
        # honest 429 with a computed Retry-After, mapped onto the shed
        # contract (quota admission runs BEFORE the cache check, so even
        # this would-be cache hit consumes admission)
        with pytest.raises(ServiceUnavailableError) as exc:
            tenant_fleet.adapt(x, y, tenant="acme")
        assert exc.value.status == 429
        assert 0 < exc.value.retry_after_s <= 1.0
        # the breach is acme's alone: bravo and the default tenant serve on
        assert tenant_fleet.adapt(x, y, tenant="bravo")["tenant"] == "bravo"
        assert "tenant" not in tenant_fleet.adapt(x, y)
        m = tenant_fleet.metrics()
        assert m["tenants"]["quotas"]["rejections"] == {"acme.rate": 1}
        assert m["tenants"]["by_tenant"]["acme"]["adapt.shed"] == 1
        assert m["tenants"]["by_tenant"]["acme"]["adapt.ok"] == 1
        assert m["tenants"]["by_tenant"]["bravo"]["adapt.ok"] == 1
        assert m["tenants"]["by_tenant"]["default"]["adapt.ok"] == 1
        assert m["tenants"]["registry"]["hosted"] == 2
    finally:
        tenant_fleet.quotas = saved


def test_cross_tenant_adaptation_id_is_honest_404(tenant_fleet):
    # distinct masters => distinct fingerprints => the cache key for
    # acme's id under bravo can never exist
    x, y, xq = _episode(12)
    acme_id = tenant_fleet.adapt(x, y, tenant="acme")["adaptation_id"]
    probs = tenant_fleet.predict(acme_id, xq, tenant="acme")
    assert probs.shape[0] == xq.shape[0]
    with pytest.raises(UnknownAdaptationError):
        tenant_fleet.predict(acme_id, xq, tenant="bravo")
    with pytest.raises(UnknownAdaptationError):
        tenant_fleet.predict(acme_id, xq)  # nor the default tenant's


def test_engine_without_registry_rejects_tenant_traffic():
    cfg = _config(
        serving=ServingConfig(support_buckets=[10], query_buckets=[15])
    )
    system = _system(cfg)
    engine = AdaptationEngine(system, system.init_train_state())
    assert engine.registry is None and engine.pager is None
    x, y, _ = _episode(13)
    frontend = ServingFrontend(engine)
    try:
        with pytest.raises(ValueError, match="no tenant registry"):
            frontend.adapt(x, y, tenant="acme")
    finally:
        frontend.close()


def test_tenant_budget_bytes_flows_from_config(tmp_path):
    cfg = _config(
        serving=ServingConfig(
            support_buckets=[10], query_buckets=[15],
            tenant_budget_bytes=12345,
        )
    )
    system = _system(cfg)
    state = system.init_train_state()
    registry = synthetic_registry(["a"], state, str(tmp_path))
    engine = AdaptationEngine(system, state, registry=registry)
    assert engine.pager is not None
    assert engine.pager.budget_bytes == 12345
    assert engine.pager.template is engine.state
    # the template's own bytes never count against the budget
    assert engine.pager.stats()["resident_bytes"] == 0
    assert tree_bytes(engine.state) > 0
