#!/usr/bin/env python
"""Minimal buffer-donation reproducer: same seed, same batch sequence, two
arms — ``donate_train_state=true`` vs ``false`` — run stepwise with a FRESH
``device_put`` of a different batch every step (mimicking the training
loader's H2D churn, which the repeated-batch descent probe never exercises:
a donated buffer freed mid-step and reused by an incoming transfer is
exactly the aliasing bug class that only shows up with streaming inputs).

Donation must be a pure memory optimization: both arms must produce the
same per-step losses and final parameters up to float reordering. A
divergence on the chip (CPU control is bit-identical because donation is
ignored there) is the smoking gun for the 20-way collapse's top suspect
(results/r4/DIAG_20way_r4.md).

Argv: [n_steps=40] [n_way=20] [k_shot=5] [batch_size=8]

``selfcheck`` as argv[1] runs the determinism control instead: each arm
twice on the identical stream, compared to ITSELF. Same-program re-runs
diverging = the chip is nondeterministic in general; self-reproducible arms
that differ from each other = donation (the only program difference) is the
corruption. This closes the one confound in the A/B verdict — donate and
no-donate compile different programs, so in principle float reordering
could differ between them (though reorder noise is ~1e-6 rel, far below
the measured 3.2e-1).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import dataclasses

import jax.numpy as jnp
import numpy as np

from howtotrainyourmamlpytorch_tpu.config import Config
from howtotrainyourmamlpytorch_tpu.core import MAMLSystem
from howtotrainyourmamlpytorch_tpu.data.synthetic import synthetic_batch


def run_arm(cfg: Config, n_steps: int, n_batches: int = 16, system: MAMLSystem = None):
    # selfcheck passes the arm's system in so the re-run reuses its compiled
    # program instead of burning a second multi-minute on-chip compile
    system = system or MAMLSystem(cfg)
    state = system.init_train_state()
    losses = []
    for i in range(n_steps):
        # fresh host->device transfer every step, like the real loader —
        # the donated previous state's buffers are free for reuse by these
        # incoming copies, which is the aliasing window under test
        host = synthetic_batch(
            cfg.batch_size,
            cfg.num_classes_per_set,
            cfg.num_samples_per_class,
            cfg.num_target_samples,
            cfg.image_shape,
            seed=i % n_batches,
        )
        batch = {k: jax.device_put(np.asarray(v)) for k, v in host.items()}
        state, out = system.train_step(state, batch, epoch=0)
        losses.append(float(out.loss))
    return losses, jax.device_get(state.params)


def _rel_divs(params_a, params_b):
    """[(path_str, rel ||a-b||/||b||)] per leaf, two same-structure trees."""
    out = []
    for (path_a, leaf_a), (_, leaf_b) in zip(
        jax.tree_util.tree_flatten_with_path(params_a)[0],
        jax.tree_util.tree_flatten_with_path(params_b)[0],
    ):
        a, b = np.asarray(leaf_a, np.float64), np.asarray(leaf_b, np.float64)
        rel = np.linalg.norm(a - b) / (np.linalg.norm(b) or 1.0)
        out.append((jax.tree_util.keystr(path_a), rel))
    return out


def _worst_rel(params_a, params_b):
    return max(rel for _, rel in _rel_divs(params_a, params_b))


def selfcheck(argv):
    n_steps = int(argv[0]) if len(argv) > 0 else 40
    n_way = int(argv[1]) if len(argv) > 1 else 20
    k_shot = int(argv[2]) if len(argv) > 2 else 5
    batch_size = int(argv[3]) if len(argv) > 3 else 8
    base = Config(
        num_classes_per_set=n_way,
        num_samples_per_class=k_shot,
        batch_size=batch_size,
        unroll_inner_steps=True,
        remat_inner_steps=False,
    )
    print(
        f"donation selfcheck: backend={jax.default_backend()} n_steps={n_steps} "
        f"{n_way}w{k_shot}s b{batch_size}",
        flush=True,
    )
    runs = {}
    for donate in (True, False):
        cfg = dataclasses.replace(base, donate_train_state=donate)
        system = MAMLSystem(cfg)
        runs[donate] = [run_arm(cfg, n_steps, system=system) for _ in range(2)]
        (loss_a, p_a), (loss_b, p_b) = runs[donate]
        max_loss = max(abs(x - y) for x, y in zip(loss_a, loss_b))
        rel = _worst_rel(p_a, p_b)
        # two-signal label like main()'s verdict: a loss-trace deviation is
        # nondeterminism even if the params happen to land back together
        nondet = rel > 1e-4 or max_loss > 1e-4
        print(
            f"  donate={donate} run-vs-rerun: max |loss dev| = {max_loss:.3e}, "
            f"worst param rel |d| = {rel:.3e} "
            f"({'NONDETERMINISTIC' if nondet else 'self-reproducible'})",
            flush=True,
        )
    cross = _worst_rel(runs[True][0][1], runs[False][0][1])
    print(f"  donate-vs-nodonate (run 0): worst param rel |d| = {cross:.3e}", flush=True)


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "selfcheck":
        selfcheck(sys.argv[2:])
        return
    n_steps = int(sys.argv[1]) if len(sys.argv) > 1 else 40
    n_way = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    k_shot = int(sys.argv[3]) if len(sys.argv) > 3 else 5
    batch_size = int(sys.argv[4]) if len(sys.argv) > 4 else 8

    base = Config(
        num_classes_per_set=n_way,
        num_samples_per_class=k_shot,
        batch_size=batch_size,
        unroll_inner_steps=True,  # the production program family
        remat_inner_steps=False,
    )
    print(
        f"donation probe: backend={jax.default_backend()} n_steps={n_steps} "
        f"{n_way}w{k_shot}s b{batch_size}",
        flush=True,
    )
    loss_d, params_d = run_arm(dataclasses.replace(base, donate_train_state=True), n_steps)
    loss_n, params_n = run_arm(dataclasses.replace(base, donate_train_state=False), n_steps)

    max_loss_dev = max(abs(a - b) for a, b in zip(loss_d, loss_n))
    first_dev = next(
        (i for i, (a, b) in enumerate(zip(loss_d, loss_n)) if abs(a - b) > 1e-5), None
    )
    print(f"per-step loss: max |donate - nodonate| = {max_loss_dev:.3e} "
          f"(first step deviating >1e-5: {first_dev})", flush=True)

    divs = _rel_divs(params_d, params_n)
    worst_rel = max(rel for _, rel in divs)
    for path, rel in divs:
        if rel > 1e-4:
            print(f"  DIVERGED {path}: rel |Δ| = {rel:.3e}", flush=True)
    print(f"final params: worst relative divergence = {worst_rel:.3e}", flush=True)
    # float-reorder noise between two identical-math programs is ~1e-6 rel;
    # donation corruption is orders of magnitude beyond it
    verdict = "DONATION-CORRUPTION" if (worst_rel > 1e-3 or max_loss_dev > 1e-2) else "clean"
    print(f"verdict: {verdict}", flush=True)


if __name__ == "__main__":
    main()
