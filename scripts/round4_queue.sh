#!/bin/bash
# Round-4 chip-work queue: serialize everything that needs the single
# tunneled chip, in priority order, fully unattended (the tunnel wedges for
# hours; whenever it answers, this drains the queue):
#   1. capture the round-4 bench numbers (bench.py waits out wedges itself)
#   2. run the accuracy-matrix sweep rows (VERDICT r3 item 3 priority order),
#      LED by the full-budget donation-off 20-way rows — simultaneously the
#      donation-fix verification (results/r4/DIAG_20way_r4.md verdict:
#      DONATION-CORRUPTION) and the missing 20-way parity rows.
# The diag chain (scripts/diag_chain.sh) is NOT queued anymore: its
# donation A/B probe delivered the on-chip verdict in session 2 and the
# remaining 3-epoch arms are subsumed by the sweep's guarded nodonate rows
# (X8 == those rows' first 3 epochs; X3/X7 only matter if they abort).
#
# Usage: scripts/round4_queue.sh [deadline_epoch]
set -u
cd /root/repo
# $1 (optional) is a deadline in EPOCH SECONDS; earlier revisions took a pid
# here, so reject anything not clearly in the future (a stale-style pid arg
# would silently become a 1970 deadline and the sweep would start zero rows)
case "${1:-}" in
  *[!0-9]*)
    # non-numeric arg: [ -le ] would error-and-continue and DEADLINE_EPOCH
    # would export as garbage, silently disarming every later deadline
    # comparison here and in sweep.sh (ADVICE r4) — reject it instead
    echo "round4_queue.sh: deadline_epoch must be an integer epoch, got '$1'" >&2
    exit 2;;
esac
if [ -n "${1:-}" ] && [ "$1" -le "$(date +%s)" ]; then
  echo "round4_queue.sh: deadline_epoch $1 is in the past" >&2
  exit 2
fi
LOG=exps/round4_queue.log
mkdir -p exps
echo "=== $(date -u +%H:%M:%S) queue start (chain cut; straight to bench+sweep)" >> "$LOG"

# outer timeout > startup deadline (7200) + worst-case sum of the bench's
# internal stage budgets (~6300) so the in-process watchdog, which can
# salvage a measured headline, always fires before SIGTERM does
BENCH_STARTUP_DEADLINE_S=7200 timeout --kill-after=30 14400 \
  python bench.py > exps/bench_r04.json 2> exps/bench_r04.err
rc=$?
# exps/ is gitignored and wiped on container resets (this exact loss mode
# cost round 3 its bench number) — copy the capture somewhere durable
# immediately
mkdir -p results/r4
cp -f exps/bench_r04.json results/r4/bench_r04_capture.json 2>/dev/null
tail -c 4096 exps/bench_r04.err > results/r4/bench_r04_capture.err 2>/dev/null
echo "=== $(date -u +%H:%M:%S) bench rc=$rc -> exps/bench_r04.json (+ results/r4/)" >> "$LOG"

# throughput cost of the 20-way fix candidate (f32-quality matmuls): same
# flagship program at matmul_precision=high
BENCH_MATMUL_PRECISION=high BENCH_STARTUP_DEADLINE_S=3600 \
  timeout --kill-after=30 10800 \
  python bench.py > exps/bench_r04_high.json 2> exps/bench_r04_high.err
cp -f exps/bench_r04_high.json results/r4/bench_r04_high.json 2>/dev/null
echo "=== $(date -u +%H:%M:%S) bench(high) rc=$? -> results/r4/bench_r04_high.json" >> "$LOG"

# ~1h/row full-budget; DEADLINE_EPOCH (exported to sweep.sh) stops starting
# rows that would overrun the round.
export DEADLINE_EPOCH=${1:-$(( $(date +%s) + 9 * 3600 ))}
# Config defaults are the reference's 20-way 5-shot — every row must pin
# its own n_way/k_shot explicitly.
#
# The two 20-way donation-off rows lead: round-4 CPU evidence (fresh-stream
# probes healthy at f32 AND under MXU-default emulation, results/r4/
# DIAG_20way_r4.md) isolates the on-chip collapse to platform execution,
# with jit buffer donation the top suspect (ignored on CPU, matches the
# cumulative-corruption signature). If donation is it, these rows are
# simultaneously the fix verification and the missing 20-way parity rows
# (ref 99.13±0.13 / 97.21±0.11).
W5S1="num_classes_per_set=5 num_samples_per_class=1"
W5S5="num_classes_per_set=5 num_samples_per_class=5"
W20S1="num_classes_per_set=20 num_samples_per_class=1"
W20S5="num_classes_per_set=20 num_samples_per_class=5"
# early-abort: if a nodonate row is still <15% train acc after 3 epochs the
# donation fix didn't take — release the chip (rc=3, permanent) instead of
# burning its 150-epoch budget
EABORT="early_abort_train_acc=0.15 early_abort_epoch=3"
NODONATE5="omniglot.20.5.vgg.gd.nodonate.0 $W20S5 donate_train_state=false $EABORT"
NODONATE1="omniglot.20.1.vgg.gd.nodonate.0 $W20S1 donate_train_state=false $EABORT"
# If the chain's X8 arm (3-epoch 20w5s donation-off) already ran and STILL
# collapsed (epoch-2 train acc <= 0.25), donation isn't the fix — demote the
# full-budget nodonate rows behind the guaranteed-value 5-way rows. The
# first 'epoch 2:' line in chain.log is X8's (the probe arms before it
# print no epoch lines).
# exps/ is wiped on container resets, so fall back to the committed durable
# copy of the chain log — a refuted donation hypothesis must survive a reset
x8_acc=$(cat exps/diag/chain.log results/r4/diag_chain.log 2>/dev/null \
  | grep -oE 'epoch 2: train_acc=[0-9.]+' \
  | head -1 | grep -oE '[0-9.]+$')
if [ -n "$x8_acc" ] && awk "BEGIN{exit !($x8_acc <= 0.25)}"; then
  echo "=== X8 donation-off arm collapsed too (epoch-2 acc $x8_acc) — demoting nodonate rows" >> "$LOG"
  set -- \
    "omniglot.5.1.resnet-4.gd.0 $W5S1 net=resnet-4" \
    "omniglot.5.1.vgg.adam.0 $W5S1 inner_optim=adam" \
    "omniglot.5.1.vgg.gd.1 $W5S1 seed=1 train_seed=1 val_seed=1" \
    "omniglot.5.5.vgg.gd.1 $W5S5 seed=1 train_seed=1 val_seed=1" \
    "omniglot.5.5.densenet-8.gd.0 $W5S5 net=densenet-8" \
    "omniglot.5.1.vgg.gd.2 $W5S1 seed=2 train_seed=2 val_seed=2" \
    "omniglot.5.5.vgg.gd.2 $W5S5 seed=2 train_seed=2 val_seed=2" \
    "$NODONATE5" \
    "$NODONATE1"
else
  set -- \
    "$NODONATE5" \
    "$NODONATE1" \
    "omniglot.5.1.resnet-4.gd.0 $W5S1 net=resnet-4" \
    "omniglot.5.1.vgg.adam.0 $W5S1 inner_optim=adam" \
    "omniglot.5.1.vgg.gd.1 $W5S1 seed=1 train_seed=1 val_seed=1" \
    "omniglot.5.5.vgg.gd.1 $W5S5 seed=1 train_seed=1 val_seed=1" \
    "omniglot.5.5.densenet-8.gd.0 $W5S5 net=densenet-8" \
    "omniglot.5.1.vgg.gd.2 $W5S1 seed=2 train_seed=2 val_seed=2" \
    "omniglot.5.5.vgg.gd.2 $W5S5 seed=2 train_seed=2 val_seed=2"
fi
bash scripts/sweep.sh "$@" >> "$LOG" 2>&1
# durable copy of run artifacts (not checkpoints) for every finished row
for d in exps/omniglot.*; do
  [ -d "$d/logs" ] || continue
  name=$(basename "$d")
  mkdir -p "results/r4/$name"
  cp -f "$d"/logs/*.csv "$d"/logs/*.json "$d"/lrs.csv "$d"/betas.csv \
    "$d"/config.yaml "results/r4/$name/" 2>/dev/null
done
# regenerate the aggregated accuracy report from everything that finished
python analyze_results.py exps/ --out results/r4/analysis >> "$LOG" 2>&1
# if the headline bench never got a value (wedge outlasted its startup
# deadline), retry once now — the sweep just proved the chip answers again
if ! grep -q '"value": [0-9]' exps/bench_r04.json 2>/dev/null; then
  echo "=== $(date -u +%H:%M:%S) bench had no value; end-of-queue retry" >> "$LOG"
  BENCH_STARTUP_DEADLINE_S=3600 timeout --kill-after=30 10800 \
    python bench.py > exps/bench_r04.json 2> exps/bench_r04.err
  cp -f exps/bench_r04.json results/r4/bench_r04_capture.json 2>/dev/null
  tail -c 4096 exps/bench_r04.err > results/r4/bench_r04_capture.err 2>/dev/null
  echo "=== $(date -u +%H:%M:%S) bench retry rc=$? -> results/r4/" >> "$LOG"
fi
echo "=== $(date -u +%H:%M:%S) queue done (artifacts copied to results/r4/)" >> "$LOG"
